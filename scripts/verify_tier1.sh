#!/usr/bin/env bash
# Tier-1 pass-count regression guard (verify half).
#
# Runs the ROADMAP tier-1 command verbatim and asserts DOTS_PASSED
# against the committed floor in TIER1_BASELINE.json -- a green suite
# that quietly passes FEWER tests than the baseline fails here. The
# static twin (tests/test_baseline_count.py) guards the test-function
# count from inside the suite itself.
#
# Usage: scripts/verify_tier1.sh   (from the repo root)
set -u
cd "$(dirname "$0")/.."

FLOOR=$(python -c "import json; print(json.load(open('TIER1_BASELINE.json'))['dots_passed_floor'])")

# full-tree contract analysis first: it is seconds, and a contract
# violation fails fast with an actionable finding instead of surfacing
# as a distant test failure (warn-severity findings print, don't gate)
if ! JAX_PLATFORMS=cpu python -m tempo_tpu.analysis --strict; then
  echo "tier-1 FAILED (static analysis --strict)"
  exit 1
fi

set -o pipefail
rm -f /tmp/_t1.log
timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' \
  --continue-on-collection-errors -p no:cacheprovider -p no:xdist -p no:randomly \
  2>&1 | tee /tmp/_t1.log
rc=${PIPESTATUS[0]}
DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log | tr -cd . | wc -c)
echo "DOTS_PASSED=${DOTS_PASSED} (floor ${FLOOR})"
# Gate on FAILURES and the pass-count floor, not on pytest's raw exit
# code: environment-gated suites (e.g. proto interop without protoc on
# PATH) error at collection on images that can't run them, and the
# committed floor already prices that in. A REAL collection regression
# (a test module that stops importing) drops DOTS_PASSED below the
# floor and fails here.
if grep -aqE '[0-9]+ failed' /tmp/_t1.log; then
  echo "tier-1 FAILED (test failures; exit $rc)"
  exit 1
fi
if [ "$DOTS_PASSED" -lt "$FLOOR" ]; then
  echo "tier-1 regression: DOTS_PASSED ${DOTS_PASSED} < floor ${FLOOR} (TIER1_BASELINE.json)"
  exit 1
fi
if [ "$rc" -ne 0 ]; then
  echo "tier-1 OK with env-gated collection errors (exit $rc tolerated; floor held)"
  exit 0
fi
echo "tier-1 OK"
