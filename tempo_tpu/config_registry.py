"""Single registry of every operator-facing knob: TEMPO_* environment
variables and the `tempo-tpu` server CLI flags.

Before this module the knob surface lived wherever each subsystem read
it -- 45 env vars across 20 files, documented (or not) wherever a PR
happened to touch. The static analyzer's config-contract rules keep
this registry honest from both ends:

  * env-unregistered: code reads a TEMPO_* name missing here;
  * env-dead: a registered name no code reads;
  * env-doc-drift: a registered name absent from README.md/ops docs.

Both dicts are plain literals on purpose: the analyzer consumes them
via ast.literal_eval without importing anything, and the runtime
helpers below give services a typed read path so new knobs have no
excuse to bypass the registry.

KNOBS maps env name -> (type, default, doc) where type is one of
"bool" (unset/1 = on, "0"/"false" = off unless noted), "int", "float",
"str", "path". Defaults are given as the string the reader falls back
to ("" = unset).
"""

from __future__ import annotations

import os

# env name -> (type, default, one-line doc)
KNOBS: dict[str, tuple[str, str, str]] = {
    "TEMPO_AFFINITY": (
        "bool", "1",
        "cache-affinity query placement across device domains (0 = off)"),
    "TEMPO_AFFINITY_STEAL_MS": (
        "float", "25.0",
        "idle-domain work-steal patience before breaking affinity"),
    "TEMPO_BATCH": (
        "bool", "1",
        "admission-window query batching (0/false = per-query launches)"),
    "TEMPO_BATCH_MAX": (
        "int", "16", "max queries fused into one batched launch"),
    "TEMPO_BATCH_MQ_BUDGET": (
        "int", "1073741824",
        "fused-launch HBM intermediate budget in bytes; groups past it "
        "run sequentially"),
    "TEMPO_BATCH_WINDOW_MS": (
        "float", "2.0", "admission window the batcher holds a leader open"),
    "TEMPO_BREAKER_WINDOW_S": (
        "float", "30.0", "circuit-breaker rolling error window"),
    "TEMPO_BREAKER_MIN_VOLUME": (
        "int", "8", "calls in window before the breaker may trip"),
    "TEMPO_BREAKER_ERROR_RATE": (
        "float", "0.5", "error fraction in window that trips the breaker"),
    "TEMPO_BREAKER_OPEN_S": (
        "float", "5.0", "open-state hold before half-open probing"),
    "TEMPO_BREAKER_PROBES": (
        "int", "2", "successful half-open probes required to close"),
    "TEMPO_BREAKER_PROBE_TIMEOUT_S": (
        "float", "30.0", "half-open probe reply deadline"),
    "TEMPO_CHAOS": (
        "str", "",
        "fault-injection rules: inline JSON or a rules file path "
        "('' = chaos off)"),
    "TEMPO_CHUNK_CACHE": (
        "bool", "1",
        "host-RAM compressed column-chunk tier under the HBM staged "
        "cache (0 = evictions discard, misses re-read the backend)"),
    "TEMPO_CHUNK_CACHE_BUDGET": (
        "int", "1073741824",
        "chunk-tier host pool budget in compressed bytes"),
    "TEMPO_CHUNK_CACHE_CODEC": (
        "str", "none",
        "chunk-tier recompression codec: none/lz4/snappy/zstd -- the "
        "default stores raw bytes (a restage must beat the backend "
        "read + decode + assemble it replaces; recompression only "
        "pays where a native codec wheel is installed)"),
    "TEMPO_CHUNK_CACHE_MAX_ENTRY": (
        "int", "268435456",
        "largest single staged-column set the chunk tier admits (raw "
        "bytes)"),
    "TEMPO_CHUNK_CACHE_MIN_REUSE": (
        "int", "1",
        "stage count a block needs before eviction demotes instead of "
        "discards (bytes x reuse admission)"),
    "TEMPO_COMPACT_CONCURRENCY": (
        "int", "1", "parallel compaction pipeline workers"),
    "TEMPO_COMPACT_MEM_BUDGET": (
        "int", "1073741824",
        "compaction pipeline admission budget in bytes"),
    "TEMPO_COMPACT_PASSTHROUGH": (
        "bool", "1",
        "copy untouched blocks' compressed bytes verbatim during "
        "compaction (0 = always re-encode)"),
    "TEMPO_COMPILE_CACHE_DIR": (
        "path", "",
        "persistent XLA compile cache directory ('' = in-memory only)"),
    "TEMPO_COSTMODEL": (
        "bool", "1", "per-(op, bucket) device cost capture (0 = off)"),
    "TEMPO_COSTMODEL_MEMORY": (
        "bool", "1",
        "XLA memory-analysis capture alongside FLOPs (0 = off)"),
    "TEMPO_COST_LEDGER": (
        "path", "",
        "measured-crossover CostLedger artifact path ('' = "
        "<storage>/cost_ledger.json)"),
    "TEMPO_CUT_ENGINE": (
        "str", "",
        "pin block-cut engine to 'device' or 'host' ('' = measured "
        "crossover routing)"),
    "TEMPO_FIND_MODE": (
        "str", "",
        "pin trace-by-id lookup to 'host'/'device'/'auto' ('' = auto)"),
    "TEMPO_KERNELTEL_SYNC": (
        "bool", "",
        "1 = device timers block_until_ready (true device time), "
        "0 = dispatch time only ('' = auto by backend)"),
    "TEMPO_LIVE_CROSSOVER_ROWS": (
        "float", "4096",
        "live-search host/device crossover seed in staged rows"),
    "TEMPO_LIVE_ENGINE": (
        "str", "",
        "pin the live-search engine to 'device' or 'host' ('' = "
        "measured routing)"),
    "TEMPO_LIVE_FIND_DEVICE": (
        "bool", "0", "1 = lower live trace-by-id onto staged rows"),
    "TEMPO_LIVE_STAGE": (
        "bool", "1", "live-head HBM staging of pushed spans (0 = off)"),
    "TEMPO_LOCK_PROFILE": (
        "bool", "0", "1 = contended-lock wait profiling on hot locks"),
    "TEMPO_LOG_LEVEL": (
        "str", "INFO", "structured-log level (DEBUG/INFO/WARNING/ERROR)"),
    "TEMPO_MESH_BATCH": (
        "bool", "1",
        "mesh-sharded batched launches on multi-device (0/false = "
        "single-chip fused path)"),
    "TEMPO_PROFILE_DIR": (
        "path", "",
        "flamegraph/slow-query artifact directory ('' = artifacts off)"),
    "TEMPO_PROFILE_HZ": (
        "float", "19.0", "continuous profiler sampling rate (0 = off)"),
    "TEMPO_RESULT_CACHE": (
        "bool", "1",
        "frontend query-result cache ahead of queue admission (0 = "
        "every query executes; byte-identical to a cacheless build)"),
    "TEMPO_RESULT_CACHE_EXTEND": (
        "bool", "1",
        "incremental extension of cached results for moving now-edge "
        "ranges (0 = exact-range hits only)"),
    "TEMPO_RESULT_CACHE_LIVE_WINDOW_S": (
        "float", "30.0",
        "trailing window treated as mutable live head: ranges ending "
        "inside it key on the ingester live generation, and extension "
        "prefixes stop this far behind now"),
    "TEMPO_RESULT_CACHE_MAX_BYTES": (
        "int", "67108864",
        "result-cache LRU budget in serialized-payload bytes"),
    "TEMPO_RESULT_CACHE_TTL_S": (
        "float", "300.0",
        "result-cache entry lifetime; bounds staleness from spans "
        "arriving later than the live window into old ranges"),
    "TEMPO_RETRY_BUDGET": (
        "int", "0",
        "per-query retry budget override (0 = max(4, jobs/4))"),
    "TEMPO_SELFTRACE_QUEUE": (
        "int", "256", "self-trace export queue depth before drops"),
    "TEMPO_SLO_EVAL_S": (
        "float", "15", "SLO engine evaluation interval"),
    "TEMPO_SLO_FRESHNESS_P99_S": (
        "float", "2.5", "live-search write-to-visible freshness SLO p99"),
    "TEMPO_SLO_GENERATOR_FRESHNESS_P99_S": (
        "float", "2.5", "metrics-generator tap-to-series freshness SLO p99"),
    "TEMPO_SLO_TRACES_P99_S": (
        "float", "1.0", "trace-by-id latency SLO p99"),
    "TEMPO_SLO_SEARCH_P99_S": (
        "float", "2.5", "search latency SLO p99"),
    "TEMPO_SLO_STREAM_P99_S": (
        "float", "5.0", "streamed-search latency SLO p99"),
    "TEMPO_SLO_METRICS_P99_S": (
        "float", "10.0", "TraceQL metrics latency SLO p99"),
    "TEMPO_STREAM_MEM_BUDGET": (
        "int", "268435456",
        "cold-streaming pipeline in-flight byte budget"),
    "TEMPO_STREAM_PREFETCH_DEPTH": (
        "int", "2",
        "cold-streaming units fetched ahead of the consumer (0 = serial)"),
    "TEMPO_STREAM_WORKERS": (
        "int", "0",
        "cold-streaming stage pool size (0 = max(4, cpus/2))"),
    "TEMPO_STRUCT_PACK": (
        "bool", "1",
        "hoisted + bit-packed structural collectives (0/false = legacy "
        "full-width gathers)"),
}

# `tempo-tpu` server flags (services/app.py main): flag -> (type, doc).
# Defaults are all None = "not given" -- a set flag always overrides the
# config file, so the effective defaults live with the config schema.
FLAGS: dict[str, tuple[str, str]] = {
    "--config.file": ("path", "YAML/JSON config file"),
    "--config.expand-env": ("bool", "substitute ${VAR} in the config file"),
    "--target": ("str", "module preset (all/distributor/querier/...)"),
    "--http.port": ("int", "HTTP listen port"),
    "--storage.path": ("path", "block storage root"),
    "--overrides.path": ("path", "per-tenant overrides file"),
    "--multitenancy": ("bool", "enforce X-Scope-OrgID"),
    "--kv.dir": ("path", "shared ring-KV dir for multi-process topologies"),
    "--memberlist.bind": ("str", "gossip bind host:port"),
    "--memberlist.join": ("str", "comma-separated gossip seed peers"),
    "--memberlist.advertise": ("str", "gossip addr peers dial"),
    "--advertise.addr": ("str", "address other processes reach this one at"),
    "--instance.id": ("str", "ring instance identity"),
    "--replication.factor": ("int", "ingest replication factor"),
    "--internal.token": ("str", "shared secret for /internal/*"),
    "--querier.frontend-address": ("str", "frontend addr(s) a standalone "
                                          "querier pulls jobs from"),
    "--distributor.otlp-grpc-port": ("int", "OTLP gRPC receiver port"),
    "--distributor.opencensus-grpc-port": ("int", "OpenCensus receiver port"),
    "--distributor.jaeger-grpc-port": ("int", "Jaeger gRPC collector port"),
    "--distributor.jaeger-agent-port": ("int", "Jaeger agent UDP port"),
    "--self-tracing.tenant": ("str", "tenant for the app's own timelines"),
    "--compile-cache.dir": ("path", "persistent XLA compile cache dir"),
    "--cost-ledger.path": ("path", "CostLedger artifact path"),
    "--chaos.rules": ("str", "fault-injection rules (JSON or file)"),
    "--warmup.shapes": ("bool", "AOT-compile the recorded shape corpus"),
    "--querier.search-external-endpoints": ("str", "serverless search URLs"),
    "--distributor.kafka-brokers": ("str", "Kafka broker host:port"),
    "--distributor.kafka-topic": ("str", "Kafka ingest topic"),
    "--distributor.kafka-tenant": ("str", "tenant kafka messages ingest into"),
    "--ring.heartbeat-timeout": ("float", "ring liveness window seconds"),
    "--rpc.deadline": ("float", "per-RPC deadline for remote clients"),
    "--querier.worker-concurrency": ("int", "standalone-querier job threads"),
}


# ------------------------------------------------------- runtime helpers
def get(name: str) -> str:
    """Registered read: raises on unregistered names so new knobs go
    through the registry (the analyzer catches the literal-string
    bypass)."""
    if name not in KNOBS:
        raise KeyError(f"unregistered knob {name!r}: add it to "
                       "tempo_tpu/config_registry.py KNOBS")
    return os.environ.get(name, KNOBS[name][1])


def get_bool(name: str) -> bool:
    return get(name) not in ("", "0", "false")


def get_int(name: str) -> int:
    try:
        return int(float(get(name)))
    except ValueError:
        return int(float(KNOBS[name][1] or 0))


def get_float(name: str) -> float:
    try:
        return float(get(name))
    except ValueError:
        return float(KNOBS[name][1] or 0)
