"""Gossip ring KV: multi-host membership without shared storage.

The role of the reference's memberlist KV (cmd/tempo/app/modules.go:
288-316): every process binds a gossip port, joins via seed addresses,
and periodically push-pull syncs FULL ring state with a random known
peer (memberlist's anti-entropy TCP sync; we skip the UDP probe layer
-- rings piggyback liveness on heartbeat timestamps anyway).

Merge rules: per (ring, instance) the newer heartbeat_ts wins;
removals become tombstones stamped at removal time, beat older updates,
and expire after a grace period. The peer set itself gossips alongside
ring state, so one seed is enough to find everyone.

Wire format: one JSON object per sync over a TCP connection
(length-prefixed), answered with the full local state -- both sides
converge in one round trip.
"""

from __future__ import annotations

import json
import random
import socket
import socketserver
import struct
import threading
import time

from ..ring.ring import InstanceDesc, InstanceState

# Tombstones must outlive any plausible partition: a node cut off longer
# than this TTL that still holds the removed instance's descriptor would
# resurrect it cluster-wide on rejoin (memberlist keeps tombstones until
# state sync confirms). 1h >> HEARTBEAT_TIMEOUT_S, so even a resurrected
# descriptor would already be unhealthy (stale heartbeat) by the time its
# tombstone could have been GC'd.
_TOMBSTONE_TTL_S = 3600.0
_PEER_TTL_S = 120.0  # drop non-seed peers unseen this long (dead addrs)
_LEN = struct.Struct("<I")
_MAX_MSG = 16 << 20


def _desc_to_dict(d: InstanceDesc) -> dict:
    return {"instance_id": d.instance_id, "addr": d.addr, "state": d.state.value,
            "tokens": d.tokens, "heartbeat_ts": d.heartbeat_ts}


def _desc_from_dict(v: dict) -> InstanceDesc:
    return InstanceDesc(
        instance_id=v["instance_id"], addr=v.get("addr", ""),
        state=InstanceState(v.get("state", InstanceState.ACTIVE.value)),
        tokens=v.get("tokens", []), heartbeat_ts=v.get("heartbeat_ts", 0.0),
    )


class GossipKV:
    def __init__(self, bind: str = "127.0.0.1:0", seeds: list[str] | None = None,
                 interval_s: float = 1.0, advertise: str = ""):
        """advertise: the addr OTHER nodes dial (required when binding
        0.0.0.0/ephemeral across hosts; defaults to the bound addr)."""
        host, _, port = bind.partition(":")
        self._lock = threading.RLock()
        # ring_key -> instance_id -> {"desc": dict|None, "ts": float}
        # (desc None = tombstone; ts orders merges)
        self._state: dict[str, dict[str, dict]] = {}
        self._seeds = tuple(seeds or [])  # never expire: rejoin anchors
        self._peers: dict[str, float] = {a: time.time() for a in self._seeds}
        self.interval_s = interval_s
        self.syncs = 0

        kv = self

        class _Handler(socketserver.BaseRequestHandler):
            def handle(self):
                try:
                    # a stalled peer must not pin a handler thread forever
                    self.request.settimeout(5.0)
                    theirs = _recv_msg(self.request)
                    mine = kv._merge_and_snapshot(theirs)
                    _send_msg(self.request, mine)
                except (OSError, ValueError, ConnectionError):
                    pass

        socketserver.ThreadingTCPServer.allow_reuse_address = True
        self._server = socketserver.ThreadingTCPServer((host or "127.0.0.1",
                                                        int(port or 0)), _Handler)
        self._server.daemon_threads = True
        bound = f"{self._server.server_address[0]}:{self._server.server_address[1]}"
        if not advertise and bound.startswith(("0.0.0.0:", ":")):
            raise ValueError(
                "gossip bound to a wildcard address: peers cannot dial "
                "0.0.0.0 -- pass an advertise addr (--memberlist.advertise)"
            )
        self.addr = advertise or bound
        threading.Thread(target=self._server.serve_forever, daemon=True,
                         name="gossip-server").start()
        self._stop = threading.Event()
        threading.Thread(target=self._gossip_loop, daemon=True,
                         name="gossip-loop").start()

    # --------------------------------------------------------- KV interface
    def update(self, ring_key: str, desc: InstanceDesc) -> None:
        with self._lock:
            self._state.setdefault(ring_key, {})[desc.instance_id] = {
                "desc": _desc_to_dict(desc), "ts": desc.heartbeat_ts or time.time(),
            }

    def remove(self, ring_key: str, instance_id: str) -> None:
        with self._lock:
            self._state.setdefault(ring_key, {})[instance_id] = {
                "desc": None, "ts": time.time(),  # tombstone
            }

    def get_all(self, ring_key: str) -> dict[str, InstanceDesc]:
        with self._lock:
            out = {}
            for iid, ent in self._state.get(ring_key, {}).items():
                if ent["desc"] is not None:
                    out[iid] = _desc_from_dict(ent["desc"])
            return out

    # ------------------------------------------------------------- gossip
    def _snapshot(self) -> dict:
        """COPIES under the lock: callers serialize outside it, and the
        live dicts mutate concurrently (updates / inbound merges)."""
        with self._lock:
            now = time.time()
            # expire old tombstones so state doesn't grow forever
            for ring in self._state.values():
                for iid in [i for i, e in ring.items()
                            if e["desc"] is None and now - e["ts"] > _TOMBSTONE_TTL_S]:
                    del ring[iid]
            # prune dead peer addrs (ephemeral rebinds accumulate);
            # seeds stay forever as rejoin anchors
            self._peers = {
                a: t for a, t in self._peers.items()
                if a != self.addr and (a in self._seeds or now - t < _PEER_TTL_S)
            }
            state = {rk: dict(ring) for rk, ring in self._state.items()}
            return {"state": state, "peers": {**self._peers, self.addr: now}}

    def _merge_and_snapshot(self, theirs: dict) -> dict:
        # chaos seam (inbound): a dropped recv answers with local state
        # but ignores the peer's -- a one-directional partition
        from ..chaos import plane as chaos_plane

        if chaos_plane.tap("gossip.recv", key=self.addr) is not chaos_plane.DROP:
            self._merge(theirs)
        return self._snapshot()

    def _merge(self, theirs: dict) -> None:
        if not isinstance(theirs, dict):
            return
        with self._lock:
            state = theirs.get("state")
            for ring_key, instances in (state.items() if isinstance(state, dict) else ()):
                if not isinstance(instances, dict):
                    continue
                ring = self._state.setdefault(ring_key, {})
                for iid, ent in instances.items():
                    if not isinstance(ent, dict):
                        continue
                    cur = ring.get(iid)
                    if cur is None or ent.get("ts", 0) > cur["ts"]:
                        ring[iid] = {"desc": ent.get("desc"), "ts": ent.get("ts", 0)}
            peers = theirs.get("peers")
            for addr, seen in (peers.items() if isinstance(peers, dict) else ()):
                if addr != self.addr and isinstance(seen, (int, float)):
                    self._peers[addr] = max(self._peers.get(addr, 0), seen)

    def sync_once(self, peer: str | None = None) -> bool:
        """One push-pull with a random (or given) peer."""
        with self._lock:
            peers = [a for a in self._peers if a != self.addr]
        if peer is None:
            if not peers:
                return False
            peer = random.choice(peers)
        # chaos seam (outbound): drop = this sync never leaves the host
        # (partition toward `peer`); error/latency simulate a flaky link
        from ..chaos import plane as chaos_plane

        try:
            if chaos_plane.tap("gossip.sync", key=peer) is chaos_plane.DROP:
                return False
        except (OSError, ConnectionError):
            return False
        host, _, port = peer.partition(":")
        try:
            with socket.create_connection((host, int(port)), timeout=3.0) as s:
                _send_msg(s, self._snapshot())
                self._merge(_recv_msg(s))
            self.syncs += 1
            return True
        except (OSError, ValueError, ConnectionError):
            return False

    def _gossip_loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.sync_once()
            except Exception:  # noqa: BLE001 - the loop must outlive bugs
                pass

    def close(self) -> None:
        self._stop.set()
        self._server.shutdown()


def _send_msg(sock: socket.socket, obj: dict) -> None:
    data = json.dumps(obj).encode()
    sock.sendall(_LEN.pack(len(data)) + data)


def _recv_msg(sock: socket.socket) -> dict:
    hdr = _recv_exact(sock, _LEN.size)
    (n,) = _LEN.unpack(hdr)
    if n > _MAX_MSG:
        raise ValueError(f"gossip message too large: {n}")
    return json.loads(_recv_exact(sock, n))


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    out = bytearray()
    while len(out) < n:
        chunk = sock.recv(n - len(out))
        if not chunk:
            raise ConnectionError("gossip peer closed")
        out += chunk
    return bytes(out)
