"""Ingester clients over the role boundary.

`client_registry` resolves an instance addr to a client: in-process
objects for the single binary, HTTPIngesterClient for `http://...`
addrs (the reference's gRPC ingester client seam,
modules/distributor/distributor.go:148-153 factory).

Wire format: the DATA plane (segment push, generator forward, find
responses) runs on length-prefixed binary frames (transport/frames.py,
<5% overhead, optional whole-body zstd -- the reference's gRPC+snappy
analog); small control payloads stay JSON. Legacy JSON+base64 remains
accepted server-side, and pushes retry as JSON once when a pre-frames
server rejects the binary body (rolling upgrades).
"""

from __future__ import annotations

import base64
import json
import urllib.error
import urllib.request

from ..db.search import SearchRequest, SearchResponse
from ..wire import otlp_json
from ..wire.model import Trace


class TransportError(Exception):
    def __init__(self, status: int, msg: str):
        super().__init__(msg)
        self.status = status


def _raise_http_error(e: urllib.error.HTTPError):
    """Shared HTTPError -> typed exception mapping (ingester-side limit
    errors keep their real status for the caller's retry policy)."""
    try:
        msg = json.loads(e.read()).get("error", "")
    except Exception:
        msg = str(e)
    from ..services.distributor import PushError

    raise PushError(e.code, msg) if e.code in (400, 429) else TransportError(e.code, msg)


class HTTPIngesterClient:
    def __init__(self, addr: str, timeout: float = 10.0, token: str = ""):
        self.addr = addr.rstrip("/")
        self.timeout = timeout
        self.token = token

    @staticmethod
    def _chaos_tap(path: str) -> None:
        """RPC chaos seam: injected latency/error/black-hole on every
        ingester-client call (drop surfaces as a transport error -- a
        black-holed request IS a timeout to its caller)."""
        from ..chaos import plane as chaos_plane

        if chaos_plane.tap("rpc.client", key=path) is chaos_plane.DROP:
            raise TransportError(0, "chaos: request black-holed")

    def _post(self, path: str, payload: dict) -> dict:
        self._chaos_tap(path)
        headers = {"Content-Type": "application/json"}
        if self.token:
            headers["X-Tempo-Internal-Token"] = self.token
        req = urllib.request.Request(
            self.addr + path,
            data=json.dumps(payload).encode(),
            headers=headers,
        )
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as r:
                body = r.read()
                return json.loads(body) if body else {}
        except urllib.error.HTTPError as e:
            _raise_http_error(e)

    def _post_frames(self, path: str, body: bytes) -> None:
        from . import frames

        self._chaos_tap(path)
        headers = {"Content-Type": frames.CONTENT_TYPE}
        if self.token:
            headers["X-Tempo-Internal-Token"] = self.token
        req = urllib.request.Request(self.addr + path, data=body, headers=headers)
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as r:
                r.read()
        except urllib.error.HTTPError as e:
            _raise_http_error(e)
        except urllib.error.URLError as e:
            raise TransportError(0, str(e))

    # ------------------------------------------------- Pusher (write path)
    def push_segments(self, tenant: str, batch) -> None:
        from . import frames

        try:
            self._post_frames("/internal/push", frames.encode_push(tenant, batch))
        except TransportError:
            # rolling-upgrade interop: a pre-frames server 500s on the
            # binary body; retry once as legacy JSON+base64
            self._post(
                "/internal/push",
                {"tenant": tenant,
                 "batch": [[tid.hex(), s, e, base64.b64encode(seg).decode()]
                           for tid, s, e, seg in batch]},
            )

    def push_generator_blobs(self, tenant: str, blobs: list[bytes]) -> None:
        """Forward traces to a remote metrics-generator as otlp-proto
        bytes sliced from segments (the shuffle-sharded generator write
        path, distributor.go:410-442): zero decode/encode on the send
        side. The legacy-JSON fallback is the only path that must
        decode."""
        from . import frames

        try:
            self._post_frames("/internal/genpush",
                              frames.encode_trace_blobs(tenant, blobs))
        except TransportError:
            from ..wire import otlp_pb

            self._post(
                "/internal/genpush",
                {"tenant": tenant,
                 "traces": [otlp_json.dumps(otlp_pb.decode_trace(b))
                            for b in blobs]},
            )

    # ------------------------------------------------ Querier (read path)
    def find_trace_by_id(self, tenant: str, trace_id: bytes) -> Trace | None:
        """Find over the binary plane: the response body is the raw
        otlp-proto trace (Accept negotiation keeps old servers working)."""
        from ..wire import otlp_pb

        self._chaos_tap("/internal/find")
        headers = {"Content-Type": "application/json",
                   "Accept": "application/x-protobuf"}
        if self.token:
            headers["X-Tempo-Internal-Token"] = self.token
        req = urllib.request.Request(
            self.addr + "/internal/find",
            data=json.dumps({"tenant": tenant, "trace_id": trace_id.hex()}).encode(),
            headers=headers,
        )
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as r:
                body = r.read()
                if r.headers.get("Content-Type", "").startswith("application/x-protobuf"):
                    return otlp_pb.decode_trace(body) if body else None
                out = json.loads(body) if body else {}
        except urllib.error.HTTPError as e:
            _raise_http_error(e)
        except urllib.error.URLError as e:
            raise TransportError(0, str(e))
        if not out.get("trace"):
            return None
        return otlp_json.loads(out["trace"])

    def search(self, tenant: str, req: SearchRequest) -> SearchResponse:
        from ..db.search import request_to_dict, response_from_dict

        out = self._post(
            "/internal/search", {"tenant": tenant, "req": request_to_dict(req)}
        )
        return response_from_dict(out)

    def metrics_query_range(self, tenant: str, req):
        """Live-head TraceQL metrics leg against a remote ingester
        (None when it holds nothing for the tenant)."""
        from ..db.metrics_exec import (
            request_to_dict as metrics_request_to_dict,
            response_from_dict as metrics_response_from_dict,
        )

        out = self._post(
            "/internal/metrics",
            {"tenant": tenant, "req": metrics_request_to_dict(req)},
        )
        return metrics_response_from_dict(out) if out else None

    def trace_snapshot(self, tenant: str, trace_id: bytes) -> list[tuple[str, bytes]]:
        """Replica segment snapshot for a quorum read: [(digest, seg)]."""
        out = self._post(
            "/internal/snapshot",
            {"tenant": tenant, "trace_id": trace_id.hex()},
        )
        return [(d, base64.b64decode(seg))
                for d, seg in out.get("segments", [])]


def client_registry(local: dict, token: str = "", timeout: float = 10.0):
    """addr -> client resolver: in-process objects first, HTTP for the
    rest. `timeout` is the per-RPC deadline every HTTP client gets (the
    fleet's replica-write/read deadline knob)."""
    cache: dict[str, HTTPIngesterClient] = {}

    def resolve(addr: str):
        if addr in local:
            return local[addr]
        if addr.startswith("http://") or addr.startswith("https://"):
            c = cache.get(addr)
            if c is None:
                c = cache[addr] = HTTPIngesterClient(addr, timeout=timeout,
                                                     token=token)
            return c
        raise KeyError(f"unknown instance addr {addr!r}")

    return resolve


# ----------------------------------------------------------- server side


def handle_internal(app, path: str, payload: dict, raw_body: bytes = b"",
                    content_type: str = "", accept: str = ""):
    """Dispatch one internal-API request against this process's modules.
    Returns (status, dict) or (status, (bytes, content_type)) for binary
    responses. Binary-frame bodies (transport/frames.py) arrive with
    payload={} and the raw body; JSON bodies keep the legacy dict path
    so mixed-version fleets interoperate."""
    from . import frames

    binary = content_type.startswith(frames.CONTENT_TYPE)
    if binary and path == "/internal/push":
        if app.ingester is None:
            return 404, {"error": f"target {app.cfg.target} hosts no ingester"}
        tenant, batch = frames.decode_push(raw_body)
        app.ingester.push_segments(tenant, batch)
        return 200, {}
    if binary and path == "/internal/genpush":
        if app.generator is None:
            return 404, {"error": f"target {app.cfg.target} hosts no generator"}
        tenant, traces = frames.decode_traces(raw_body)
        app.generator.push(tenant, traces)
        return 200, {}
    if path == "/internal/chaos":
        # runtime fault-rule control (tempo-tpu-cli chaos inject):
        # {"rules": [...], "seed": n} swaps the plane, {"clear": true}
        # tears it down. Token-gated like every /internal route. Note:
        # the backend seam's wrapper interposes at TempoDB build time,
        # so rules injected into a process that started UNARMED reach
        # the rpc/device/wal/gossip seams only.
        from ..chaos import plane as chaos_plane

        try:
            if payload.get("clear"):
                chaos_plane.clear()
            elif "rules" in payload or "seed" in payload:
                rules, seed = chaos_plane.parse_rules(payload)
                chaos_plane.configure(rules, seed=seed)
        except (ValueError, TypeError) as e:
            return 400, {"error": f"bad chaos rules: {e}"}
        return 200, chaos_plane.status()
    if path == "/internal/jobs/poll":
        # remote querier pull (services/worker.py) against this frontend
        if app.frontend is None:
            return 404, {"error": f"target {app.cfg.target} hosts no frontend"}
        job = app.frontend.poll_job(wait_s=float(payload.get("wait_s", 5.0)),
                                    worker_id=payload.get("worker_id", ""))
        return 200, (job or {})
    if path == "/internal/jobs/result":
        if app.frontend is None:
            return 404, {"error": f"target {app.cfg.target} hosts no frontend"}
        app.frontend.complete_job(
            payload.get("id", ""), bool(payload.get("ok")),
            result=payload.get("result"), error=payload.get("error", ""),
            retryable=bool(payload.get("retryable")),
            self_spans=payload.get("self_spans"),
            skipped=bool(payload.get("skipped")),
        )
        return 200, {}
    if path == "/internal/genpush":
        if app.generator is None:
            return 404, {"error": f"target {app.cfg.target} hosts no generator"}
        traces = [otlp_json.loads(t) for t in payload.get("traces", [])]
        app.generator.push(payload.get("tenant", ""), traces)
        return 200, {}
    if app.ingester is None:
        return 404, {"error": f"target {app.cfg.target} hosts no ingester"}
    tenant = payload.get("tenant", "")
    if path == "/internal/push":
        batch = [
            (bytes.fromhex(tid), s, e, base64.b64decode(seg))
            for tid, s, e, seg in payload.get("batch", [])
        ]
        app.ingester.push_segments(tenant, batch)
        return 200, {}
    if path == "/internal/find":
        tr = app.ingester.find_trace_by_id(tenant, bytes.fromhex(payload["trace_id"]))
        if "application/x-protobuf" in accept:
            from ..wire import otlp_pb

            body = otlp_pb.encode_trace(tr) if tr is not None else b""
            return 200, (body, "application/x-protobuf")
        return 200, {"trace": otlp_json.dumps(tr) if tr is not None else None}
    if path == "/internal/search":
        from ..db.search import request_from_dict, response_to_dict

        resp = app.ingester.search(tenant, request_from_dict(payload.get("req", {})))
        return 200, response_to_dict(resp)
    if path == "/internal/snapshot":
        # quorum-read replica snapshot: raw segments + content digests
        segs = app.ingester.trace_snapshot(tenant, bytes.fromhex(payload["trace_id"]))
        return 200, {"segments": [[d, base64.b64encode(s).decode()]
                                  for d, s in segs]}
    if path == "/internal/metrics":
        # live-head TraceQL metrics leg (querier merges it with blocks)
        from ..db.metrics_exec import (
            request_from_dict as metrics_request_from_dict,
            response_to_dict as metrics_response_to_dict,
        )

        resp = app.ingester.metrics_query_range(
            tenant, metrics_request_from_dict(payload.get("req", {})))
        return 200, (metrics_response_to_dict(resp) if resp is not None else {})
    return 404, {"error": f"no internal route {path}"}
