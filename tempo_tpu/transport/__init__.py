"""Inter-process transport: the data-plane boundary between roles.

The reference speaks gRPC (gogo codec + snappy) between distributor,
ingesters and queriers, with memberlist gossip for ring state
(SURVEY.md 2.10, 5.8). Here the same boundaries are HTTP+JSON/base64
internal endpoints (transport/http_internal.py) and a shared-directory
ring KV (transport/filekv.py) for multi-process topologies on one host
or a shared filesystem; the in-memory KV + in-process client registry
remain the single-binary fast path.
"""

from .client import HTTPIngesterClient, client_registry
from .filekv import FileKV

__all__ = ["HTTPIngesterClient", "client_registry", "FileKV"]
