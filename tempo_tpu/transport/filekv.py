"""File-backed ring KV: one JSON file per instance under
<dir>/<ring_key>/, written atomically. Any process sharing the
directory (host-local or network filesystem) sees the same ring --
the multi-process stand-in for the reference's memberlist gossip KV
(cmd/tempo/app/modules.go:288-316).
"""

from __future__ import annotations

import json
import os
import tempfile
import time

from ..ring.ring import InstanceDesc, InstanceState


class FileKV:
    def __init__(self, dirpath: str, cache_ttl_s: float = 1.0):
        self.dir = dirpath
        os.makedirs(dirpath, exist_ok=True)
        # get_all sits on the per-push / per-query hot path; descriptors
        # only change on heartbeats, so a short TTL absorbs the file IO
        self.cache_ttl_s = cache_ttl_s
        self._cache: dict[str, tuple[float, dict[str, InstanceDesc]]] = {}

    def _ring_dir(self, ring_key: str) -> str:
        d = os.path.join(self.dir, ring_key)
        os.makedirs(d, exist_ok=True)
        return d

    def update(self, ring_key: str, desc: InstanceDesc) -> None:
        d = self._ring_dir(ring_key)
        payload = json.dumps(
            {
                "instance_id": desc.instance_id,
                "addr": desc.addr,
                "state": desc.state.value,
                "tokens": desc.tokens,
                "heartbeat_ts": desc.heartbeat_ts,
            }
        ).encode()
        fd, tmp = tempfile.mkstemp(dir=d, prefix=".tmp-")
        try:
            os.write(fd, payload)
            os.close(fd)
            os.replace(tmp, os.path.join(d, desc.instance_id + ".json"))
            self._cache.pop(ring_key, None)
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise

    def remove(self, ring_key: str, instance_id: str) -> None:
        try:
            os.unlink(os.path.join(self._ring_dir(ring_key), instance_id + ".json"))
        except FileNotFoundError:
            pass
        self._cache.pop(ring_key, None)

    def get_all(self, ring_key: str) -> dict[str, InstanceDesc]:
        hit = self._cache.get(ring_key)
        if hit is not None and time.monotonic() - hit[0] < self.cache_ttl_s:
            return dict(hit[1])
        out: dict[str, InstanceDesc] = {}
        d = self._ring_dir(ring_key)
        for name in os.listdir(d):
            if not name.endswith(".json"):
                continue
            try:
                with open(os.path.join(d, name)) as f:
                    j = json.load(f)
                out[j["instance_id"]] = InstanceDesc(
                    instance_id=j["instance_id"],
                    addr=j.get("addr", ""),
                    state=InstanceState(j.get("state", "ACTIVE")),
                    tokens=j.get("tokens", []),
                    heartbeat_ts=j.get("heartbeat_ts", 0.0),
                )
            except (OSError, ValueError, KeyError):
                continue  # torn write or foreign file: skip
        self._cache[ring_key] = (time.monotonic(), dict(out))
        return out
