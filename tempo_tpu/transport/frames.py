"""Binary internal data plane: length-prefixed proto-wire frames.

The round-3 internal API shipped segment bytes as JSON + base64 -- a
self-acknowledged 33% framing tax. The payloads already ARE compact
proto-wire bytes (wire/segment.py), so the data plane now frames them
raw: a tiny varint-framed envelope (<1% overhead), optionally
zstd-compressed as a whole body. The reference's internal plane is
gRPC + snappy (cmd/tempo/app/config.go:103-106); same shape, no gRPC
dependency on the hot path.

Envelope (all integers uvarint unless noted):

    magic "TBF1" | flags u8 (bit0: zstd body follows)   -- outer header
    body := tenant_len tenant | n_records | records...
    push record  := 16B trace id | start_s | end_s | seg_len | seg bytes
    trace record := blob_len | otlp-proto Trace bytes

JSON + base64 remains accepted server-side for mixed-version fleets;
clients of this version always send frames.
"""

from __future__ import annotations

import io

MAGIC = b"TBF1"
CONTENT_TYPE = "application/x-tempo-frames"
_FLAG_ZSTD = 1
_COMPRESS_MIN = 4 << 10


def _w_uvarint(out: io.BytesIO, v: int) -> None:
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            out.write(bytes([b | 0x80]))
        else:
            out.write(bytes([b]))
            return


def _r_uvarint(b: memoryview, pos: int) -> tuple[int, int]:
    shift = v = 0
    while True:
        byte = b[pos]
        pos += 1
        v |= (byte & 0x7F) << shift
        if not (byte & 0x80):
            return v, pos
        shift += 7
        if shift > 63:
            raise ValueError("uvarint overflow")


def _seal(body: bytes) -> bytes:
    if len(body) >= _COMPRESS_MIN:
        try:
            import zstandard
        except ModuleNotFoundError:  # image without the wheel
            # frames cross NODE boundaries: a zlib-shim body tagged
            # _FLAG_ZSTD would be undecodable by a peer that has the
            # real wheel (mixed-image fleet), so ship uncompressed
            return MAGIC + bytes([0]) + body

        comp = zstandard.ZstdCompressor(level=1).compress(body)
        if len(comp) < len(body):
            return MAGIC + bytes([_FLAG_ZSTD]) + comp
    return MAGIC + bytes([0]) + body


def _open(data: bytes) -> memoryview:
    if data[:4] != MAGIC:
        raise ValueError("not a tempo binary frame body (bad magic)")
    flags = data[4]
    body = data[5:]
    if flags & _FLAG_ZSTD:
        try:
            import zstandard
        except ModuleNotFoundError:  # image without the wheel
            from ..util import zstdshim as zstandard

        body = zstandard.ZstdDecompressor().decompress(body)
    return memoryview(body)


def encode_push(tenant: str, batch) -> bytes:
    """batch: [(trace_id 16B, start_s, end_s, segment bytes)]."""
    out = io.BytesIO()
    t = tenant.encode()
    _w_uvarint(out, len(t))
    out.write(t)
    _w_uvarint(out, len(batch))
    for tid, s, e, seg in batch:
        out.write(tid.rjust(16, b"\x00")[:16])
        _w_uvarint(out, int(s))
        _w_uvarint(out, int(e))
        _w_uvarint(out, len(seg))
        out.write(seg)
    return _seal(out.getvalue())


def decode_push(data: bytes) -> tuple[str, list[tuple[bytes, int, int, bytes]]]:
    b = _open(data)
    n, pos = _r_uvarint(b, 0)
    tenant = bytes(b[pos : pos + n]).decode()
    pos += n
    count, pos = _r_uvarint(b, pos)
    batch = []
    for _ in range(count):
        tid = bytes(b[pos : pos + 16])
        pos += 16
        s, pos = _r_uvarint(b, pos)
        e, pos = _r_uvarint(b, pos)
        ln, pos = _r_uvarint(b, pos)
        batch.append((tid, s, e, bytes(b[pos : pos + ln])))
        pos += ln
    return tenant, batch


def encode_trace_blobs(tenant: str, blobs: list[bytes]) -> bytes:
    """blobs: otlp-proto trace bytes, shipped verbatim -- the
    distributor's generator tap slices these straight out of segments
    (segment_payload), so the remote-generator leg never decodes or
    re-encodes. Wire-identical to encode_traces."""
    out = io.BytesIO()
    t = tenant.encode()
    _w_uvarint(out, len(t))
    out.write(t)
    _w_uvarint(out, len(blobs))
    for blob in blobs:
        _w_uvarint(out, len(blob))
        out.write(blob)
    return _seal(out.getvalue())


def encode_traces(tenant: str, traces) -> bytes:
    """traces: wire-model Trace objects, shipped as otlp-proto blobs
    (the generator forward path)."""
    from ..wire import otlp_pb

    return encode_trace_blobs(tenant, [otlp_pb.encode_trace(tr) for tr in traces])


def decode_traces(data: bytes) -> tuple[str, list]:
    from ..wire import otlp_pb

    b = _open(data)
    n, pos = _r_uvarint(b, 0)
    tenant = bytes(b[pos : pos + n]).decode()
    pos += n
    count, pos = _r_uvarint(b, pos)
    traces = []
    for _ in range(count):
        ln, pos = _r_uvarint(b, pos)
        traces.append(otlp_pb.decode_trace(bytes(b[pos : pos + ln])))
        pos += ln
    return tenant, traces
