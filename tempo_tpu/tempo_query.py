"""tempo-query: Jaeger gRPC storage-plugin shim.

The reference's cmd/tempo-query is a separate process implementing the
Jaeger storage API (jaeger.storage.v1.SpanReaderPlugin) against Tempo's
HTTP API, so a stock Jaeger query/UI uses Tempo as its span store. Same
shape here: a grpc generic handler (no generated stubs, like
services/otlp_grpc.py) serving GetTrace / FindTraces / GetServices /
GetOperations / FindTraceIDs, translating to /api/traces + /api/search
+ /api/search/tag/... on a tempo-tpu instance and encoding jaeger
api_v2 spans with wire/jaeger_pb.

Run: python -m tempo_tpu.tempo_query --backend http://host:3200 --grpc-port 7777
"""

from __future__ import annotations

import json
import urllib.parse
import urllib.request

from .wire import jaeger_pb, otlp_json

_SERVICE = "jaeger.storage.v1.SpanReaderPlugin"


class TempoHTTP:
    """Minimal client for the public query API."""

    def __init__(self, base: str, tenant: str = "", timeout: float = 10.0):
        self.base = base.rstrip("/")
        self.tenant = tenant
        self.timeout = timeout

    def _get(self, path: str) -> bytes:
        req = urllib.request.Request(self.base + path)
        if self.tenant:
            req.add_header("X-Scope-OrgID", self.tenant)
        with urllib.request.urlopen(req, timeout=self.timeout) as r:
            return r.read()

    def trace(self, trace_id_hex: str):
        try:
            return otlp_json.loads(self._get(f"/api/traces/{trace_id_hex}"))
        except urllib.error.HTTPError as e:
            if e.code == 404:
                return None
            raise

    def search(self, q: dict) -> list[str]:
        out = json.loads(self._get("/api/search?" + urllib.parse.urlencode(q)))
        return [t["traceID"] for t in out.get("traces", [])]

    def tag_values(self, tag: str) -> list[str]:
        out = json.loads(self._get(f"/api/search/tag/{urllib.parse.quote(tag)}/values"))
        return out.get("tagValues", [])


class JaegerStoragePlugin:
    def __init__(self, tempo: TempoHTTP):
        self.tempo = tempo
        self.requests = 0

    # each handler: bytes in -> iterator/bytes out (server streaming for
    # the span-chunk responses, unary for the rest)
    def get_trace(self, request: bytes, context):
        self.requests += 1
        tid = jaeger_pb.decode_get_trace_request(request)
        tr = self.tempo.trace(tid.hex())
        if tr is None:
            import grpc

            context.abort(grpc.StatusCode.NOT_FOUND, "trace not found")
        yield jaeger_pb.encode_spans_chunk(tr)

    def find_traces(self, request: bytes, context):
        self.requests += 1
        q = jaeger_pb.decode_find_traces_request(request)
        params: dict = {"limit": q["num_traces"] or 20}
        tags = dict(q["tags"])
        if q["service_name"]:
            tags["service.name"] = q["service_name"]
        if q["operation_name"]:
            tags["name"] = q["operation_name"]
        if tags:
            params["tags"] = " ".join(f"{k}={v}" for k, v in tags.items())
        if q["start_min"]:
            params["start"] = q["start_min"]
        if q["start_max"]:
            params["end"] = q["start_max"]
        if q["dur_min_ms"]:
            params["minDuration"] = q["dur_min_ms"] / 1000.0
        if q["dur_max_ms"]:
            params["maxDuration"] = q["dur_max_ms"] / 1000.0
        for tid_hex in self.tempo.search(params):
            tr = self.tempo.trace(tid_hex)
            if tr is not None:
                yield jaeger_pb.encode_spans_chunk(tr)

    def find_trace_ids(self, request: bytes, context) -> bytes:
        self.requests += 1
        q = jaeger_pb.decode_find_traces_request(request)
        params: dict = {"limit": q["num_traces"] or 20}
        if q["service_name"]:
            params["tags"] = f"service.name={q['service_name']}"
        ids = self.tempo.search(params)
        return jaeger_pb.encode_trace_ids_response([bytes.fromhex(t) for t in ids])

    def get_services(self, request: bytes, context) -> bytes:
        self.requests += 1
        return jaeger_pb.encode_services_response(
            self.tempo.tag_values("service.name"))

    def get_operations(self, request: bytes, context) -> bytes:
        self.requests += 1
        return jaeger_pb.encode_operations_response(self.tempo.tag_values("name"))

    def capabilities(self, request: bytes, context) -> bytes:
        return b""  # no archive/streaming writer capabilities


def serve(tempo: TempoHTTP, port: int = 0, host: str = "127.0.0.1",
          max_workers: int = 8):
    """-> (grpc server, bound port, plugin)."""
    from concurrent import futures

    import grpc

    plugin = JaegerStoragePlugin(tempo)
    handler = grpc.method_handlers_generic_handler(_SERVICE, {
        "GetTrace": grpc.unary_stream_rpc_method_handler(plugin.get_trace),
        "FindTraces": grpc.unary_stream_rpc_method_handler(plugin.find_traces),
        "FindTraceIDs": grpc.unary_unary_rpc_method_handler(plugin.find_trace_ids),
        "GetServices": grpc.unary_unary_rpc_method_handler(plugin.get_services),
        "GetOperations": grpc.unary_unary_rpc_method_handler(plugin.get_operations),
    })
    cap_handler = grpc.method_handlers_generic_handler(
        "jaeger.storage.v1.PluginCapabilities",
        {"Capabilities": grpc.unary_unary_rpc_method_handler(plugin.capabilities)},
    )
    server = grpc.server(futures.ThreadPoolExecutor(
        max_workers=max_workers, thread_name_prefix="tempo-query"))
    server.add_generic_rpc_handlers((handler, cap_handler))
    bound = server.add_insecure_port(f"{host}:{port}")
    server.start()
    return server, bound, plugin


def main(argv=None):
    import argparse

    ap = argparse.ArgumentParser("tempo-query")
    ap.add_argument("--backend", required=True, help="tempo-tpu base URL")
    ap.add_argument("--grpc-port", type=int, default=7777)
    ap.add_argument("--host", default="0.0.0.0")
    ap.add_argument("--tenant", default="")
    args = ap.parse_args(argv)
    server, port, _ = serve(TempoHTTP(args.backend, tenant=args.tenant),
                            args.grpc_port, args.host)
    print(f"tempo-query (jaeger storage grpc) listening on {args.host}:{port}",
          flush=True)
    server.wait_for_termination()


if __name__ == "__main__":
    main()
