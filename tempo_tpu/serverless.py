"""Stateless search-one-block-shard handler (tempo-serverless analog).

The reference ships a Lambda/Cloud Run handler that searches one shard
of one backend block per invocation (cmd/tempo-serverless/handler.go:49,
once-initialised reader). Same contract here: a JSON event naming the
backend, tenant, block and row-group range; the process holds a cached
backend + block-reader so warm invocations skip setup.
"""

from __future__ import annotations

import threading
from collections import OrderedDict

from .backend import open_backend
from .block.meta import BlockMeta
from .block.reader import BackendBlock
from .db.search import request_from_dict, response_to_dict, search_block

_lock = threading.Lock()
_backends: dict = {}
_blocks: OrderedDict = OrderedDict()
_MAX_CACHED_BLOCKS = 64  # LRU cap: warm workers touch many blocks over time


def _backend_key(cfg: dict) -> tuple:
    return tuple(sorted((k, str(v)) for k, v in cfg.items()))


def _backend(cfg: dict):
    key = _backend_key(cfg)
    with _lock:
        b = _backends.get(key)
        if b is None:
            b = _backends[key] = open_backend(cfg)
        return b


def handler(event: dict) -> dict:
    """event: {backend: {...}, tenant, block_id, groups: [lo, hi) | null,
    search: <db.search.request_to_dict form>}
    -> db.search.response_to_dict form."""
    backend = _backend(event["backend"])
    tenant = event["tenant"]
    block_id = event["block_id"]
    # keyed by backend too: a warm worker may serve events naming
    # different buckets for the same (tenant, block id)
    cache_key = (_backend_key(event["backend"]), tenant, block_id)
    with _lock:
        blk = _blocks.get(cache_key)
        if blk is not None:
            _blocks.move_to_end(cache_key)
    if blk is None:
        from .backend.base import meta_name

        meta = BlockMeta.from_json(backend.read(tenant, block_id, meta_name()))
        from .block.versioned import open_block_versioned

        blk = open_block_versioned(backend, meta)
        with _lock:
            _blocks[cache_key] = blk
            while len(_blocks) > _MAX_CACHED_BLOCKS:
                _blocks.popitem(last=False)

    # the search payload and the response both reuse the internal job
    # plane's wire helpers (db/search request/response dicts) so the
    # serverless hop can never drift from the frontend's format
    req = request_from_dict(event.get("search", {}))
    groups = event.get("groups")
    groups_range = list(range(groups[0], groups[1])) if groups else None
    resp = search_block(blk, req, groups_range=groups_range)
    return response_to_dict(resp)


def serve(port: int, host: str = "127.0.0.1"):
    """HTTP front for the handler: POST / with the event JSON (the
    Cloud-Run flavor of the reference's serverless deploys; Lambda would
    wrap `handler` directly). Returns the bound server (threaded)."""
    import json
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    class _H(BaseHTTPRequestHandler):
        def do_POST(self):
            try:
                body = self.rfile.read(int(self.headers.get("Content-Length", 0)))
                out = handler(json.loads(body))
                data = json.dumps(out).encode()
                self.send_response(200)
            except Exception as e:  # one bad event must not kill the worker
                data = json.dumps({"error": f"{type(e).__name__}: {e}"}).encode()
                self.send_response(500)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)

        def log_message(self, *a):
            pass

    return ThreadingHTTPServer((host, port), _H)


def main(argv=None):
    import argparse

    ap = argparse.ArgumentParser("tempo-serverless")
    ap.add_argument("--port", type=int, default=8077)
    ap.add_argument("--host", default="0.0.0.0")
    args = ap.parse_args(argv)
    srv = serve(args.port, args.host)
    print(f"tempo-serverless listening on {args.host}:{args.port}", flush=True)
    srv.serve_forever()


if __name__ == "__main__":
    main()
