"""Stateless search-one-block-shard handler (tempo-serverless analog).

The reference ships a Lambda/Cloud Run handler that searches one shard
of one backend block per invocation (cmd/tempo-serverless/handler.go:49,
once-initialised reader). Same contract here: a JSON event naming the
backend, tenant, block and row-group range; the process holds a cached
backend + block-reader so warm invocations skip setup.
"""

from __future__ import annotations

import threading
from collections import OrderedDict

from .backend import open_backend
from .block.meta import BlockMeta
from .block.reader import BackendBlock
from .db.search import SearchRequest, search_block

_lock = threading.Lock()
_backends: dict = {}
_blocks: OrderedDict = OrderedDict()
_MAX_CACHED_BLOCKS = 64  # LRU cap: warm workers touch many blocks over time


def _backend_key(cfg: dict) -> tuple:
    return tuple(sorted((k, str(v)) for k, v in cfg.items()))


def _backend(cfg: dict):
    key = _backend_key(cfg)
    with _lock:
        b = _backends.get(key)
        if b is None:
            b = _backends[key] = open_backend(cfg)
        return b


def handler(event: dict) -> dict:
    """event: {backend: {...}, tenant, block_id, groups: [lo, hi) | null,
    search: {tags, query, minDurationMs, maxDurationMs, start, end, limit}}
    -> {traces: [...], metrics: {...}}"""
    backend = _backend(event["backend"])
    tenant = event["tenant"]
    block_id = event["block_id"]
    # keyed by backend too: a warm worker may serve events naming
    # different buckets for the same (tenant, block id)
    cache_key = (_backend_key(event["backend"]), tenant, block_id)
    with _lock:
        blk = _blocks.get(cache_key)
        if blk is not None:
            _blocks.move_to_end(cache_key)
    if blk is None:
        from .backend.base import meta_name

        meta = BlockMeta.from_json(backend.read(tenant, block_id, meta_name()))
        from .block.versioned import open_block_versioned

        blk = open_block_versioned(backend, meta)
        with _lock:
            _blocks[cache_key] = blk
            while len(_blocks) > _MAX_CACHED_BLOCKS:
                _blocks.popitem(last=False)

    s = event.get("search", {})
    req = SearchRequest(
        tags=s.get("tags", {}),
        query=s.get("query", ""),
        min_duration_ms=s.get("minDurationMs", 0),
        max_duration_ms=s.get("maxDurationMs", 0),
        start=s.get("start", 0),
        end=s.get("end", 0),
        limit=s.get("limit", 20),
    )
    groups = event.get("groups")
    groups_range = list(range(groups[0], groups[1])) if groups else None
    resp = search_block(blk, req, groups_range=groups_range)
    return {
        "traces": [t.to_dict() for t in resp.traces],
        "metrics": {
            "inspectedBytes": resp.inspected_bytes,
            "inspectedSpans": resp.inspected_spans,
        },
    }
