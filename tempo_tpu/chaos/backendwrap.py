"""Chaos taps over the RawBackend seam.

`ChaosBackend` interposes between TempoDB and the real backend so
every object-store operation -- the seam where the real world fails
most -- can take injected latency, 5xx, truncated ranged reads or
corrupt bytes. It forwards to `inner` verbatim (preserving backend-
specific fast paths: LocalBackend's streamed appender, S3's server-side
CopyObject) and keeps an `.inner` attribute so the /metrics wrapper
walk (cache hits, hedged requests) still reaches the stack below.

`maybe_wrap` only interposes when the process is ARMED (TEMPO_CHAOS
set, --chaos.rules, or a plane configured programmatically before the
TempoDB was built): an unarmed process pays zero indirection, which is
what the faults-off differential certifies. Rules installed at runtime
(POST /internal/chaos) reach backend taps only in an armed process;
every other seam's inline tap engages regardless.
"""

from __future__ import annotations

from ..backend.base import Appender, RawBackend
from . import plane


class _NullAppender(Appender):
    """A dropped open_append: accepts every append, writes nothing."""

    def close(self) -> None:
        self._parts = []


class ChaosBackend(RawBackend):
    def __init__(self, inner: RawBackend):
        self.inner = inner
        self.is_remote = inner.is_remote

    # ---- read
    def read(self, tenant, block_id, name):
        return plane.call("backend.read",
                          lambda: self.inner.read(tenant, block_id, name),
                          tenant=tenant, key=f"{block_id}/{name}")

    def read_range(self, tenant, block_id, name, offset, length):
        return plane.call(
            "backend.read_range",
            lambda: self.inner.read_range(tenant, block_id, name,
                                          offset, length),
            tenant=tenant, key=f"{block_id}/{name}")

    def read_tenant_object(self, tenant, name):
        return plane.call("backend.read_tenant",
                          lambda: self.inner.read_tenant_object(tenant, name),
                          tenant=tenant, key=name)

    # ---- write (drop = the operation is silently LOST -- the torn-
    # commit / eventual-consistency fault class)
    def write(self, tenant, block_id, name, data):
        if plane.tap("backend.write", tenant=tenant,
                     key=f"{block_id}/{name}") is plane.DROP:
            return
        self.inner.write(tenant, block_id, name, data)

    def write_tenant_object(self, tenant, name, data):
        if plane.tap("backend.write_tenant", tenant=tenant,
                     key=name) is plane.DROP:
            return
        self.inner.write_tenant_object(tenant, name, data)

    def open_append(self, tenant, block_id, name) -> Appender:
        # tap at open; the appender itself stays the inner backend's
        # (LocalBackend streams true appends -- wrapping per-append
        # would change its IO shape, not just inject into it). A drop
        # discards the WHOLE object: everything appended goes nowhere.
        if plane.tap("backend.write", tenant=tenant,
                     key=f"{block_id}/{name}") is plane.DROP:
            return _NullAppender(self, tenant, block_id, name)
        return self.inner.open_append(tenant, block_id, name)

    def copy_object(self, tenant, src_block_id, name, dst_block_id):
        if plane.tap("backend.copy", tenant=tenant,
                     key=f"{src_block_id}/{name}") is plane.DROP:
            return 0  # the part silently never lands
        return self.inner.copy_object(tenant, src_block_id, name,
                                      dst_block_id)

    # ---- list
    def tenants(self):
        plane.tap("backend.list", key="")
        return self.inner.tenants()

    def blocks(self, tenant):
        plane.tap("backend.list", tenant=tenant, key=tenant)
        return self.inner.blocks(tenant)

    # ---- delete (drop = the delete silently no-ops: retention and
    # compacted-marker garbage survives)
    def delete_block(self, tenant, block_id):
        if plane.tap("backend.delete", tenant=tenant,
                     key=block_id) is plane.DROP:
            return
        self.inner.delete_block(tenant, block_id)

    def delete_tenant_object(self, tenant, name):
        if plane.tap("backend.delete", tenant=tenant,
                     key=name) is plane.DROP:
            return
        self.inner.delete_tenant_object(tenant, name)

    def _delete_object(self, tenant, block_id, name):
        if plane.tap("backend.delete", tenant=tenant,
                     key=f"{block_id}/{name}") is plane.DROP:
            return
        self.inner._delete_object(tenant, block_id, name)

    # ---- compacted-marker protocol: the inner backend may override it
    # (marker semantics are backend-specific); its object ops come back
    # through the wrapper only for the base implementation, so tap the
    # marker write explicitly to keep the seam covered either way
    def mark_compacted(self, tenant, block_id):
        if plane.tap("backend.write", tenant=tenant,
                     key=f"{block_id}/meta.compacted.json") is plane.DROP:
            return  # the marker rename is silently lost
        self.inner.mark_compacted(tenant, block_id)


def maybe_wrap(backend: RawBackend) -> RawBackend:
    """Interpose the chaos wrapper iff the process is armed."""
    if isinstance(backend, ChaosBackend):
        return backend
    if plane.is_active():
        return ChaosBackend(backend)
    return backend
