"""Deterministic fault-injection plane (the Chaos-Monkey/Jepsen seam).

Every robustness claim in this tree -- retry, hedging, steal,
publish-last commit, burn-rate paging -- used to be exercised only by
hand-rolled monkeypatches scattered through tests. This module makes
faults a first-class, seeded, reproducible subsystem: one process-wide
`FaultPlane` holds declarative rules and every IO/device seam carries a
tap that consults it.

A rule is match + action + trigger:

  match    site glob (`backend.read`, `backend.*`, `rpc.*`, ...) plus
           optional tenant / key globs (key is the seam's natural
           operand: object key, RPC path, op name, peer addr).
  action   error (typed: backend_5xx, oserror, timeout, connection,
           transport, device_oom, compile_failure, does_not_exist),
           latency (added sleep), truncate (partial read), corrupt
           (deterministic byte flip), drop (black-hole; the seam
           decides what a drop means), wedge (block until released or
           the rule's window expires).
  trigger  probability `p`, every-`nth` matching call, an active
           window (`begin_s`/`for_s` relative to plane activation) and
           a `max_fires` cap.

Determinism: probability draws are NOT consumed from a shared PRNG
stream (thread interleaving would break replay) -- the decision for the
N-th matching call of rule R is a pure hash of (plane seed, rule index,
N). Two runs that issue the same per-rule call sequences inject exactly
the same faults; the bounded injection log is the replay artifact tests
compare byte for byte.

Activation: `TEMPO_CHAOS=<json | path | @path>` (checked lazily, once),
the app's `--chaos.rules`, or `configure()`/`POST /internal/chaos` at
runtime. With no plane configured every tap is a single `is None` check
-- zero overhead, zero behavior change (the faults-off differential in
tests/test_chaos.py holds the tree to that).

Surface: `tempo_chaos_injected_total{site,action}` rides the kerneltel
/metrics exposition; `/status/chaos` serves the active-rule list with
per-rule call/fire counts and the recent injection log.
"""

from __future__ import annotations

import fnmatch
import hashlib
import json
import threading
import time
from collections import deque
from dataclasses import asdict, dataclass, field

from ..util.metrics import Counter

ENV = "TEMPO_CHAOS"

LOG_MAX = 512  # injection-log entries kept for replay comparison

# every tapped seam, with the operand its `key` matches against
SITES = {
    "backend.read": "whole-object read (key: '<block>/<name>')",
    "backend.read_range": "ranged read; truncate/corrupt apply to the bytes",
    "backend.read_tenant": "tenant-object read (key: object name)",
    "backend.write": "object write / append open (key: '<block>/<name>'); "
                     "drop = the write is silently lost",
    "backend.write_tenant": "tenant-object write (key: object name); "
                            "drop = lost write",
    "backend.list": "tenants()/blocks() listings (key: tenant or '')",
    "backend.delete": "block / tenant-object / object deletes; "
                      "drop = the delete silently no-ops",
    "backend.copy": "backend-side part copies (key: '<src>/<name>'); "
                    "drop = the part is never copied",
    "rpc.client": "ingester-client HTTP calls (key: URL path)",
    "rpc.worker": "querier-worker poll/result posts (key: URL path)",
    "rpc.external": "querier calls to external serverless search "
                    "endpoints (key: endpoint URL); drop = endpoint "
                    "black-holed",
    "rpc.remotewrite": "metrics-generator remote-write pushes "
                       "(key: endpoint URL); drop = push silently lost",
    "device.launch": "device kernel launches (key: op name); "
                     "device_oom / compile_failure / slow launch",
    "wal.append": "WAL record append; truncate = torn tail, drop = lost",
    "wal.fsync": "WAL flush/fsync (error = failed stable write)",
    "gossip.sync": "outbound gossip push-pull (key: peer addr); "
                   "drop = partition this direction",
    "gossip.recv": "inbound gossip merge (drop = ignore peer state)",
}

ACTIONS = ("error", "latency", "truncate", "corrupt", "drop", "wedge")

# which sites can honor which data-shaped actions: truncate/corrupt
# need bytes flowing through the tap; drop needs a seam with "silently
# lost" semantics (a lost write/delete/copy/message). Rules whose site
# glob can reach NONE of the capable sites are rejected at parse time
# -- a drill that "injects" no-ops would certify robustness that was
# never exercised.
DATA_SITES = frozenset(
    {"backend.read", "backend.read_range", "backend.read_tenant",
     "wal.append"})
DROP_SITES = frozenset(
    {"backend.write", "backend.write_tenant", "backend.delete",
     "backend.copy", "wal.append", "gossip.sync", "gossip.recv",
     "rpc.client", "rpc.worker", "rpc.external", "rpc.remotewrite"})

# what a bare action="error" means per seam family: the error class the
# real world throws there (and the retry/breaker layers classify)
DEFAULT_ERROR = {
    "backend": "backend_5xx",
    "rpc.client": "transport",
    "rpc.worker": "oserror",
    "rpc.external": "transport",
    "rpc.remotewrite": "transport",
    "device": "device_oom",
    "wal": "oserror",
    "gossip": "connection",
}

# which module implements (taps) each seam, keyed by path relative to
# the package root. This is the contract the static checker's
# chaos-seam-gap rule enforces both ways: every SITES key must be
# claimed here, every claim must be real (the module names the site),
# and a module doing remote I/O in services/transport/fleet scope must
# appear here at all -- an empty tuple declares "this module is a fault
# *source*, not a seam" (the certification harness drives drills; its
# own urlopens are the measurement, not the system under test).
SEAM_MODULES = {
    "chaos/backendwrap.py": (
        "backend.read", "backend.read_range", "backend.read_tenant",
        "backend.write", "backend.write_tenant", "backend.list",
        "backend.delete", "backend.copy"),
    "transport/client.py": ("rpc.client",),
    "transport/gossip.py": ("gossip.sync", "gossip.recv"),
    "services/worker.py": ("rpc.worker",),
    "services/querier.py": ("rpc.external",),
    "services/remotewrite.py": ("rpc.remotewrite",),
    "ops/device.py": ("device.launch",),
    "db/wal.py": ("wal.append", "wal.fsync"),
    "fleet/harness.py": (),  # certification driver: fault source
}


class ChaosError(OSError):
    """Default injected fault: an OSError, i.e. retryable transport/IO."""


class ChaosDeviceOOM(RuntimeError):
    """XLA-shaped device OOM (deterministic: the query fails, the
    shard degrades; retrying the same launch would OOM again)."""


class ChaosCompileError(RuntimeError):
    """Simulated XLA compile failure."""


class _Drop:
    def __repr__(self):  # pragma: no cover - debugging aid
        return "<chaos DROP>"


DROP = _Drop()  # sentinel a tap returns when the seam should black-hole

INJECTED = Counter(
    "tempo_chaos_injected_total",
    help="chaos faults injected by site and action")


def _error_factory(name: str):
    if name == "backend_5xx":
        from ..backend.base import BackendError

        return BackendError("chaos: injected backend 5xx")
    if name == "does_not_exist":
        from ..backend.base import DoesNotExist

        return DoesNotExist("chaos: injected missing object")
    if name == "transport":
        from ..transport.client import TransportError

        return TransportError(503, "chaos: injected transport error")
    if name == "timeout":
        return TimeoutError("chaos: injected timeout")
    if name == "connection":
        return ConnectionError("chaos: injected connection reset")
    if name == "device_oom":
        return ChaosDeviceOOM("RESOURCE_EXHAUSTED: chaos: injected device OOM")
    if name == "compile_failure":
        return ChaosCompileError("chaos: injected XLA compile failure")
    return ChaosError(f"chaos: injected fault ({name or 'oserror'})")


def _default_error(site: str) -> str:
    for prefix, name in DEFAULT_ERROR.items():
        if site == prefix or site.startswith(prefix + "."):
            return name
    return "oserror"


@dataclass
class FaultRule:
    """One declarative rule; see module docstring for field meaning."""

    site: str
    action: str = "error"
    error: str = ""       # error class; "" = the site's natural default
    tenant: str = ""      # glob, "" = any
    key: str = ""         # glob, "" = any
    p: float = 1.0        # probability per matching call (unless nth set)
    nth: int = 0          # fire on every nth matching call (1-based)
    begin_s: float = 0.0  # window start, seconds since plane activation
    for_s: float = 0.0    # window length (0 = forever)
    max_fires: int = 0    # total fire cap (0 = unlimited)
    latency_s: float = 0.05
    frac: float = 0.5     # fraction of bytes kept by truncate
    id: str = ""          # label for logs/status ("" = rule-<index>)
    # runtime counters (status surface; calls counts MATCHING calls,
    # fires counts injections)
    calls: int = field(default=0, compare=False)
    fires: int = field(default=0, compare=False)

    def __post_init__(self):
        if self.action not in ACTIONS:
            raise ValueError(
                f"unknown chaos action {self.action!r}; one of {ACTIONS}")
        if not any(fnmatch.fnmatch(s, self.site) for s in SITES):
            raise ValueError(
                f"rule site {self.site!r} matches no known site "
                f"(see {sorted(SITES)})")
        if self.action in ("truncate", "corrupt") and not any(
                fnmatch.fnmatch(s, self.site) for s in DATA_SITES):
            raise ValueError(
                f"action {self.action!r} needs a data-bearing site "
                f"(one of {sorted(DATA_SITES)}); {self.site!r} matches none")
        if self.action == "drop" and not any(
                fnmatch.fnmatch(s, self.site) for s in DROP_SITES):
            raise ValueError(
                f"action 'drop' needs a droppable site (one of "
                f"{sorted(DROP_SITES)}); {self.site!r} matches none")
        if not 0.0 <= self.p <= 1.0:
            raise ValueError(f"rule p={self.p} outside [0, 1]")
        if self.nth < 0 or self.max_fires < 0:
            raise ValueError("nth / max_fires must be >= 0")


def _draw(seed: int, rule_idx: int, n: int) -> float:
    """Pure-hash uniform in [0, 1) for the n-th matching call of one
    rule: replayable regardless of thread interleaving."""
    h = hashlib.sha256(f"{seed}:{rule_idx}:{n}".encode()).digest()
    return int.from_bytes(h[:8], "big") / float(1 << 64)


class FaultPlane:
    """The process-wide rule registry + decision engine. Thread-safe;
    decisions happen under one lock, sleeps/wedges happen outside it."""

    def __init__(self, rules: list[FaultRule], seed: int = 0):
        self.rules = list(rules)
        for i, r in enumerate(self.rules):
            if not r.id:
                r.id = f"rule-{i}"
        self.seed = int(seed)
        self.t0 = time.monotonic()
        self.activated_unix = time.time()
        self._lock = threading.Lock()
        self._seq = 0
        self.log: deque = deque(maxlen=LOG_MAX)
        self._released = threading.Event()  # releases every wedge

    # ------------------------------------------------------------ decide
    def _decide(self, site: str, tenant: str, key: str) -> FaultRule | None:
        with self._lock:
            now = time.monotonic() - self.t0
            for i, r in enumerate(self.rules):
                if not fnmatch.fnmatchcase(site, r.site):
                    continue
                if r.tenant and not fnmatch.fnmatchcase(tenant, r.tenant):
                    continue
                if r.key and not fnmatch.fnmatchcase(key, r.key):
                    continue
                # data-shaped actions only match sites that can honor
                # them (a glob rule may span both kinds): a fired rule
                # must always have a real effect, or drills lie
                if r.action in ("truncate", "corrupt") and site not in DATA_SITES:
                    continue
                if r.action == "drop" and site not in DROP_SITES:
                    continue
                # the call counter ticks on every MATCHING call, before
                # window/cap checks: the draw sequence (and so replay)
                # depends only on the per-rule call sequence
                r.calls += 1
                n = r.calls
                if now < r.begin_s:
                    continue
                if r.for_s and now > r.begin_s + r.for_s:
                    continue
                if r.max_fires and r.fires >= r.max_fires:
                    continue
                if r.nth:
                    if n % r.nth:
                        continue
                elif r.p < 1.0 and _draw(self.seed, i, n) >= r.p:
                    continue
                r.fires += 1
                self._seq += 1
                self.log.append((self._seq, site, r.action, r.id, key))
                return r
        return None

    def _expired(self, r: FaultRule) -> bool:
        return bool(r.for_s) and (
            time.monotonic() - self.t0 > r.begin_s + r.for_s)

    # ------------------------------------------------------------- apply
    def _apply(self, r: FaultRule, site: str):
        """Execute a fired rule's action (outside the decision lock).
        Returns DROP for drop, None otherwise; raises for errors."""
        INJECTED.inc(labels=f'site="{site}",action="{r.action}"')
        if r.action == "latency":
            time.sleep(r.latency_s)
            return None
        if r.action == "drop":
            return DROP
        if r.action == "wedge":
            # hold the caller until release()/clear() or window expiry;
            # polled so an expired rule frees its captives on its own
            while not self._released.wait(0.05):
                if self._expired(r):
                    break
            return None
        if r.action == "error":
            raise _error_factory(r.error or _default_error(site))
        return r  # truncate/corrupt: caller applies _mangle to its data

    def _mangle(self, r: FaultRule, data: bytes) -> bytes:
        if not isinstance(data, (bytes, bytearray)) or not data:
            return data
        if r.action == "truncate":
            return bytes(data[: max(0, int(len(data) * r.frac))])
        # corrupt: deterministic single-byte flip keyed by the rule's
        # fire count (already advanced), so replays corrupt identically
        pos = (r.fires * 2654435761) % len(data)
        out = bytearray(data)
        out[pos] ^= 0xFF
        return bytes(out)

    # ----------------------------------------------------------- tapping
    def tap(self, site: str, tenant: str = "", key: str = ""):
        """Data-less tap: may sleep, raise, or return DROP."""
        r = self._decide(site, tenant, key)
        if r is None:
            return None
        out = self._apply(r, site)
        return DROP if out is DROP else None

    def call(self, site: str, fn, tenant: str = "", key: str = ""):
        """Wrap one data-producing operation: error/latency/wedge fire
        before `fn`, truncate/corrupt mangle its result, drop raises
        (an object read cannot be silently dropped)."""
        r = self._decide(site, tenant, key)
        if r is None:
            return fn()
        out = self._apply(r, site)
        if out is DROP:
            raise _error_factory(_default_error(site))
        if out is None:
            return fn()
        return self._mangle(r, fn())

    def mangle(self, site: str, data: bytes, tenant: str = "", key: str = ""):
        """Tap for seams that HOLD the bytes (WAL append): truncate /
        corrupt transform them, drop empties them, errors raise."""
        r = self._decide(site, tenant, key)
        if r is None:
            return data
        out = self._apply(r, site)
        if out is DROP:
            return b""
        if out is None:
            return data
        return self._mangle(r, data)

    # ---------------------------------------------------------- control
    def release(self) -> None:
        """Free every wedged caller (and any future wedge fires)."""
        self._released.set()

    def injection_log(self) -> list[tuple]:
        with self._lock:
            return list(self.log)

    def status(self) -> dict:
        from dataclasses import fields as dc_fields

        # show fields that DIFFER from the dataclass defaults (plus the
        # always-interesting core): "!= default", not "falsy" -- an
        # explicit latency_s=0.0 / frac=0.0 drill must not render
        # indistinguishably from the defaults
        defaults = {f.name: f.default for f in dc_fields(FaultRule)}
        core = ("site", "action", "p", "calls", "fires")
        with self._lock:
            rules = []
            for r in self.rules:
                d = {k: v for k, v in asdict(r).items()
                     if k in core or v != defaults.get(k)}
                rules.append(d)
            log = list(self.log)[-32:]
        return {
            "enabled": True,
            "seed": self.seed,
            "activated_unix": round(self.activated_unix, 3),
            "rules": rules,
            "injected_total": sum(r["fires"] for r in rules),
            "recent_injections": [
                {"seq": s, "site": site, "action": a, "rule": rid,
                 "key": k}
                for s, site, a, rid, k in log],
        }


# ------------------------------------------------------------ singleton
_plane: FaultPlane | None = None
_env_checked = False
_plane_lock = threading.Lock()


def _check_env_locked() -> None:
    global _plane, _env_checked
    _env_checked = True
    import os

    spec = os.environ.get(ENV, "")
    if spec:
        _plane = _plane_from_spec(spec)


def active() -> FaultPlane | None:
    """The live plane, arming lazily from TEMPO_CHAOS on first ask.
    The post-arming fast path is a plain attribute read."""
    if _env_checked:
        return _plane
    with _plane_lock:
        if not _env_checked:
            _check_env_locked()
        return _plane


def is_active() -> bool:
    return active() is not None


# --------------------------------------------------- module-level taps
def tap(site: str, tenant: str = "", key: str = ""):
    p = active()
    if p is None:
        return None
    return p.tap(site, tenant, key)


def call(site: str, fn, tenant: str = "", key: str = ""):
    p = active()
    if p is None:
        return fn()
    return p.call(site, fn, tenant, key)


def mangle(site: str, data: bytes, tenant: str = "", key: str = ""):
    p = active()
    if p is None:
        return data
    return p.mangle(site, data, tenant, key)


# ------------------------------------------------------- configuration
def parse_rules(doc) -> tuple[list[FaultRule], int]:
    """Normalize a rules document: a list of rule dicts, or
    {"seed": int, "rules": [...]}. Raises ValueError on anything the
    plane would not run."""
    seed = 0
    rules_doc = doc
    if isinstance(doc, dict):
        seed = int(doc.get("seed", 0))
        rules_doc = doc.get("rules", [])
    if not isinstance(rules_doc, list):
        raise ValueError('chaos rules must be a list (or {"seed", "rules"})')
    valid = {f for f in FaultRule.__dataclass_fields__
             if f not in ("calls", "fires")}
    rules = []
    for i, rd in enumerate(rules_doc):
        if not isinstance(rd, dict) or "site" not in rd:
            raise ValueError(f"chaos rule #{i} must be a dict with a 'site'")
        unknown = set(rd) - valid
        if unknown:
            raise ValueError(f"chaos rule #{i} has unknown fields "
                             f"{sorted(unknown)}")
        rules.append(FaultRule(**rd))
    return rules, seed


def _plane_from_spec(spec: str) -> FaultPlane:
    """Spec string -> plane: inline JSON, a path, or @path."""
    text = spec.strip()
    if not text.startswith(("[", "{")):
        path = text[1:] if text.startswith("@") else text
        with open(path) as f:
            text = f.read()
    rules, seed = parse_rules(json.loads(text))
    return FaultPlane(rules, seed=seed)


def configure(rules, seed: int = 0) -> FaultPlane:
    """Install a plane from already-parsed rules (dicts or FaultRules)."""
    global _plane, _env_checked
    parsed = [r if isinstance(r, FaultRule) else FaultRule(**r)
              for r in rules]
    with _plane_lock:
        if _plane is not None:
            _plane.release()
        _plane = FaultPlane(parsed, seed=seed)
        _env_checked = True
        return _plane


def configure_spec(spec: str) -> FaultPlane:
    """Install a plane from a spec string (inline JSON / path / @path)."""
    global _plane, _env_checked
    new = _plane_from_spec(spec)
    with _plane_lock:
        if _plane is not None:
            _plane.release()
        _plane = new
        _env_checked = True
        return _plane


def clear() -> None:
    """Tear the plane down (releasing wedges); taps become no-ops."""
    global _plane, _env_checked
    with _plane_lock:
        if _plane is not None:
            _plane.release()
        _plane = None
        _env_checked = True


def reset_for_tests() -> None:
    """Forget everything INCLUDING the lazy env check."""
    global _plane, _env_checked
    with _plane_lock:
        if _plane is not None:
            _plane.release()
        _plane = None
        _env_checked = False


def status() -> dict:
    p = active()
    if p is None:
        return {"enabled": False, "rules": [], "sites": sorted(SITES)}
    out = p.status()
    out["sites"] = sorted(SITES)
    return out


# ------------------------------------------------------------ metrics
def metrics_lines() -> list[str]:
    return INJECTED.text()


def help_entries() -> dict[str, str]:
    return {"tempo_chaos_injected": INJECTED.help}
