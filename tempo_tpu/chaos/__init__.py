"""tempo_tpu.chaos: deterministic fault injection across every
IO/device seam (see plane.py for the full model).

    from tempo_tpu.chaos import plane
    plane.configure([{"site": "backend.read", "action": "error",
                      "p": 0.05}], seed=7)

Seams tapped: backend objects (chaos.backendwrap via db/tempodb),
ingester-client + querier-worker RPC (transport/client, services/
worker), device launches (ops/device.launch_tap via kerneltel),
WAL append/fsync (db/wal), and gossip send/recv (transport/gossip).
"""

from .backendwrap import ChaosBackend, maybe_wrap  # noqa: F401
from .plane import (  # noqa: F401
    ACTIONS,
    DROP,
    SITES,
    FaultPlane,
    FaultRule,
    clear,
    configure,
    configure_spec,
    is_active,
    parse_rules,
    status,
)
