"""OTLP/JSON codec (the standard OTLP HTTP JSON encoding).

Follows the OTLP JSON mapping rules: trace/span ids are hex strings,
64-bit ints are decimal strings, enums are numbers, AnyValue is a
one-key object ({"stringValue": ...} etc.). Gives the HTTP receiver
parity with the reference's otel-collector OTLP receiver
(modules/distributor/receiver/shim.go:95-101).
"""

from __future__ import annotations

import json
from typing import Any

from .model import (
    AnyValue,
    Event,
    Link,
    Resource,
    ResourceSpans,
    Scope,
    ScopeSpans,
    Span,
    Trace,
)


def _value_to_json(v: AnyValue) -> dict[str, Any]:
    if isinstance(v, bool):
        return {"boolValue": v}
    if isinstance(v, str):
        return {"stringValue": v}
    if isinstance(v, int):
        return {"intValue": str(v)}
    if isinstance(v, float):
        return {"doubleValue": v}
    if isinstance(v, bytes):
        import base64

        return {"bytesValue": base64.b64encode(v).decode("ascii")}
    if isinstance(v, list):
        return {"arrayValue": {"values": [_value_to_json(x) for x in v]}}
    return {"stringValue": str(v)}


def _value_from_json(d: dict[str, Any]) -> AnyValue:
    if "stringValue" in d:
        return d["stringValue"]
    if "boolValue" in d:
        return bool(d["boolValue"])
    if "intValue" in d:
        return int(d["intValue"])
    if "doubleValue" in d:
        return float(d["doubleValue"])
    if "bytesValue" in d:
        import base64

        return base64.b64decode(d["bytesValue"])
    if "arrayValue" in d:
        return [_value_from_json(x) for x in d["arrayValue"].get("values", [])]
    if "kvlistValue" in d:
        return [
            [kv.get("key", ""), _value_from_json(kv.get("value", {}))]
            for kv in d["kvlistValue"].get("values", [])
        ]
    return ""


def _attrs_to_json(attrs: dict[str, AnyValue]) -> list[dict]:
    return [{"key": k, "value": _value_to_json(v)} for k, v in attrs.items()]


def _attrs_from_json(lst: list[dict]) -> dict[str, AnyValue]:
    return {kv.get("key", ""): _value_from_json(kv.get("value", {})) for kv in lst}


def span_to_json(sp: Span) -> dict:
    d: dict[str, Any] = {
        "traceId": sp.trace_id.hex(),
        "spanId": sp.span_id.hex(),
        "name": sp.name,
        "kind": int(sp.kind),
        "startTimeUnixNano": str(sp.start_unix_nano),
        "endTimeUnixNano": str(sp.end_unix_nano),
    }
    if sp.parent_span_id:
        d["parentSpanId"] = sp.parent_span_id.hex()
    if sp.trace_state:
        d["traceState"] = sp.trace_state
    if sp.attrs:
        d["attributes"] = _attrs_to_json(sp.attrs)
    if sp.dropped_attributes_count:
        d["droppedAttributesCount"] = sp.dropped_attributes_count
    if sp.events:
        d["events"] = [
            {
                "timeUnixNano": str(e.time_unix_nano),
                "name": e.name,
                "attributes": _attrs_to_json(e.attrs),
                **(
                    {"droppedAttributesCount": e.dropped_attributes_count}
                    if e.dropped_attributes_count
                    else {}
                ),
            }
            for e in sp.events
        ]
    if sp.links:
        d["links"] = [
            {
                "traceId": l.trace_id.hex(),
                "spanId": l.span_id.hex(),
                "attributes": _attrs_to_json(l.attrs),
                **({"traceState": l.trace_state} if l.trace_state else {}),
            }
            for l in sp.links
        ]
    if sp.status_code or sp.status_message:
        st: dict[str, Any] = {"code": int(sp.status_code)}
        if sp.status_message:
            st["message"] = sp.status_message
        d["status"] = st
    return d


def span_from_json(d: dict) -> Span:
    sp = Span(
        trace_id=bytes.fromhex(d.get("traceId", "")),
        span_id=bytes.fromhex(d.get("spanId", "")),
        parent_span_id=bytes.fromhex(d.get("parentSpanId", "") or ""),
        trace_state=d.get("traceState", ""),
        name=d.get("name", ""),
        kind=int(d.get("kind", 0)),
        start_unix_nano=int(d.get("startTimeUnixNano", 0)),
        end_unix_nano=int(d.get("endTimeUnixNano", 0)),
        attrs=_attrs_from_json(d.get("attributes", [])),
        dropped_attributes_count=int(d.get("droppedAttributesCount", 0)),
    )
    for e in d.get("events", []):
        sp.events.append(
            Event(
                time_unix_nano=int(e.get("timeUnixNano", 0)),
                name=e.get("name", ""),
                attrs=_attrs_from_json(e.get("attributes", [])),
                dropped_attributes_count=int(e.get("droppedAttributesCount", 0)),
            )
        )
    for l in d.get("links", []):
        sp.links.append(
            Link(
                trace_id=bytes.fromhex(l.get("traceId", "")),
                span_id=bytes.fromhex(l.get("spanId", "")),
                trace_state=l.get("traceState", ""),
                attrs=_attrs_from_json(l.get("attributes", [])),
            )
        )
    st = d.get("status", {})
    sp.status_code = int(st.get("code", 0))
    sp.status_message = st.get("message", "")
    return sp


def trace_to_json(t: Trace) -> dict:
    return {
        "resourceSpans": [
            {
                "resource": {"attributes": _attrs_to_json(rs.resource.attrs)},
                "scopeSpans": [
                    {
                        "scope": {"name": ss.scope.name, "version": ss.scope.version},
                        "spans": [span_to_json(sp) for sp in ss.spans],
                    }
                    for ss in rs.scope_spans
                ],
            }
            for rs in t.resource_spans
        ]
    }


def trace_from_json(d: dict) -> Trace:
    t = Trace()
    for rs_j in d.get("resourceSpans", []):
        rs = ResourceSpans(
            resource=Resource(attrs=_attrs_from_json(rs_j.get("resource", {}).get("attributes", [])))
        )
        for ss_j in rs_j.get("scopeSpans", []) or rs_j.get("instrumentationLibrarySpans", []):
            scope_j = ss_j.get("scope", {}) or ss_j.get("instrumentationLibrary", {})
            ss = ScopeSpans(scope=Scope(name=scope_j.get("name", ""), version=scope_j.get("version", "")))
            for sp_j in ss_j.get("spans", []):
                ss.spans.append(span_from_json(sp_j))
            rs.scope_spans.append(ss)
        t.resource_spans.append(rs)
    return t


def dumps(t: Trace) -> str:
    return json.dumps(trace_to_json(t))


def loads(s: str | bytes) -> Trace:
    return trace_from_json(json.loads(s))
