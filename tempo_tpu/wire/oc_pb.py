"""OpenCensus trace protobuf codec (decode-only).

The reference's receiver shim registers an OpenCensus receiver alongside
OTLP/Jaeger/Zipkin/Kafka (modules/distributor/receiver/shim.go:98-101).
OC is the pre-OTel agent protocol: a bidi-streamed
`opencensus.proto.agent.trace.v1.TraceService/Export` whose requests
carry `node = 1`, `spans = 2` (opencensus.proto.trace.v1.Span) and
`resource = 3` -- node/resource are STICKY per stream (a message that
omits them inherits the last seen ones).

This module decodes those messages with the generic pbwire reader and
converts straight into the internal OTLP-shaped model (wire/model.py),
mirroring the otel-collector's opencensus translator: node identity
becomes resource attributes (service.name from Node.service_info.name,
host.hostname / process.pid from Node.identifier), OC resource labels
pass through, and OC's {string,int,bool,double} attribute values map
onto AnyValue.
"""

from __future__ import annotations

from . import pbwire as w
from .model import Event, Link, Resource, ResourceSpans, Scope, ScopeSpans, Span, SpanKind, StatusCode, Trace

# OC SpanKind: 0 unspecified, 1 SERVER, 2 CLIENT
_KIND = {0: SpanKind.UNSPECIFIED, 1: SpanKind.SERVER, 2: SpanKind.CLIENT}


def _truncatable(data: bytes) -> str:
    """TruncatableString { value = 1 }."""
    for f, wt, val in w.iter_fields(data):
        if f == 1:
            return val.decode("utf-8", "replace")
    return ""


def _timestamp_ns(data: bytes) -> int:
    """google.protobuf.Timestamp { seconds = 1, nanos = 2 }."""
    sec = nanos = 0
    for f, wt, val in w.iter_fields(data):
        if f == 1:
            sec = w.to_signed64(val)
        elif f == 2:
            nanos = w.to_signed64(val)
    return sec * 1_000_000_000 + nanos


def _attr_value(data: bytes):
    """AttributeValue oneof { string_value = 1 (TruncatableString),
    int_value = 2, bool_value = 3, double_value = 4 }."""
    for f, wt, val in w.iter_fields(data):
        if f == 1:
            return _truncatable(val)
        if f == 2:
            return w.to_signed64(val)
        if f == 3:
            return bool(val)
        if f == 4:
            return w.fixed64_to_double(val)
    return ""


def _attributes(data: bytes) -> tuple[dict, int]:
    """Attributes { attribute_map = 1 (map<string, AttributeValue>),
    dropped_attributes_count = 2 } -> (attrs, dropped)."""
    attrs: dict = {}
    dropped = 0
    for f, wt, val in w.iter_fields(data):
        if f == 1:  # one map entry: { key = 1, value = 2 }
            k, v = "", ""
            for mf, mwt, mval in w.iter_fields(val):
                if mf == 1:
                    k = mval.decode("utf-8", "replace")
                elif mf == 2:
                    v = _attr_value(mval)
            if k:
                attrs[k] = v
        elif f == 2:
            dropped = w.to_signed64(val)
    return attrs, dropped


def _tracestate(data: bytes) -> str:
    """Span.Tracestate { entries = 1 { key = 1, value = 2 } } rendered
    in the W3C comma-joined form the model stores."""
    parts = []
    for f, wt, val in w.iter_fields(data):
        if f == 1:
            k = v = ""
            for ef, ewt, eval_ in w.iter_fields(val):
                if ef == 1:
                    k = eval_.decode("utf-8", "replace")
                elif ef == 2:
                    v = eval_.decode("utf-8", "replace")
            if k:
                parts.append(f"{k}={v}")
    return ",".join(parts)


def _time_events(data: bytes) -> list[Event]:
    """TimeEvents { time_event = 1 }; each TimeEvent { time = 1,
    annotation = 2 { description = 1, attributes = 2 },
    message_event = 3 { type = 1, id = 2, uncompressed_size = 3,
    compressed_size = 4 } }."""
    out: list[Event] = []
    for f, wt, val in w.iter_fields(data):
        if f != 1:
            continue
        t_ns = 0
        ev: Event | None = None
        for tf, twt, tval in w.iter_fields(val):
            if tf == 1:
                t_ns = _timestamp_ns(tval)
            elif tf == 2:  # annotation
                name = ""
                attrs: dict = {}
                dropped = 0
                for af, awt, aval in w.iter_fields(tval):
                    if af == 1:
                        name = _truncatable(aval)
                    elif af == 2:
                        attrs, dropped = _attributes(aval)
                ev = Event(name=name, attrs=attrs,
                           dropped_attributes_count=dropped)
            elif tf == 3:  # message event (the collector maps these to
                # "message" events with message.* attributes)
                attrs = {}
                for mf, mwt, mval in w.iter_fields(tval):
                    if mf == 1:
                        attrs["message.type"] = (
                            "SENT" if w.to_signed64(mval) == 1 else "RECEIVED")
                    elif mf == 2:
                        attrs["message.id"] = w.to_signed64(mval)
                    elif mf == 3:
                        attrs["message.uncompressed_size"] = w.to_signed64(mval)
                    elif mf == 4:
                        attrs["message.compressed_size"] = w.to_signed64(mval)
                ev = Event(name="message", attrs=attrs)
        if ev is not None:
            ev.time_unix_nano = t_ns
            out.append(ev)
    return out


def _links(data: bytes) -> list[Link]:
    """Links { link = 1 { trace_id = 1, span_id = 2, type = 3,
    attributes = 4 } }."""
    out: list[Link] = []
    for f, wt, val in w.iter_fields(data):
        if f != 1:
            continue
        ln = Link()
        for lf, lwt, lval in w.iter_fields(val):
            if lf == 1:
                ln.trace_id = bytes(lval)
            elif lf == 2:
                ln.span_id = bytes(lval)
            elif lf == 4:
                ln.attrs, _ = _attributes(lval)
        out.append(ln)
    return out


def decode_span(data: bytes) -> tuple[Span, dict | None]:
    """One opencensus.proto.trace.v1.Span -> (model Span, per-span
    resource attrs or None).

    CAUTION on field numbers: OC's Span numbering is NOT OTLP's --
    OTLP renumbered when it forked. Ground truth is the reference's
    vendored codegen (census-instrumentation/opencensus-proto gen-go
    trace/v1/trace.pb.go): 3=parent_span_id, 4=name, 5=start_time,
    6=end_time, 7=attributes, 8=stack_trace, 9=time_events, 10=links,
    11=status, 12=same_process_as_parent_span, 13=child_span_count,
    14=kind, 15=tracestate, 16=resource."""
    s = Span()
    res_attrs: dict | None = None
    for f, wt, val in w.iter_fields(data):
        if f == 1:
            s.trace_id = bytes(val)
        elif f == 2:
            s.span_id = bytes(val)
        elif f == 3:
            s.parent_span_id = bytes(val)
        elif f == 4:
            s.name = _truncatable(val)
        elif f == 5:
            s.start_unix_nano = _timestamp_ns(val)
        elif f == 6:
            s.end_unix_nano = _timestamp_ns(val)
        elif f == 7:
            s.attrs, s.dropped_attributes_count = _attributes(val)
        elif f == 9:
            s.events = _time_events(val)
        elif f == 10:
            s.links = _links(val)
        elif f == 11:  # Status { code = 1, message = 2 }; OC uses gRPC
            # codes, so 0 = OK maps to UNSET (the collector's mapping)
            # and anything else is an error with the message carried
            code = 0
            msg = ""
            for sf, swt, sval in w.iter_fields(val):
                if sf == 1:
                    code = w.to_signed64(sval)
                elif sf == 2:
                    msg = sval.decode("utf-8", "replace")
            if code != 0:
                s.status_code = StatusCode.ERROR
                s.status_message = msg
        elif f == 14:
            s.kind = _KIND.get(w.to_signed64(val), SpanKind.UNSPECIFIED)
        elif f == 15:
            s.trace_state = _tracestate(val)
        elif f == 16:  # per-span Resource override
            res_attrs = _resource_attrs(val)
    return s, res_attrs


def _resource_attrs(data: bytes) -> dict:
    """opencensus.proto.resource.v1.Resource { type = 1,
    labels = 2 (map<string,string>) } -> resource attrs."""
    attrs: dict = {}
    for f, wt, val in w.iter_fields(data):
        if f == 1:
            t = val.decode("utf-8", "replace")
            if t:
                attrs["opencensus.resourcetype"] = t
        elif f == 2:
            k = v = ""
            for mf, mwt, mval in w.iter_fields(val):
                if mf == 1:
                    k = mval.decode("utf-8", "replace")
                elif mf == 2:
                    v = mval.decode("utf-8", "replace")
            if k:
                attrs[k] = v
    return attrs


def node_attrs(data: bytes) -> dict:
    """opencensus.proto.agent.common.v1.Node -> resource attrs the way
    the otel-collector's OC translator maps node identity:
    service_info.name -> service.name, identifier.host_name ->
    host.hostname, identifier.pid -> process.pid, plus the node's
    free-form attributes map (Node { identifier = 1, library_info = 2,
    service_info = 3, attributes = 4 } per the vendored codegen)."""
    attrs: dict = {}
    for f, wt, val in w.iter_fields(data):
        if f == 1:  # ProcessIdentifier { host_name = 1, pid = 2 }
            for pf, pwt, pval in w.iter_fields(val):
                if pf == 1:
                    hn = pval.decode("utf-8", "replace")
                    if hn:
                        attrs["host.hostname"] = hn
                elif pf == 2:
                    attrs["process.pid"] = w.to_signed64(pval)
        elif f == 3:  # ServiceInfo { name = 1 }
            for sf, swt, sval in w.iter_fields(val):
                if sf == 1:
                    sn = sval.decode("utf-8", "replace")
                    if sn:
                        attrs["service.name"] = sn
        elif f == 4:  # attributes map<string,string>
            k = v = ""
            for mf, mwt, mval in w.iter_fields(val):
                if mf == 1:
                    k = mval.decode("utf-8", "replace")
                elif mf == 2:
                    v = mval.decode("utf-8", "replace")
            if k:
                attrs[k] = v
    return attrs


def decode_export_request(data: bytes) -> tuple[dict | None, dict | None, list[tuple[Span, dict | None]]]:
    """ExportTraceServiceRequest { node = 1, spans = 2, resource = 3 }
    -> (node attrs | None, resource attrs | None, [(span, span-level
    resource attrs | None)]). None means "absent in this message":
    the receiver substitutes its per-stream sticky state."""
    node: dict | None = None
    resource: dict | None = None
    spans: list[tuple[Span, dict | None]] = []
    for f, wt, val in w.iter_fields(data):
        if f == 1:
            node = node_attrs(val)
        elif f == 2:
            spans.append(decode_span(val))
        elif f == 3:
            resource = _resource_attrs(val)
    return node, resource, spans


def to_trace(node: dict | None, resource: dict | None,
             spans: list[tuple[Span, dict | None]]) -> Trace:
    """Group decoded spans into a model Trace: spans sharing the request
    (node+resource) identity land in one ResourceSpans; spans with a
    per-span resource override get their own."""
    base: dict = {}
    if node:
        base.update(node)
    if resource:
        base.update(resource)
    groups: dict[tuple, ResourceSpans] = {}
    out = Trace()
    for sp, res_over in spans:
        attrs = dict(base)
        if res_over:
            attrs.update(res_over)
        key = tuple(sorted((k, repr(v)) for k, v in attrs.items()))
        rs = groups.get(key)
        if rs is None:
            rs = ResourceSpans(resource=Resource(attrs=attrs),
                               scope_spans=[ScopeSpans(scope=Scope())])
            groups[key] = rs
            out.resource_spans.append(rs)
        rs.scope_spans[0].spans.append(sp)
    return out
