"""Jaeger api_v2 model + storage_v1 plugin protobuf codec.

The reference ships cmd/tempo-query: a separate process implementing
the Jaeger gRPC storage-plugin API so a stock Jaeger UI/query can use
Tempo as its backing store. The wire surface (hand-rolled over
wire/pbwire, like every proto in this repo):

* jaeger.api_v2.Span / Process / KeyValue / SpanRef with
  google.protobuf Timestamp/Duration fields
  (model/proto/model.proto field numbering);
* storage_v1 requests (GetTraceRequest, TraceQueryParameters) and the
  streamed SpansResponseChunk / GetServicesResponse /
  GetOperationsResponse (plugin/storage/grpc/proto/storage.proto).
"""

from __future__ import annotations

from . import pbwire as w
from .model import Resource, Span, Trace

# KeyValue v_type enum
_VT_STRING, _VT_BOOL, _VT_INT64, _VT_FLOAT64, _VT_BINARY = 0, 1, 2, 3, 4


def _ts(buf: bytearray, field_no: int, unix_nano: int) -> None:
    """google.protobuf.Timestamp {seconds=1, nanos=2}."""
    m = bytearray()
    w.write_varint_field(m, 1, unix_nano // 1_000_000_000)
    w.write_varint_field(m, 2, unix_nano % 1_000_000_000)
    w.write_message_field(buf, field_no, bytes(m))


def _dur(buf: bytearray, field_no: int, nanos: int) -> None:
    """google.protobuf.Duration {seconds=1, nanos=2}."""
    m = bytearray()
    w.write_varint_field(m, 1, nanos // 1_000_000_000)
    w.write_varint_field(m, 2, nanos % 1_000_000_000)
    w.write_message_field(buf, field_no, bytes(m))


def _kv(key: str, value) -> bytes:
    m = bytearray()
    w.write_string_field(m, 1, key)
    if isinstance(value, bool):
        w.write_varint_field(m, 2, _VT_BOOL)
        w.write_varint_field(m, 4, 1 if value else 0)
    elif isinstance(value, int):
        w.write_varint_field(m, 2, _VT_INT64)
        w.write_varint_field(m, 5, value & 0xFFFFFFFFFFFFFFFF)
    elif isinstance(value, float):
        w.write_varint_field(m, 2, _VT_FLOAT64)
        w.write_double_field(m, 6, value)
    elif isinstance(value, bytes):
        w.write_varint_field(m, 2, _VT_BINARY)
        w.write_bytes_field(m, 7, value)
    else:
        w.write_varint_field(m, 2, _VT_STRING)
        w.write_string_field(m, 3, str(value))
    return bytes(m)


def encode_span(sp: Span, res: Resource) -> bytes:
    """One jaeger.api_v2.Span with an inlined Process (field 10)."""
    m = bytearray()
    w.write_bytes_field(m, 1, sp.trace_id.rjust(16, b"\x00")[:16])
    w.write_bytes_field(m, 2, sp.span_id.rjust(8, b"\x00")[:8])
    w.write_string_field(m, 3, sp.name)
    p = sp.parent_span_id
    if p and p.strip(b"\x00"):
        ref = bytearray()  # SpanRef {trace_id=1, span_id=2, ref_type=3 CHILD_OF=0}
        w.write_bytes_field(ref, 1, sp.trace_id.rjust(16, b"\x00")[:16])
        w.write_bytes_field(ref, 2, p.rjust(8, b"\x00")[:8])
        w.write_message_field(m, 4, bytes(ref))
    _ts(m, 6, sp.start_unix_nano)
    _dur(m, 7, max(0, sp.end_unix_nano - sp.start_unix_nano))
    for k, v in sp.attrs.items():
        w.write_message_field(m, 8, _kv(k, v))
    if sp.kind:
        kind_names = {1: "internal", 2: "server", 3: "client", 4: "producer", 5: "consumer"}
        w.write_message_field(m, 8, _kv("span.kind", kind_names.get(int(sp.kind), "unspecified")))
    if int(sp.status_code) == 2:
        w.write_message_field(m, 8, _kv("error", True))
    proc = bytearray()  # Process {service_name=1, tags=2}
    w.write_string_field(proc, 1, res.service_name or "unknown")
    for k, v in res.attrs.items():
        if k != "service.name":
            w.write_message_field(proc, 2, _kv(k, v))
    w.write_message_field(m, 10, bytes(proc))
    return bytes(m)


def encode_spans_chunk(trace: Trace) -> bytes:
    """SpansResponseChunk {repeated Span spans=1}."""
    m = bytearray()
    for rs in trace.resource_spans:
        for ss in rs.scope_spans:
            for sp in ss.spans:
                w.write_message_field(m, 1, encode_span(sp, rs.resource))
    return bytes(m)


def encode_services_response(services: list[str]) -> bytes:
    m = bytearray()
    for s in services:
        w.write_string_field(m, 1, s)
    return bytes(m)


def encode_operations_response(operations: list[str]) -> bytes:
    """GetOperationsResponse: legacy operationNames=1 AND Operation
    messages=2 (name=1) so both client generations work."""
    m = bytearray()
    for op in operations:
        w.write_string_field(m, 1, op)
    for op in operations:
        sub = bytearray()
        w.write_string_field(sub, 1, op)
        w.write_message_field(m, 2, bytes(sub))
    return bytes(m)


def encode_trace_ids_response(trace_ids: list[bytes]) -> bytes:
    m = bytearray()
    for tid in trace_ids:
        w.write_bytes_field(m, 1, tid.rjust(16, b"\x00")[:16])
    return bytes(m)


# ------------------------------------------------------------- requests


def decode_get_trace_request(data: bytes) -> bytes:
    """GetTraceRequest {trace_id bytes=1} -> 16-byte id."""
    for field_no, wt, val in w.iter_fields(data):
        if field_no == 1 and wt == 2:
            return bytes(val).rjust(16, b"\x00")[:16]
    return b"\x00" * 16


def _decode_ts(data: bytes) -> int:
    sec = nanos = 0
    for field_no, wt, val in w.iter_fields(data):
        if field_no == 1:
            sec = int(val)
        elif field_no == 2:
            nanos = int(val)
    return sec * 1_000_000_000 + nanos


def decode_find_traces_request(data: bytes) -> dict:
    """FindTracesRequest {TraceQueryParameters query=1} -> dict with
    service_name, operation_name, tags, start_min/max (unix s),
    duration_min/max (ms), num_traces."""
    out = {"service_name": "", "operation_name": "", "tags": {},
           "start_min": 0, "start_max": 0, "dur_min_ms": 0, "dur_max_ms": 0,
           "num_traces": 20}
    for field_no, wt, val in w.iter_fields(data):
        if field_no != 1 or wt != 2:
            continue
        for f, wt2, v in w.iter_fields(bytes(val)):
            if f == 1:
                out["service_name"] = bytes(v).decode()
            elif f == 2:
                out["operation_name"] = bytes(v).decode()
            elif f == 3:  # map<string,string> entry {key=1, value=2}
                k = vv = ""
                for mf, _, mv in w.iter_fields(bytes(v)):
                    if mf == 1:
                        k = bytes(mv).decode()
                    elif mf == 2:
                        vv = bytes(mv).decode()
                if k:
                    out["tags"][k] = vv
            elif f == 4:
                out["start_min"] = _decode_ts(bytes(v)) // 1_000_000_000
            elif f == 5:
                out["start_max"] = -(-_decode_ts(bytes(v)) // 1_000_000_000)
            elif f == 6:
                out["dur_min_ms"] = _decode_ts(bytes(v)) // 1_000_000
            elif f == 7:
                out["dur_max_ms"] = _decode_ts(bytes(v)) // 1_000_000
            elif f == 8:
                out["num_traces"] = int(v)
    return out


def decode_get_operations_request(data: bytes) -> str:
    for field_no, wt, val in w.iter_fields(data):
        if field_no == 1 and wt == 2:
            return bytes(val).decode()
    return ""
