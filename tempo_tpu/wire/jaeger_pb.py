"""Jaeger api_v2 model + storage_v1 plugin protobuf codec.

The reference ships cmd/tempo-query: a separate process implementing
the Jaeger gRPC storage-plugin API so a stock Jaeger UI/query can use
Tempo as its backing store. The wire surface (hand-rolled over
wire/pbwire, like every proto in this repo):

* jaeger.api_v2.Span / Process / KeyValue / SpanRef with
  google.protobuf Timestamp/Duration fields
  (model/proto/model.proto field numbering);
* storage_v1 requests (GetTraceRequest, TraceQueryParameters) and the
  streamed SpansResponseChunk / GetServicesResponse /
  GetOperationsResponse (plugin/storage/grpc/proto/storage.proto).
"""

from __future__ import annotations

from . import pbwire as w
from .model import Resource, Span, Trace

# KeyValue v_type enum
_VT_STRING, _VT_BOOL, _VT_INT64, _VT_FLOAT64, _VT_BINARY = 0, 1, 2, 3, 4


def _ts(buf: bytearray, field_no: int, unix_nano: int) -> None:
    """google.protobuf.Timestamp {seconds=1, nanos=2}."""
    m = bytearray()
    w.write_varint_field(m, 1, unix_nano // 1_000_000_000)
    w.write_varint_field(m, 2, unix_nano % 1_000_000_000)
    w.write_message_field(buf, field_no, bytes(m))


def _dur(buf: bytearray, field_no: int, nanos: int) -> None:
    """google.protobuf.Duration {seconds=1, nanos=2}."""
    m = bytearray()
    w.write_varint_field(m, 1, nanos // 1_000_000_000)
    w.write_varint_field(m, 2, nanos % 1_000_000_000)
    w.write_message_field(buf, field_no, bytes(m))


def _kv(key: str, value) -> bytes:
    m = bytearray()
    w.write_string_field(m, 1, key)
    if isinstance(value, bool):
        w.write_varint_field(m, 2, _VT_BOOL)
        w.write_varint_field(m, 4, 1 if value else 0)
    elif isinstance(value, int):
        w.write_varint_field(m, 2, _VT_INT64)
        w.write_varint_field(m, 5, value & 0xFFFFFFFFFFFFFFFF)
    elif isinstance(value, float):
        w.write_varint_field(m, 2, _VT_FLOAT64)
        w.write_double_field(m, 6, value)
    elif isinstance(value, bytes):
        w.write_varint_field(m, 2, _VT_BINARY)
        w.write_bytes_field(m, 7, value)
    else:
        w.write_varint_field(m, 2, _VT_STRING)
        w.write_string_field(m, 3, str(value))
    return bytes(m)


def encode_span(sp: Span, res: Resource) -> bytes:
    """One jaeger.api_v2.Span with an inlined Process (field 10)."""
    m = bytearray()
    w.write_bytes_field(m, 1, sp.trace_id.rjust(16, b"\x00")[:16])
    w.write_bytes_field(m, 2, sp.span_id.rjust(8, b"\x00")[:8])
    w.write_string_field(m, 3, sp.name)
    p = sp.parent_span_id
    if p and p.strip(b"\x00"):
        ref = bytearray()  # SpanRef {trace_id=1, span_id=2, ref_type=3 CHILD_OF=0}
        w.write_bytes_field(ref, 1, sp.trace_id.rjust(16, b"\x00")[:16])
        w.write_bytes_field(ref, 2, p.rjust(8, b"\x00")[:8])
        w.write_message_field(m, 4, bytes(ref))
    _ts(m, 6, sp.start_unix_nano)
    _dur(m, 7, max(0, sp.end_unix_nano - sp.start_unix_nano))
    for k, v in sp.attrs.items():
        w.write_message_field(m, 8, _kv(k, v))
    if sp.kind:
        kind_names = {1: "internal", 2: "server", 3: "client", 4: "producer", 5: "consumer"}
        w.write_message_field(m, 8, _kv("span.kind", kind_names.get(int(sp.kind), "unspecified")))
    if int(sp.status_code) == 2:
        w.write_message_field(m, 8, _kv("error", True))
    proc = bytearray()  # Process {service_name=1, tags=2}
    w.write_string_field(proc, 1, res.service_name or "unknown")
    for k, v in res.attrs.items():
        if k != "service.name":
            w.write_message_field(proc, 2, _kv(k, v))
    w.write_message_field(m, 10, bytes(proc))
    return bytes(m)


def encode_spans_chunk(trace: Trace) -> bytes:
    """SpansResponseChunk {repeated Span spans=1}."""
    m = bytearray()
    for rs in trace.resource_spans:
        for ss in rs.scope_spans:
            for sp in ss.spans:
                w.write_message_field(m, 1, encode_span(sp, rs.resource))
    return bytes(m)


def encode_services_response(services: list[str]) -> bytes:
    m = bytearray()
    for s in services:
        w.write_string_field(m, 1, s)
    return bytes(m)


def encode_operations_response(operations: list[str]) -> bytes:
    """GetOperationsResponse: legacy operationNames=1 AND Operation
    messages=2 (name=1) so both client generations work."""
    m = bytearray()
    for op in operations:
        w.write_string_field(m, 1, op)
    for op in operations:
        sub = bytearray()
        w.write_string_field(sub, 1, op)
        w.write_message_field(m, 2, bytes(sub))
    return bytes(m)


def encode_trace_ids_response(trace_ids: list[bytes]) -> bytes:
    m = bytearray()
    for tid in trace_ids:
        w.write_bytes_field(m, 1, tid.rjust(16, b"\x00")[:16])
    return bytes(m)


# ------------------------------------------------------------- requests


def decode_get_trace_request(data: bytes) -> bytes:
    """GetTraceRequest {trace_id bytes=1} -> 16-byte id."""
    for field_no, wt, val in w.iter_fields(data):
        if field_no == 1 and wt == 2:
            return bytes(val).rjust(16, b"\x00")[:16]
    return b"\x00" * 16


def _decode_ts(data: bytes) -> int:
    sec = nanos = 0
    for field_no, wt, val in w.iter_fields(data):
        if field_no == 1:
            sec = int(val)
        elif field_no == 2:
            nanos = int(val)
    return sec * 1_000_000_000 + nanos


def decode_find_traces_request(data: bytes) -> dict:
    """FindTracesRequest {TraceQueryParameters query=1} -> dict with
    service_name, operation_name, tags, start_min/max (unix s),
    duration_min/max (ms), num_traces."""
    out = {"service_name": "", "operation_name": "", "tags": {},
           "start_min": 0, "start_max": 0, "dur_min_ms": 0, "dur_max_ms": 0,
           "num_traces": 20}
    for field_no, wt, val in w.iter_fields(data):
        if field_no != 1 or wt != 2:
            continue
        for f, wt2, v in w.iter_fields(bytes(val)):
            if f == 1:
                out["service_name"] = bytes(v).decode()
            elif f == 2:
                out["operation_name"] = bytes(v).decode()
            elif f == 3:  # map<string,string> entry {key=1, value=2}
                k = vv = ""
                for mf, _, mv in w.iter_fields(bytes(v)):
                    if mf == 1:
                        k = bytes(mv).decode()
                    elif mf == 2:
                        vv = bytes(mv).decode()
                if k:
                    out["tags"][k] = vv
            elif f == 4:
                out["start_min"] = _decode_ts(bytes(v)) // 1_000_000_000
            elif f == 5:
                out["start_max"] = -(-_decode_ts(bytes(v)) // 1_000_000_000)
            elif f == 6:
                out["dur_min_ms"] = _decode_ts(bytes(v)) // 1_000_000
            elif f == 7:
                out["dur_max_ms"] = _decode_ts(bytes(v)) // 1_000_000
            elif f == 8:
                out["num_traces"] = int(v)
    return out


def decode_get_operations_request(data: bytes) -> str:
    for field_no, wt, val in w.iter_fields(data):
        if field_no == 1 and wt == 2:
            return bytes(val).decode()
    return ""


# ------------------------------------------------- collector ingest side
# jaeger.api_v2.CollectorService/PostSpans: the Jaeger agent/client's
# primary gRPC transport (reference: the receiver shim's jaeger factory,
# modules/distributor/receiver/shim.go). Field numbers from
# jaeger model/proto/model.proto: Batch{1 spans, 2 process},
# PostSpansRequest{1 batch}; KeyValue v_type 0 str / 1 bool / 2 int64 /
# 3 float64 / 4 binary (NOTE: a different enum order than thrift).


def _decode_kv(data: bytes) -> tuple[str, object]:
    key, vtype = "", 0
    v_str, v_bool, v_int, v_float, v_bin = "", False, 0, 0.0, b""
    for f, wt, v in w.iter_fields(data):
        if f == 1 and wt == 2:
            key = v.decode("utf-8", "replace")
        elif f == 2 and wt == 0:
            vtype = int(v)
        elif f == 3 and wt == 2:
            v_str = v.decode("utf-8", "replace")
        elif f == 4 and wt == 0:
            v_bool = bool(v)
        elif f == 5 and wt == 0:
            v_int = w.to_signed64(int(v))
        elif f == 6 and wt == 1:
            v_float = w.fixed64_to_double(int(v))
        elif f == 7 and wt == 2:
            v_bin = bytes(v)
    if vtype == _VT_BOOL:
        return key, v_bool
    if vtype == _VT_INT64:
        return key, v_int
    if vtype == _VT_FLOAT64:
        return key, v_float
    if vtype == _VT_BINARY:
        return key, v_bin.hex()  # hex like the reference's translator
    return key, v_str


def _decode_kvs(items: list[bytes]) -> dict:
    attrs = {}
    for data in items:
        k, v = _decode_kv(data)
        if k:
            attrs[k] = v
    return attrs


def _decode_process(data: bytes) -> dict:
    service, tags = "", []
    for f, wt, v in w.iter_fields(data):
        if f == 1 and wt == 2:
            service = v.decode("utf-8", "replace")
        elif f == 2 and wt == 2:
            tags.append(v)
    attrs = _decode_kvs(tags)
    attrs["service.name"] = service
    return attrs


def decode_post_spans(data: bytes) -> list:
    """PostSpansRequest bytes -> list[ResourceSpans] (one per distinct
    process: batch-level by default, span-level process overrides get
    their own resource, mirroring the OTel jaeger translator)."""
    from .model import Event, Link, Resource, ResourceSpans, Scope, ScopeSpans
    from .model import Span as MSpan
    from .model import SpanKind, StatusCode

    batch = None
    for f, wt, v in w.iter_fields(data):
        if f == 1 and wt == 2:
            batch = v
    if batch is None:
        return []
    span_msgs: list[bytes] = []
    batch_proc: dict = {"service.name": ""}
    for f, wt, v in w.iter_fields(batch):
        if f == 1 and wt == 2:
            span_msgs.append(v)
        elif f == 2 and wt == 2:
            batch_proc = _decode_process(v)

    _KIND_MAP = {
        "client": SpanKind.CLIENT, "server": SpanKind.SERVER,
        "producer": SpanKind.PRODUCER, "consumer": SpanKind.CONSUMER,
        "internal": SpanKind.INTERNAL,
    }

    by_proc: dict[tuple, list] = {}
    proc_attrs: dict[tuple, dict] = {}
    for msg in span_msgs:
        tid = b"\x00" * 16
        sid = b"\x00" * 8
        name = ""
        refs: list[bytes] = []
        start_ns = dur_ns = 0
        tags: list[bytes] = []
        logs: list[bytes] = []
        own_proc = None
        for f, wt, v in w.iter_fields(msg):
            if f == 1 and wt == 2:
                tid = bytes(v).rjust(16, b"\x00")[:16]
            elif f == 2 and wt == 2:
                sid = bytes(v).rjust(8, b"\x00")[:8]
            elif f == 3 and wt == 2:
                name = v.decode("utf-8", "replace")
            elif f == 4 and wt == 2:
                refs.append(v)
            elif f == 6 and wt == 2:
                start_ns = _decode_ts(v)
            elif f == 7 and wt == 2:
                dur_ns = _decode_ts(v)
            elif f == 8 and wt == 2:
                tags.append(v)
            elif f == 9 and wt == 2:
                logs.append(v)
            elif f == 10 and wt == 2:
                own_proc = _decode_process(v)
        parent = b""
        links: list = []
        for rdata in refs:
            r_tid, r_sid, r_type = b"", b"", 0
            for f, wt, v in w.iter_fields(rdata):
                if f == 1 and wt == 2:
                    r_tid = bytes(v).rjust(16, b"\x00")[:16]
                elif f == 2 and wt == 2:
                    r_sid = bytes(v).rjust(8, b"\x00")[:8]
                elif f == 3 and wt == 0:
                    r_type = int(v)
            if r_type == 0 and not parent:  # CHILD_OF -> parent
                parent = r_sid
            elif r_type != 0:  # FOLLOWS_FROM -> link (otel mapping)
                links.append(Link(trace_id=r_tid, span_id=r_sid,
                                  attrs={"jaeger.ref_type": "follows_from"}))
        events = []
        for ldata in logs:
            l_ts, l_fields = 0, []
            for f, wt, v in w.iter_fields(ldata):
                if f == 1 and wt == 2:
                    l_ts = _decode_ts(v)
                elif f == 2 and wt == 2:
                    l_fields.append(v)
            events.append(Event(time_unix_nano=l_ts, name="log",
                                attrs=_decode_kvs(l_fields)))
        attrs = _decode_kvs(tags)
        kind = _KIND_MAP.get(str(attrs.pop("span.kind", "")).lower(),
                             SpanKind.INTERNAL)
        status = StatusCode.UNSET
        if str(attrs.get("error", "")).lower() in ("true", "1"):
            status = StatusCode.ERROR
        proc = own_proc if own_proc is not None else batch_proc
        pkey = tuple(sorted((k, str(v)) for k, v in proc.items()))
        proc_attrs[pkey] = proc
        by_proc.setdefault(pkey, []).append(MSpan(
            trace_id=tid,
            span_id=sid,
            parent_span_id=parent,
            name=name,
            kind=kind,
            start_unix_nano=start_ns,
            end_unix_nano=start_ns + dur_ns,
            status_code=status,
            attrs=attrs,
            events=events,
            links=links,
        ))
    return [
        ResourceSpans(
            resource=Resource(attrs=proc_attrs[k]),
            scope_spans=[ScopeSpans(scope=Scope(name="jaeger"), spans=spans)],
        )
        for k, spans in by_proc.items()
    ]
