"""Trace combination and sorting.

Partial traces for the same ID arrive from replicated ingesters, sharded
queriers and compaction inputs; combining them must dedupe spans that were
replicated RF-way. The reference dedupes by span-ID token and re-sorts
(pkg/model/trace/combine.go, sort.go); we dedupe on (span_id, start) and
sort batches by earliest span start.
"""

from __future__ import annotations

from .model import Resource, ResourceSpans, ScopeSpans, Span, Trace


def _span_key(sp: Span) -> tuple:
    return (sp.span_id, sp.start_unix_nano, sp.name)


def combine_traces(traces: list[Trace]) -> Trace:
    """Merge traces, deduping spans; keeps the first-seen copy of a span.

    Never mutates its inputs: the result shares Span objects with the
    inputs but owns all list structure.
    """
    seen: set[tuple] = set()
    out = Trace()
    # group output batches by resource identity to avoid exploding batches
    by_resource: dict[tuple, ResourceSpans] = {}
    for t in traces:
        for rs in t.resource_spans:
            rkey = tuple(sorted((k, repr(v)) for k, v in rs.resource.attrs.items()))
            dst = by_resource.get(rkey)
            if dst is None:
                dst = ResourceSpans(resource=Resource(attrs=dict(rs.resource.attrs)))
                by_resource[rkey] = dst
                out.resource_spans.append(dst)
            for ss in rs.scope_spans:
                kept = []
                for sp in ss.spans:
                    k = _span_key(sp)
                    if k in seen:
                        continue
                    seen.add(k)
                    kept.append(sp)
                if kept:
                    dst.scope_spans.append(ScopeSpans(scope=ss.scope, spans=kept))
    return sort_trace(out)


def sort_trace(t: Trace) -> Trace:
    """Return a structurally-new trace with batches ordered by earliest span
    start and spans within each scope by start time: deterministic output
    for tests and compaction. Shares Span objects with the input."""

    def batch_start(rs: ResourceSpans) -> int:
        starts = [sp.start_unix_nano for ss in rs.scope_spans for sp in ss.spans]
        return min(starts) if starts else 0

    new_batches = [
        ResourceSpans(
            resource=rs.resource,
            scope_spans=[
                ScopeSpans(
                    scope=ss.scope,
                    spans=sorted(ss.spans, key=lambda sp: (sp.start_unix_nano, sp.span_id)),
                )
                for ss in rs.scope_spans
            ],
        )
        for rs in t.resource_spans
    ]
    new_batches.sort(key=batch_start)
    return Trace(resource_spans=new_batches)
