"""Jaeger UI JSON encoding of a trace (tempo-query analog).

The reference ships tempo-query, a Jaeger storage-plugin shim that lets
the Jaeger UI read traces from Tempo (cmd/tempo-query). Here the same
capability is the /jaeger/api/traces/{id} endpoint encoding the wire
model in the Jaeger HTTP API's JSON shape ({data:[{traceID, spans,
processes}]}, public API format).
"""

from __future__ import annotations

from .model import SpanKind, StatusCode, Trace

_KIND_TAG = {
    SpanKind.CLIENT: "client",
    SpanKind.SERVER: "server",
    SpanKind.PRODUCER: "producer",
    SpanKind.CONSUMER: "consumer",
}


def _tag(key: str, value) -> dict:
    if isinstance(value, bool):
        return {"key": key, "type": "bool", "value": value}
    if isinstance(value, int):
        return {"key": key, "type": "int64", "value": value}
    if isinstance(value, float):
        return {"key": key, "type": "float64", "value": value}
    return {"key": key, "type": "string", "value": str(value)}


def trace_to_jaeger(tr: Trace) -> dict:
    """-> the Jaeger HTTP API response body for one trace."""
    tid_hex = tr.trace_id().hex()
    processes: dict[str, dict] = {}
    proc_ids: dict[tuple, str] = {}
    spans = []
    for res, scope, sp in tr.all_spans():
        pkey = tuple(sorted((k, str(v)) for k, v in res.attrs.items()))
        pid = proc_ids.get(pkey)
        if pid is None:
            pid = proc_ids[pkey] = f"p{len(proc_ids) + 1}"
            processes[pid] = {
                "serviceName": res.service_name,
                "tags": [_tag(k, v) for k, v in res.attrs.items() if k != "service.name"],
            }
        tags = [_tag(k, v) for k, v in sp.attrs.items()]
        if sp.kind in _KIND_TAG:
            tags.append(_tag("span.kind", _KIND_TAG[sp.kind]))
        if sp.status_code == StatusCode.ERROR:
            tags.append(_tag("error", True))
        refs = []
        if sp.parent_span_id.strip(b"\x00"):
            refs.append(
                {"refType": "CHILD_OF", "traceID": tid_hex,
                 "spanID": sp.parent_span_id.hex()}
            )
        spans.append(
            {
                "traceID": tid_hex,
                "spanID": sp.span_id.hex(),
                "operationName": sp.name,
                "references": refs,
                "startTime": sp.start_unix_nano // 1000,  # jaeger: microseconds
                "duration": max(0, sp.duration_nanos) // 1000,
                "tags": tags,
                "logs": [
                    {
                        "timestamp": ev.time_unix_nano // 1000,
                        "fields": [_tag("event", ev.name)]
                        + [_tag(k, v) for k, v in ev.attrs.items()],
                    }
                    for ev in sp.events
                ],
                "processID": pid,
            }
        )
    return {"data": [{"traceID": tid_hex, "spans": spans, "processes": processes}]}
