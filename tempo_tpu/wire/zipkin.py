"""Zipkin v2 JSON receiver: decode POST /api/v2/spans payloads into the
wire model.

The reference embeds the otel-collector zipkin receiver
(modules/distributor/receiver/shim.go:95-101); here the v2 JSON span
format (public Zipkin API spec) is decoded directly: spans group by
localEndpoint.serviceName into per-service ResourceSpans batches.
"""

from __future__ import annotations

import json
from collections import defaultdict

from .model import Resource, ResourceSpans, Scope, ScopeSpans, Span, SpanKind

_KIND = {
    "CLIENT": SpanKind.CLIENT,
    "SERVER": SpanKind.SERVER,
    "PRODUCER": SpanKind.PRODUCER,
    "CONSUMER": SpanKind.CONSUMER,
}


def _id_bytes(hex_str: str, width: int) -> bytes:
    return bytes.fromhex(hex_str.rjust(width * 2, "0"))


def _coerce(key: str, v):
    """Zipkin tag values are strings BY SPEC and stay strings verbatim
    (coercing would corrupt values like "007" and break string-equality
    queries). The one OTel-compatible translation: http.status_code to
    int, which routes it to the dedicated numeric column."""
    if key == "http.status_code" and isinstance(v, str) and v.isdigit():
        return int(v)
    return v


def decode_spans(body: bytes | str) -> list[ResourceSpans]:
    """One POST /api/v2/spans payload -> ResourceSpans batches."""
    data = json.loads(body)
    if not isinstance(data, list):
        raise ValueError("zipkin v2 payload must be a JSON array of spans")
    by_service: dict[str, list[Span]] = defaultdict(list)
    for zs in data:
        ts_us = int(zs.get("timestamp", 0))
        dur_us = int(zs.get("duration", 0))
        attrs = {k: _coerce(k, v) for k, v in (zs.get("tags") or {}).items()}
        remote = (zs.get("remoteEndpoint") or {}).get("serviceName")
        if remote:
            attrs.setdefault("peer.service", remote)
        sp = Span(
            trace_id=_id_bytes(zs["traceId"], 16),
            span_id=_id_bytes(zs["id"], 8),
            parent_span_id=_id_bytes(zs["parentId"], 8) if zs.get("parentId") else b"",
            name=zs.get("name", ""),
            kind=_KIND.get((zs.get("kind") or "").upper(), SpanKind.INTERNAL),
            start_unix_nano=ts_us * 1000,
            end_unix_nano=(ts_us + dur_us) * 1000,
            attrs=attrs,
        )
        svc = (zs.get("localEndpoint") or {}).get("serviceName", "")
        by_service[svc].append(sp)
    out = []
    for svc, spans in by_service.items():
        res = Resource(attrs={"service.name": svc} if svc else {})
        out.append(
            ResourceSpans(
                resource=res,
                scope_spans=[ScopeSpans(scope=Scope(name="zipkin-receiver"), spans=spans)],
            )
        )
    return out
