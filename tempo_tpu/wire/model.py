"""Canonical in-memory trace model, wire-compatible with OTLP.

The reference's wire model is gogo-proto generated OTLP clones
(pkg/tempopb/trace/v1, SURVEY.md section 2.8); a Trace is the list of
resource-span batches of an OTLP ExportTraceServiceRequest
(modules/distributor/receiver/shim.go:209-215). We keep the same shape
as plain dataclasses: cheap to build from any receiver format and to
flatten into the columnar block layout.

Attribute values are restricted to the OTLP AnyValue space: str, bool,
int, float, bytes, or a (possibly nested) list of those.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Union

AnyValue = Union[str, bool, int, float, bytes, list]


class SpanKind(enum.IntEnum):
    UNSPECIFIED = 0
    INTERNAL = 1
    SERVER = 2
    CLIENT = 3
    PRODUCER = 4
    CONSUMER = 5


class StatusCode(enum.IntEnum):
    UNSET = 0
    OK = 1
    ERROR = 2


@dataclass
class Event:
    time_unix_nano: int = 0
    name: str = ""
    attrs: dict[str, AnyValue] = field(default_factory=dict)
    dropped_attributes_count: int = 0


@dataclass
class Link:
    trace_id: bytes = b""
    span_id: bytes = b""
    trace_state: str = ""
    attrs: dict[str, AnyValue] = field(default_factory=dict)


@dataclass
class Span:
    trace_id: bytes = b""
    span_id: bytes = b""
    parent_span_id: bytes = b""
    trace_state: str = ""
    name: str = ""
    kind: int = SpanKind.UNSPECIFIED
    start_unix_nano: int = 0
    end_unix_nano: int = 0
    attrs: dict[str, AnyValue] = field(default_factory=dict)
    dropped_attributes_count: int = 0
    events: list[Event] = field(default_factory=list)
    links: list[Link] = field(default_factory=list)
    status_code: int = StatusCode.UNSET
    status_message: str = ""

    @property
    def duration_nanos(self) -> int:
        return max(0, self.end_unix_nano - self.start_unix_nano)


@dataclass
class Resource:
    attrs: dict[str, AnyValue] = field(default_factory=dict)

    @property
    def service_name(self) -> str:
        v = self.attrs.get("service.name", "")
        return v if isinstance(v, str) else str(v)


@dataclass
class Scope:
    name: str = ""
    version: str = ""


@dataclass
class ScopeSpans:
    scope: Scope = field(default_factory=Scope)
    spans: list[Span] = field(default_factory=list)


@dataclass
class ResourceSpans:
    resource: Resource = field(default_factory=Resource)
    scope_spans: list[ScopeSpans] = field(default_factory=list)


@dataclass
class Trace:
    """One trace (or a partial trace segment): a batch of ResourceSpans."""

    resource_spans: list[ResourceSpans] = field(default_factory=list)

    def all_spans(self):
        for rs in self.resource_spans:
            for ss in rs.scope_spans:
                for sp in ss.spans:
                    yield rs.resource, ss.scope, sp

    def span_count(self) -> int:
        return sum(1 for _ in self.all_spans())

    def trace_id(self) -> bytes:
        for _, _, sp in self.all_spans():
            if sp.trace_id:
                return sp.trace_id
        return b""

    def time_range_nanos(self) -> tuple[int, int]:
        lo, hi = None, None
        for _, _, sp in self.all_spans():
            if lo is None or sp.start_unix_nano < lo:
                lo = sp.start_unix_nano
            if hi is None or sp.end_unix_nano > hi:
                hi = sp.end_unix_nano
        return (lo or 0, hi or 0)
