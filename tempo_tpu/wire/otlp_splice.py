"""Zero-decode OTLP rebatching: split an ExportTraceServiceRequest's
raw bytes into per-trace segments by BYTE SPLICING.

The distributor's hot write loop regroups spans by trace id
(reference: requestsByTraceID, modules/distributor/distributor.go:451).
The model path decodes the payload into wire objects and re-encodes one
proto per trace -- all Python, and the single biggest ingest cost. Here
the native structural scanner (native/vtpu_native.cc vtpu_otlp_scan)
finds every span submessage's byte range plus its trace id and
timestamps, and this module reassembles per-trace TracesData bytes from
slices of the ORIGINAL payload: resource/scope envelope bytes are
reused verbatim, span bodies are never touched. Proto semantics make
the splice exact: repeated fields may appear in any order and split
across messages, so concatenating envelope fields with a subset of
span fields re-encodes the same logical message.

Falls back to None (caller uses the model path) when the native layer
is absent or the payload doesn't parse cleanly.
"""

from __future__ import annotations

import numpy as np

from . import pbwire as w
from .segment import _HDR, _V1

_SPAN_TAG = bytes([0x12])  # ScopeSpans.spans = 2, wire type 2
_SS_TAG = bytes([0x12])  # ResourceSpans.scope_spans = 2, wire type 2
_RS_TAG = bytes([0x0A])  # TracesData.resource_spans = 1, wire type 2


def _frame(tag: bytes, body: bytes | bytearray) -> bytes:
    hdr = bytearray(tag)
    w.write_varint(hdr, len(body))
    return bytes(hdr) + bytes(body)


def split_by_trace(payload: bytes):
    """-> (segments, n_spans) or None.

    segments: {trace_id bytes: (start_s, end_s, segment_bytes)} where
    segment_bytes is the wire segment (s1 header + per-trace TracesData)
    exactly as segment_for_write would have produced for the same spans
    (same span bytes, same envelope fields).

    Fast path: ONE native call (vtpu_otlp_splice) scans, groups and
    emits finished segments; Python only slices the output buffer. The
    scan-here-splice-in-Python path below remains as the fallback and
    as the differential oracle for the native emitter."""
    from ..native import otlp_splice

    res = otlp_splice(payload)
    if res is not None:
        tids, seg_off, seg_len, st, en, out, n_spans = res
        # one bulk copy out of the native buffer, then plain python
        # slicing -- per-element numpy indexing is the slow part here
        tidb = tids.tobytes()
        outb = out[: int(seg_off[-1] + seg_len[-1])].tobytes() if len(seg_off) else b""
        offs = seg_off.tolist()
        lens = seg_len.tolist()
        sts = st.tolist()
        ens = en.tolist()
        segments: dict[bytes, tuple[int, int, bytes]] = {}
        for u, o in enumerate(offs):
            segments[tidb[u * 16 : u * 16 + 16]] = (
                sts[u], ens[u], outb[o : o + lens[u]])
        return segments, n_spans
    return _split_by_trace_py(payload)


def _split_by_trace_py(payload: bytes):
    from ..native import otlp_scan

    scan = otlp_scan(payload)
    if scan is None:
        return None
    (span_off, span_len, span_rs, span_ss, tids, start_ns, end_ns,
     env, senv, rs_off, rs_len, ss_off, ss_len, ss_rs) = scan
    k = span_off.shape[0]
    if k == 0:
        return {}, 0

    # group span indices by 16-byte trace id (one vectorized pass)
    tid_void = np.ascontiguousarray(tids).view([("v", "V16")]).reshape(-1)
    uniq, inverse = np.unique(tid_void, return_inverse=True)
    order = np.argsort(inverse, kind="stable")
    bounds = np.searchsorted(inverse[order], np.arange(uniq.shape[0] + 1))

    # per-trace time range (min over starts, max over ends -- the
    # model path's Trace.time_range_nanos over the same spans)
    lo_ns = np.minimum.reduceat(start_ns[order], bounds[:-1])
    hi_ns = np.maximum.reduceat(end_ns[order], bounds[:-1])

    segments: dict[bytes, tuple[int, int, bytes]] = {}
    mv = memoryview(payload)
    for u in range(uniq.shape[0]):
        idxs = order[bounds[u] : bounds[u + 1]]
        body = bytearray()
        i = 0
        while i < len(idxs):
            rs = int(span_rs[idxs[i]])
            rs_body = bytearray(
                env[int(rs_off[rs]) : int(rs_off[rs] + rs_len[rs])])
            while i < len(idxs) and int(span_rs[idxs[i]]) == rs:
                ss = int(span_ss[idxs[i]])
                ss_body = bytearray(
                    senv[int(ss_off[ss]) : int(ss_off[ss] + ss_len[ss])])
                while i < len(idxs) and int(span_ss[idxs[i]]) == ss:
                    j = int(idxs[i])
                    ss_body += _frame(
                        _SPAN_TAG, mv[span_off[j] : span_off[j] + span_len[j]])
                    i += 1
                rs_body += _frame(_SS_TAG, ss_body)
            body += _frame(_RS_TAG, rs_body)
        tid = uniq[u].tobytes()
        lo = int(lo_ns[u])
        hi = int(hi_ns[u])
        start_s = lo // 10**9
        end_s = (hi + 10**9 - 1) // 10**9
        seg = _HDR.pack(_V1, start_s & 0xFFFFFFFF, end_s & 0xFFFFFFFF) + bytes(body)
        segments[tid] = (start_s, end_s, seg)
    return segments, k
