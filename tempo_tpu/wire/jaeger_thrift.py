"""Jaeger thrift-binary ingest codec.

Reference: the receiver shim's jaeger receiver accepts thrift Batch
payloads on /api/traces (modules/distributor/receiver/shim.go; the
jaeger collector's HTTP endpoint). This is a hand-rolled thrift BINARY
protocol reader (the wire format is a public spec: typed fields with
i16 ids, length-prefixed strings, typed lists) feeding the same wire
model OTLP ingest uses -- no thrift toolchain.

Model (jaeger.thrift): Batch{1:Process, 2:[Span]};
Process{1:serviceName, 2:[Tag]}; Span{1:traceIdLow, 2:traceIdHigh,
3:spanId, 4:parentSpanId, 5:operationName, 6:[SpanRef], 7:flags,
8:startTime us, 9:duration us, 10:[Tag], 11:[Log]};
Tag{1:key, 2:vType, 3:vStr, 4:vDouble, 5:vBool, 6:vLong, 7:vBinary};
SpanRef{1:refType, 2:traceIdLow, 3:traceIdHigh, 4:spanId}.
"""

from __future__ import annotations

import struct

from .model import (
    Event,
    Link,
    Resource,
    ResourceSpans,
    Scope,
    ScopeSpans,
    Span,
    SpanKind,
    StatusCode,
)

# thrift binary type codes
_STOP, _BOOL, _BYTE, _DOUBLE, _I16, _I32, _I64 = 0, 2, 3, 4, 6, 8, 10
_STRING, _STRUCT, _MAP, _SET, _LIST = 11, 12, 13, 14, 15


class ThriftError(ValueError):
    pass


class _Reader:
    def __init__(self, data: bytes):
        self.data = data
        self.pos = 0

    def _take(self, n: int) -> bytes:
        if self.pos + n > len(self.data):
            raise ThriftError("truncated thrift payload")
        out = self.data[self.pos : self.pos + n]
        self.pos += n
        return out

    def read(self, ttype: int):
        if ttype == _BOOL:
            return self._take(1)[0] != 0
        if ttype == _BYTE:
            return self._take(1)[0]
        if ttype == _DOUBLE:
            return struct.unpack(">d", self._take(8))[0]
        if ttype == _I16:
            return struct.unpack(">h", self._take(2))[0]
        if ttype == _I32:
            return struct.unpack(">i", self._take(4))[0]
        if ttype == _I64:
            return struct.unpack(">q", self._take(8))[0]
        if ttype == _STRING:
            (n,) = struct.unpack(">i", self._take(4))
            if n < 0:
                raise ThriftError("negative string length")
            return self._take(n)
        if ttype == _STRUCT:
            return self.read_struct()
        if ttype in (_LIST, _SET):
            et = self._take(1)[0]
            (n,) = struct.unpack(">i", self._take(4))
            if n < 0:
                raise ThriftError("negative list length")
            return [self.read(et) for _ in range(n)]
        if ttype == _MAP:
            kt, vt = self._take(1)[0], self._take(1)[0]
            (n,) = struct.unpack(">i", self._take(4))
            return {self.read(kt): self.read(vt) for _ in range(max(0, n))}
        raise ThriftError(f"unsupported thrift type {ttype}")

    def read_struct(self) -> dict[int, object]:
        out: dict[int, object] = {}
        while True:
            ttype = self._take(1)[0]
            if ttype == _STOP:
                return out
            (fid,) = struct.unpack(">h", self._take(2))
            out[fid] = self.read(ttype)


class _CompactReader(_Reader):
    """Thrift COMPACT protocol reader producing the same generic struct
    dicts as _Reader (the jaeger AGENT's UDP wire form, port 6831:
    zigzag-varint ints, delta-encoded field ids, little-endian doubles,
    bool values folded into the field-header type). Shares the cursor
    (_take) with the binary reader; read/read_struct are overridden
    wholesale for the compact encodings."""

    # compact type codes
    _CT_BOOL_TRUE, _CT_BOOL_FALSE = 1, 2
    _CT_BYTE, _CT_I16, _CT_I32, _CT_I64 = 3, 4, 5, 6
    _CT_DOUBLE, _CT_BINARY = 7, 8
    _CT_LIST, _CT_SET, _CT_MAP, _CT_STRUCT = 9, 10, 11, 12

    def varint(self) -> int:
        v = shift = 0
        while True:
            b = self._take(1)[0]
            v |= (b & 0x7F) << shift
            if not (b & 0x80):
                return v
            shift += 7
            if shift > 70:
                raise ThriftError("varint too long")

    def zigzag(self) -> int:
        v = self.varint()
        return (v >> 1) ^ -(v & 1)

    def read(self, ct: int):
        if ct == self._CT_BOOL_TRUE:
            return True
        if ct == self._CT_BOOL_FALSE:
            return False
        if ct == self._CT_BYTE:
            b = self._take(1)[0]
            return b - 256 if b >= 128 else b
        if ct in (self._CT_I16, self._CT_I32, self._CT_I64):
            return self.zigzag()
        if ct == self._CT_DOUBLE:
            return struct.unpack("<d", self._take(8))[0]
        if ct == self._CT_BINARY:
            return self._take(self.varint())
        if ct == self._CT_STRUCT:
            return self.read_struct()
        if ct in (self._CT_LIST, self._CT_SET):
            hdr = self._take(1)[0]
            n, et = hdr >> 4, hdr & 0xF
            if n == 0xF:
                n = self.varint()
            return [self._read_elem(et) for _ in range(n)]
        if ct == self._CT_MAP:
            n = self.varint()
            if n == 0:
                return {}
            kv = self._take(1)[0]
            kt, vt = kv >> 4, kv & 0xF
            return {self._read_elem(kt): self._read_elem(vt) for _ in range(n)}
        raise ThriftError(f"unsupported compact type {ct}")

    def _read_elem(self, et: int):
        """Container-element read: unlike field values (where the bool
        IS the field-header type code and carries no bytes), bool
        elements inside list/set/map occupy one byte each -- 1 = true,
        2 = false per the spec, and thrift-py writers emit 0 for false.
        Dispatching them to read() would consume nothing and desync the
        cursor on untrusted UDP payloads."""
        if et in (self._CT_BOOL_TRUE, self._CT_BOOL_FALSE):
            b = self._take(1)[0]
            if b not in (0, self._CT_BOOL_TRUE, self._CT_BOOL_FALSE):
                raise ThriftError(f"bad bool element value {b}")
            return b == self._CT_BOOL_TRUE
        return self.read(et)

    def read_struct(self) -> dict[int, object]:
        out: dict[int, object] = {}
        fid = 0
        while True:
            hdr = self._take(1)[0]
            if hdr == _STOP:
                return out
            delta, ct = hdr >> 4, hdr & 0xF
            fid = fid + delta if delta else self.zigzag()
            # bool-in-field: the header's type IS the value
            out[fid] = self.read(ct)


def decode_agent_message(data: bytes) -> "ResourceSpans | None":
    """One jaeger AGENT UDP datagram (agent.thrift emitBatch, compact
    0x82 or strict-binary framing, auto-detected) -> ResourceSpans, or
    None for other methods (emitZipkinBatch is unsupported)."""
    if not data:
        raise ThriftError("empty datagram")
    if data[0] == 0x82:  # compact protocol message header
        r = _CompactReader(data)
        r._take(1)  # protocol id
        r._take(1)  # (type << 5) | version
        r.varint()  # seqid
        name = r._take(r.varint())
        if name != b"emitBatch":
            return None
        args = r.read_struct()
    else:  # strict binary: i32 (version|type), string name, i32 seqid
        r = _Reader(data)
        (ver,) = struct.unpack(">i", r._take(4))
        if ver >= 0:  # old-style unframed: i32 name len first -- reject
            raise ThriftError("not a strict-binary thrift message")
        (nlen,) = struct.unpack(">i", r._take(4))
        name = r._take(nlen)
        r._take(4)  # seqid
        if name != b"emitBatch":
            return None
        args = r.read_struct()
    batch = args.get(1)
    if not isinstance(batch, dict):
        raise ThriftError("emitBatch args missing Batch")
    return batch_to_resource_spans(batch)


def _tags_to_attrs(tags) -> dict:
    attrs = {}
    for t in tags or []:
        key = (t.get(1) or b"").decode("utf-8", "replace")
        vtype = t.get(2, 0)
        if vtype == 0:
            attrs[key] = (t.get(3) or b"").decode("utf-8", "replace")
        elif vtype == 1:
            attrs[key] = float(t.get(4, 0.0))
        elif vtype == 2:
            attrs[key] = bool(t.get(5, False))
        elif vtype == 3:
            attrs[key] = int(t.get(6, 0))
        else:  # binary: hex like the reference's translator
            attrs[key] = (t.get(7) or b"").hex()
    return attrs


_KIND_MAP = {
    "client": SpanKind.CLIENT, "server": SpanKind.SERVER,
    "producer": SpanKind.PRODUCER, "consumer": SpanKind.CONSUMER,
    "internal": SpanKind.INTERNAL,
}


def decode_batch(data: bytes) -> ResourceSpans:
    """One thrift-binary Batch -> one ResourceSpans (Process ==
    resource); the collector HTTP endpoint's payload form."""
    return batch_to_resource_spans(_Reader(data).read_struct())


def batch_to_resource_spans(batch: dict) -> ResourceSpans:
    """Generic parsed Batch struct -> ResourceSpans: shared by the
    binary collector payload and both agent UDP protocols."""
    process = batch.get(1) or {}
    service = (process.get(1) or b"").decode("utf-8", "replace")
    res_attrs = _tags_to_attrs(process.get(2))
    res_attrs["service.name"] = service

    spans = []
    for s in batch.get(2) or []:
        tid = (int(s.get(2, 0)) & (2**64 - 1)).to_bytes(8, "big") + \
              (int(s.get(1, 0)) & (2**64 - 1)).to_bytes(8, "big")
        parent = int(s.get(4, 0)) & (2**64 - 1)
        links: list[Link] = []
        for ref in s.get(6) or []:
            ref_tid = ((int(ref.get(3, 0)) & (2**64 - 1)).to_bytes(8, "big")
                       + (int(ref.get(2, 0)) & (2**64 - 1)).to_bytes(8, "big"))
            ref_sid = (int(ref.get(4, 0)) & (2**64 - 1)).to_bytes(8, "big")
            if ref.get(1, 0) == 0 and not parent:  # CHILD_OF -> parent
                parent = int(ref.get(4, 0)) & (2**64 - 1)
            elif ref.get(1, 0) != 0:  # FOLLOWS_FROM -> link (otel mapping)
                links.append(Link(trace_id=ref_tid, span_id=ref_sid,
                                  attrs={"jaeger.ref_type": "follows_from"}))
        events = [  # Jaeger logs -> otel events (the standard translator)
            Event(
                time_unix_nano=int(log.get(1, 0)) * 1000,
                name="log",
                attrs=_tags_to_attrs(log.get(2)),
            )
            for log in s.get(11) or []
        ]
        attrs = _tags_to_attrs(s.get(10))
        kind = _KIND_MAP.get(str(attrs.pop("span.kind", "")).lower(),
                             SpanKind.INTERNAL)
        status = StatusCode.UNSET
        if str(attrs.get("error", "")).lower() in ("true", "1"):
            status = StatusCode.ERROR
        start_us = int(s.get(8, 0))
        dur_us = int(s.get(9, 0))
        spans.append(Span(
            trace_id=tid,
            span_id=(int(s.get(3, 0)) & (2**64 - 1)).to_bytes(8, "big"),
            parent_span_id=parent.to_bytes(8, "big") if parent else b"",
            name=(s.get(5) or b"").decode("utf-8", "replace"),
            kind=kind,
            start_unix_nano=start_us * 1000,
            end_unix_nano=(start_us + dur_us) * 1000,
            status_code=status,
            attrs=attrs,
            events=events,
            links=links,
        ))
    return ResourceSpans(
        resource=Resource(attrs=res_attrs),
        scope_spans=[ScopeSpans(scope=Scope(name="jaeger"), spans=spans)],
    )
