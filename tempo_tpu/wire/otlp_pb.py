"""OTLP trace protobuf codec over the generic wire reader/writer.

Implements the public OTLP field numbering
(opentelemetry.proto.trace.v1 / common.v1 / resource.v1, and the
collector ExportTraceServiceRequest whose field 1 is the repeated
ResourceSpans) so encoded traces interoperate with any OTLP exporter.
The reference treats tempopb.Trace as wire-compatible with the export
request the same way (modules/distributor/receiver/shim.go:209-215).
"""

from __future__ import annotations

import struct

from . import pbwire as w
from .model import (
    AnyValue,
    Event,
    Link,
    Resource,
    ResourceSpans,
    Scope,
    ScopeSpans,
    Span,
    Trace,
)

# ---------------------------------------------------------------- AnyValue


def _encode_any_value(v: AnyValue) -> bytes:
    buf = bytearray()
    if isinstance(v, bool):  # before int: bool is an int subclass
        # emit the varint even for False so the oneof arm is present
        w.write_tag(buf, 2, w.WT_VARINT)
        w.write_varint(buf, 1 if v else 0)
    elif isinstance(v, str):
        w.write_string_field(buf, 1, v)
    elif isinstance(v, int):
        w.write_tag(buf, 3, w.WT_VARINT)
        w.write_varint(buf, v)
    elif isinstance(v, float):
        w.write_tag(buf, 4, w.WT_FIXED64)
        buf.extend(struct.pack("<d", v))
    elif isinstance(v, bytes):
        # emit the arm even for b"" so the value keeps its bytes type
        w.write_message_field(buf, 7, v)
    elif isinstance(v, list):
        arr = bytearray()
        for item in v:
            w.write_message_field(arr, 1, _encode_any_value(item))
        w.write_message_field(buf, 5, bytes(arr))
    else:
        w.write_string_field(buf, 1, str(v))
    return bytes(buf)


def _decode_any_value(data: bytes) -> AnyValue:
    for field_no, wt, val in w.iter_fields(data):
        if field_no == 1:
            return val.decode("utf-8", errors="replace")
        if field_no == 2:
            return bool(val)
        if field_no == 3:
            return w.to_signed64(val)
        if field_no == 4:
            return w.fixed64_to_double(val)
        if field_no == 5:
            out = []
            for f2, _, v2 in w.iter_fields(val):
                if f2 == 1:
                    out.append(_decode_any_value(v2))
            return out
        if field_no == 7:
            return val
    return ""


def _encode_kv(k: str, v: AnyValue) -> bytes:
    kv = bytearray()
    w.write_string_field(kv, 1, k)
    w.write_message_field(kv, 2, _encode_any_value(v))
    return bytes(kv)


def _decode_kv(data: bytes) -> tuple[str, AnyValue]:
    key, value = "", ""
    for field_no, _, val in w.iter_fields(data):
        if field_no == 1:
            key = val.decode("utf-8", errors="replace")
        elif field_no == 2:
            value = _decode_any_value(val)
    return key, value


# ---------------------------------------------------------------- Span


def _encode_event(e: Event) -> bytes:
    buf = bytearray()
    w.write_fixed64_field(buf, 1, e.time_unix_nano)
    w.write_string_field(buf, 2, e.name)
    for k, v in e.attrs.items():
        w.write_message_field(buf, 3, _encode_kv(k, v))
    w.write_varint_field(buf, 4, e.dropped_attributes_count)
    return bytes(buf)


def _decode_event(data: bytes) -> Event:
    e = Event()
    for field_no, _, val in w.iter_fields(data):
        if field_no == 1:
            e.time_unix_nano = val
        elif field_no == 2:
            e.name = val.decode("utf-8", errors="replace")
        elif field_no == 3:
            k, v = _decode_kv(val)
            e.attrs[k] = v
        elif field_no == 4:
            e.dropped_attributes_count = val
    return e


def _encode_link(l: Link) -> bytes:
    buf = bytearray()
    w.write_bytes_field(buf, 1, l.trace_id)
    w.write_bytes_field(buf, 2, l.span_id)
    w.write_string_field(buf, 3, l.trace_state)
    for k, v in l.attrs.items():
        w.write_message_field(buf, 4, _encode_kv(k, v))
    return bytes(buf)


def _decode_link(data: bytes) -> Link:
    l = Link()
    for field_no, _, val in w.iter_fields(data):
        if field_no == 1:
            l.trace_id = val
        elif field_no == 2:
            l.span_id = val
        elif field_no == 3:
            l.trace_state = val.decode("utf-8", errors="replace")
        elif field_no == 4:
            k, v = _decode_kv(val)
            l.attrs[k] = v
    return l


def _encode_status(code: int, message: str) -> bytes:
    buf = bytearray()
    w.write_string_field(buf, 2, message)
    w.write_varint_field(buf, 3, code)
    return bytes(buf)


def encode_span(s: Span) -> bytes:
    buf = bytearray()
    w.write_bytes_field(buf, 1, s.trace_id)
    w.write_bytes_field(buf, 2, s.span_id)
    w.write_string_field(buf, 3, s.trace_state)
    w.write_bytes_field(buf, 4, s.parent_span_id)
    w.write_string_field(buf, 5, s.name)
    w.write_varint_field(buf, 6, s.kind)
    w.write_fixed64_field(buf, 7, s.start_unix_nano)
    w.write_fixed64_field(buf, 8, s.end_unix_nano)
    for k, v in s.attrs.items():
        w.write_message_field(buf, 9, _encode_kv(k, v))
    w.write_varint_field(buf, 10, s.dropped_attributes_count)
    for e in s.events:
        w.write_message_field(buf, 11, _encode_event(e))
    for l in s.links:
        w.write_message_field(buf, 13, _encode_link(l))
    if s.status_code or s.status_message:
        w.write_message_field(buf, 15, _encode_status(s.status_code, s.status_message))
    return bytes(buf)


def decode_span(data: bytes) -> Span:
    s = Span()
    for field_no, _, val in w.iter_fields(data):
        if field_no == 1:
            s.trace_id = val
        elif field_no == 2:
            s.span_id = val
        elif field_no == 3:
            s.trace_state = val.decode("utf-8", errors="replace")
        elif field_no == 4:
            s.parent_span_id = val
        elif field_no == 5:
            s.name = val.decode("utf-8", errors="replace")
        elif field_no == 6:
            s.kind = val
        elif field_no == 7:
            s.start_unix_nano = val
        elif field_no == 8:
            s.end_unix_nano = val
        elif field_no == 9:
            k, v = _decode_kv(val)
            s.attrs[k] = v
        elif field_no == 10:
            s.dropped_attributes_count = val
        elif field_no == 11:
            s.events.append(_decode_event(val))
        elif field_no == 13:
            s.links.append(_decode_link(val))
        elif field_no == 15:
            for f2, _, v2 in w.iter_fields(val):
                if f2 == 2:
                    s.status_message = v2.decode("utf-8", errors="replace")
                elif f2 == 3:
                    s.status_code = v2
    return s


# ---------------------------------------------------------------- batches


def _encode_scope(scope: Scope) -> bytes:
    buf = bytearray()
    w.write_string_field(buf, 1, scope.name)
    w.write_string_field(buf, 2, scope.version)
    return bytes(buf)


def _encode_scope_spans(ss: ScopeSpans) -> bytes:
    buf = bytearray()
    if ss.scope.name or ss.scope.version:
        w.write_message_field(buf, 1, _encode_scope(ss.scope))
    for sp in ss.spans:
        w.write_message_field(buf, 2, encode_span(sp))
    return bytes(buf)


def _decode_scope_spans(data: bytes) -> ScopeSpans:
    ss = ScopeSpans()
    for field_no, _, val in w.iter_fields(data):
        if field_no == 1:
            for f2, _, v2 in w.iter_fields(val):
                if f2 == 1:
                    ss.scope.name = v2.decode("utf-8", errors="replace")
                elif f2 == 2:
                    ss.scope.version = v2.decode("utf-8", errors="replace")
        elif field_no == 2:
            ss.spans.append(decode_span(val))
    return ss


def _encode_resource(r: Resource) -> bytes:
    buf = bytearray()
    for k, v in r.attrs.items():
        w.write_message_field(buf, 1, _encode_kv(k, v))
    return bytes(buf)


def _decode_resource(data: bytes) -> Resource:
    r = Resource()
    for field_no, _, val in w.iter_fields(data):
        if field_no == 1:
            k, v = _decode_kv(val)
            r.attrs[k] = v
    return r


def encode_resource_spans(rs: ResourceSpans) -> bytes:
    buf = bytearray()
    w.write_message_field(buf, 1, _encode_resource(rs.resource))
    for ss in rs.scope_spans:
        w.write_message_field(buf, 2, _encode_scope_spans(ss))
    return bytes(buf)


def decode_resource_spans(data: bytes) -> ResourceSpans:
    rs = ResourceSpans()
    for field_no, _, val in w.iter_fields(data):
        if field_no == 1:
            rs.resource = _decode_resource(val)
        elif field_no == 2:
            rs.scope_spans.append(_decode_scope_spans(val))
    return rs


def encode_trace(t: Trace) -> bytes:
    """Encode as ExportTraceServiceRequest-compatible bytes
    (field 1 = repeated ResourceSpans)."""
    buf = bytearray()
    for rs in t.resource_spans:
        w.write_message_field(buf, 1, encode_resource_spans(rs))
    return bytes(buf)


def decode_trace(data: bytes) -> Trace:
    t = Trace()
    for field_no, _, val in w.iter_fields(data):
        if field_no == 1:
            t.resource_spans.append(decode_resource_spans(val))
    return t
