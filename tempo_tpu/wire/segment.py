"""Segment and object codecs: the distributor->ingester->block data path.

Mirrors the reference's two-level codec seam (pkg/model/segment_decoder.go:19-32,
pkg/model/object_decoder.go:21-33): a *segment* is one distributor push for one
trace; an *object* is the concatenation of all segments for a trace as stored
in the WAL / row blocks. Like the reference's v2 codec, segments carry a
start/end-seconds header so time-range filtering never decodes span payloads
("FastRange").

Format "s1":
  segment := 0x01 | uint32le start_sec | uint32le end_sec | otlp_trace_bytes
  object  := repeated (uvarint len | segment)
"""

from __future__ import annotations

import struct

from . import pbwire as w
from .combine import combine_traces
from .model import Trace
from .otlp_pb import decode_trace, encode_trace

CURRENT_VERSION = "s1"
_HDR = struct.Struct("<BII")
_V1 = 0x01


class DecodeError(ValueError):
    pass


def segment_for_write(trace: Trace, start_sec: int, end_sec: int) -> bytes:
    return _HDR.pack(_V1, start_sec & 0xFFFFFFFF, end_sec & 0xFFFFFFFF) + encode_trace(trace)


def segment_fast_range(segment: bytes) -> tuple[int, int]:
    if len(segment) < _HDR.size or segment[0] != _V1:
        raise DecodeError("bad segment header")
    _, start, end = _HDR.unpack_from(segment, 0)
    return start, end


def segment_payload(segment: bytes) -> bytes:
    """The raw otlp-proto trace bytes inside a segment (no decode):
    the generator forward plane ships these blobs verbatim."""
    if len(segment) < _HDR.size or segment[0] != _V1:
        raise DecodeError("bad segment header")
    return segment[_HDR.size :]


def segment_to_trace(segment: bytes) -> Trace:
    if len(segment) < _HDR.size or segment[0] != _V1:
        raise DecodeError("bad segment header")
    return decode_trace(segment[_HDR.size :])


def segments_to_object(segments: list[bytes]) -> bytes:
    buf = bytearray()
    for seg in segments:
        w.write_varint(buf, len(seg))
        buf.extend(seg)
    return bytes(buf)


def object_segments(obj: bytes) -> list[bytes]:
    out = []
    pos = 0
    while pos < len(obj):
        ln, pos = w.read_varint(obj, pos)
        if pos + ln > len(obj):
            raise DecodeError("truncated object segment")
        out.append(obj[pos : pos + ln])
        pos += ln
    return out


def object_to_trace(obj: bytes) -> Trace:
    traces = [segment_to_trace(seg) for seg in object_segments(obj)]
    if len(traces) == 1:
        return traces[0]
    return combine_traces(traces)


def object_fast_range(obj: bytes) -> tuple[int, int]:
    lo, hi = None, None
    for seg in object_segments(obj):
        s, e = segment_fast_range(seg)
        lo = s if lo is None else min(lo, s)
        hi = e if hi is None else max(hi, e)
    if lo is None:
        return 0, 0
    return lo, hi


def combine_objects(a: bytes, b: bytes) -> bytes:
    """Concatenate two objects' segments (cheap combine used by compaction
    when the same trace id appears in two blocks; span-level dedupe happens
    at read time in object_to_trace via combine_traces)."""
    return a + b
