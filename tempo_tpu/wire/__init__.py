from .model import (
    AnyValue,
    Event,
    Link,
    Resource,
    ResourceSpans,
    Scope,
    ScopeSpans,
    Span,
    SpanKind,
    StatusCode,
    Trace,
)
from .otlp_pb import decode_trace, encode_trace
