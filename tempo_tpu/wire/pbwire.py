"""Minimal protobuf wire-format reader/writer.

A generic varint/length-delimited codec implementing the public protobuf
encoding spec. Used by the OTLP codec (otlp_pb.py) so the framework
speaks standard OTLP without a protoc toolchain; the reference instead
ships gogo-proto generated code (pkg/tempopb). This module is a natural
future C++ target (native/), but the Python version is already fast
enough for control-plane-sized messages.

Wire types: 0 varint, 1 fixed64, 2 length-delimited, 5 fixed32.
"""

from __future__ import annotations

import struct
from typing import Iterator

WT_VARINT = 0
WT_FIXED64 = 1
WT_LEN = 2
WT_FIXED32 = 5


def write_varint(buf: bytearray, v: int) -> None:
    if v < 0:
        v &= 0xFFFFFFFFFFFFFFFF  # two's complement 64-bit
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            buf.append(b | 0x80)
        else:
            buf.append(b)
            return


def read_varint(data: bytes, pos: int) -> tuple[int, int]:
    result = 0
    shift = 0
    while True:
        if pos >= len(data):
            raise ValueError("truncated varint")
        b = data[pos]
        pos += 1
        if shift == 63 and (b & 0x7F) > 1:
            raise ValueError("varint exceeds 64 bits")
        result |= (b & 0x7F) << shift
        if not (b & 0x80):
            return result, pos
        shift += 7
        if shift > 63:
            raise ValueError("varint too long")


def tag(field_no: int, wire_type: int) -> int:
    return (field_no << 3) | wire_type


def write_tag(buf: bytearray, field_no: int, wire_type: int) -> None:
    write_varint(buf, tag(field_no, wire_type))


def write_bytes_field(buf: bytearray, field_no: int, data: bytes) -> None:
    if not data:
        return
    write_tag(buf, field_no, WT_LEN)
    write_varint(buf, len(data))
    buf.extend(data)


def write_string_field(buf: bytearray, field_no: int, s: str) -> None:
    if s:
        write_bytes_field(buf, field_no, s.encode("utf-8"))


def write_varint_field(buf: bytearray, field_no: int, v: int) -> None:
    if v:
        write_tag(buf, field_no, WT_VARINT)
        write_varint(buf, v)


def write_fixed64_field(buf: bytearray, field_no: int, v: int) -> None:
    if v:
        write_tag(buf, field_no, WT_FIXED64)
        buf.extend(struct.pack("<Q", v & 0xFFFFFFFFFFFFFFFF))


def write_double_field(buf: bytearray, field_no: int, v: float) -> None:
    if v != 0.0:
        write_tag(buf, field_no, WT_FIXED64)
        buf.extend(struct.pack("<d", v))


def write_message_field(buf: bytearray, field_no: int, msg: bytes) -> None:
    """Write a submessage even when empty (presence-significant)."""
    write_tag(buf, field_no, WT_LEN)
    write_varint(buf, len(msg))
    buf.extend(msg)


def iter_fields(data: bytes) -> Iterator[tuple[int, int, object]]:
    """Yield (field_no, wire_type, value); value is int for varint/fixed,
    bytes for length-delimited."""
    pos = 0
    n = len(data)
    while pos < n:
        t, pos = read_varint(data, pos)
        field_no, wt = t >> 3, t & 7
        if wt == WT_VARINT:
            v, pos = read_varint(data, pos)
            yield field_no, wt, v
        elif wt == WT_FIXED64:
            if pos + 8 > n:
                raise ValueError("truncated fixed64")
            yield field_no, wt, struct.unpack_from("<Q", data, pos)[0]
            pos += 8
        elif wt == WT_LEN:
            ln, pos = read_varint(data, pos)
            if pos + ln > n:
                raise ValueError("truncated length-delimited field")
            yield field_no, wt, bytes(data[pos : pos + ln])
            pos += ln
        elif wt == WT_FIXED32:
            if pos + 4 > n:
                raise ValueError("truncated fixed32")
            yield field_no, wt, struct.unpack_from("<I", data, pos)[0]
            pos += 4
        else:
            raise ValueError(f"unsupported wire type {wt}")


def fixed64_to_double(v: int) -> float:
    return struct.unpack("<d", struct.pack("<Q", v))[0]


def to_signed64(v: int) -> int:
    return v - (1 << 64) if v >= (1 << 63) else v
