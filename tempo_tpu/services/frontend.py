"""Query frontend: per-tenant fair queue, job sharding, retry, combine.

Reference: modules/frontend -- trace-by-ID pipeline (deduper->sharder->
retry, frontend.go:96-183), search sharder (searchsharding.go:69-247),
trace-ID-space sharding (tracebyidsharding.go:30-48), and the per-tenant
queue queriers pull from (v1/frontend.go:50-90, pkg/scheduler/queue).

Jobs carry BOTH a local closure (in-process worker threads, the
single-binary fast path) and a wire form (kind + payload): standalone
querier processes attach over HTTP long-poll (/internal/jobs/poll) and
pull the same queue the local workers drain -- the reference's
querier-worker frontend_processor loop (frontend_processor.go:57-80),
dispatcher and execution fully decoupled.

Search jobs are block BATCHES, not 10-MiB page shards: the device
engine answers a whole batch of blocks in one fused program + one
device sync (db/search.search_blocks_fused), so the unit of dispatch
is sized to amortize the sync, not to bound a Go worker's scan time.
Oversized single blocks still shard by row-group range.

Cache-affinity scheduling: block-carrying jobs hash their lead block ID
onto a consistent-hash ring over the live cache domains (this process's
local worker pool + every attached remote querier), and the dequeue
prefers handing a job to its affinity owner so a block staged in one
querier's HBM (ops/stage staged cache) stays staged there instead of
being re-fetched, re-padded and re-uploaded by whichever worker happens
to poll first. A bounded anti-starvation steal timeout
(TEMPO_AFFINITY_STEAL_MS) lets any worker take a job its owner hasn't
claimed in time, so a slow or dead owner never strands work; with
affinity off (TEMPO_AFFINITY=0) or a single cache domain the dequeue
path is exactly the legacy head-of-queue behavior.
"""

from __future__ import annotations

import os
import threading
import time
import uuid
from collections import deque
from dataclasses import dataclass, field

from ..db.search import (
    SearchRequest,
    SearchResponse,
    request_to_dict,
    response_from_dict,
)
from .. import config_registry as _cfg
from ..ring.ring import InMemoryKV, InstanceDesc, InstanceState, Ring, deterministic_tokens
from ..util.breaker import CircuitOpen, RetryBudget, get_breaker
from ..util.profiler import timed_lock
from ..wire.combine import combine_traces, sort_trace
from .overrides import QueryAdmission
from .querier import Querier

TARGET_BATCH_BYTES = 256 << 20  # block-batch job size (device engine unit)
DEFAULT_CONCURRENT_JOBS = 50
MAX_RETRIES = 3
MAX_BLOCKS_PER_BATCH = 64
FIND_SHARD_BLOCKS = 16  # candidate blocks per ID-shard find job

# job kinds that scan backend blocks: the legs the backend circuit
# breaker guards (a shed search shard degrades coverage via the
# existing failed-shard tolerance; find/metrics fail fast -- their
# shard-loss rules forbid silent partials -- instead of hammering a
# dying backend)
BACKEND_KINDS = frozenset(
    {"search_blocks", "search_block_shard", "find_blocks",
     "metrics_query_range"})

AFFINITY_RING_KEY = "querier-affinity"
AFFINITY_STEAL_MS = 75.0  # default anti-starvation steal timeout
AFFINITY_SCAN_WINDOW = 64  # queued jobs per tenant an affinity scan inspects


class TooManyRequests(Exception):
    pass


def _retryable(e: Exception) -> bool:
    """Transient (IO / backend / timeout) errors retry; deterministic
    failures (parse errors, bad values) fail fast."""
    from ..backend.base import BackendError, DoesNotExist

    if isinstance(e, DoesNotExist):
        return False  # deterministic: the object is gone
    return isinstance(e, (OSError, TimeoutError, ConnectionError, BackendError))


class RequestQueue:
    """Per-tenant fair FIFO: tenants round-robin, jobs FIFO within a
    tenant (pkg/scheduler/queue/queue.go). Drained tenants are pruned
    from the rotation (a churning tenant population used to grow
    self.order without bound, and every dequeue scanned the corpses)."""

    CLAIM_RECHECK_S = 0.02  # re-scan cadence while steal clocks run

    def __init__(self, max_per_tenant: int = 2000):
        # cataloged hot lock: every enqueue/dequeue (and the affinity
        # claim scan) serializes here; TEMPO_LOCK_PROFILE arms wait
        # timing. The Condition wraps the same lock either way.
        self.lock = timed_lock("frontend_queue")
        self.cv = threading.Condition(self.lock)
        self.queues: dict[str, deque] = {}
        self.order: deque[str] = deque()
        self.max_per_tenant = max_per_tenant
        self.closed = False

    def enqueue(self, tenant: str, job) -> None:
        with self.cv:
            q = self.queues.get(tenant)
            if q is None:
                q = self.queues[tenant] = deque()
                self.order.append(tenant)
            if len(q) >= self.max_per_tenant:
                raise TooManyRequests(f"tenant {tenant} queue full")  # 429
            q.append(job)
            try:
                # a re-dispatched job must not carry its previous
                # dequeue's placement: the next dequeue stamps its own
                # (or none, on the legacy path) -- stale "own" would
                # double-count affinity telemetry and misattribute
                # staged-cache lookups on the retry worker
                job.placement = ""
            except AttributeError:
                pass
            if getattr(job, "queued_at", None) == 0.0:
                # steal clock starts at FIRST enqueue only: a hedged
                # twin keeps its original stamp (long past the steal
                # window by hedge time) and a retried job is demoted to
                # placement-free by the retry paths -- re-dispatch
                # exists precisely to dodge the owner that failed it
                job.queued_at = time.monotonic()
            # notify_all, not notify: under affinity a single wakeup can
            # land on a non-owner that defers the job and goes back to
            # waiting -- the sleeping owner would never hear about its
            # own job and every dequeue would pay the steal timeout
            self.cv.notify_all()

    def depths(self) -> dict[str, int]:
        """Per-tenant queue depth snapshot -- the fleet's autoscaling
        SLI (tempo_query_queue_depth): sustained depth means the
        querier pool is under-provisioned for the offered load."""
        with self.cv:
            return {t: len(q) for t, q in self.queues.items() if q}

    def _prune_locked(self, tenant: str, q) -> None:
        """Drop a drained tenant from both maps (invariant: a tenant is
        in self.order iff it has a non-empty deque)."""
        if not q:
            self.queues.pop(tenant, None)
            try:
                self.order.remove(tenant)
            except ValueError:
                pass

    def dequeue(self, timeout: float = 0.5, allowed=None, claim=None):
        """Next (tenant, job), fair across tenants; allowed(tenant) False
        skips a tenant for THIS caller (per-tenant querier shuffle-shard,
        pkg/scheduler/queue/user_queues.go). claim(tenant, job, now) ->
        placement string | None gates WHICH job this caller may take (block->
        querier affinity): the first claimable job within
        AFFINITY_SCAN_WINDOW of each tenant's FIFO is taken and stamped
        with its placement; jobs deferred to their owner are re-checked
        every CLAIM_RECHECK_S so steal timeouts fire without a notify.
        claim=None (affinity off / single cache domain) is exactly the
        legacy head-of-queue path."""
        with self.cv:
            if claim is None:
                while True:
                    item = self._take_head_locked(allowed)
                    if item is not None:
                        return item
                    if self.closed:
                        return None
                    if not self.cv.wait(timeout):
                        return None
            deadline = time.monotonic() + timeout
            while True:
                item, deferred = self._take_claimed_locked(allowed, claim)
                if item is not None:
                    return item
                if self.closed:
                    return None
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return None
                self.cv.wait(min(remaining, self.CLAIM_RECHECK_S)
                             if deferred else remaining)

    def _take_head_locked(self, allowed):
        """One fair pass taking the head job of the first allowed
        tenant -- the pre-affinity dequeue, byte for byte."""
        n = len(self.order)
        scanned = 0
        while scanned < n:
            tenant = self.order[0]
            q = self.queues.get(tenant)
            if not q:
                # drained (or orphaned) rotation slot: prune it
                self.order.popleft()
                self.queues.pop(tenant, None)
                n -= 1
                continue
            self.order.rotate(-1)
            scanned += 1
            if allowed is None or allowed(tenant):
                job = q.popleft()
                self._prune_locked(tenant, q)
                return tenant, job
        return None

    def _take_claimed_locked(self, allowed, claim):
        """One fair pass under affinity: per tenant, take the first job
        (within the scan window) the claimer may have. claim(tenant,
        job, now) sees the tenant because ownership is resolved within
        the tenant's reachable worker subset (querier shuffle-shard).
        Returns ((tenant, job), False) or (None, deferred) where
        deferred means jobs exist that only their owner (or the steal
        clock) can release."""
        now = time.monotonic()
        deferred = False
        n = len(self.order)
        scanned = 0
        while scanned < n:
            tenant = self.order[0]
            q = self.queues.get(tenant)
            if not q:
                self.order.popleft()
                self.queues.pop(tenant, None)
                n -= 1
                continue
            self.order.rotate(-1)
            scanned += 1
            if allowed is not None and not allowed(tenant):
                continue
            for i, job in enumerate(q):
                if i >= AFFINITY_SCAN_WINDOW:
                    break
                placement = claim(tenant, job, now)
                if placement:
                    del q[i]
                    try:
                        job.placement = placement
                    except AttributeError:
                        pass
                    self._prune_locked(tenant, q)
                    return (tenant, job), False
            deferred = True
        return None, deferred

    def dequeue_batch(self, timeout: float = 0.5, allowed=None,
                      max_batch: int = 1, key_fn=None, claim=None):
        """Fair dequeue of one job plus up to max_batch-1 ALREADY-QUEUED
        jobs sharing its coalesce key (key_fn(job), None = unbatchable),
        collected in one pass over the tenant rotation -- fairness within
        the window means every tenant's matching head jobs join the same
        fused launch rather than queueing behind it. Never waits for
        more jobs, so a lone query is never delayed here (the admission
        window lives in db/batchexec). Under affinity (claim) the
        same-key extras ride the lead's claim wherever they sit in the
        scan window: same blocks means same owner, so a coalesced
        multi-query launch lands whole on the warm staged cache.
        Returns (tenant, job, extras) where extras is a list of
        (tenant, job)."""
        item = self.dequeue(timeout, allowed, claim=claim)
        if item is None:
            return None
        tenant, job = item
        extras: list = []
        key = key_fn(job) if key_fn is not None else None
        if key is not None and max_batch > 1:
            lead_placement = getattr(job, "placement", "")
            with self.cv:
                for _ in range(len(self.order)):
                    if len(extras) >= max_batch - 1 or not self.order:
                        break
                    t2 = self.order[0]
                    self.order.rotate(-1)
                    q = self.queues.get(t2)
                    if not q or (allowed is not None and not allowed(t2)):
                        continue
                    if claim is None:
                        while (q and len(extras) < max_batch - 1
                               and key_fn(q[0]) == key):
                            extras.append((t2, q.popleft()))
                    else:
                        i = 0
                        while (i < min(len(q), AFFINITY_SCAN_WINDOW)
                               and len(extras) < max_batch - 1):
                            if key_fn(q[i]) == key:
                                j2 = q[i]
                                del q[i]
                                try:
                                    j2.placement = lead_placement
                                except AttributeError:
                                    pass
                                extras.append((t2, j2))
                            else:
                                i += 1
                    self._prune_locked(t2, q)
        return tenant, job, extras

    def close(self):
        with self.cv:
            self.closed = True
            self.cv.notify_all()


@dataclass
class _Job:
    kind: str  # wire kind: search_recent|search_blocks|search_block_shard|
    # find_recent|find_blocks
    payload: dict  # wire-shippable arguments (ids, not objects)
    fn: object  # local execution closure (in-process workers)
    args: tuple
    result: object = None
    error: Exception | None = None
    done: threading.Event = field(default_factory=threading.Event)
    tries: int = 0
    cancelled: bool = False
    hedged: bool = False
    enqueued_at: float = 0.0  # monotonic (hedging)
    started_wall: float = 0.0  # wall clock (self-trace spans)
    done_at: float = 0.0  # wall clock
    batch_cv: threading.Condition | None = None
    # active SelfTracer trace, parked in the kerneltel contextvar around
    # local execution so engine code can attach per-block kernel spans;
    # span_id is this job's PRE-ASSIGNED span in that trace (engine
    # spans nest under it; remote legs parent onto it over the wire),
    # dequeued_wall closes the queue-wait span
    trace: object = None
    span_id: bytes = b""
    dequeued_wall: float = 0.0
    # cross-query coalescing: jobs sharing a non-None batch_key target
    # the same data unit (block batch / shard / candidate partition) and
    # may execute together via batch_fn(group) -> list of results
    batch_key: tuple | None = None
    batch_fn: object = None
    # progressive delivery: a streaming collector's condition, notified
    # (in addition to batch_cv) whenever this job finishes so partial
    # results flush to the client as each shard completes
    stream_cv: threading.Condition | None = None
    # cache-affinity scheduling: the block ID this job's placement
    # hashes on (None = placement-free, claimable by anyone), the
    # monotonic stamp its steal clock runs from (set at first enqueue),
    # and the dequeue outcome ("own"/"steal"/"unowned") it executed under
    affinity_key: str | None = None
    queued_at: float = 0.0
    placement: str = ""
    # resilience plane: the query-wide retry budget this job draws
    # from, the caller's wall-clock deadline (rides the wire job so
    # remote workers skip work nobody can use), and hedge attribution
    # (exec_seq counts dispatches; the leg that lands the result says
    # whether the hedge twin won, lost, or never even started)
    retry_budget: object = None
    deadline_unix: float = 0.0
    exec_seq: int = 0
    hedge_started: bool = False
    hedge_outcome: str = ""
    lease_redispatched: bool = False  # re-enqueued by lease expiry

    def finish(self) -> None:
        if not self.done.is_set():  # a late hedge twin must not clobber
            self.done_at = time.time()  # the winner's end time
        self.done.set()
        cv = self.batch_cv
        if cv is not None:
            with cv:
                cv.notify_all()
        scv = self.stream_cv
        if scv is not None:
            with scv:
                scv.notify_all()


def attach_trace(jobs: list, trace) -> None:
    """Bind jobs to the active self-trace, pre-assigning each job's
    span id so the span EXISTS as an address before the job runs:
    nested engine spans and remote-leg spans parent onto it, and
    _emit_self_trace materializes it retroactively with the measured
    times."""
    if trace is None:
        return
    for j in jobs:
        j.trace = trace
        j.span_id = os.urandom(8)


def decode_job_result(kind: str, out: dict):
    """Wire result -> the object the local closure would have returned."""
    if kind == "metrics_query_range":
        from ..db.metrics_exec import response_from_dict as metrics_response_from_dict

        return metrics_response_from_dict(out)
    if kind.startswith("search"):
        return response_from_dict(out)
    tr = out.get("trace")
    if not tr:
        return None
    from ..wire import otlp_json

    return otlp_json.loads(tr)


class Frontend:
    """Owns the queue + sharding logic; local worker threads and remote
    querier processes both pull from the queue."""

    def __init__(self, querier: Querier, n_workers: int = 8,
                 concurrent_jobs: int = DEFAULT_CONCURRENT_JOBS,
                 batch_bytes: int = TARGET_BATCH_BYTES,
                 hedge_after_s: float = 2.0,
                 lease_s: float = 30.0,
                 overrides=None,
                 worker_expiry_s: float = 60.0,
                 affinity: bool | None = None,
                 affinity_steal_ms: float | None = None):
        self.querier = querier
        self.queue = RequestQueue()
        self.concurrent_jobs = concurrent_jobs
        self.batch_bytes = batch_bytes
        self.hedge_after_s = hedge_after_s
        self.lease_s = lease_s
        self.overrides = overrides
        self.worker_expiry_s = worker_expiry_s
        # block->querier affinity routing (None = TEMPO_AFFINITY env,
        # default on; it is a no-op until a second cache domain appears)
        if affinity is None:
            affinity = os.environ.get("TEMPO_AFFINITY", "") != "0"
        self.affinity_enabled = affinity
        if affinity_steal_ms is None:
            try:
                affinity_steal_ms = float(
                    os.environ.get("TEMPO_AFFINITY_STEAL_MS", AFFINITY_STEAL_MS))
            except ValueError:
                affinity_steal_ms = AFFINITY_STEAL_MS
        self.affinity_steal_ms = affinity_steal_ms
        self._aff_ring = Ring(InMemoryKV(), AFFINITY_RING_KEY)
        self._aff_descs: dict[str, InstanceDesc] = {}  # member -> tokens
        self._local_member = "local" if n_workers > 0 else None
        # per-tenant read QoS (concurrency / inflight-byte budgets):
        # overrides-driven, so without overrides there is no gate
        self.qos = QueryAdmission(overrides) if overrides is not None else None
        self._remote_workers: dict[str, float] = {}  # worker id -> last poll
        # backend-leg circuit breaker (util/breaker): block-scanning
        # jobs shed fast onto the shard-degradation path while the
        # backend is dying, with half-open probes for recovery
        self.backend_breaker = get_breaker("backend")
        # lease id -> ([(tenant, job), ...], expiry, [exec_seq, ...]);
        # a `multi` wire job leases its whole merged batch under one id
        self._leases: dict[str, tuple] = {}
        self._lease_lock = threading.Lock()
        self.stats_jobs_remote = 0
        self.stats_jobs_local = 0
        from ..util.metrics import Histogram

        self.query_latency = Histogram("tempo_frontend_query_duration_seconds")
        self.self_tracer = None  # set by the app when self-tracing is on
        # Tier A result cache, AHEAD of queue admission: a hit answers
        # without touching QoS budgets, the queue, or a device. With
        # TEMPO_RESULT_CACHE=0 no cache object exists at all and every
        # query path below is byte-identical to a cacheless build. The
        # app points live_gen at the local ingester when one exists.
        if _cfg.get_bool("TEMPO_RESULT_CACHE"):
            from .resultcache import ResultCache

            # blocklists without a generation feed (stub queriers in
            # tests, exotic embeddings) get a constant generation: the
            # cache still keys correctly, it just can't observe block
            # churn -- real db.Blocklist always provides one
            bl_gen = getattr(
                getattr(querier.db, "blocklist", None), "generation", None)
            self.result_cache = ResultCache(
                blocklist_gen=bl_gen or (lambda t: 0))
        else:
            self.result_cache = None
        self._workers = [
            threading.Thread(target=self._worker, daemon=True, name=f"frontend-worker-{i}")
            for i in range(n_workers)
        ]
        for w in self._workers:
            w.start()

    def _emit_self_trace(self, jobs: list[_Job], t) -> None:
        """Materialize the per-job spans of the active trace: one span
        per dispatched job at its PRE-ASSIGNED id (engine/remote spans
        already parent onto it), with the enqueue->dequeue wait as a
        child -- the queue-wait leg of the timeline."""
        for j in jobs:
            if not (j.started_wall and j.done_at):
                continue
            attrs = {"cancelled": j.cancelled, "hedged": j.hedged,
                     "error": j.error is not None}
            if j.hedge_outcome:
                attrs["hedge"] = j.hedge_outcome  # win | lose | unneeded
            if j.tries:
                attrs["tries"] = j.tries
            if j.placement:
                attrs["placement"] = j.placement
            sid = t.child(f"job:{j.kind}", j.started_wall, j.done_at, attrs,
                          parent=t.root_id, span_id=j.span_id or None)
            if j.dequeued_wall and j.dequeued_wall >= j.started_wall:
                t.child("queue-wait", j.started_wall, j.dequeued_wall,
                        {}, parent=sid)

    # --------------------------------------------------- affinity routing
    def _affinity_members(self) -> list[InstanceDesc]:
        """The live cache domains jobs can be placed on: this process
        (when it runs local workers -- its threads share one staged
        cache) plus every remote querier that polled within
        worker_expiry_s. Token sets are deterministic per member id, so
        every frontend replica computes the same placement."""
        now = time.monotonic()
        members = [self._local_member] if self._local_member else []
        out = []
        with self._lease_lock:
            remote = [w for w, t in self._remote_workers.items()
                      if now - t < self.worker_expiry_s]
            members += sorted(remote)
            live = set(members)
            for m in list(self._aff_descs):
                if m not in live:  # churned worker ids must not accumulate
                    del self._aff_descs[m]
            for m in members:
                d = self._aff_descs.get(m)
                if d is None:
                    d = self._aff_descs[m] = InstanceDesc(
                        instance_id=m, state=InstanceState.ACTIVE,
                        tokens=deterministic_tokens(AFFINITY_RING_KEY, m))
                out.append(d)
        return out

    def _claimer(self, member: str):
        """Build claim(tenant, job, now) for one dequeue pass by
        `member`, or None when affinity is off or there is at most one
        cache domain (the legacy dequeue, preserved exactly). Ownership
        is resolved within the tenant's REACHABLE domains: with querier
        shuffle-shard on, a block is placed on the shard-member subset
        (plus the local pool, which shuffle-shard never filters), never
        on a worker the tenant's jobs can't be handed to -- otherwise
        every such job would pay the full steal timeout for an owner
        that can never claim it. Lookups memoize per pass -- one ring
        walk per distinct (tenant, block) per dequeue."""
        if not self.affinity_enabled or not member:
            return None
        members = self._affinity_members()
        if len(members) <= 1:
            return None
        ring = self._aff_ring
        steal_s = self.affinity_steal_ms / 1000.0
        owners: dict[tuple[str, str], str | None] = {}
        shards: dict[str, list[InstanceDesc]] = {}

        def shard_members(tenant: str) -> list[InstanceDesc]:
            ms = shards.get(tenant)
            if ms is None:
                ms = shards[tenant] = [
                    d for d in members
                    if d.instance_id == self._local_member
                    or self._tenant_allowed(tenant, d.instance_id)]
            return ms

        def claim(tenant: str, job, now: float) -> str | None:
            key = getattr(job, "affinity_key", None)
            if not key:
                return "unowned"
            ck = (tenant, key)
            if ck in owners:
                owner = owners[ck]
            else:
                owner = owners[ck] = ring.owner_of(
                    key, instances=shard_members(tenant))
            if owner == member:
                return "own"
            if owner is None:
                return "unowned"
            queued = getattr(job, "queued_at", 0.0)
            if queued and now - queued < steal_s:
                return None  # owner's job; steal clock still running
            return "steal"

        return claim

    def _note_placements(self, jobs: list) -> None:
        """Count dequeue placements (kerneltel affinity counters)."""
        from ..util.kerneltel import TEL

        for j in jobs:
            p = getattr(j, "placement", "")
            if p:
                TEL.record_affinity(p)

    # ------------------------------------------------------ per-tenant QoS
    def _qos_admit(self, tenant: str, est_bytes: int) -> int:
        """Admit one query against the tenant's QoS budgets; returns the
        byte charge release() must return (0 when no gate is wired).
        Sheds with TooManyRequests (the HTTP layer's 429)."""
        if self.qos is None:
            return 0
        refused = self.qos.try_admit(tenant, est_bytes)
        if refused is not None:
            from ..util.kerneltel import TEL

            TEL.record_shed(tenant, refused)
            raise TooManyRequests(
                f"tenant {tenant} over per-tenant {refused} budget")
        return est_bytes

    def _qos_release(self, tenant: str, est_bytes: int) -> None:
        if self.qos is not None:
            self.qos.release(tenant, est_bytes)

    # ------------------------------------------------------- local workers
    WORKER_DEQUEUE_BATCH = 16  # same-key jobs one worker drains per pull

    def _worker(self):
        while True:
            item = self.queue.dequeue_batch(
                timeout=1.0, max_batch=self.WORKER_DEQUEUE_BATCH,
                key_fn=lambda j: j.batch_key,
                claim=self._claimer(self._local_member or ""))
            if item is None:
                if self.queue.closed:
                    return
                continue
            tenant, job, extras = item
            self._note_placements([job] + [j for _, j in extras])
            if extras and job.batch_fn is not None:
                self._execute_batch([(tenant, job)] + extras)
                continue
            self._execute_one(tenant, job)
            for t2, j2 in extras:  # batch_fn-less jobs never batch
                self._execute_one(t2, j2)

    def _execute_batch(self, group: list) -> None:
        """Run same-key jobs as ONE multi-job call (the coalesced db
        APIs); any failure degrades to per-job execution so a batch is
        never worse than the jobs run singly. Only the lead job's
        self-trace is parked (the fused launch is one device step)."""
        live = []
        for t, j in group:
            if j.cancelled or j.done.is_set():
                j.finish()
            else:
                live.append((t, j))
        if not live:
            return
        from ..util.kerneltel import TEL
        from .selftrace import reset_current_span, set_current_span

        br = self._breaker_for(live[0][1].kind)
        if br is not None and br.state != "closed":
            # open/half-open: run the group per job so breaker probe
            # accounting stays one allow() per call -- a fused batch
            # would ram N block scans through one half-open probe slot
            # (and close the breaker off N records from one grant)
            for t, j in live:
                self._execute_one(t, j)
            return
        now_wall = time.time()
        seqs: dict[int, int] = {}
        for _, j in live:
            if not j.dequeued_wall:
                j.dequeued_wall = now_wall
            j.exec_seq += 1
            seqs[id(j)] = j.exec_seq
            if j.exec_seq >= 2:
                j.hedge_started = True
        lead = live[0][1]
        token = (TEL.set_active_trace(lead.trace)
                 if lead.trace is not None else None)
        stoken = (set_current_span(lead.span_id)
                  if lead.trace is not None and lead.span_id else None)
        ptoken = TEL.set_affinity_placement(lead.placement)
        results = None
        try:
            results = lead.batch_fn(live)
        except Exception:
            results = None
        finally:
            TEL.reset_affinity_placement(ptoken)
            if stoken is not None:
                reset_current_span(stoken)
            if token is not None:
                TEL.reset_active_trace(token)
        # window mates rode the lead's fused launch: stamp each mate's
        # OWN trace with a span under its job span naming the lead, so
        # every coalesced query's timeline shows where its device step
        # actually ran (the batch-window propagation contract)
        t1_wall = time.time()
        for _, j in live[1:]:
            if j.trace is not None and j.trace is not lead.trace:
                j.trace.child(
                    "batch:ride", now_wall, t1_wall,
                    {"lead_trace": (lead.trace.trace_id.hex()
                                    if lead.trace is not None else ""),
                     "occupancy": len(live)},
                    parent=j.span_id or None)
        if isinstance(results, list) and len(results) == len(live):
            for (t, j), r in zip(live, results):
                if isinstance(r, Exception):
                    # per-item failure inside the batch: same retry
                    # policy as single execution, isolated to this job
                    if br is not None and _retryable(r):
                        br.record(False)
                    self._fail_job(t, j, r)
                    continue
                if br is not None:
                    br.record(True)
                self._note_result(j, seqs.get(id(j), 1))
                if not j.done.is_set():
                    j.result = r
                self.stats_jobs_local += 1
                j.finish()
        else:
            for t, j in live:
                self._execute_one(t, j)

    def _breaker_for(self, kind: str):
        """The backend-leg breaker for block-scanning kinds (lazy: a
        partially-built Frontend -- tests use __new__ -- still gets
        one on first use)."""
        if kind not in BACKEND_KINDS:
            return None
        br = getattr(self, "backend_breaker", None)
        if br is None:
            br = self.backend_breaker = get_breaker("backend")
        return br

    def _grant_retry(self, job) -> bool:
        """One more dispatch for a retryable shard failure? The per-
        query RetryBudget caps TOTAL retries across all of a query's
        jobs, so a dying backend can't amplify one query into a
        jobs x MAX_RETRIES storm."""
        from ..util.kerneltel import TEL

        b = job.retry_budget
        if b is None or b.take():
            TEL.record_retry("retry")
            return True
        TEL.record_retry("budget_exhausted")
        return False

    def _note_result(self, job, seq: int) -> None:
        """Hedge attribution, called by the execution leg that produced
        a result BEFORE publishing it: on the first completion of a
        hedged job, say whether the twin won (seq >= 2), lost (the
        original won after the twin started), or was unneeded (the
        original won before the twin ever ran). A job that also
        RETRIED is left unattributed: retry re-dispatches share the
        exec_seq counter, so a retry completion would masquerade as a
        hedge win exactly in the fault regimes hedging is watched in."""
        if not job.hedged or job.done.is_set() or job.hedge_outcome:
            return
        if job.tries or job.lease_redispatched:
            # retries and lease-expiry redispatches share exec_seq, so
            # their completions would masquerade as hedge wins exactly
            # in the fault regimes this metric is watched in
            return
        from ..util.kerneltel import TEL

        if seq >= 2:
            outcome = "win"
        elif job.exec_seq >= 2 or job.hedge_started:
            outcome = "lose"
        else:
            outcome = "unneeded"
        job.hedge_outcome = outcome
        TEL.record_hedge(outcome)

    def _fail_job(self, tenant: str, job, e: Exception) -> None:
        """Apply the single-job failure policy (transient -> re-enqueue
        up to MAX_RETRIES within the query's retry budget, else error)
        to one job."""
        if job.done.is_set():
            return
        job.tries += 1
        if _retryable(e) and job.tries < MAX_RETRIES and self._grant_retry(job):
            try:
                job.affinity_key = None  # retry dodges the failing owner
                self.queue.enqueue(tenant, job)
                return
            except TooManyRequests:
                pass
        # re-check: a hedge twin may have succeeded while we attempted
        # the re-enqueue -- its result must not be clobbered with an
        # error the waiter would raise
        if not job.done.is_set():
            job.error = e
        job.finish()

    def _execute_one(self, tenant: str, job) -> None:
        if job.cancelled or job.done.is_set():
            job.finish()
            return
        if job.deadline_unix and time.time() > job.deadline_unix:
            # the caller's deadline already passed: don't burn an
            # engine pass nobody can use. Stamp the SAME TimeoutError
            # the dispatch deadline does -- a silently-cancelled shard
            # would let find/metrics return partial results their
            # shard-loss rule forbids
            job.error = TimeoutError("query deadline exceeded before "
                                     "execution")
            job.cancelled = True
            job.finish()
            return
        from ..util.kerneltel import TEL
        from .selftrace import reset_current_span, set_current_span

        br = self._breaker_for(job.kind)
        if br is not None and not br.allow():
            # shed fast onto the shard-degradation path: search merges
            # what the healthy shards return; CircuitOpen is not
            # retryable, so the job never re-enters the open breaker
            job.error = CircuitOpen("backend circuit breaker open")
            job.finish()
            return
        job.exec_seq += 1
        seq = job.exec_seq
        if seq >= 2:
            job.hedge_started = True
        if not job.dequeued_wall:
            job.dequeued_wall = time.time()
        token = (TEL.set_active_trace(job.trace)
                 if job.trace is not None else None)
        stoken = (set_current_span(job.span_id)
                  if job.trace is not None and job.span_id else None)
        ptoken = TEL.set_affinity_placement(getattr(job, "placement", ""))
        try:
            res = job.fn(*job.args)
            if br is not None:
                br.record(True)
            self._note_result(job, seq)
            if not job.done.is_set():
                job.result = res
            self.stats_jobs_local += 1
        except Exception as e:
            # retry only transient failures (reference retries 5xx
            # only, modules/frontend/retry.go); a parse error or bad
            # argument fails identically every try. A hedge twin's
            # failure must never clobber its sibling's success.
            # Breaker food is TRANSIENT IO failures only: a device
            # fault / bad query failing a block job says nothing about
            # backend health and must not open the backend leg.
            if br is not None and _retryable(e):
                br.record(False)
            self._fail_job(tenant, job, e)
            return
        finally:
            TEL.reset_affinity_placement(ptoken)
            if stoken is not None:
                reset_current_span(stoken)
            if token is not None:
                TEL.reset_active_trace(token)
        job.finish()

    # -------------------------------------------- coalesced job execution
    def _batch_search_blocks(self, group: list) -> list:
        """Same-key search_blocks jobs -> one multi-request db call (the
        batching executor fuses eligible ones into one launch)."""
        return self.querier.search_blocks_multi(
            [(j.args[0], j.args[1], j.args[2]) for _, j in group])

    def _batch_search_shards(self, group: list) -> list:
        return self.querier.search_block_shard_multi(
            [(j.args[0], j.args[1], j.args[2], j.args[3]) for _, j in group])

    def _batch_find_blocks(self, group: list) -> list:
        return self.querier.find_in_blocks_multi(
            [(j.args[0], j.args[1], j.args[2]) for _, j in group])

    # ------------------------------------------------ remote querier pull
    def _tenant_allowed(self, tenant: str, worker_id: str) -> bool:
        """Per-tenant querier shuffle-shard: with max_queriers_per_tenant
        set, each tenant's jobs go to a deterministic subset of the
        currently-attached workers (user_queues.go). Subsets re-shuffle
        as workers come and go, and every tenant always has at least one
        live assigned worker by construction."""
        if not worker_id or self.overrides is None:
            return True
        k = self.overrides.for_tenant(tenant).max_queriers_per_tenant
        if k <= 0:
            return True
        now = time.monotonic()
        with self._lease_lock:
            self._remote_workers = {
                w: t for w, t in self._remote_workers.items()
                if now - t < self.worker_expiry_s
            }
            workers = sorted(self._remote_workers)
        if k >= len(workers):
            return True
        import random

        from ..util.hashing import fnv1a_32

        rng = random.Random(fnv1a_32(tenant.encode()))
        return worker_id in rng.sample(workers, k)

    REMOTE_BATCH_MAX = 8  # same-key jobs merged into one wire pull

    def poll_job(self, wait_s: float = 5.0, worker_id: str = ""):
        """Long-poll dequeue for a remote querier worker
        (frontend_processor.go's stream recv). Returns a wire job dict
        or None on timeout. Same-key jobs queued at poll time merge into
        ONE `multi` wire job (the remote face of the batch-aware
        dequeue), leased together. Expired leases re-enter the queue
        first. Affinity: this worker prefers jobs whose block hashes to
        it on the cache-domain ring; a peer's jobs become claimable only
        past the steal timeout. The wire job carries the dequeue
        placement so the remote process attributes its staged-cache
        hits."""
        if worker_id:
            with self._lease_lock:
                self._remote_workers[worker_id] = time.monotonic()
        self._requeue_expired()
        allowed = (lambda t: self._tenant_allowed(t, worker_id)) if worker_id else None
        deadline = time.monotonic() + wait_s
        while True:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                return None
            item = self.queue.dequeue_batch(
                timeout=min(remaining, 1.0), allowed=allowed,
                max_batch=self.REMOTE_BATCH_MAX,
                key_fn=lambda j: j.batch_key,
                claim=self._claimer(worker_id))
            if item is None:
                if self.queue.closed:
                    return None
                continue
            tenant, job, extras = item
            pairs = []
            for t, j in [(tenant, job)] + list(extras):
                if j.cancelled or j.done.is_set():
                    j.finish()
                elif (j.kind in BACKEND_KINDS
                      and not self._breaker_for(j.kind).allow()):
                    # remote pulls shed at the same breaker as local
                    # workers: an open backend breaker means NOBODY
                    # scans blocks, not just this process
                    j.error = CircuitOpen("backend circuit breaker open")
                    j.finish()
                else:
                    pairs.append((t, j))
            if not pairs:
                continue
            self._note_placements([j for _, j in pairs])
            now_wall = time.time()
            seqs = []
            for _, j in pairs:
                if not j.dequeued_wall:
                    j.dequeued_wall = now_wall
                j.exec_seq += 1
                seqs.append(j.exec_seq)
                if j.exec_seq >= 2:
                    j.hedge_started = True
            jid = uuid.uuid4().hex
            with self._lease_lock:
                self._leases[jid] = (pairs, time.monotonic() + self.lease_s,
                                     seqs)
            placement = pairs[0][1].placement
            # deadline propagation, gRPC-style RELATIVE budget: the
            # remaining seconds at dispatch ride the wire job, so the
            # worker's skip decision never depends on clock agreement
            # between the two hosts (an absolute unix deadline would
            # silently shrink -- or zero -- under NTP skew). A merged
            # multi job spans SEVERAL queries, so it carries the MAX:
            # the worker may only skip when every window-mate's caller
            # has given up -- min() would let one expired straggler
            # poison fresh queries merged into its window
            deadlines = [j.deadline_unix for _, j in pairs
                         if j.deadline_unix]
            deadline_in_s = (round(max(deadlines) - time.time(), 3)
                             if deadlines else None)
            # self-trace propagation: the remote leg records its spans
            # against (trace_id, parent=this job's span) and ships them
            # back with the result -- one timeline tree, wherever the
            # leg ran. A multi job rides the LEAD's context (the fused
            # launch is one device step, same as local batch execution).
            lead = pairs[0][1]
            trace_ctx = (lead.trace.wire_context(lead.span_id or None)
                         if lead.trace is not None else None)
            if len(pairs) == 1:
                t0, j0 = pairs[0]
                return {"id": jid, "tenant": t0, "kind": j0.kind,
                        "payload": j0.payload, "placement": placement,
                        "deadline_in_s": deadline_in_s,
                        "trace": trace_ctx}
            return {"id": jid, "tenant": pairs[0][0], "kind": "multi",
                    "placement": placement, "trace": trace_ctx,
                    "deadline_in_s": deadline_in_s,
                    "payload": {"kind": pairs[0][1].kind,
                                "tenants": [t for t, _ in pairs],
                                "jobs": [j.payload for _, j in pairs]}}

    def complete_job(self, jid: str, ok: bool, result: dict | None = None,
                     error: str = "", retryable: bool = False,
                     self_spans: list | None = None,
                     skipped: bool = False) -> None:
        """Remote worker posts a job result (or a `multi` result list,
        demuxed per leased job). Unknown/expired lease ids are dropped
        (the job was re-dispatched or timed out). self_spans: the remote
        leg's recorded timeline spans, grafted into the lead job's
        trace (they were recorded against its span ids)."""
        with self._lease_lock:
            lease = self._leases.pop(jid, None)
        if lease is None:
            return
        pairs, _, lease_seqs = lease
        if self_spans:
            lead = pairs[0][1]
            if lead.trace is not None:
                lead.trace.add_remote_spans(self_spans)
        # whether this result actually EXERCISED the backend: worker-
        # side deadline skips and (below) undecodable/short results are
        # client/worker faults -- feeding them to the backend breaker
        # would let a backlogged queue or a buggy worker trip it and
        # shed block scans while the object store is perfectly healthy
        backend_exercised = not skipped
        results: list = [result or {}]
        if ok and len(pairs) > 1:
            results = (result or {}).get("results") or []
            if len(results) != len(pairs):
                ok, retryable = False, True
                error = error or "multi result arity mismatch"
                backend_exercised = False
        for i, (tenant, job) in enumerate(pairs):
            if job.done.is_set():
                continue
            job_ok, job_retryable, job_error = ok, retryable, error
            job_exercised = backend_exercised
            # results may be short (worker posted ok=False, or a multi
            # arity mismatch): never index past it -- every leased job
            # must still reach the retry/fail policy below, not hang
            # until the dispatch deadline on an IndexError
            if len(pairs) == 1:
                res_i = results[0]
            else:
                res_i = results[i] if i < len(results) else None
            if job_ok and isinstance(res_i, dict) and "__job_error__" in res_i:
                # per-job failure marker from a multi worker: only THIS
                # job fails/retries, its window-mates keep their results
                job_ok = False
                job_retryable = bool(res_i.get("__retryable__"))
                job_error = str(res_i["__job_error__"])
            elif job_ok:
                try:
                    decoded = decode_job_result(job.kind, res_i)
                except Exception as e:  # malformed result from a buggy
                    # worker: treat as a retryable failure so the request
                    # doesn't hang with its lease already popped
                    job_ok, job_retryable = False, True
                    job_error = f"undecodable result: {e}"
                    job_exercised = False  # worker bug, not a backend one
                else:
                    self._note_result(
                        job, lease_seqs[i] if i < len(lease_seqs) else 1)
                    job.result = decoded
                    self.stats_jobs_remote += 1
            # breaker food is results that exercised the backend AND
            # (on failure) look transient -- deterministic failures
            # (bad query, missing object) say nothing about its health
            if job_exercised and (job_ok or job_retryable):
                br = self._breaker_for(job.kind)
                if br is not None:
                    br.record(job_ok)
            if not job_ok:
                job.tries += 1
                if (job_retryable and job.tries < MAX_RETRIES
                        and self._grant_retry(job)):
                    try:
                        # demote to placement-free: a sick-but-alive
                        # owner polls fastest right after failing and
                        # would win its own job back every retry inside
                        # the steal window
                        job.affinity_key = None
                        self.queue.enqueue(tenant, job)
                        continue
                    except TooManyRequests:
                        pass
                job.error = RuntimeError(job_error or "remote job failed")
            job.finish()

    def _requeue_expired(self) -> None:
        now = time.monotonic()
        expired = []
        with self._lease_lock:
            for jid, (pairs, exp, _seqs) in list(self._leases.items()):
                if exp < now:
                    expired.extend(pairs)
                    del self._leases[jid]
        for tenant, job in expired:
            if not (job.done.is_set() or job.cancelled):
                try:
                    job.lease_redispatched = True
                    self.queue.enqueue(tenant, job)
                except TooManyRequests:
                    job.error = TimeoutError("job lease expired, queue full")
                    job.finish()

    # ---------------------------------------------------------- dispatch
    @staticmethod
    def _retry_budget_total(n_jobs: int) -> int:
        """Per-query retry cap: enough to absorb transient faults on a
        few shards, sublinear in fan-out so a dying backend sees
        additive (not multiplicative) retry load. TEMPO_RETRY_BUDGET
        overrides."""
        try:
            env = int(os.environ.get("TEMPO_RETRY_BUDGET", "") or 0)
        except ValueError:
            env = 0
        return env if env > 0 else max(4, n_jobs // 4)

    def _run_jobs(self, tenant: str, jobs: list[_Job], early_exit=None,
                  timeout: float = 60.0) -> None:
        """Enqueue with bounded in-flight jobs, reap completions in ANY
        order (one slow shard no longer stalls dispatch), hedge jobs
        stuck past hedge_after_s, and cancel everything at the deadline
        so late workers see job.cancelled and skip. Every job shares
        one RetryBudget (total retries per QUERY, not per job) and
        carries the wall-clock deadline so remote workers skip jobs
        whose caller already gave up."""
        cv = threading.Condition()
        budget = RetryBudget(self._retry_budget_total(len(jobs)))
        deadline_unix = time.time() + timeout
        for j in jobs:
            j.batch_cv = cv
            j.retry_budget = budget
            j.deadline_unix = deadline_unix
        deadline = time.monotonic() + timeout
        pending = list(jobs)
        inflight: list[_Job] = []
        while pending or inflight:
            if early_exit is not None and early_exit():
                for j in pending:
                    j.cancelled = True
                    j.finish()
                pending = []
            while pending and len(inflight) < self.concurrent_jobs:
                j = pending.pop(0)
                j.enqueued_at = time.monotonic()
                j.started_wall = time.time()
                self.queue.enqueue(tenant, j)
                inflight.append(j)
            inflight = [j for j in inflight if not j.done.is_set()]
            if not inflight and not pending:
                break
            now = time.monotonic()
            if now >= deadline:
                for j in inflight + pending:
                    j.error = TimeoutError("query job timed out")
                    j.cancelled = True
                    j.finish()  # stamps done_at: the slow job must show
                    # up in self-traces -- it IS the pathology
                break
            if self.hedge_after_s > 0:
                for j in inflight:
                    if not j.hedged and now - j.enqueued_at > self.hedge_after_s:
                        j.hedged = True  # re-enqueue; first completion wins
                        try:
                            self.queue.enqueue(tenant, j)
                        except TooManyRequests:
                            pass
            with cv:
                cv.wait(min(0.25, deadline - now))

    # ----------------------------------------------------------- trace by id
    def find_trace_by_id(self, tenant: str, trace_id: bytes,
                         time_start: int = 0, time_end: int = 0):
        """ID-sharded lookup: one ingester-leg job plus the candidate
        blocks partitioned into parallel backend jobs, partial traces
        combined (tracebyidsharding.go:30-48 splits the ID space; here
        the candidate block set IS the shardable space, since the device
        engine answers a whole partition in one batched lookup)."""
        from ..util.kerneltel import TEL

        t0 = time.perf_counter()
        self_tid = ""
        outcome = "ok"
        try:
            if self.self_tracer is None or tenant == self.self_tracer.tenant:
                return self._find_trace_by_id(tenant, trace_id, time_start, time_end)
            with self.self_tracer.trace(
                "frontend.find_trace_by_id", {"tenant": tenant}
            ) as t:
                self_tid = t.trace_id.hex()
                return self._find_trace_by_id(tenant, trace_id, time_start, time_end,
                                              trace=t)
        except TooManyRequests:
            outcome = "shed"  # QoS budget refusal, not a serving failure
            raise
        except Exception:
            outcome = "error"
            raise
        finally:
            dt = time.perf_counter() - t0
            # exemplar: the latency histogram links to the self-trace
            self.query_latency.observe(dt, 'op="traces"',
                                       exemplar=self_tid or None)
            TEL.record_query("traces", dt, self_tid, trace_id.hex(),
                             outcome=outcome)

    def _qos_admit_traced(self, tenant: str, est_bytes: int, trace) -> int:
        """_qos_admit with a timeline span when a trace is active (the
        QoS admission leg; a shed shows as error=true on the root)."""
        if trace is None or self.qos is None:
            return self._qos_admit(tenant, est_bytes)
        t0 = time.time()
        try:
            return self._qos_admit(tenant, est_bytes)
        finally:
            trace.child("qos-admit", t0, time.time(),
                        {"est_bytes": int(est_bytes)})

    def _find_trace_by_id(self, tenant: str, trace_id: bytes,
                          time_start: int = 0, time_end: int = 0, trace=None):
        rc = self.result_cache
        if rc is None:
            return self._find_trace_exec(tenant, trace_id, time_start,
                                         time_end, trace)
        hex_id = trace_id.hex()
        tr = rc.probe_trace(tenant, hex_id, time_start, time_end)
        if tr is not None:
            return tr
        tr = self._find_trace_exec(tenant, trace_id, time_start, time_end, trace)
        if tr is not None:
            # sized by span count (a serialization pass per store would
            # cost more than the lookup it saves); ~1KiB/span wire-side
            rc.store_trace(tenant, hex_id, time_start, time_end, tr,
                           nbytes=max(1024, tr.span_count() * 1024))
        return tr

    def _find_trace_exec(self, tenant: str, trace_id: bytes,
                         time_start: int = 0, time_end: int = 0, trace=None):
        db = self.querier.db
        candidates = db.find_candidates(tenant, trace_id, time_start, time_end)
        charge = self._qos_admit_traced(
            tenant, sum(m.size_bytes or 0 for m in candidates), trace)
        try:
            jobs = [_Job(
                kind="find_recent",
                payload={"trace_id": trace_id.hex()},
                fn=self.querier.find_trace_by_id,
                args=(tenant, trace_id, time_start, time_end, True, False),
            )]
            for i in range(0, len(candidates), FIND_SHARD_BLOCKS):
                part = candidates[i : i + FIND_SHARD_BLOCKS]
                jobs.append(_Job(
                    kind="find_blocks",
                    payload={"trace_id": trace_id.hex(),
                             "block_ids": [m.block_id for m in part]},
                    fn=self.querier.find_in_blocks,
                    args=(tenant, trace_id, part),
                    batch_key=("find_blocks", tenant,
                               tuple(m.block_id for m in part)),
                    batch_fn=self._batch_find_blocks,
                    affinity_key=part[0].block_id,
                ))
            attach_trace(jobs, trace)
            self._run_jobs(tenant, jobs)
        finally:
            self._qos_release(tenant, charge)
        if trace is not None:
            self._emit_self_trace(jobs, trace)
        partials = []
        for j in jobs:
            if j.error is not None:
                # a failed shard means the combined trace could silently
                # miss spans: fail the request (reference behavior)
                raise j.error
            if j.result is not None:
                partials.append(j.result)
        if not partials:
            return None
        return sort_trace(combine_traces(partials)) if len(partials) > 1 else partials[0]

    # ---------------------------------------------------------------- search
    def search(self, tenant: str, req: SearchRequest) -> SearchResponse:
        """Sharded search: ingester job + block-batch jobs (+ row-group
        shard jobs for oversized blocks), bounded concurrency, early
        exit at limit."""
        from ..util.kerneltel import TEL

        t0 = time.perf_counter()
        self_tid = ""
        outcome = "ok"
        try:
            if self.self_tracer is None or tenant == self.self_tracer.tenant:
                return self._search(tenant, req)
            with self.self_tracer.trace(
                "frontend.search", {"tenant": tenant, "q": req.query or ""}
            ) as t:
                self_tid = t.trace_id.hex()
                return self._search(tenant, req, trace=t)
        except TooManyRequests:
            outcome = "shed"
            raise
        except Exception:
            outcome = "error"
            raise
        finally:
            dt = time.perf_counter() - t0
            self.query_latency.observe(dt, 'op="search"',
                                       exemplar=self_tid or None)
            TEL.record_query("search", dt, self_tid,
                             req.query or " ".join(
                                 f"{k}={v}" for k, v in req.tags.items()),
                             outcome=outcome)

    def _build_search_jobs(self, tenant: str, req: SearchRequest,
                           req_d: dict, metas: list) -> list[_Job]:
        """The search shard plan: one ingester-leg job FIRST (the
        newest data -- streaming delivery leans on this ordering), then
        block-batch jobs (+ row-group shard jobs for oversized
        blocks)."""
        jobs: list[_Job] = [_Job(
            kind="search_recent", payload={"req": req_d},
            fn=self.querier.search_recent, args=(tenant, req),
        )]
        batch: list = []
        batch_bytes = 0

        def flush_batch():
            nonlocal batch, batch_bytes
            if batch:
                part = batch
                jobs.append(_Job(
                    kind="search_blocks",
                    payload={"req": req_d, "block_ids": [m.block_id for m in part]},
                    fn=self.querier.search_blocks, args=(tenant, part, req),
                    batch_key=("search_blocks", tenant,
                               tuple(m.block_id for m in part)),
                    batch_fn=self._batch_search_blocks,
                    affinity_key=part[0].block_id,
                ))
                batch, batch_bytes = [], 0

        for m in metas:
            size = m.size_bytes or 0
            if size > self.batch_bytes:
                # a single oversized block: shard it by row-group range
                for groups in self._group_chunks(m):
                    jobs.append(_Job(
                        kind="search_block_shard",
                        payload={"req": req_d, "block_id": m.block_id, "groups": groups},
                        fn=self.querier.search_block_shard, args=(tenant, m, req, groups),
                        batch_key=("search_block_shard", tenant, m.block_id,
                                   tuple(groups)),
                        batch_fn=self._batch_search_shards,
                        affinity_key=m.block_id,
                    ))
                continue
            if batch_bytes + size > self.batch_bytes or len(batch) >= MAX_BLOCKS_PER_BATCH:
                flush_batch()
            batch.append(m)
            batch_bytes += size
        flush_batch()
        return jobs

    def _search(self, tenant: str, req: SearchRequest, trace=None) -> SearchResponse:
        rc = self.result_cache
        if rc is None:
            return self._search_exec(tenant, req, trace)
        out = rc.probe_search(tenant, req)
        if isinstance(out, SearchResponse):
            return out  # exact hit: no QoS charge, no jobs, no device
        if out is not None:
            # incremental extension: execute ONLY the mutable tail
            # slice through the normal shard plan, merge with the
            # cached immutable prefix
            tail = self._search_exec(tenant, out.tail_req, trace)
            return rc.complete_search_extension(out, tail)
        resp = self._search_exec(tenant, req, trace)
        rc.store_search(tenant, req, resp)
        return resp

    def _search_exec(self, tenant: str, req: SearchRequest,
                     trace=None) -> SearchResponse:
        limit = req.limit or 20
        resp = SearchResponse()
        lock = threading.Lock()
        req_d = request_to_dict(req)

        metas = [
            m for m in self.querier.db.blocklist.metas(tenant)
            if m.overlaps_time(req.start, req.end)
        ]
        charge = self._qos_admit_traced(
            tenant, sum(m.size_bytes or 0 for m in metas), trace)
        try:
            jobs = self._build_search_jobs(tenant, req, req_d, metas)
            attach_trace(jobs, trace)

            def early():
                with lock:
                    return len(resp.traces) >= limit

            # collect results as jobs complete, merging under the limit
            collector_done = threading.Event()

            def collect():
                t0_merge = time.time()
                for j in jobs:
                    j.done.wait()
                    if j.error is None and j.result is not None:
                        with lock:
                            resp.merge(j.result, limit)
                if trace is not None:
                    # the cross-shard merge leg of the timeline
                    trace.child("merge", t0_merge, time.time(),
                                {"jobs": len(jobs)})
                collector_done.set()

            t = threading.Thread(target=collect, daemon=True)
            t.start()
            self._run_jobs(tenant, jobs, early_exit=early)
            collector_done.wait(timeout=60.0)
        finally:
            self._qos_release(tenant, charge)
        if trace is not None:
            self._emit_self_trace(jobs, trace)
            trace.add_cost("bytes_scanned", sum(
                j.result.inspected_bytes for j in jobs
                if j.error is None and j.result is not None))
        resp.traces.sort(key=lambda r: -r.start_time_unix_nano)
        resp.traces = resp.traces[:limit]
        return resp

    # ------------------------------------------------- progressive search
    def search_stream(self, tenant: str, req: SearchRequest):
        """Progressive search: a generator of result snapshots, one per
        completed shard wave, newest-first. Each yield is a dict
        {"traces": [...], "metrics": {...}, "done": bool,
        "jobsCompleted": n, "jobsTotal": m}; the final item has
        done=True and is the exact /api/search response body. Jobs ride
        the SAME queue/lease plane as blocking search -- local workers
        and remote querier polls both complete them -- the frontend just
        flushes the merged snapshot to the client as each completes
        instead of holding everything until the slowest shard."""
        from ..util.kerneltel import TEL
        from ..util.metrics import timed

        t0 = time.perf_counter()
        outcome = "ok"
        try:
            # its OWN query class: progressive delivery has a different
            # latency contract (time-to-final spans the slowest shard
            # by design), so the SLO layer must not fold it into the
            # blocking-search p99
            with timed(self.query_latency, 'op="search_stream"'):
                yield from self._search_stream(tenant, req)
        except TooManyRequests:
            outcome = "shed"
            raise
        except GeneratorExit:
            outcome = "cancelled"  # client went away, not a failure
            raise
        except Exception:
            outcome = "error"
            raise
        finally:
            TEL.record_query("search_stream", time.perf_counter() - t0, "",
                             req.query or " ".join(
                                 f"{k}={v}" for k, v in req.tags.items()),
                             outcome=outcome)

    @staticmethod
    def _stream_final_body(resp: SearchResponse, limit: int) -> dict:
        return {
            "traces": [t.to_dict() for t in resp.traces[:limit]],
            "metrics": {
                "inspectedBytes": str(resp.inspected_bytes),
                "inspectedSpans": str(resp.inspected_spans),
            },
            "done": True,
            "jobsCompleted": 0,  # served from cache: no jobs dispatched
            "jobsTotal": 0,
        }

    def _search_stream(self, tenant: str, req: SearchRequest):
        limit = req.limit or 20
        rc = self.result_cache
        if rc is not None:
            out = rc.probe_search(tenant, req)
            if isinstance(out, SearchResponse):
                # progressive delivery collapses to its final event --
                # the cached response IS the exact /api/search body
                yield self._stream_final_body(out, limit)
                return
            if out is not None:
                tail = self._search_exec(tenant, out.tail_req)
                yield self._stream_final_body(
                    rc.complete_search_extension(out, tail), limit)
                return
        req_d = request_to_dict(req)
        metas = [
            m for m in self.querier.db.blocklist.metas(tenant)
            if m.overlaps_time(req.start, req.end)
        ]
        charge = self._qos_admit(tenant, sum(m.size_bytes or 0 for m in metas))
        runner = None
        jobs: list[_Job] = []
        try:
            jobs = self._build_search_jobs(tenant, req, req_d, metas)
            cv = threading.Condition()
            for j in jobs:
                j.stream_cv = cv
            resp = SearchResponse()
            lock = threading.Lock()

            def early():
                with lock:
                    return len(resp.traces) >= limit

            runner = threading.Thread(
                target=self._run_jobs, args=(tenant, jobs),
                kwargs={"early_exit": early}, daemon=True,
                name="search-stream-dispatch")
            runner.start()

            def body(done: bool) -> dict:
                with lock:
                    traces = sorted(resp.traces,
                                    key=lambda r: -r.start_time_unix_nano)
                    return {
                        "traces": [t.to_dict() for t in traces[:limit]],
                        "metrics": {
                            "inspectedBytes": str(resp.inspected_bytes),
                            "inspectedSpans": str(resp.inspected_spans),
                        },
                        "done": done,
                        "jobsCompleted": len(reaped),
                        "jobsTotal": len(jobs),
                    }

            reaped: set[int] = set()
            while len(reaped) < len(jobs):
                with cv:
                    if not any(j.done.is_set() and id(j) not in reaped
                               for j in jobs):
                        cv.wait(0.25)
                fresh = False
                for j in jobs:
                    if id(j) in reaped or not j.done.is_set():
                        continue
                    reaped.add(id(j))
                    # same tolerance as blocking search: a failed shard
                    # degrades coverage, it doesn't fail the stream
                    if j.error is None and j.result is not None:
                        with lock:
                            n0 = len(resp.traces)
                            resp.merge(j.result, limit)
                            fresh = fresh or len(resp.traces) > n0
                if fresh and len(reaped) < len(jobs):
                    yield body(False)
            runner.join(timeout=5.0)
            runner = None
            with lock:
                resp.traces.sort(key=lambda r: -r.start_time_unix_nano)
                resp.traces = resp.traces[:limit]
            if rc is not None:
                rc.store_search(tenant, req, resp)  # blocking search shares keys
            yield body(True)
        finally:
            if runner is not None:
                # client went away mid-stream: cancel the orphaned jobs
                # FIRST (workers skip cancelled jobs, finish() unblocks
                # the dispatcher), then settle the dispatcher, and only
                # then return the byte charge -- releasing while shard
                # jobs still run would let the tenant exceed its budget
                for j in jobs:
                    if not j.done.is_set():
                        j.cancelled = True
                        j.finish()
                runner.join(timeout=5.0)
            self._qos_release(tenant, charge)

    # ------------------------------------------------------------ metrics
    METRICS_BUCKETS_PER_JOB = 64  # time-shard unit of /api/metrics/query_range

    def metrics_query_range(self, tenant: str, req):
        """Time-sharded metrics range query: the step-aligned bucket
        axis splits into sub-range jobs (the metrics analog of the
        reference's searchsharding time splits), each executed by a
        local worker or a remote querier pull, partial series merged by
        label -- alignment to one global grid makes the shard merge
        exact (metrics_exec.align_params)."""
        from ..util.kerneltel import TEL

        t0 = time.perf_counter()
        self_tid = ""
        outcome = "ok"
        try:
            if self.self_tracer is None or tenant == self.self_tracer.tenant:
                return self._metrics_query_range(tenant, req)
            with self.self_tracer.trace(
                "frontend.metrics_query_range", {"tenant": tenant, "q": req.query}
            ) as t:
                self_tid = t.trace_id.hex()
                return self._metrics_query_range(tenant, req, trace=t)
        except TooManyRequests:
            outcome = "shed"
            raise
        except Exception:
            outcome = "error"
            raise
        finally:
            dt = time.perf_counter() - t0
            self.query_latency.observe(dt, 'op="metrics"',
                                       exemplar=self_tid or None)
            TEL.record_query("metrics", dt, self_tid, req.query,
                             outcome=outcome)

    def _metrics_query_range(self, tenant: str, req, trace=None):
        rc = self.result_cache
        if rc is None:
            return self._metrics_exec(tenant, req, trace)
        out = rc.probe_metrics(tenant, req)
        if out is None:
            resp = self._metrics_exec(tenant, req, trace)
            rc.store_metrics(tenant, req, resp)
            return resp
        from .resultcache import MetricsExtension

        if isinstance(out, MetricsExtension):
            # re-execute only the tail buckets; the prefix accumulator
            # states merge exactly like the time-shard jobs below
            tail = self._metrics_exec(tenant, out.tail_req, trace)
            return rc.complete_metrics_extension(out, tail)
        return out  # exact hit

    def _metrics_exec(self, tenant: str, req, trace=None):
        from ..db.metrics_exec import (
            MetricsRequest,
            MetricsResponse,
            expr_label,
            parse_metrics_query,
            request_to_dict as metrics_request_to_dict,
        )

        q = parse_metrics_query(req.query)  # ParseError -> 400 at the API
        charge = self._qos_admit_traced(tenant, 0, trace)  # concurrency only
        try:
            nb = req.n_buckets
            n_jobs = max(1, -(-nb // self.METRICS_BUCKETS_PER_JOB))
            if nb >= 2 and n_jobs < 2:
                n_jobs = 2  # the shard/merge path is the production path: keep it hot
            per_job = -(-nb // n_jobs)
            jobs: list[_Job] = []
            for lo in range(0, nb, per_job):
                hi = min(lo + per_job, nb)
                sub = MetricsRequest(
                    query=req.query,
                    start_ms=req.start_ms + lo * req.step_ms,
                    end_ms=req.start_ms + hi * req.step_ms,
                    step_ms=req.step_ms,
                )
                jobs.append(_Job(
                    kind="metrics_query_range",
                    payload={"req": metrics_request_to_dict(sub)},
                    fn=self.querier.metrics_query_range, args=(tenant, sub),
                ))
            attach_trace(jobs, trace)
            self._run_jobs(tenant, jobs)
        finally:
            self._qos_release(tenant, charge)
        if trace is not None:
            self._emit_self_trace(jobs, trace)
        resp = MetricsResponse(
            fn=q.agg.fn, start_ms=req.start_ms, step_ms=req.step_ms,
            n_buckets=nb,
            label_names=tuple(expr_label(e, i) for i, e in enumerate(q.agg.by)),
        )
        for j in jobs:
            if j.error is not None:
                # a lost time shard would silently zero part of every
                # series: fail the request (same rule as find shards)
                raise j.error
            if j.result is not None:
                resp.merge(j.result)
        return resp

    def _group_chunks(self, meta) -> list[list[int]]:
        """Split an oversized block's row groups into jobs of
        ~batch_bytes (searchsharding.go:266-310 page-range jobs)."""
        n_groups = max(1, len(meta.row_groups) or 1)
        size = meta.size_bytes or 0
        per_group = max(1, size // n_groups)
        per_job = max(1, int(self.batch_bytes // per_group))
        return [list(range(i, min(i + per_job, n_groups))) for i in range(0, n_groups, per_job)]

    def stop(self):
        self.queue.close()
