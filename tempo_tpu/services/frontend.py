"""Query frontend: per-tenant fair queue, job sharding, retry, combine.

Reference: modules/frontend -- trace-by-ID pipeline (deduper->sharder->
retry, frontend.go:96-183), search sharder (searchsharding.go:69-247:
time range -> block list -> per-block row-group jobs of
~targetBytesPerRequest, bounded concurrency, early exit at limit), and
the per-tenant queue queriers pull from (v1/frontend.go, pkg/scheduler/
queue). Here queriers pull jobs from the queue with worker threads --
the same decoupling, in-process.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from dataclasses import dataclass, field

from ..db.search import SearchRequest, SearchResponse
from .querier import Querier

TARGET_BYTES_PER_JOB = 10 * 1024 * 1024  # searchsharding.go:25-28
DEFAULT_CONCURRENT_JOBS = 50
MAX_RETRIES = 3


class TooManyRequests(Exception):
    pass


def _retryable(e: Exception) -> bool:
    """Transient (IO / backend / timeout) errors retry; deterministic
    failures (parse errors, bad values) fail fast."""
    from ..backend.base import BackendError, DoesNotExist

    if isinstance(e, DoesNotExist):
        return False  # deterministic: the object is gone
    return isinstance(e, (OSError, TimeoutError, ConnectionError, BackendError))


class RequestQueue:
    """Per-tenant fair FIFO: tenants round-robin, jobs FIFO within a
    tenant (pkg/scheduler/queue/queue.go)."""

    def __init__(self, max_per_tenant: int = 2000):
        self.lock = threading.Lock()
        self.cv = threading.Condition(self.lock)
        self.queues: dict[str, deque] = {}
        self.order: deque[str] = deque()
        self.max_per_tenant = max_per_tenant
        self.closed = False

    def enqueue(self, tenant: str, job) -> None:
        with self.cv:
            q = self.queues.get(tenant)
            if q is None:
                q = self.queues[tenant] = deque()
                self.order.append(tenant)
            if len(q) >= self.max_per_tenant:
                raise TooManyRequests(f"tenant {tenant} queue full")  # 429
            q.append(job)
            self.cv.notify()

    def dequeue(self, timeout: float = 0.5):
        with self.cv:
            while True:
                for _ in range(len(self.order)):
                    tenant = self.order[0]
                    self.order.rotate(-1)
                    q = self.queues.get(tenant)
                    if q:
                        return tenant, q.popleft()
                if self.closed:
                    return None
                if not self.cv.wait(timeout):
                    return None

    def close(self):
        with self.cv:
            self.closed = True
            self.cv.notify_all()


@dataclass
class _Job:
    fn: object
    args: tuple
    result: object = None
    error: Exception | None = None
    done: threading.Event = field(default_factory=threading.Event)
    tries: int = 0


class Frontend:
    """Owns the queue + sharding logic; queriers attach as workers."""

    def __init__(self, querier: Querier, n_workers: int = 8,
                 concurrent_jobs: int = DEFAULT_CONCURRENT_JOBS,
                 target_bytes_per_job: int = TARGET_BYTES_PER_JOB):
        self.querier = querier
        self.queue = RequestQueue()
        self.concurrent_jobs = concurrent_jobs
        self.target_bytes_per_job = target_bytes_per_job
        self._workers = [
            threading.Thread(target=self._worker, daemon=True, name=f"frontend-worker-{i}")
            for i in range(n_workers)
        ]
        for w in self._workers:
            w.start()

    def _worker(self):
        while True:
            item = self.queue.dequeue(timeout=1.0)
            if item is None:
                if self.queue.closed:
                    return
                continue
            tenant, job = item
            try:
                job.result = job.fn(*job.args)
            except Exception as e:
                # retry only transient failures (reference retries 5xx
                # only, modules/frontend/retry.go); a parse error or bad
                # argument fails identically every try
                job.tries += 1
                if _retryable(e) and job.tries < MAX_RETRIES:
                    try:
                        self.queue.enqueue(tenant, job)
                        continue
                    except TooManyRequests:
                        pass
                job.error = e
            job.done.set()

    def _run_jobs(self, tenant: str, jobs: list[_Job], early_exit=None,
                  timeout: float = 60.0) -> None:
        """Enqueue with bounded in-flight jobs; early_exit() True stops
        dispatching (searchsharding.go early exit at limit)."""
        pending = list(jobs)
        inflight: list[_Job] = []
        while pending or inflight:
            while pending and len(inflight) < self.concurrent_jobs:
                if early_exit is not None and early_exit():
                    for j in pending:
                        j.done.set()
                    pending = []
                    break
                j = pending.pop(0)
                self.queue.enqueue(tenant, j)
                inflight.append(j)
            if not inflight:
                break
            j = inflight.pop(0)
            if not j.done.wait(timeout):
                j.error = TimeoutError("query job timed out")
                j.done.set()

    # ----------------------------------------------------------- trace by id
    def find_trace_by_id(self, tenant: str, trace_id: bytes,
                         time_start: int = 0, time_end: int = 0):
        """The ingester leg + backend leg both run through the queue
        (tracebyidsharding.go shards the block space; our backend leg
        already fans out per block inside TempoDB.find)."""
        jobs = [
            _Job(self.querier.find_trace_by_id, (tenant, trace_id, time_start, time_end, True)),
        ]
        self._run_jobs(tenant, jobs)
        j = jobs[0]
        if j.error:
            raise j.error
        return j.result

    # ---------------------------------------------------------------- search
    def search(self, tenant: str, req: SearchRequest) -> SearchResponse:
        """Sharded search: ingester job + per-(block, row-group-chunk)
        backend jobs, bounded concurrency, early exit at limit."""
        limit = req.limit or 20
        resp = SearchResponse()
        lock = threading.Lock()

        metas = [
            m for m in self.querier.db.blocklist.metas(tenant)
            if m.overlaps_time(req.start, req.end)
        ]
        jobs: list[_Job] = [_Job(self.querier.search_recent, (tenant, req))]
        for m in metas:
            for groups in self._group_chunks(m):
                jobs.append(_Job(self.querier.search_block_shard, (tenant, m, req, groups)))

        def early():
            with lock:
                return len(resp.traces) >= limit

        # collect results as jobs complete, merging under the limit
        collector_done = threading.Event()

        def collect():
            for j in jobs:
                j.done.wait()
                if j.error is None and j.result is not None:
                    with lock:
                        resp.merge(j.result, limit)
            collector_done.set()

        t = threading.Thread(target=collect, daemon=True)
        t.start()
        self._run_jobs(tenant, jobs, early_exit=early)
        collector_done.wait(timeout=60.0)
        resp.traces.sort(key=lambda r: -r.start_time_unix_nano)
        resp.traces = resp.traces[:limit]
        return resp

    def _group_chunks(self, meta) -> list[list[int]]:
        """Split a block's row groups into jobs of ~target_bytes_per_job
        (searchsharding.go:266-310 page-range jobs)."""
        n_groups = max(1, len(meta.row_groups) or 1)
        size = meta.size_bytes or 0
        per_group = max(1, size // n_groups)
        per_job = max(1, int(self.target_bytes_per_job // per_group))
        return [list(range(i, min(i + per_job, n_groups))) for i in range(0, n_groups, per_job)]

    def stop(self):
        self.queue.close()
