"""Jaeger gRPC collector ingest: jaeger.api_v2.CollectorService/PostSpans.

Reference: the receiver shim registers the full Jaeger factory
(modules/distributor/receiver/shim.go:95-101), whose primary transport
is the gRPC collector endpoint (:14250) that jaeger agents and clients
push model.proto Batches to. Same generic-handler pattern as the OTLP
receiver (services/otlp_grpc.py): no generated stubs, the hand-rolled
api_v2 codec (wire/jaeger_pb.decode_post_spans) feeds the distributor's
model push path; PostSpansResponse serializes to b"".
"""

from __future__ import annotations

from concurrent import futures

_SERVICE = "jaeger.api_v2.CollectorService"
_METHOD = "PostSpans"


class JaegerGrpcReceiver:
    def __init__(self, app, max_workers: int = 8):
        self.app = app
        self._max_workers = max_workers
        self._server = None
        self.port = 0
        self.requests = 0
        self.failures = 0

    def start(self, port: int = 14250, host: str = "127.0.0.1") -> int:
        import grpc

        from ..wire.jaeger_pb import decode_post_spans
        from .otlp_grpc import push_grpc_code

        app = self.app
        recv = self

        def post_spans(request: bytes, context) -> bytes:
            recv.requests += 1
            # decode OUTSIDE the push try-block: context.abort raises to
            # unwind, and a surrounding except would re-abort as INTERNAL
            try:
                batches = decode_post_spans(request)
            except ValueError as e:  # malformed proto: fatal, not retryable
                recv.failures += 1
                context.abort(grpc.StatusCode.INVALID_ARGUMENT,
                              f"bad PostSpansRequest: {e}")
            try:
                md = {k.lower(): v for k, v in (context.invocation_metadata() or [])}
                tenant = app.tenant_of({"X-Scope-OrgID": md.get("x-scope-orgid", "")})
                if batches:
                    app.distributor.push(tenant, batches)
                return b""
            except Exception as e:
                recv.failures += 1
                context.abort(push_grpc_code(e, grpc), f"{type(e).__name__}: {e}")

        handler = grpc.method_handlers_generic_handler(
            _SERVICE,
            {
                _METHOD: grpc.unary_unary_rpc_method_handler(
                    post_spans,
                    request_deserializer=None,  # raw bytes in
                    response_serializer=None,  # raw bytes out
                )
            },
        )
        self._server = grpc.server(
            futures.ThreadPoolExecutor(max_workers=self._max_workers,
                                       thread_name_prefix="jaeger-grpc"),
        )
        self._server.add_generic_rpc_handlers((handler,))
        self.port = self._server.add_insecure_port(f"{host}:{port}")
        self._server.start()
        return self.port

    def stop(self) -> None:
        if self._server is not None:
            self._server.stop(grace=1.0)
            self._server = None
