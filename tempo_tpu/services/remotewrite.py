"""Prometheus remote-write for the metrics-generator registry.

Reference: the generator's registry ships series to a remote-write
endpoint (modules/generator/registry + prometheus remote_write). The
wire is `snappy(protobuf WriteRequest)` POSTed with the prometheus
remote-write headers. Both layers are hand-rolled here:

- WriteRequest proto (prompb): repeated TimeSeries{labels{name,value},
  samples{value,timestamp_ms}} -- encoded with the same pbwire helpers
  the OTLP codec uses.
- snappy framing: the block format's header + ALL-LITERAL chunks, which
  every spec-compliant decoder accepts (compression level is a quality
  knob, not a validity requirement; python has no snappy module baked
  in, and metrics bodies are small).

Series come from the generator's exposition text, so every processor
(span-metrics, service-graphs) ships without knowing about remote-write.
"""

from __future__ import annotations

import re
import struct
import threading
import time
import urllib.request

from ..wire import pbwire as w

_LABEL_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')


def snappy_block_encode(data: bytes) -> bytes:
    """Valid snappy block stream of all-literal chunks (max literal tag
    length 2^32-1; we emit <=65536-byte literals with 2-byte lengths)."""
    out = bytearray()
    w.write_varint(out, len(data))  # uncompressed length header
    pos = 0
    while pos < len(data):
        chunk = data[pos : pos + 65536]
        n = len(chunk) - 1
        # literal tag: 61 in the length field = 2-byte little-endian len
        out.append((61 << 2) | 0)
        out += n.to_bytes(2, "little")
        out += chunk
        pos += len(chunk)
    return bytes(out)


def encode_write_request(series: list[tuple[dict, float, int]]) -> bytes:
    """series: (labels incl __name__, value, timestamp_ms) -> WriteRequest."""
    req = bytearray()
    for labels, value, ts_ms in series:
        ts = bytearray()
        for name in sorted(labels):  # prometheus requires sorted label names
            lab = bytearray()
            w.write_string_field(lab, 1, name)
            w.write_string_field(lab, 2, str(labels[name]))
            w.write_message_field(ts, 1, bytes(lab))
        sample = bytearray()
        # explicit encoding: pbwire's field helpers elide proto3 zero
        # defaults, but a remote-write sample of 0 is a real observation
        sample.append((1 << 3) | 1)  # value: fixed64
        sample += struct.pack("<d", float(value))
        sample.append((2 << 3) | 0)  # timestamp: varint
        w.write_varint(sample, int(ts_ms))
        w.write_message_field(ts, 2, bytes(sample))
        w.write_message_field(req, 1, bytes(ts))
    return bytes(req)


def _split_series(line: str) -> tuple[str, str, str] | None:
    """(name, labelstr, rest-after-labels); quote-aware, so label values
    containing braces, spaces or ' # ' never confuse the split."""
    line = line.strip()
    if not line or line.startswith("#"):
        return None
    brace = line.find("{")
    sp = line.find(" ")
    if brace < 0 or (0 <= sp < brace):  # no label set
        if sp < 0:
            return None
        return line[:sp], "", line[sp:]
    i, in_quote, esc = brace + 1, False, False
    while i < len(line):
        c = line[i]
        if esc:
            esc = False
        elif c == "\\":
            esc = True
        elif c == '"':
            in_quote = not in_quote
        elif c == "}" and not in_quote:
            return line[:brace], line[brace + 1 : i], line[i + 1 :]
        i += 1
    return None


def parse_exposition(lines: list[str]) -> list[tuple[dict, float]]:
    """Prometheus text lines -> (labels incl __name__, value). Exemplar
    suffixes (` # {...} v`) after the sample value are ignored."""
    out = []
    for line in lines:
        parts = _split_series(line)
        if parts is None:
            continue
        name, labelstr, rest = parts
        toks = rest.split()
        if not toks:
            continue
        labels = {"__name__": name}
        for lm in _LABEL_RE.finditer(labelstr):
            labels[lm.group(1)] = lm.group(2).replace('\\"', '"')
        try:
            out.append((labels, float(toks[0])))
        except ValueError:
            continue
    return out


class RemoteWriter:
    """Periodic shipper: generator exposition -> remote-write pushes."""

    def __init__(self, generator, url: str, tenant_header: str = "",
                 interval_s: float = 15.0, timeout_s: float = 10.0):
        self.generator = generator
        self.url = url
        self.tenant_header = tenant_header
        self.interval_s = interval_s
        self.timeout_s = timeout_s
        self.pushes = 0
        self.failures = 0
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def push_once(self) -> bool:
        t0 = time.perf_counter()
        try:
            return self._push_once()
        finally:
            # shipping rides the generator stage histogram so /status/
            # kernels shows the full pipeline: fold stages + export
            try:
                from ..util.kerneltel import TEL

                TEL.record_generator_stage("remote_write",
                                           time.perf_counter() - t0)
            except Exception:
                pass

    def _push_once(self) -> bool:
        series = parse_exposition(self.generator.metrics_text())
        if not series:
            return True
        ts_ms = int(time.time() * 1000)
        body = snappy_block_encode(
            encode_write_request([(lab, v, ts_ms) for lab, v in series])
        )
        headers = {
            "Content-Type": "application/x-protobuf",
            "Content-Encoding": "snappy",
            "X-Prometheus-Remote-Write-Version": "0.1.0",
        }
        if self.tenant_header:
            headers["X-Scope-OrgID"] = self.tenant_header
        from ..chaos import plane as chaos_plane

        if chaos_plane.tap("rpc.remotewrite", key=self.url) is chaos_plane.DROP:
            self.failures += 1  # push silently lost downstream
            return False
        try:
            req = urllib.request.Request(self.url, data=body, headers=headers)
            with urllib.request.urlopen(req, timeout=self.timeout_s):
                pass
            self.pushes += 1
            return True
        except Exception:
            self.failures += 1
            return False

    def start(self) -> None:
        def loop():
            while not self._stop.wait(self.interval_s):
                self.push_once()

        self._thread = threading.Thread(target=loop, daemon=True, name="remote-write")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
