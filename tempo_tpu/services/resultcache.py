"""Tier A of the cache plane: the frontend query-result cache.

Dashboard read traffic is dominated by repeats of the same
search/metrics/by-id queries; the reference wraps its backend in
memcached/redis for blooms and pages (tempodb/backend/cache). Here the
cache sits one layer higher -- at the frontend, AHEAD of queue
admission -- so a hit costs microseconds of host work and never touches
QoS budgets, the queue, or a device.

Keys and invalidation: every entry is keyed on (tenant, the normalized
query identity, the exact time range) and carries the generation pair
it was computed under -- the tenant's blocklist generation
(db/blocklist bumps on flush/compaction/poll drift) plus, for ranges
that touch the live head, the ingester's live-head generation (bumps on
every push/cut/flush). A generation change counts as an invalidation
and replaces the entry, so corpus mutations invalidate naturally. A
range "touches the live head" when it ends within
TEMPO_RESULT_CACHE_LIVE_WINDOW_S of now (or is unbounded); spans
arriving LATER than that window into an older range are invisible to
the generation pair, so TEMPO_RESULT_CACHE_TTL_S bounds that staleness.

Incremental extension (the big win for moving now-edge dashboards): a
search/metrics response over [s, e] also stores its *immutable prefix*
-- results up to cut = now - live_window, which the live head can no
longer change under an unchanged blocklist generation. A later request
[s', e'] with s <= s' < cut re-executes only the tail [cut, e'] and
merges: a 1h range refreshed every 10s re-executes seconds of data,
not the hour. Extension stays in the under-limit regime (a truncated
result set is not a complete prefix); the search time filter is
trace-start within [start, end] (db/search._verify_candidates), so
splitting at `cut` partitions exactly.

Kill switch: TEMPO_RESULT_CACHE=0 makes the frontend skip construction
entirely -- the query path is byte-identical to a build without this
module.
"""

from __future__ import annotations

import contextvars
import json
import threading
import time
from collections import OrderedDict
from dataclasses import dataclass, field

from .. import config_registry as _cfg
from ..db.search import (
    SearchRequest,
    SearchResponse,
    response_from_dict,
    response_to_dict,
)

# the per-request cache decision, for the HTTP layer's X-Tempo-Cache
# response header (soak/vulture classify hits client-side from it):
# "hit" | "miss" | "extend" | None (cache off / route not cacheable)
LAST_OUTCOME: contextvars.ContextVar = contextvars.ContextVar(
    "result_cache_outcome", default=None)


def _tel():
    from ..util.kerneltel import TEL

    return TEL


@dataclass
class SearchExtension:
    """A probe result saying: execute `tail_req` (the only slice the
    cached prefix cannot answer) and hand the partial response to
    ResultCache.complete_search_extension."""

    tenant: str
    req: SearchRequest
    tail_req: SearchRequest
    cut: int  # unix seconds; prefix covers trace starts in [req.start, cut)
    prefix_traces: list = field(default_factory=list)  # wire dicts


@dataclass
class MetricsExtension:
    tenant: str
    req: object  # MetricsRequest
    tail_req: object
    cut_ms: int
    prefix: dict = field(default_factory=dict)  # MetricsResponse wire dict


class ResultCache:
    """Bounded-byte LRU over serialized query results + immutable
    prefixes. One lock, microsecond operations only -- nothing in here
    does IO or touches a device."""

    def __init__(self, blocklist_gen, live_gen=None):
        self._lock = threading.Lock()
        self._store: OrderedDict[tuple, dict] = OrderedDict()
        self._bytes = 0
        self.blocklist_gen = blocklist_gen  # tenant -> int
        # tenant -> int | None; None = no local live-head view, so
        # live-touching ranges are uncacheable (extension still works:
        # the prefix depends only on the blocklist generation)
        self.live_gen = live_gen or (lambda tenant: None)
        self.max_bytes = _cfg.get_int("TEMPO_RESULT_CACHE_MAX_BYTES")
        self.ttl_s = _cfg.get_float("TEMPO_RESULT_CACHE_TTL_S")
        self.live_window_s = _cfg.get_float("TEMPO_RESULT_CACHE_LIVE_WINDOW_S")
        self.extend_enabled = _cfg.get_bool("TEMPO_RESULT_CACHE_EXTEND")
        self.stats_hits = 0
        self.stats_misses = 0
        self.stats_extensions = 0
        self.stats_invalidations = 0

    # ------------------------------------------------------------- store
    def _get_locked(self, key: tuple, gens: tuple, now: float):
        """Entry payload for key iff fresh and generation-matched;
        drops stale entries (a generation mismatch counts as an
        invalidation, expiry does not)."""
        ent = self._store.get(key)
        if ent is None:
            return None
        if now >= ent["expires"]:
            self._evict_locked(key)
            return None
        if ent["gens"] != gens:
            self._evict_locked(key)
            self.stats_invalidations += 1
            _tel().result_cache_invalidations.inc()
            return None
        self._store.move_to_end(key)
        return ent["payload"]

    def _put_locked(self, key: tuple, gens: tuple, payload, now: float,
                    nbytes: int | None = None, extra: dict | None = None) -> None:
        if nbytes is None:
            nbytes = len(json.dumps(payload, separators=(",", ":")).encode())
        nbytes = max(nbytes, 256)
        self._evict_locked(key)
        self._store[key] = {
            "expires": now + self.ttl_s, "gens": gens,
            "payload": payload, "nbytes": nbytes,
            **(extra or {}),
        }
        self._bytes += nbytes
        while self._bytes > self.max_bytes and self._store:
            k = next(iter(self._store))
            self._evict_locked(k)
        _tel().result_cache_bytes.set(self._bytes)

    def _evict_locked(self, key: tuple) -> None:
        ent = self._store.pop(key, None)
        if ent is not None:
            self._bytes -= ent["nbytes"]
            _tel().result_cache_bytes.set(self._bytes)

    # ------------------------------------------------------------ keying
    def _touches_live(self, end: float, now: float) -> bool:
        return end <= 0 or end >= now - self.live_window_s

    def _gens_for(self, tenant: str, end_s: float, now: float):
        """(gens tuple, cacheable) for a range ending at end_s (unix
        seconds; <=0 = unbounded)."""
        bl = self.blocklist_gen(tenant)
        if not self._touches_live(end_s, now):
            return ("bl", bl), True
        lv = self.live_gen(tenant)
        if lv is None:
            return None, False
        return ("bl", bl, "lv", lv), True

    @staticmethod
    def _search_qkey(tenant: str, req: SearchRequest) -> tuple:
        return ("search", tenant, req.query,
                tuple(sorted(req.tags.items())),
                req.min_duration_ms, req.max_duration_ms, req.limit)

    # ------------------------------------------------------------ search
    def probe_search(self, tenant: str, req: SearchRequest, now: float | None = None):
        """SearchResponse (exact hit) | SearchExtension (execute the
        tail, then complete_search_extension) | None (miss)."""
        now = now or time.time()
        t0 = time.time()
        qkey = self._search_qkey(tenant, req)
        gens, cacheable = self._gens_for(tenant, req.end, now)
        if cacheable:
            with self._lock:
                payload = self._get_locked(qkey + (req.start, req.end), gens, now)
            if payload is not None:
                self.stats_hits += 1
                _tel().result_cache_hits.inc()
                _tel().child_span("cache:result-hit", t0, time.time(),
                                  {"kind": "search", "tenant": tenant})
                LAST_OUTCOME.set("hit")
                return response_from_dict(payload)
        ext = self._probe_search_extension(tenant, req, now)
        if ext is not None:
            self.stats_extensions += 1
            _tel().result_cache_extensions.inc()
            _tel().child_span("cache:extend", t0, time.time(),
                              {"kind": "search", "tenant": tenant,
                               "tail_s": max(0, req.end - ext.cut)})
            LAST_OUTCOME.set("extend")
            return ext
        self.stats_misses += 1
        _tel().result_cache_misses.inc()
        LAST_OUTCOME.set("miss")
        return None

    def _probe_search_extension(self, tenant: str, req: SearchRequest,
                                now: float) -> SearchExtension | None:
        if not (self.extend_enabled and req.start > 0 and req.end > 0
                and self._touches_live(req.end, now)):
            return None
        bl = self.blocklist_gen(tenant)
        pkey = ("searchx",) + self._search_qkey(tenant, req)
        with self._lock:
            p = self._get_locked(pkey, ("bl", bl), now)
            if p is None:
                return None
            prefix_start, cut, traces = p["start"], p["cut"], list(p["traces"])
        if not (prefix_start <= req.start < cut <= req.end):
            return None
        # filter the stored prefix to this request's start edge (the
        # time filter is trace-start in [start, end], so this slice is
        # exactly what a fresh execution would keep below `cut`)
        lo_ns = req.start * 1_000_000_000
        keep = [t for t in traces if int(t.get("startTimeUnixNano", "0")) >= lo_ns]
        if len(keep) >= (req.limit or 20):
            return None  # the truncation regime: extension can't be exact
        tail = SearchRequest(
            tags=dict(req.tags), query=req.query,
            min_duration_ms=req.min_duration_ms,
            max_duration_ms=req.max_duration_ms,
            start=cut, end=req.end, limit=req.limit)
        return SearchExtension(tenant=tenant, req=req, tail_req=tail,
                               cut=cut, prefix_traces=keep)

    def complete_search_extension(self, ext: SearchExtension,
                                  tail: SearchResponse,
                                  now: float | None = None) -> SearchResponse:
        """Merge the cached prefix with the freshly executed tail; store
        the advanced prefix when the merge is provably complete."""
        now = now or time.time()
        limit = ext.req.limit or 20
        merged = response_from_dict({"traces": ext.prefix_traces})
        merged.inspected_bytes = tail.inspected_bytes
        merged.inspected_spans = tail.inspected_spans
        seen = {t.trace_id for t in merged.traces}
        for t in tail.traces:
            if t.trace_id not in seen:
                merged.traces.append(t)
                seen.add(t.trace_id)
        merged.traces.sort(key=lambda r: -r.start_time_unix_nano)
        complete = len(merged.traces) < limit and len(tail.traces) < limit
        merged.traces = merged.traces[:limit]
        if complete:
            self._store_search_prefix(ext.tenant, ext.req, merged, now)
        return merged

    def store_search(self, tenant: str, req: SearchRequest,
                     resp: SearchResponse, now: float | None = None) -> None:
        now = now or time.time()
        qkey = self._search_qkey(tenant, req)
        gens, cacheable = self._gens_for(tenant, req.end, now)
        if cacheable:
            with self._lock:
                self._put_locked(qkey + (req.start, req.end), gens,
                                 response_to_dict(resp), now)
        if len(resp.traces) < (req.limit or 20):
            self._store_search_prefix(tenant, req, resp, now)

    def _store_search_prefix(self, tenant: str, req: SearchRequest,
                             resp: SearchResponse, now: float) -> None:
        """Keep the immutable part of an under-limit response as the
        extension prefix: trace starts below cut = now - live_window
        can only change via the blocklist generation."""
        if not (self.extend_enabled and req.start > 0 and req.end > 0):
            return
        cut = min(req.end, int(now - self.live_window_s))
        if cut <= req.start:
            return
        cut_ns = cut * 1_000_000_000
        traces = [
            {**t.to_dict(), "matchedSpans": t.matched_spans}
            for t in resp.traces if t.start_time_unix_nano < cut_ns
        ]
        bl = self.blocklist_gen(tenant)
        pkey = ("searchx",) + self._search_qkey(tenant, req)
        with self._lock:
            self._put_locked(
                pkey, ("bl", bl),
                {"start": req.start, "cut": cut, "traces": traces}, now)

    # ----------------------------------------------------------- by-id
    def probe_trace(self, tenant: str, hex_id: str,
                    time_start: int = 0, time_end: int = 0):
        """The cached Trace, or None on a miss (negative lookups are
        not cached: by-id results can grow from any push, so entries
        always carry both generations)."""
        now = time.time()
        t0 = now
        gens, cacheable = self._gens_for(tenant, 0, now)  # always live-keyed
        if not cacheable:
            self.stats_misses += 1
            _tel().result_cache_misses.inc()
            LAST_OUTCOME.set("miss")
            return None
        key = ("trace", tenant, hex_id, time_start, time_end)
        with self._lock:
            alive = self._get_locked(key, gens, now)
            tr = self._store[key]["trace"] if alive else None
        if tr is not None:
            self.stats_hits += 1
            _tel().result_cache_hits.inc()
            _tel().child_span("cache:result-hit", t0, time.time(),
                              {"kind": "trace", "tenant": tenant})
            LAST_OUTCOME.set("hit")
            return tr
        self.stats_misses += 1
        _tel().result_cache_misses.inc()
        LAST_OUTCOME.set("miss")
        return None

    def store_trace(self, tenant: str, hex_id: str, time_start: int,
                    time_end: int, trace, nbytes: int) -> None:
        now = time.time()
        gens, cacheable = self._gens_for(tenant, 0, now)
        if not cacheable:
            return
        key = ("trace", tenant, hex_id, time_start, time_end)
        with self._lock:
            # the Trace object rides outside any JSON payload, sized by
            # the caller's serialized response length
            self._put_locked(key, gens, True, now, nbytes=nbytes,
                             extra={"trace": trace})

    # ---------------------------------------------------------- metrics
    @staticmethod
    def _metrics_qkey(tenant: str, req) -> tuple:
        return ("metrics", tenant, req.query, req.step_ms)

    def probe_metrics(self, tenant: str, req, now: float | None = None):
        """MetricsResponse | MetricsExtension | None (miss). req is an
        aligned MetricsRequest (ms since epoch, end exclusive)."""
        from ..db.metrics_exec import response_from_dict as m_from_dict

        now = now or time.time()
        t0 = time.time()
        qkey = self._metrics_qkey(tenant, req)
        gens, cacheable = self._gens_for(tenant, req.end_ms / 1000.0, now)
        if cacheable:
            with self._lock:
                payload = self._get_locked(
                    qkey + (req.start_ms, req.end_ms), gens, now)
            if payload is not None:
                self.stats_hits += 1
                _tel().result_cache_hits.inc()
                _tel().child_span("cache:result-hit", t0, time.time(),
                                  {"kind": "metrics", "tenant": tenant})
                LAST_OUTCOME.set("hit")
                return m_from_dict(payload)
        ext = self._probe_metrics_extension(tenant, req, now)
        if ext is not None:
            self.stats_extensions += 1
            _tel().result_cache_extensions.inc()
            _tel().child_span("cache:extend", t0, time.time(),
                              {"kind": "metrics", "tenant": tenant,
                               "tail_ms": max(0, req.end_ms - ext.cut_ms)})
            LAST_OUTCOME.set("extend")
            return ext
        self.stats_misses += 1
        _tel().result_cache_misses.inc()
        LAST_OUTCOME.set("miss")
        return None

    def _probe_metrics_extension(self, tenant: str, req,
                                 now: float) -> MetricsExtension | None:
        from ..db.metrics_exec import MetricsRequest

        if not (self.extend_enabled
                and self._touches_live(req.end_ms / 1000.0, now)):
            return None
        bl = self.blocklist_gen(tenant)
        pkey = ("metricsx",) + self._metrics_qkey(tenant, req)
        with self._lock:
            p = self._get_locked(pkey, ("bl", bl), now)
            if p is None:
                return None
            p = dict(p)
        cut_ms = p["cut_ms"]
        if not (p["start_ms"] <= req.start_ms < cut_ms <= req.end_ms):
            return None
        tail = MetricsRequest(query=req.query, start_ms=cut_ms,
                              end_ms=req.end_ms, step_ms=req.step_ms)
        return MetricsExtension(tenant=tenant, req=req, tail_req=tail,
                                cut_ms=cut_ms, prefix=p["resp"])

    def complete_metrics_extension(self, ext: MetricsExtension, tail,
                                   now: float | None = None):
        """Merge the cached per-series accumulator prefix (sliced onto
        this request's bucket axis) with the tail execution -- exactly
        the shard merge the frontend's time-sharded jobs already do."""
        from ..db.metrics_exec import (
            MetricsResponse,
            response_from_dict as m_from_dict,
        )

        now = now or time.time()
        req = ext.req
        pre = m_from_dict(ext.prefix)
        nb = req.n_buckets
        resp = MetricsResponse(
            fn=pre.fn, start_ms=req.start_ms, step_ms=req.step_ms,
            n_buckets=nb, label_names=pre.label_names or tail.label_names)
        lo = (req.start_ms - pre.start_ms) // req.step_ms
        hi = (ext.cut_ms - pre.start_ms) // req.step_ms
        for labels, state in pre.series.items():
            sliced = {f: a[lo:hi] for f, a in state.items()}
            if not _state_has_data(sliced):
                continue  # a fresh run of this window would not emit it
            resp.add_partial(labels, sliced, offset=0)
        resp.merge(tail)  # also carries the tail's inspected counts
        self._store_metrics_prefix(ext.tenant, req, resp, now)
        return resp

    def store_metrics(self, tenant: str, req, resp,
                      now: float | None = None) -> None:
        from ..db.metrics_exec import response_to_dict as m_to_dict

        now = now or time.time()
        qkey = self._metrics_qkey(tenant, req)
        gens, cacheable = self._gens_for(tenant, req.end_ms / 1000.0, now)
        if cacheable:
            with self._lock:
                self._put_locked(qkey + (req.start_ms, req.end_ms), gens,
                                 m_to_dict(resp), now)
        self._store_metrics_prefix(tenant, req, resp, now)

    def _store_metrics_prefix(self, tenant: str, req, resp,
                              now: float) -> None:
        from ..db.metrics_exec import MetricsResponse, response_to_dict as m_to_dict

        if not self.extend_enabled:
            return
        cut_ms = int((now - self.live_window_s) * 1000)
        cut_ms = (cut_ms // req.step_ms) * req.step_ms  # step-grid aligned
        cut_ms = min(cut_ms, req.end_ms)
        if cut_ms <= req.start_ms:
            return
        nbp = (cut_ms - req.start_ms) // req.step_ms
        pre = MetricsResponse(
            fn=resp.fn, start_ms=req.start_ms, step_ms=req.step_ms,
            n_buckets=nbp, label_names=resp.label_names)
        for labels, state in resp.series.items():
            sliced = {f: a[:nbp].copy() for f, a in state.items()}
            if _state_has_data(sliced):
                pre.series[labels] = sliced
        bl = self.blocklist_gen(tenant)
        pkey = ("metricsx",) + self._metrics_qkey(tenant, req)
        with self._lock:
            self._put_locked(
                pkey, ("bl", bl),
                {"start_ms": req.start_ms, "cut_ms": cut_ms,
                 "resp": m_to_dict(pre)}, now)

    # ------------------------------------------------------------ stats
    def stats(self) -> dict:
        with self._lock:
            entries = len(self._store)
            nbytes = self._bytes
        return {
            "enabled": True,
            # live-touching ranges are only cacheable with a local
            # ingester feed; probes use this to decide whether an
            # exact hit is expected on a now-edge repeat
            "live_gen_wired": self.live_gen("") is not None,
            "entries": entries,
            "bytes": int(nbytes),
            "budget_bytes": int(self.max_bytes),
            "ttl_s": self.ttl_s,
            "live_window_s": self.live_window_s,
            "hits": self.stats_hits,
            "misses": self.stats_misses,
            "extensions": self.stats_extensions,
            "invalidations": self.stats_invalidations,
        }

    def clear(self) -> None:
        with self._lock:
            self._store.clear()
            self._bytes = 0
            _tel().result_cache_bytes.set(0)


def _state_has_data(state: dict) -> bool:
    """Whether a sliced accumulator state would exist at all in a fresh
    execution of its window (empty series must not survive slicing:
    a fresh run only emits series that contributed data)."""
    arr = state.get("count")
    if arr is None:
        arr = state.get("vcnt")
    return arr is not None and bool(arr.sum())
