"""Per-tenant runtime limits (reference: modules/overrides/limits.go).

Defaults mirror limits.go:90-108; a per-tenant overrides file (YAML)
hot-reloads on a period, same as the reference's runtime-config watcher.
"""

from __future__ import annotations

import os
import threading
from dataclasses import dataclass, field, fields, replace


@dataclass(frozen=True)
class Limits:
    # ingest (limits.go:92-99)
    ingestion_rate_limit_bytes: int = 15 * 1024 * 1024
    ingestion_burst_size_bytes: int = 20 * 1024 * 1024
    max_traces_per_user: int = 10_000
    max_bytes_per_trace: int = 5 * 1024 * 1024
    # query
    max_bytes_per_tag_values_query: int = 5 * 1024 * 1024
    max_search_duration_s: int = 0  # 0 = unlimited
    max_queriers_per_tenant: int = 0  # queue shuffle-shard size; 0 = all
    # read-plane QoS (frontend admission): concurrent queries a tenant
    # may run and block bytes it may reference in flight; over budget =
    # 429 shed-load. 0 = unlimited.
    max_concurrent_queries: int = 0
    max_inflight_query_bytes: int = 0
    # storage
    block_retention_s: int = 0  # 0 = use compactor default
    # generator
    metrics_generator_processors: tuple[str, ...] = ()
    metrics_generator_max_active_series: int = 0
    metrics_generator_ring_size: int = 0  # shuffle-shard size; 0 = all
    # per-tenant registry staleness window; 0 = generator default
    metrics_generator_stale_series_s: float = 0.0


@dataclass
class Overrides:
    """Defaults + per-tenant overlay, optionally file-backed."""

    defaults: Limits = field(default_factory=Limits)
    per_tenant: dict[str, Limits] = field(default_factory=dict)
    path: str = ""
    reload_period_s: float = 10.0

    def __post_init__(self):
        self._lock = threading.Lock()
        self._mtime = 0.0
        self._stop = threading.Event()
        self._reloader: threading.Thread | None = None
        if self.path:
            self.reload()

    def start_reloader(self) -> None:
        """Hot reload every reload_period_s (reference reloads the
        runtime-config file every 10s, modules/overrides/overrides.go)."""
        if self._reloader is not None or not self.path:
            return

        def loop():
            while not self._stop.wait(self.reload_period_s):
                try:
                    self.reload()
                except Exception:  # noqa: BLE001 - keep last good overrides
                    pass

        self._reloader = threading.Thread(target=loop, daemon=True, name="overrides-reload")
        self._reloader.start()

    def stop(self) -> None:
        self._stop.set()

    def for_tenant(self, tenant: str) -> Limits:
        with self._lock:
            return self.per_tenant.get(tenant, self.defaults)

    # ------------------------------------------------------------ reload
    def reload(self) -> None:
        """Read the overrides file if it changed (reference reloads every
        10s; callers drive the period)."""
        if not self.path or not os.path.exists(self.path):
            return
        mtime = os.path.getmtime(self.path)
        if mtime == self._mtime:
            return
        import yaml

        with open(self.path) as f:
            data = yaml.safe_load(f) or {}
        valid = {f.name for f in fields(Limits)}
        per_tenant = {}
        for tenant, vals in (data.get("overrides") or {}).items():
            kw = {k: v for k, v in (vals or {}).items() if k in valid}
            if "metrics_generator_processors" in kw:
                kw["metrics_generator_processors"] = tuple(kw["metrics_generator_processors"])
            per_tenant[tenant] = replace(self.defaults, **kw)
        with self._lock:
            self.per_tenant = per_tenant
            self._mtime = mtime


class QueryAdmission:
    """Per-tenant read-plane QoS gate (used by the query frontend):
    bounds how many queries a tenant runs concurrently and how many
    block bytes it may reference in flight, so one heavy tenant cannot
    monopolize the queue or churn every other tenant's staged device
    columns out of HBM. Overrides-driven like the ingest limits;
    try_admit never blocks -- an over-budget query sheds with 429
    (frontend.TooManyRequests), the reference's queue-full response
    applied per tenant instead of per process."""

    def __init__(self, overrides: Overrides):
        self.overrides = overrides
        self._lock = threading.Lock()
        self._queries: dict[str, int] = {}  # tenant -> queries in flight
        self._bytes: dict[str, int] = {}  # tenant -> referenced block bytes

    def try_admit(self, tenant: str, est_bytes: int = 0) -> str | None:
        """Admit one query referencing est_bytes of block data. Returns
        None on admission, else the name of the refusing budget
        ("concurrency" | "bytes"). A tenant with nothing in flight
        always admits: a single query larger than its own byte budget
        is the budget's unit of progress, not a livelock."""
        lim = self.overrides.for_tenant(tenant)
        with self._lock:
            q = self._queries.get(tenant, 0)
            b = self._bytes.get(tenant, 0)
            if q > 0:
                if 0 < lim.max_concurrent_queries <= q:
                    return "concurrency"
                if (lim.max_inflight_query_bytes > 0
                        and b + est_bytes > lim.max_inflight_query_bytes):
                    return "bytes"
            self._queries[tenant] = q + 1
            self._bytes[tenant] = b + est_bytes
            return None

    def release(self, tenant: str, est_bytes: int = 0) -> None:
        """Return one admitted query's budget. Must be called exactly
        once per successful try_admit (callers pair them try/finally)."""
        with self._lock:
            q = self._queries.get(tenant, 0) - 1
            if q <= 0:
                self._queries.pop(tenant, None)
                self._bytes.pop(tenant, None)
            else:
                self._queries[tenant] = q
                self._bytes[tenant] = max(
                    0, self._bytes.get(tenant, 0) - est_bytes)

    def inflight(self, tenant: str) -> tuple[int, int]:
        """(queries, bytes) a tenant currently holds (status surfaces)."""
        with self._lock:
            return self._queries.get(tenant, 0), self._bytes.get(tenant, 0)


class RateLimiter:
    """Token-bucket per tenant (reference: distributor rate limit,
    modules/distributor/distributor.go:312-319)."""

    def __init__(self, overrides: Overrides):
        self.overrides = overrides
        self._lock = threading.Lock()
        self._buckets: dict[str, tuple[float, float]] = {}  # tenant -> (tokens, last_ts)

    def peek(self, tenant: str, nbytes: int, now: float) -> bool:
        """Would a request of nbytes pass right now? Consumes nothing:
        the cheap pre-serialization gate -- callers pass a LOWER BOUND
        on the request's wire size, so a refusal here is always also a
        refusal of the exact-bytes check, and a tenant hard over its
        limit never pays segment-encoding CPU for a doomed request."""
        lim = self.overrides.for_tenant(tenant)
        rate = lim.ingestion_rate_limit_bytes
        burst = lim.ingestion_burst_size_bytes
        if rate <= 0:
            return True
        with self._lock:
            tokens, last = self._buckets.get(tenant, (float(burst), now))
            return min(float(burst), tokens + (now - last) * rate) >= nbytes

    def allow(self, tenant: str, nbytes: int, now: float) -> bool:
        lim = self.overrides.for_tenant(tenant)
        rate = lim.ingestion_rate_limit_bytes
        burst = lim.ingestion_burst_size_bytes
        if rate <= 0:
            return True
        with self._lock:
            tokens, last = self._buckets.get(tenant, (float(burst), now))
            tokens = min(float(burst), tokens + (now - last) * rate)
            if tokens >= nbytes:
                self._buckets[tenant] = (tokens - nbytes, now)
                return True
            self._buckets[tenant] = (tokens, now)
            return False
