"""Kafka receiver: consume OTLP-proto span messages from a topic and
push them through the distributor.

The reference registers the OTel collector's kafka receiver beside OTLP
and Jaeger (modules/distributor/receiver/shim.go:100); its default
contract is topic "otlp_spans" carrying serialized
ExportTraceServiceRequest messages. Same contract here, with a
hand-rolled minimal Kafka wire client (the pattern every backend client
in this repo follows -- S3 SigV4, Azure SharedKey, GCS: speak the
protocol subset we need, no SDK):

* Metadata v0 (api 3) -- partition discovery,
* ListOffsets v0 (api 2) -- earliest/latest start position,
* Fetch v0 (api 1) -- message sets (v0/v1 message format).

Single-consumer (no group coordination): each receiver instance owns
the whole topic, offsets live in memory and start at `latest` by
default. Multi-instance partition balancing rides the distributor ring
above this layer, not Kafka groups.
"""

from __future__ import annotations

import io
import socket
import struct
import threading
import time

from ..util.log import get_logger

log = get_logger("kafka")

DEFAULT_TOPIC = "otlp_spans"

# ---------------------------------------------------------------- wire enc

_I16 = struct.Struct(">h")
_I32 = struct.Struct(">i")
_I64 = struct.Struct(">q")


def enc_str(s: str | None) -> bytes:
    if s is None:
        return _I16.pack(-1)
    b = s.encode()
    return _I16.pack(len(b)) + b


def enc_bytes(b: bytes | None) -> bytes:
    if b is None:
        return _I32.pack(-1)
    return _I32.pack(len(b)) + b


class Reader:
    def __init__(self, data: bytes):
        self.b = io.BytesIO(data)

    def i16(self) -> int:
        return _I16.unpack(self.b.read(2))[0]

    def i32(self) -> int:
        return _I32.unpack(self.b.read(4))[0]

    def i64(self) -> int:
        return _I64.unpack(self.b.read(8))[0]

    def string(self) -> str | None:
        n = self.i16()
        return None if n < 0 else self.b.read(n).decode()

    def bytes(self) -> bytes | None:
        n = self.i32()
        return None if n < 0 else self.b.read(n)

    def raw(self, n: int) -> bytes:
        return self.b.read(n)


class OffsetOutOfRange(Exception):
    """Fetch error 1: the stored offset fell off retention."""


def parse_message_set(data: bytes) -> list[tuple[int, bytes]]:
    """v0/v1 MessageSet -> [(offset, value)]. Tolerates a trailing
    partial message (brokers truncate at max_bytes). Compressed wrapper
    messages fail LOUDLY: silently feeding compressed bytes downstream
    would drop every message with no signal."""
    out: list[tuple[int, bytes]] = []
    pos = 0
    n = len(data)
    while pos + 12 <= n:
        (offset,) = _I64.unpack_from(data, pos)
        (size,) = _I32.unpack_from(data, pos + 8)
        if size < 0 or pos + 12 + size > n:
            break  # partial tail
        msg = data[pos + 12 : pos + 12 + size]
        # crc(4) magic(1) attrs(1) [v1: timestamp(8)] key value
        if len(msg) < 6:
            break
        magic = msg[4]
        if msg[5] & 0x07:
            raise ValueError(
                "compressed Kafka message sets are not supported; configure "
                "the producer with compression.type=none"
            )
        body = msg[6 + (8 if magic >= 1 else 0) :]
        r = Reader(body)
        r.bytes()  # key, unused
        value = r.bytes()
        if value is not None:
            out.append((offset, value))
        pos += 12 + size
    return out


class KafkaClient:
    """One broker connection speaking the v0 subset."""

    def __init__(self, host: str, port: int, client_id: str = "tempo-tpu",
                 timeout_s: float = 10.0):
        self.addr = (host, port)
        self.client_id = client_id
        self.timeout_s = timeout_s
        self._sock: socket.socket | None = None
        self._corr = 0

    def close(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            finally:
                self._sock = None

    def _conn(self) -> socket.socket:
        if self._sock is None:
            self._sock = socket.create_connection(self.addr, timeout=self.timeout_s)
        return self._sock

    def _call(self, api_key: int, body: bytes) -> Reader:
        self._corr += 1
        hdr = _I16.pack(api_key) + _I16.pack(0) + _I32.pack(self._corr) + enc_str(self.client_id)
        msg = hdr + body
        s = self._conn()
        try:
            s.sendall(_I32.pack(len(msg)) + msg)
            raw = self._read_exact(s, 4)
            (ln,) = _I32.unpack(raw)
            resp = self._read_exact(s, ln)
        except Exception:
            self.close()  # poisoned stream: next call reconnects
            raise
        r = Reader(resp)
        corr = r.i32()
        if corr != self._corr:
            self.close()
            raise ConnectionError(f"kafka correlation mismatch {corr} != {self._corr}")
        return r

    @staticmethod
    def _read_exact(s: socket.socket, n: int) -> bytes:
        out = b""
        while len(out) < n:
            chunk = s.recv(n - len(out))
            if not chunk:
                raise ConnectionError("kafka broker closed connection")
            out += chunk
        return out

    # ---- apis
    def partitions(self, topic: str) -> list[int]:
        body = _I32.pack(1) + enc_str(topic)
        r = self._call(3, body)
        for _ in range(r.i32()):  # brokers
            r.i32()
            r.string()
            r.i32()
        parts: list[int] = []
        for _ in range(r.i32()):  # topics
            r.i16()  # topic error
            r.string()
            for _ in range(r.i32()):
                r.i16()  # partition error
                parts.append(r.i32())
                r.i32()  # leader
                for _ in range(r.i32()):
                    r.i32()  # replicas
                for _ in range(r.i32()):
                    r.i32()  # isr
        return sorted(parts)

    def list_offset(self, topic: str, partition: int, latest: bool) -> int:
        ts = -1 if latest else -2
        body = (_I32.pack(-1) + _I32.pack(1) + enc_str(topic) + _I32.pack(1)
                + _I32.pack(partition) + _I64.pack(ts) + _I32.pack(1))
        r = self._call(2, body)
        for _ in range(r.i32()):
            r.string()
            for _ in range(r.i32()):
                r.i32()  # partition
                err = r.i16()
                offs = [r.i64() for _ in range(r.i32())]
                if err == 0 and offs:
                    return offs[0]
        return 0

    def fetch(self, topic: str, partition: int, offset: int,
              max_bytes: int = 4 << 20, max_wait_ms: int = 500) -> list[tuple[int, bytes]]:
        body = (_I32.pack(-1) + _I32.pack(max_wait_ms) + _I32.pack(1)
                + _I32.pack(1) + enc_str(topic) + _I32.pack(1)
                + _I32.pack(partition) + _I64.pack(offset) + _I32.pack(max_bytes))
        r = self._call(1, body)
        out: list[tuple[int, bytes]] = []
        for _ in range(r.i32()):
            r.string()
            for _ in range(r.i32()):
                r.i32()  # partition
                err = r.i16()
                r.i64()  # high watermark
                ms = r.bytes() or b""
                if err == 1:
                    raise OffsetOutOfRange(f"{topic}/{partition}@{offset}")
                if err == 0:
                    out.extend(parse_message_set(ms))
        return out


class KafkaReceiver:
    """Poll loop: fetch OTLP messages from every partition, decode, push
    through the distributor (the shim's receiver -> distributor.push
    contract, shim.go:116)."""

    def __init__(self, app, brokers: str, topic: str = DEFAULT_TOPIC,
                 tenant: str = "", start_latest: bool = True,
                 poll_interval_s: float = 0.2):
        # comma-separated broker list: connect to the first, rotate to
        # the next on connection failure (bootstrap failover)
        self.brokers: list[tuple[str, int]] = []
        for part in brokers.split(","):
            part = part.strip()
            if not part:
                continue
            host, _, port = part.partition(":")
            self.brokers.append((host, int(port or 9092)))
        if not self.brokers:
            raise ValueError("kafka receiver needs at least one broker addr")
        self._broker_i = 0
        self.client = KafkaClient(*self.brokers[0])
        self.app = app
        self.topic = topic
        self.tenant = tenant
        self.start_latest = start_latest
        self.poll_interval_s = poll_interval_s
        self.offsets: dict[int, int] = {}
        self.messages = 0
        self.spans = 0
        self.failures = 0
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def _rotate_broker(self) -> None:
        self.client.close()
        self._broker_i = (self._broker_i + 1) % len(self.brokers)
        self.client = KafkaClient(*self.brokers[self._broker_i])

    def start(self) -> None:
        # capture the start position SYNCHRONOUSLY: once start() returns,
        # every message produced afterwards is guaranteed consumed. Lazy
        # init in the poll loop raced producers -- with start_latest, a
        # message produced between start() and the first poll fell
        # before the captured baseline and was silently skipped.
        try:
            self._init_offsets()
        except Exception as e:
            log.warning("kafka receiver: offset init deferred (%s); "
                        "retrying in the poll loop", e)
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="kafka-receiver")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
        self.client.close()

    def _init_offsets(self) -> None:
        # build locally, assign atomically: a mid-iteration failure must
        # not leave a partial map that poll_once's `if not self.offsets`
        # guard would treat as complete (silently skipping partitions)
        offs = {}
        for p in self.client.partitions(self.topic):
            offs[p] = self.client.list_offset(
                self.topic, p, latest=self.start_latest)
        self.offsets = offs
        log.info("kafka receiver: topic %s partitions %s",
                 self.topic, sorted(self.offsets))

    def poll_once(self) -> int:
        """One fetch round over all partitions; returns messages
        consumed. Poison messages (undecodable, rejected payloads) are
        skipped with their offset advanced; TRANSIENT failures (rate
        limits, no healthy ingesters) rewind the offset and retry next
        poll -- the at-least-once contract the OTLP receivers give
        clients via 429s."""
        from .distributor import PushError

        got = 0
        if not self.offsets:
            self._init_offsets()
        for p, off in list(self.offsets.items()):
            try:
                records = self.client.fetch(self.topic, p, off)
            except OffsetOutOfRange:
                # fell off retention: restart from the earliest retained
                new = self.client.list_offset(self.topic, p, latest=False)
                log.warning("kafka receiver: %s/%d offset %d out of range, "
                            "resetting to %d", self.topic, p, off, new)
                self.offsets[p] = new
                continue
            for offset, value in records:
                tenant = self.tenant or self.app.tenant_of({})
                try:
                    # raw fast path (native scan + splice); undecodable
                    # payloads surface as PushError(400) = poison below
                    n_new = self.app.distributor.push_raw(tenant, value)
                except PushError as e:
                    if e.status in (400, 401):  # rejected payload: poison
                        self.failures += 1
                        self.offsets[p] = offset + 1
                        log.warning("kafka receiver: push rejected (%d) at "
                                    "%s/%d@%d: %s", e.status, self.topic, p, offset, e)
                        continue
                    log.warning("kafka receiver: transient push failure (%d) "
                                "at %s/%d@%d, will retry: %s",
                                e.status, self.topic, p, offset, e)
                    break  # transient: offset NOT advanced, retry next poll
                except Exception as e:
                    log.warning("kafka receiver: transient push failure at "
                                "%s/%d@%d, will retry: %s", self.topic, p, offset, e)
                    break
                self.offsets[p] = offset + 1
                got += 1
                self.messages += 1
                self.spans += n_new
        return got

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                self.poll_once()
            except Exception as e:
                self.failures += 1
                log.warning("kafka receiver: poll failed against %s:%d, "
                            "rotating broker: %s", *self.client.addr, e)
                self._rotate_broker()
                self._stop.wait(min(5.0, self.poll_interval_s * 10))
                continue
            self._stop.wait(self.poll_interval_s)
