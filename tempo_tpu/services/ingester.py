"""Ingester: per-tenant instances buffering live traces, WAL-backed,
cutting columnar blocks and flushing them to the backend.

Reference: modules/ingester -- PushBytesV2 (ingester.go:208), instance
lifecycle (instance.go:238-348), flush state machine (flush.go:185-332),
WAL replay on start (ingester.go:326-400).

Differences by design: pushes append to the WAL head block immediately
(durability at ack time instead of at trace-cut time), and block
completion writes the columnar block straight through the shared
TempoDB facade (the single-binary collapses the ingester-local staging
backend; the flush queue + retry structure is kept for the multi-process
topology).
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field

from ..db.search import SearchRequest, SearchResponse, SearchResult
from ..db.tempodb import TempoDB
from ..db.wal import DEFAULT_WAL_VERSION, WAL, WALBlock
from ..ingest.columnar import ColumnarIngest
from ..wire.combine import combine_traces, sort_trace
from ..wire.model import Trace
from ..util.metrics import Counter, Histogram, timed
from ..wire.segment import segment_to_trace
from .distributor import PushError

# process-wide ingester instrumentation (the reference's promauto
# package-level metrics, modules/ingester/flush.go)
FLUSH_DURATION = Histogram("tempo_ingester_flush_duration_seconds")
FLUSH_FAILURES = Counter("tempo_ingester_flush_failures_total")
WAL_REPLAYS = Counter("tempo_ingester_wal_replays_total")


@dataclass
class LiveTrace:
    trace_id: bytes
    segments: list[bytes] = field(default_factory=list)
    nbytes: int = 0
    last_append: float = 0.0
    start_s: int = 0
    end_s: int = 0
    # lazy search index (see _SearchEntry): built on first search touch,
    # reused until a new segment arrives. The decoded trace it was built
    # from is cached alongside (same invalidation via indexed_segments):
    # TraceQL evaluation on an unchanged trace must never re-run
    # combine_traces over every segment per request.
    search_index: object = None
    decoded: object = None
    indexed_segments: int = 0


@dataclass
class _SearchEntry:
    """Per-trace search index: the role of the reference's flatbuffer
    search data (tempodb/search/) -- tag kv pairs, names, time range and
    result fields extracted ONCE, so repeated live searches never
    re-decode segments. Built lazily at first search (zero ingest-path
    cost; the decode amortizes across every later query) and invalidated
    by segment appends."""

    kv: set  # lowered (key, value) pairs across span+resource attrs
    names: set  # span names
    start_ns: int
    dur_ms: int
    root_service: str
    root_name: str

    @classmethod
    def build(cls, tr: Trace) -> "_SearchEntry":
        kv: set = set()
        names: set = set()
        root = None
        for res, _, sp in tr.all_spans():
            if root is None:
                root = (res.service_name, sp.name)
            names.add(sp.name)
            for k, v in sp.attrs.items():
                kv.add((k, str(v).lower()))
            for k, v in res.attrs.items():
                kv.add((k, str(v).lower()))
        lo, hi = tr.time_range_nanos()
        return cls(
            kv=kv,
            names=names,
            start_ns=lo or 0,
            dur_ms=max(0, ((hi or 0) - (lo or 0)) // 1_000_000),
            root_service=root[0] if root else "",
            root_name=root[1] if root else "",
        )

    def matches_tags(self, tags: dict[str, str]) -> bool:
        for k, v in tags.items():
            if k == "name":
                if v not in self.names:
                    return False
            elif (k, v.lower()) not in self.kv:
                return False
        return True


@dataclass
class IngesterConfig:
    max_trace_idle_s: float = 10.0
    max_block_age_s: float = 120.0
    max_block_bytes: int = 64 * 1024 * 1024
    flush_check_period_s: float = 2.0
    # WAL fsync cadence: acked pushes are flushed to the OS immediately
    # and fsynced at most this often (bounded host-crash loss window,
    # covered by RF-way replication). RF=1 deployments set 0 to fsync
    # every flush.
    wal_fsync_interval_s: float = 0.25
    # WAL write format: "w2" (columnar windows + feature checkpoints,
    # db/wal.WAL2Block) or "w1" (legacy one-record-per-segment). Replay
    # reads BOTH regardless, so flipping this is a live migration.
    wal_version: str = DEFAULT_WAL_VERSION


class Instance:
    """One tenant inside one ingester (modules/ingester/instance.go)."""

    def __init__(self, tenant: str, wal: WAL, db: TempoDB, overrides, cfg: IngesterConfig):
        self.tenant = tenant
        self.wal = wal
        self.db = db
        self.overrides = overrides
        self.cfg = cfg
        self.lock = threading.RLock()
        self.live: dict[bytes, LiveTrace] = {}
        # columnar ingest plane: the shared LiveDict + decode-once
        # feature cache feeding live-search staging AND the WAL's
        # feature checkpoints (created BEFORE the live engine so the
        # engine's stager adopts the shared dictionary)
        self.columnar = ColumnarIngest()
        self.head: WALBlock = wal.new_block(tenant, cfg.wal_version)
        self.head_created = time.time()
        # traces cut from the live map, waiting to go into the next block
        self.cut: dict[bytes, LiveTrace] = {}
        # traces inside an in-flight block write: cut is cleared when the
        # flush snapshot is taken, and the backend write takes real time,
        # so without this set a trace would be invisible to find/search
        # between snapshot and blocklist update (the reference keeps
        # completing/complete blocks queryable at every stage,
        # modules/ingester/instance.go:428-476)
        self.flushing: dict[bytes, LiveTrace] = {}
        self.blocks_flushed = 0
        # live-head mutation generation: bumps on every push / cut /
        # flush so the frontend result cache can key live-touching
        # query results on the exact snapshot they were computed from
        self.live_gen = 0
        # live-head device engine (db/live_engine): staged columnar
        # tails so live searches run the fused filter->top-k kernels;
        # None = device runtime unavailable, the index path serves alone
        try:
            from ..db.live_engine import LiveEngine

            self.live_engine = LiveEngine(self)
        except Exception as e:  # pragma: no cover - jax-less fallback
            # degrade loudly: every live search will take the slow index
            # walk, and the routing counter must say WHY, or an import
            # regression ships as an unexplained latency cliff
            self.live_engine = None
            from ..util.log import get_logger

            get_logger("ingester").error(
                "live-head engine unavailable for tenant %r, falling "
                "back to index search: %s: %s",
                tenant, type(e).__name__, e)
            try:
                from ..util.kerneltel import TEL

                TEL.record_routing("search_live", "index", "engine_init_failed")
            except Exception:
                pass

    # ---------------------------------------------------------------- push
    def push_segments(self, batch: list[tuple[bytes, int, int, bytes]]) -> None:
        """batch: [(trace_id, start_s, end_s, segment)]"""
        lim = self.overrides.for_tenant(self.tenant)
        now = time.time()
        with self.lock:
            # phase 1: validate the WHOLE batch before touching any state,
            # so a limit error never leaves a half-applied batch behind
            # (a retried batch would duplicate spans otherwise)
            new_tids = {tid for tid, *_ in batch if tid not in self.live}
            if lim.max_traces_per_user and len(self.live) + len(new_tids) > lim.max_traces_per_user:
                raise PushError(429, f"tenant {self.tenant}: max live traces reached")
            if lim.max_bytes_per_trace:
                incoming: dict[bytes, int] = {}
                for tid, _, _, seg in batch:
                    incoming[tid] = incoming.get(tid, 0) + len(seg)
                for tid, add in incoming.items():
                    base = self.live[tid].nbytes if tid in self.live else 0
                    if base + add > lim.max_bytes_per_trace:
                        raise PushError(400, "trace too large")
            # phase 2: apply
            for tid, s, e, seg in batch:
                lt = self.live.get(tid)
                if lt is None:
                    lt = self.live[tid] = LiveTrace(tid, start_s=s, end_s=e)
                lt.segments.append(seg)
                lt.nbytes += len(seg)
                lt.last_append = now
                lt.start_s = min(lt.start_s or s, s)
                lt.end_s = max(lt.end_s, e)
            self.live_gen += 1
            t_wal = time.perf_counter()
            if hasattr(self.head, "append_window"):
                # columnar WAL: the whole push window is ONE framed
                # record -- one CRC, one file write on the ack path
                self.head.append_window(batch)
            else:
                for tid, s, e, seg in batch:
                    self.head.append(tid, s, e, seg)
            self.head.flush()
            t_wal = time.perf_counter() - t_wal
        try:
            from ..util.kerneltel import TEL

            TEL.record_ingest_stage("wal_append", t_wal)
            TEL.record_ingest_window(len(batch),
                                     sum(len(seg) for *_, seg in batch))
        except Exception:
            pass
        if self.live_engine is not None:
            # staging-lag clock only -- the delta decode itself happens
            # at the next refresh, OFF this push path
            self.live_engine.note_push([tid for tid, *_ in batch], now)

    def flush_wal_features(self) -> int:
        """Checkpoint already-decoded segment features into the columnar
        WAL head (WAL2Block.flush_features): replay of a checkpointed
        segment re-enters the stage buckets without proto re-decode.
        Only features the columnar cache ALREADY holds are written --
        this never adds decode work. No-op on a legacy (w1) head."""
        head = self.head
        if not hasattr(head, "flush_features"):
            return 0
        with self.lock:
            if self.head is not head:  # rotated while unlocked: next sweep
                return 0
            n = head.flush_features(self.columnar.cached, self.columnar.dict)
            if n:
                head.flush()
            return n

    # ------------------------------------------------------------ lifecycle
    def cut_complete_traces(self, force: bool = False, now: float | None = None) -> int:
        """Idle live traces move to the cut set (instance.go:238-262)."""
        now = now or time.time()
        n = 0
        with self.lock:
            for tid in list(self.live):
                lt = self.live[tid]
                if force or (now - lt.last_append) >= self.cfg.max_trace_idle_s:
                    prev = self.cut.get(tid)
                    if prev:  # late spans for an already-cut trace merge in
                        prev.segments.extend(lt.segments)
                        prev.nbytes += lt.nbytes
                        prev.start_s = min(prev.start_s, lt.start_s)
                        prev.end_s = max(prev.end_s, lt.end_s)
                    else:
                        self.cut[tid] = lt
                    del self.live[tid]
                    n += 1
            if n:
                self.live_gen += 1
        return n

    def cut_block_if_ready(self, force: bool = False, now: float | None = None):
        """Cut set -> columnar block in the backend; WAL head rotates
        (instance.go:266-289 + CompleteBlock)."""
        now = now or time.time()
        with self.lock:
            if not self.cut:
                # nothing to write; an aged head with no live traces but
                # stale bytes (e.g. traces cut+flushed by a previous block,
                # replay leftovers) rotates so the old file can be dropped
                if (force or (now - self.head_created) > self.cfg.max_block_age_s) \
                        and not self.live and self.head.size_bytes() > 0:
                    old = self.head
                    self.head = self.wal.new_block(self.tenant, self.cfg.wal_version)
                    self.head_created = now
                    old.clear()
                return None
            age = now - self.head_created
            size = self.head.size_bytes()
            if not (force or age >= self.cfg.max_block_age_s or size >= self.cfg.max_block_bytes):
                return None
            t_cut = time.perf_counter()
            traces = []
            cut_snapshot = dict(self.cut)
            for tid, lt in self.cut.items():
                parts = [segment_to_trace(s) for s in lt.segments]
                traces.append((tid, sort_trace(combine_traces(parts)) if len(parts) > 1 else parts[0]))
            self.flushing.update(cut_snapshot)  # stay visible during the write
            self.cut.clear()
            # live traces staying behind move to the NEW head's WAL file so
            # the old file can be deleted after the block lands
            old_head = self.head
            self.head = self.wal.new_block(self.tenant, self.cfg.wal_version)
            self.head_created = now
            carry = [(lt.trace_id, lt.start_s, lt.end_s, seg)
                     for lt in self.live.values() for seg in lt.segments]
            if hasattr(self.head, "append_window"):
                if carry:
                    self.head.append_window(carry)
                    # carried segments were already decoded for staging:
                    # checkpoint those features into the fresh file so a
                    # crash-now replay skips their proto decode too
                    self.head.flush_features(self.columnar.cached,
                                             self.columnar.dict)
            else:
                for tid, s, e, seg in carry:
                    self.head.append(tid, s, e, seg)
            # the new head is about to become the ONLY wal copy of the
            # carried-over live traces (the old file is deleted once the
            # block lands): force the fsync
            self.head.flush(sync=True)
            t_cut = time.perf_counter() - t_cut
        try:
            from ..util.kerneltel import TEL

            TEL.record_ingest_stage("cut", t_cut)
        except Exception:
            pass
        try:
            t_flush = time.perf_counter()
            with timed(FLUSH_DURATION):
                meta = self.db.write_block(self.tenant, traces)
            try:
                from ..util.kerneltel import TEL

                TEL.record_ingest_stage("flush", time.perf_counter() - t_flush)
            except Exception:
                pass
        except Exception:
            FLUSH_FAILURES.inc()
            # block write failed: restore the cut set for the next retry;
            # the old WAL file stays on disk as the checkpoint. MERGE into
            # any entry cut for the same id since the snapshot (setdefault
            # would silently drop the snapshot's segments).
            with self.lock:
                for tid, lt in cut_snapshot.items():
                    if self.flushing.get(tid) is lt:
                        del self.flushing[tid]
                    cur = self.cut.get(tid)
                    if cur is None:
                        self.cut[tid] = lt
                    elif cur is not lt:
                        cur.segments = lt.segments + cur.segments
                        cur.nbytes += lt.nbytes
                        cur.start_s = min(cur.start_s or lt.start_s, lt.start_s)
                        cur.end_s = max(cur.end_s, lt.end_s)
            raise
        self.blocks_flushed += 1
        with self.lock:
            # the blocklist now carries the block (db.write_block updates
            # it before returning): retire the in-flight snapshot
            for tid, lt in cut_snapshot.items():
                if self.flushing.get(tid) is lt:
                    del self.flushing[tid]
            self.live_gen += 1  # the live window's contents changed
            # flushed segments left the live window: release their
            # decoded-feature cache entries
            for lt in cut_snapshot.values():
                self.columnar.discard(lt.segments)
        old_head.clear()  # checkpoint advanced: block is durable in backend
        return meta

    # ---------------------------------------------------------------- read
    def find_trace_by_id(self, trace_id: bytes) -> Trace | None:
        if self.live_engine is not None:
            return self.live_engine.find(trace_id)
        return self._find_live_map(trace_id)

    def _find_live_map(self, trace_id: bytes) -> Trace | None:
        """Hash-map find: segments combined in live/cut/flushing order
        (both the legacy path and the device engine materialize through
        here, so the two routes are bit-identical by construction)."""
        with self.lock:
            segs = []
            for src in (self.live.get(trace_id), self.cut.get(trace_id),
                        self.flushing.get(trace_id)):
                if src is not None:
                    segs.extend(src.segments)
        if not segs:
            return None
        return sort_trace(combine_traces([segment_to_trace(s) for s in segs]))

    def trace_segments(self, trace_id: bytes) -> list[bytes]:
        """Raw live/cut/flushing segments for one trace -- the quorum
        read's replica snapshot. Returned UNDECODED: the querier-side
        merge dedupes replicas by content digest before paying the
        decode, so shipping bytes (not Trace objects) is the point."""
        with self.lock:
            segs: list[bytes] = []
            for src in (self.live.get(trace_id), self.cut.get(trace_id),
                        self.flushing.get(trace_id)):
                if src is not None:
                    segs.extend(src.segments)
        return segs

    def _index_of(self, lt: LiveTrace) -> tuple[_SearchEntry, Trace]:
        """The trace's search index, (re)built only when segments arrived
        since the last build; the decoded trace is cached alongside so
        repeated TraceQL queries on an unchanged trace never re-run
        combine_traces over every segment. The segment snapshot is taken
        under the instance lock: a segment appended mid-build must not
        be counted as indexed."""
        with self.lock:
            segs = list(lt.segments)
            idx = lt.search_index
            if idx is not None and lt.indexed_segments == len(segs):
                return idx, lt.decoded
        tr = sort_trace(combine_traces([segment_to_trace(s) for s in segs]))
        idx = _SearchEntry.build(tr)
        with self.lock:
            lt.search_index = idx
            lt.decoded = tr
            lt.indexed_segments = len(segs)
        return idx, tr

    def _live_groups(self) -> dict:
        """Consistent snapshot of the live head MERGED BY TRACE ID:
        {tid: [segments, state, start_s, end_s, [LiveTrace, ...]]} with
        segments concatenated in flushing->cut->live order (the order
        the cut/flush lifecycle keeps prefix-stable, so the staging
        layer's delta detection works by identity). A trace straddling
        lifecycle states evaluates over its FULL segment set -- the same
        contract find_trace_by_id always had."""
        groups: dict[bytes, list] = {}
        with self.lock:
            for state, src in (("flushing", self.flushing), ("cut", self.cut),
                               ("live", self.live)):
                for tid, lt in src.items():
                    g = groups.get(tid)
                    if g is None:
                        groups[tid] = [list(lt.segments), state,
                                       lt.start_s, lt.end_s, [lt]]
                    else:
                        g[0].extend(lt.segments)
                        g[1] = state  # latest lifecycle state wins
                        g[2] = min(g[2], lt.start_s)
                        g[3] = max(g[3], lt.end_s)
                        g[4].append(lt)
        return groups

    def _live_entry(self, tid: bytes, lts: list, segs: list):
        """(entry, decoded trace) for one merged live trace: the cached
        per-LiveTrace index when the tid lives in a single lifecycle
        dict (the overwhelmingly common case), a transient merged build
        otherwise. BOTH the host oracle and the device engine's verify
        step come through here -- sharing it is what makes the two
        engines bit-identical."""
        if len(lts) == 1:
            return self._index_of(lts[0])
        tr = sort_trace(combine_traces([segment_to_trace(s) for s in segs]))
        return _SearchEntry.build(tr), tr

    def search_live(self, req: SearchRequest) -> SearchResponse:
        """Live + cut + flushing traces through the live-head device
        engine (db/live_engine): fused filter->top-k over staged
        columnar tails, candidates exactly re-verified against the same
        per-trace index the host oracle uses. Falls back to the index
        walk when the engine is unavailable or killed."""
        if self.live_engine is not None:
            return self.live_engine.search(req)
        return self.search_live_index(req)

    def metrics_query_range(self, req) -> "object":
        """TraceQL metrics over the MERGED live head (live/cut/flushing
        traces) via the exact host-twin fold (metrics_exec
        .metrics_live_traces): the ingester leg that makes unflushed
        spans visible to /api/metrics/query_range. Traces are the same
        cached decodes the search oracle uses. Known transient: a query
        sampling the instant between a flushed block's blocklist
        publish and the flushing-snapshot retirement (microseconds,
        cut_block_if_ready) can count those spans in both legs --
        search dedups by trace id across the same window; aggregated
        series cannot, matching the reference's flush semantics."""
        from ..db.metrics_exec import (
            MetricsResponse,
            expr_label,
            metrics_live_traces,
            parse_metrics_query,
        )

        q = parse_metrics_query(req.query)
        resp = MetricsResponse(
            fn=q.agg.fn, start_ms=req.start_ms, step_ms=req.step_ms,
            n_buckets=req.n_buckets,
            label_names=tuple(expr_label(e, i) for i, e in enumerate(q.agg.by)),
        )
        decoded = []
        for tid, (segs, _state, start_s, end_s, lts) in self._live_groups().items():
            # push-metadata time prefilter against the request range
            # (seconds resolution; 0 = unknown, never prunes)
            if end_s and end_s * 1000 < req.start_ms:
                continue
            if start_s and start_s * 1000 >= req.end_ms:
                continue
            _, tr = self._live_entry(tid, lts, segs)
            decoded.append(tr)
        metrics_live_traces(decoded, q, req, resp)
        return resp

    def search_live_index(self, req: SearchRequest) -> SearchResponse:
        """Host index walk over the merged live head -- the differential
        oracle for the device engine and the kill-switch fallback: tag,
        duration and time predicates come from the cached per-trace
        search index; TraceQL evaluates on the cached decoded trace.
        Results are newest-first (exact start_ns, trace id tiebreak),
        truncated to the limit AFTER the sort -- the same ordering the
        device engine's top-k produces."""
        from ..traceql.hosteval import trace_matches
        from ..traceql.parser import parse

        q = parse(req.query) if req.query else None
        resp = SearchResponse()
        matches: list[tuple[int, str, _SearchEntry]] = []
        for tid, (segs, _state, start_s, end_s, lts) in self._live_groups().items():
            if req.start and end_s < req.start:
                continue
            if req.end and start_s > req.end:
                continue
            idx, decoded = self._live_entry(tid, lts, segs)
            if req.tags and not idx.matches_tags(req.tags):
                continue
            if req.min_duration_ms and idx.dur_ms < req.min_duration_ms:
                continue
            if req.max_duration_ms and idx.dur_ms > req.max_duration_ms:
                continue
            if q is not None and not trace_matches(q, decoded):
                continue
            matches.append((idx.start_ns, tid.hex(), idx))
        matches.sort(key=lambda m: (-m[0], m[1]))
        for start_ns, tid_hex, idx in matches[: (req.limit or 20)]:
            resp.traces.append(
                SearchResult(
                    trace_id=tid_hex,
                    root_service_name=idx.root_service,
                    root_trace_name=idx.root_name,
                    start_time_unix_nano=idx.start_ns,
                    duration_ms=idx.dur_ms,
                )
            )
        return resp


class Ingester:
    """All tenants of one ingester process (modules/ingester/ingester.go)."""

    def __init__(self, wal: WAL, db: TempoDB, overrides, cfg: IngesterConfig | None = None):
        self.wal = wal
        self.db = db
        self.overrides = overrides
        self.cfg = cfg or IngesterConfig()
        self.instances: dict[str, Instance] = {}
        self.lock = threading.Lock()
        self._stop = threading.Event()
        self._flush_retry_at: dict[str, float] = {}
        self._flush_backoff: dict[str, float] = {}
        self._sweeper: threading.Thread | None = None
        self.replayed_blocks = 0

    def instance(self, tenant: str) -> Instance:
        with self.lock:
            inst = self.instances.get(tenant)
            if inst is None:
                inst = self.instances[tenant] = Instance(
                    tenant, self.wal, self.db, self.overrides, self.cfg
                )
            return inst

    # --------------------------------------------------------------- push
    def push_segments(self, tenant: str, batch) -> None:
        self.instance(tenant).push_segments(batch)

    # --------------------------------------------------------------- read
    def find_trace_by_id(self, tenant: str, trace_id: bytes) -> Trace | None:
        with self.lock:
            inst = self.instances.get(tenant)
        return inst.find_trace_by_id(trace_id) if inst else None

    def search(self, tenant: str, req: SearchRequest) -> SearchResponse:
        with self.lock:
            inst = self.instances.get(tenant)
        return inst.search_live(req) if inst else SearchResponse()

    def metrics_query_range(self, tenant: str, req):
        """Live-head TraceQL metrics leg (None when this ingester holds
        nothing for the tenant -- the querier skips empty legs)."""
        with self.lock:
            inst = self.instances.get(tenant)
        return inst.metrics_query_range(req) if inst else None

    def live_generation(self, tenant: str) -> int:
        """The tenant's live-head mutation generation (0 = no instance
        yet). The frontend result cache keys live-touching query
        results on this, so every push/cut/flush invalidates them."""
        with self.lock:
            inst = self.instances.get(tenant)
        return inst.live_gen if inst else 0

    def trace_snapshot(self, tenant: str, trace_id: bytes) -> list[tuple[str, bytes]]:
        """[(segment digest, segment bytes)] this replica holds for a
        trace; the querier's quorum read unions these across replicas."""
        with self.lock:
            inst = self.instances.get(tenant)
        if inst is None:
            return []
        from ..fleet.quorum import segment_digest
        return [(segment_digest(s), s) for s in inst.trace_segments(trace_id)]

    # ---------------------------------------------------------- lifecycle
    def replay_wal(self) -> int:
        """Startup: WAL files -> live state of fresh instances, then an
        immediate cut (ingester.go:326-400 replays into blocks)."""
        WAL_REPLAYS.inc()
        n = 0
        for rb in self.wal.rescan_blocks():
            if not rb.records:
                try:
                    self.wal.delete_block_file(rb.block_id, rb.tenant)
                except OSError:
                    pass
                continue
            inst = self.instance(rb.tenant)
            with inst.lock:
                # seed the file's dictionary delta FIRST, in file-code
                # order, so replayed feature codes land deterministically
                # in the instance dictionary before any staging touches it
                for s in rb.dict_delta:
                    inst.columnar.dict.code(s)
                for rec in rb.records:
                    lt = inst.live.setdefault(rec.trace_id, LiveTrace(rec.trace_id))
                    lt.segments.append(rec.segment)
                    lt.nbytes += len(rec.segment)
                    lt.start_s = min(lt.start_s or rec.start_s, rec.start_s)
                    lt.end_s = max(lt.end_s, rec.end_s)
                    lt.last_append = 0.0  # replayed = instantly idle
                for i, feat in rb.features.items():
                    # checkpointed features replay straight into the
                    # columnar cache: staging needs no proto re-decode
                    inst.columnar.seed_strings(rb.records[i].segment, *feat)
            try:
                from ..util.kerneltel import TEL

                TEL.record_ingest_replay(len(rb.records), len(rb.features),
                                         torn=not rb.clean)
            except Exception:
                pass
            n += len(rb.records)
            # records now tracked by the instance's new head after next cut;
            # the old file is superseded once a cut block lands
            inst.cut_complete_traces(force=True)
            inst.cut_block_if_ready(force=True)
            try:
                self.wal.delete_block_file(rb.block_id, rb.tenant)
            except OSError:
                pass
            self.replayed_blocks += 1
        return n

    def sweep_all(self, force: bool = False) -> None:
        with self.lock:
            insts = list(self.instances.values())
        now = time.time()
        for inst in insts:
            inst.cut_complete_traces(force=force)
            if inst.live_engine is not None:
                try:
                    # bound push->device-visible staging lag to the sweep
                    # cadence even when no query arrives
                    inst.live_engine.maybe_refresh()
                except Exception:  # staging must never block cuts
                    pass
            try:
                # features decoded by the refresh above checkpoint into
                # the WAL head so replay skips their proto decode
                inst.flush_wal_features()
            except Exception:  # checkpointing must never block cuts
                pass
            # per-tenant exponential backoff after a failed flush
            # (reference: flushqueues retry-with-backoff, flush.go:62-67)
            # -- a broken backend must not be hammered every sweep, and
            # one tenant's failures must not skip the others' cuts
            key = inst.tenant
            if not force and now < self._flush_retry_at.get(key, 0.0):
                continue
            try:
                inst.cut_block_if_ready(force=force)
                self._flush_retry_at.pop(key, None)
                self._flush_backoff.pop(key, None)
            except Exception:
                if force:
                    raise
                backoff = min(self._flush_backoff.get(key, 1.0) * 2, 60.0)
                self._flush_backoff[key] = backoff
                self._flush_retry_at[key] = now + backoff

    def start_sweeper(self) -> None:
        def loop():
            while not self._stop.wait(self.cfg.flush_check_period_s):
                try:
                    self.sweep_all()
                except Exception:  # noqa: BLE001 - sweeper must survive
                    pass

        self._sweeper = threading.Thread(target=loop, daemon=True, name="ingester-sweep")
        self._sweeper.start()

    def flush_all(self) -> None:
        """Graceful drain (/shutdown handler, flush.go:91-115)."""
        self.sweep_all(force=True)

    def stop(self) -> None:
        self._stop.set()
        self.flush_all()
        # commit this process's measured live-engine crossovers so the
        # next restart routes from measurements, not the env seed
        for inst in list(self.instances.values()):
            if getattr(inst, "live_engine", None) is not None:
                inst.live_engine.persist_crossover()
