"""Self-tracing: the backend traces its own query path into itself.

Reference: the app instruments handlers with its tracing client and
ships those spans like any tenant's (SURVEY.md 5.1) -- dogfooding that
makes slow queries debuggable with the product itself. Here a
SelfTracer records a root span per frontend query plus one child span
per dispatched job, and pushes the finished trace through the
distributor under a dedicated tenant. Pushes from the self tenant are
never traced (no recursion), and failures are swallowed -- observability
must not fail queries.
"""

from __future__ import annotations

import os
import threading
import time

from ..wire.model import Resource, ResourceSpans, Scope, ScopeSpans, Span, SpanKind


class SelfTracer:
    def __init__(self, push, tenant: str = "self", service: str = "tempo-tpu"):
        """push(tenant, [ResourceSpans]) -- the distributor entrypoint.
        Finished traces ship from a background thread (the reference's
        async batch exporter role): the query hot path only enqueues."""
        self.push = push
        self.tenant = tenant
        self.service = service
        self.spans_emitted = 0
        self._lock = threading.Lock()
        # processed-counter ack instead of polling queue emptiness:
        # _q.empty() flips true the instant the shipper DEQUEUES, before
        # its push (and the spans_emitted update) completes, so a flush
        # built on emptiness could return while the last trace was still
        # in flight
        self._done = threading.Condition(self._lock)
        self._enqueued = 0
        self._processed = 0
        import queue

        self._q: queue.SimpleQueue = queue.SimpleQueue()
        self._shipper = threading.Thread(target=self._ship_loop, daemon=True,
                                         name="selftrace-shipper")
        self._shipper.start()

    def trace(self, name: str, attrs: dict | None = None):
        return _ActiveTrace(self, name, attrs or {})

    def _enqueue(self, rs, n_spans: int) -> None:
        with self._lock:
            self._enqueued += 1
        self._q.put((rs, n_spans))

    def _ship_loop(self) -> None:
        while True:
            rs, n_spans = self._q.get()
            try:
                self.push(self.tenant, [rs])
                with self._lock:
                    self.spans_emitted += n_spans
            except Exception:
                pass  # self-observability must never fail anything
            finally:
                with self._done:
                    self._processed += 1
                    self._done.notify_all()

    def flush(self, timeout_s: float = 2.0) -> None:
        """Best-effort drain (tests): wait until every trace enqueued
        BEFORE this call has fully shipped (push returned and
        spans_emitted updated), not merely left the queue."""
        deadline = time.time() + timeout_s
        with self._done:
            target = self._enqueued
            while self._processed < target:
                remaining = deadline - time.time()
                if remaining <= 0:
                    break
                self._done.wait(remaining)


class _ActiveTrace:
    """One root span + flat children, finished and pushed on __exit__."""

    def __init__(self, tracer: SelfTracer, name: str, attrs: dict):
        self.tracer = tracer
        self.name = name
        self.attrs = attrs
        self.trace_id = os.urandom(16)
        self.root_id = os.urandom(8)
        self.t0 = 0.0
        self.children: list[tuple[str, float, float, dict]] = []
        self._lock = threading.Lock()

    def child(self, name: str, t_start: float, t_end: float, attrs: dict | None = None):
        with self._lock:
            self.children.append((name, t_start, t_end, attrs or {}))

    def __enter__(self):
        self.t0 = time.time()
        return self

    def __exit__(self, exc_type, exc, tb):
        t1 = time.time()
        if exc_type is not None:
            self.attrs["error"] = True
            self.attrs["error.type"] = exc_type.__name__
        spans = [Span(
            trace_id=self.trace_id,
            span_id=self.root_id,
            name=self.name,
            kind=SpanKind.SERVER,
            start_unix_nano=int(self.t0 * 1e9),
            end_unix_nano=int(t1 * 1e9),
            attrs=self.attrs,
        )]
        for name, cs, ce, attrs in self.children:
            spans.append(Span(
                trace_id=self.trace_id,
                span_id=os.urandom(8),
                parent_span_id=self.root_id,
                name=name,
                kind=SpanKind.INTERNAL,
                start_unix_nano=int(cs * 1e9),
                end_unix_nano=int(ce * 1e9),
                attrs=attrs,
            ))
        rs = ResourceSpans(
            resource=Resource(attrs={"service.name": self.tracer.service}),
            scope_spans=[ScopeSpans(scope=Scope(name="selftrace"), spans=spans)],
        )
        self.tracer._enqueue(rs, len(spans))
        return False
