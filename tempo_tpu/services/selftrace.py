"""Self-tracing: the backend traces its own query path into itself.

Reference: the app instruments handlers with its tracing client and
ships those spans like any tenant's (SURVEY.md 5.1) -- dogfooding that
makes slow queries debuggable with the product itself. A SelfTracer
records one HIERARCHICAL trace per frontend query: a root span, one
span per dispatched job (queue-wait as a child), and nested engine
spans (batch window, stream fetch/decompress/upload, kernel launches
with compile attrs, exact verify) attached by the hot paths through an
ambient contextvar -- no signature threading. Remote querier legs
propagate by (trace_id, parent_span_id) riding the wire job: the
remote process records its spans into a RemoteSpanRecorder and ships
them back WITH the job result, so the whole query lands as one tree
under the `self` tenant no matter where its legs ran.

Span capture on the hot path is two wall-clock reads and a list append
under a small lock; finished traces ship from a background thread (the
reference's async batch exporter role) through the distributor like any
tenant's push. The in-flight queue is BOUNDED: a stalled distributor
drops whole traces (counted, exported via kerneltel) instead of
growing process memory without limit. Pushes from the self tenant are
never traced (no recursion), and failures are swallowed --
observability must not fail queries.

Per-query cost attribution closes the loop: engine hooks accumulate
device ms / staged bytes / compiles / verified rows onto the active
trace (kerneltel add_query_cost); at root-span finish the totals become
`cost.*` root attrs and fold into per-tenant counters in kerneltel
(/status/kernels "query_costs", tempo_query_cost_total).
"""

from __future__ import annotations

import contextvars
import os
import threading
import time

from ..wire.model import Resource, ResourceSpans, Scope, ScopeSpans, Span, SpanKind

# in-flight trace cap: a stalled shipper must bound memory, not grow it
DEFAULT_QUEUE_MAX = 256

# ambient parent span id for the CURRENT execution context: set around
# job execution (frontend/worker) and nested span() bodies so engine
# child spans parent correctly without threading ids through signatures
_CURRENT_SPAN: contextvars.ContextVar = contextvars.ContextVar(
    "tempo_selftrace_span", default=None)


def set_current_span(span_id: bytes | None):
    """Park the ambient parent span id; returns a reset token."""
    return _CURRENT_SPAN.set(span_id)


def reset_current_span(token) -> None:
    try:
        _CURRENT_SPAN.reset(token)
    except Exception:
        pass


def current_span() -> bytes | None:
    return _CURRENT_SPAN.get()


class SelfTracer:
    def __init__(self, push, tenant: str = "self", service: str = "tempo-tpu",
                 queue_max: int | None = None):
        """push(tenant, [ResourceSpans]) -- the distributor entrypoint.
        Finished traces ship from a background thread (the reference's
        async batch exporter role): the query hot path only enqueues."""
        self.push = push
        self.tenant = tenant
        self.service = service
        self.spans_emitted = 0
        self.traces_dropped = 0
        if queue_max is None:
            try:
                queue_max = int(os.environ.get("TEMPO_SELFTRACE_QUEUE",
                                               DEFAULT_QUEUE_MAX))
            except ValueError:
                queue_max = DEFAULT_QUEUE_MAX
        self.queue_max = max(1, queue_max)
        self._lock = threading.Lock()
        # processed-counter ack instead of polling queue emptiness:
        # _q.empty() flips true the instant the shipper DEQUEUES, before
        # its push (and the spans_emitted update) completes, so a flush
        # built on emptiness could return while the last trace was still
        # in flight. (_enqueued - _processed) is also the in-flight
        # depth the bounded-queue drop policy gates on.
        self._done = threading.Condition(self._lock)
        self._enqueued = 0
        self._processed = 0
        import queue

        self._q: queue.SimpleQueue = queue.SimpleQueue()
        self._shipper = threading.Thread(target=self._ship_loop, daemon=True,
                                         name="selftrace-shipper")
        self._shipper.start()

    def trace(self, name: str, attrs: dict | None = None):
        return _ActiveTrace(self, name, attrs or {})

    def _enqueue(self, rs, n_spans: int) -> None:
        from ..util.kerneltel import TEL

        with self._lock:
            if self._enqueued - self._processed >= self.queue_max:
                # stalled shipper: drop the WHOLE trace with a counter --
                # self-observability must never grow memory unbounded
                self.traces_dropped += 1
                TEL.record_selftrace("dropped", n_spans)
                return
            self._enqueued += 1
        self._q.put((rs, n_spans))

    def _ship_loop(self) -> None:
        from ..util.kerneltel import TEL

        while True:
            rs, n_spans = self._q.get()
            try:
                self.push(self.tenant, [rs])
                with self._lock:
                    self.spans_emitted += n_spans
                TEL.record_selftrace("shipped", n_spans)
            except Exception:
                # self-observability must never fail anything -- but a
                # failing distributor must still COUNT: without this
                # outcome the queue drains fast, nothing ever reads as
                # dropped, and the TempoSelfTraceDropped alert stays
                # silent while every timeline is lost
                TEL.record_selftrace("push_failed", n_spans)
            finally:
                with self._done:
                    self._processed += 1
                    self._done.notify_all()

    def flush(self, timeout_s: float = 2.0) -> None:
        """Best-effort drain (tests): wait until every trace enqueued
        BEFORE this call has fully shipped (push returned and
        spans_emitted updated), not merely left the queue."""
        deadline = time.time() + timeout_s
        with self._done:
            target = self._enqueued
            while self._processed < target:
                remaining = deadline - time.time()
                if remaining <= 0:
                    break
                self._done.wait(remaining)


class _SpanCM:
    """One live nested span: `with trace.span("stage") as s:` parents
    under the ambient span, becomes the ambient parent for its body."""

    __slots__ = ("trace", "name", "attrs", "span_id", "parent_id", "t0", "_token")

    def __init__(self, trace: "_ActiveTrace", name: str, attrs: dict):
        self.trace = trace
        self.name = name
        self.attrs = attrs
        self.span_id = os.urandom(8)

    def __enter__(self):
        self.parent_id = _CURRENT_SPAN.get() or self.trace.root_id
        self.t0 = time.time()
        self._token = _CURRENT_SPAN.set(self.span_id)
        return self

    def __exit__(self, exc_type, exc, tb):
        reset_current_span(self._token)
        if exc_type is not None:
            self.attrs["error"] = True
            self.attrs["error.type"] = exc_type.__name__
        self.trace._record(self.name, self.t0, time.time(), self.attrs,
                           self.span_id, self.parent_id)
        return False


class _ActiveTrace:
    """One root span + a TREE of children, finished and pushed on
    __exit__. Children attach three ways: span() (nested context
    manager), child() (retroactive, measured by the caller), and
    add_remote_spans() (a remote leg's recorder shipped back with its
    job result). All are safe from any thread."""

    def __init__(self, tracer: SelfTracer, name: str, attrs: dict):
        self.tracer = tracer
        self.name = name
        self.attrs = attrs
        self.trace_id = os.urandom(16)
        self.root_id = os.urandom(8)
        self.t0 = 0.0
        # finished spans: (name, t0, t1, attrs, span_id, parent_id)
        self.spans: list[tuple] = []
        self.cost: dict[str, float] = {}
        self._lock = threading.Lock()

    # ------------------------------------------------------------- spans
    def span(self, name: str, attrs: dict | None = None) -> _SpanCM:
        return _SpanCM(self, name, attrs or {})

    def _record(self, name, t0, t1, attrs, span_id, parent_id) -> None:
        with self._lock:
            self.spans.append((name, t0, t1, attrs, span_id, parent_id))

    def child(self, name: str, t_start: float, t_end: float,
              attrs: dict | None = None, parent: bytes | None = None,
              span_id: bytes | None = None) -> bytes:
        """Retroactive child span (caller already measured it). Parent
        resolution: explicit arg > ambient contextvar > root. Returns
        the span id so callers can hang further children under it."""
        sid = span_id or os.urandom(8)
        pid = parent or _CURRENT_SPAN.get() or self.root_id
        self._record(name, t_start, t_end, attrs or {}, sid, pid)
        return sid

    def add_remote_spans(self, spans: list[dict]) -> None:
        """Graft a remote leg's recorded spans (RemoteSpanRecorder
        .to_wire() payload): ids/parents were assigned remotely against
        this trace's id space, so they land already linked."""
        for s in spans:
            try:
                if s.get("name") == "__cost__":
                    # the remote leg's cost totals fold into this
                    # trace's root attrs, not a rendered span
                    for k, v in (s.get("attrs") or {}).items():
                        self.add_cost(str(k), float(v))
                    continue
                self._record(
                    str(s["name"]), float(s["t0"]), float(s["t1"]),
                    dict(s.get("attrs") or {}),
                    bytes.fromhex(s["span_id"]), bytes.fromhex(s["parent_id"]))
            except Exception:
                continue  # a malformed remote span must not drop the trace

    def wire_context(self, parent_span_id: bytes | None = None) -> dict:
        """The (trace_id, parent_span_id) a wire job carries so a remote
        leg's spans parent into this tree."""
        return {"trace_id": self.trace_id.hex(),
                "parent_span_id": (parent_span_id or self.root_id).hex()}

    # -------------------------------------------------------------- cost
    def add_cost(self, key: str, value: float) -> None:
        """Accumulate one per-query cost dimension (device_ms,
        staged_bytes, compiles, rows_verified, ...) -- kerneltel's
        add_query_cost lands here from any thread the trace is parked
        in."""
        with self._lock:
            self.cost[key] = self.cost.get(key, 0) + value

    # --------------------------------------------------------- lifecycle
    def __enter__(self):
        self.t0 = time.time()
        return self

    def __exit__(self, exc_type, exc, tb):
        t1 = time.time()
        if exc_type is not None:
            self.attrs["error"] = True
            self.attrs["error.type"] = exc_type.__name__
        with self._lock:
            children = list(self.spans)
            cost = dict(self.cost)
        for k, v in sorted(cost.items()):
            self.attrs[f"cost.{k}"] = round(v, 3) if isinstance(v, float) else v
        spans = [Span(
            trace_id=self.trace_id,
            span_id=self.root_id,
            name=self.name,
            kind=SpanKind.SERVER,
            start_unix_nano=int(self.t0 * 1e9),
            end_unix_nano=int(t1 * 1e9),
            attrs=self.attrs,
        )]
        for name, cs, ce, attrs, sid, pid in children:
            spans.append(Span(
                trace_id=self.trace_id,
                span_id=sid,
                parent_span_id=pid,
                name=name,
                kind=SpanKind.INTERNAL,
                start_unix_nano=int(cs * 1e9),
                end_unix_nano=int(ce * 1e9),
                attrs=attrs,
            ))
        rs = ResourceSpans(
            resource=Resource(attrs={"service.name": self.tracer.service}),
            scope_spans=[ScopeSpans(scope=Scope(name="selftrace"), spans=spans)],
        )
        if cost:
            from ..util.kerneltel import TEL

            TEL.record_query_cost(str(self.attrs.get("tenant", "")), cost)
        self.tracer._enqueue(rs, len(spans))
        return False


class RemoteSpanRecorder:
    """The remote face of an _ActiveTrace: a querier worker executing a
    wire job builds one from the job's (trace_id, parent_span_id),
    parks it in the kerneltel contextvar, and every engine span hook
    (child_span / span() / add_cost) lands here exactly as it would on
    the frontend's trace. The recorded spans ship back WITH the job
    result (to_wire) and graft into the originating tree -- the query's
    remote leg joins the same timeline."""

    def __init__(self, trace_id_hex: str, parent_span_id_hex: str,
                 worker_id: str = ""):
        self.trace_id = bytes.fromhex(trace_id_hex)
        self.root_id = bytes.fromhex(parent_span_id_hex)  # remote spans'
        # default parent is the frontend-side JOB span, not a new root
        self.worker_id = worker_id
        self.spans: list[tuple] = []
        self.cost: dict[str, float] = {}
        self._lock = threading.Lock()

    def span(self, name: str, attrs: dict | None = None) -> _SpanCM:
        return _SpanCM(self, name, attrs or {})

    def _record(self, name, t0, t1, attrs, span_id, parent_id) -> None:
        with self._lock:
            self.spans.append((name, t0, t1, attrs, span_id, parent_id))

    def child(self, name: str, t_start: float, t_end: float,
              attrs: dict | None = None, parent: bytes | None = None,
              span_id: bytes | None = None) -> bytes:
        sid = span_id or os.urandom(8)
        pid = parent or _CURRENT_SPAN.get() or self.root_id
        self._record(name, t_start, t_end, attrs or {}, sid, pid)
        return sid

    def add_cost(self, key: str, value: float) -> None:
        with self._lock:
            self.cost[key] = self.cost.get(key, 0) + value

    def to_wire(self) -> list[dict]:
        with self._lock:
            spans = list(self.spans)
            cost = dict(self.cost)
        out = []
        for name, t0, t1, attrs, sid, pid in spans:
            a = dict(attrs)
            if self.worker_id:
                a.setdefault("querier", self.worker_id)
            out.append({"name": name, "t0": t0, "t1": t1, "attrs": a,
                        "span_id": sid.hex(), "parent_id": pid.hex()})
        if cost:
            # remote leg's cost rides as attrs on a zero-length span so
            # the frontend can fold it into the root totals
            out.append({"name": "__cost__", "t0": 0.0, "t1": 0.0,
                        "attrs": cost, "span_id": os.urandom(8).hex(),
                        "parent_id": self.root_id.hex()})
        return out
