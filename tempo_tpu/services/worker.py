"""Querier worker: attaches a standalone querier process to remote
query-frontends and pulls jobs.

Reference: modules/querier/worker -- each querier dials every frontend
and runs processor loops that recv a job, execute it locally, and send
the result back (frontend_processor.go:57-80). Here the stream is HTTP
long-poll against /internal/jobs/poll + /internal/jobs/result; the
frontend's queue and lease bookkeeping live in services/frontend.py.
"""

from __future__ import annotations

import json
import threading
import urllib.error
import urllib.request

from ..db.search import request_from_dict, response_to_dict
from ..util.kerneltel import TEL
from ..wire import otlp_json
from .querier import Querier


def _metas_for(querier: Querier, tenant: str, block_ids: list):
    """Resolve block ids against the local blocklist, refreshing once on
    poll lag (the same retry the single-job kinds do)."""
    metas = querier.db.blocklist.metas_by_id(tenant, block_ids)
    if len(metas) != len(block_ids):
        querier.db.poll_now()
        metas = querier.db.blocklist.metas_by_id(tenant, block_ids)
        if len(metas) != len(block_ids):
            raise OSError("blocklist lags the frontend: unknown block ids")
    return metas


def execute_job(querier: Querier, tenant: str, kind: str, payload: dict) -> dict:
    """Run one wire job against the local querier; returns the wire
    result dict (the inverse of frontend.decode_job_result)."""
    if kind == "multi":
        # frontend-merged same-key jobs: execute as ONE coalesced call so
        # the fused kernel batch forms here too (db/batchexec); kinds
        # without a multi API fall back to a per-job loop. Per-job
        # failures ship as __job_error__ markers so one poisoned query
        # never fails (or retries) its window-mates at the frontend.
        sub = payload["kind"]
        tenants = payload["tenants"]
        jobs = payload["jobs"]

        def wire(r, encode):
            if isinstance(r, Exception):
                from .frontend import _retryable

                return {"__job_error__": f"{type(r).__name__}: {r}",
                        "__retryable__": _retryable(r)}
            return encode(r)

        try:
            if sub == "search_blocks":
                items = [(t, _metas_for(querier, t, p["block_ids"]),
                          request_from_dict(p["req"]))
                         for t, p in zip(tenants, jobs)]
                return {"results": [
                    wire(r, response_to_dict)
                    for r in querier.search_blocks_multi(items)]}
            if sub == "search_block_shard":
                items = [(t, _metas_for(querier, t, [p["block_id"]])[0],
                          request_from_dict(p["req"]), p["groups"])
                         for t, p in zip(tenants, jobs)]
                return {"results": [
                    wire(r, response_to_dict)
                    for r in querier.search_block_shard_multi(items)]}
            if sub == "find_blocks":
                items = [(t, bytes.fromhex(p["trace_id"]),
                          _metas_for(querier, t, p["block_ids"]))
                         for t, p in zip(tenants, jobs)]
                return {"results": [
                    wire(tr, lambda v: {"trace": otlp_json.dumps(v)
                                        if v is not None else None})
                    for tr in querier.find_in_blocks_multi(items)]}
        except Exception:
            pass  # coalesced call itself failed: degrade to per job
        out = []
        for t, p in zip(tenants, jobs):
            try:
                out.append(execute_job(querier, t, sub, p))
            except Exception as e:
                out.append(wire(e, None))
        return {"results": out}
    if kind == "search_recent":
        req = request_from_dict(payload["req"])
        return response_to_dict(querier.search_recent(tenant, req))
    if kind == "search_blocks":
        req = request_from_dict(payload["req"])
        metas = querier.db.blocklist.metas_by_id(tenant, payload["block_ids"])
        if len(metas) != len(payload["block_ids"]):
            querier.db.poll_now()  # poll lag: refresh once before failing
            metas = querier.db.blocklist.metas_by_id(tenant, payload["block_ids"])
            if len(metas) != len(payload["block_ids"]):
                raise OSError("blocklist lags the frontend: unknown block ids")
        return response_to_dict(querier.search_blocks(tenant, metas, req))
    if kind == "search_block_shard":
        req = request_from_dict(payload["req"])
        metas = querier.db.blocklist.metas_by_id(tenant, [payload["block_id"]])
        if not metas:
            querier.db.poll_now()
            metas = querier.db.blocklist.metas_by_id(tenant, [payload["block_id"]])
            if not metas:
                raise OSError("blocklist lags the frontend: unknown block id")
        return response_to_dict(
            querier.search_block_shard(tenant, metas[0], req, payload["groups"])
        )
    if kind == "metrics_query_range":
        from ..db.metrics_exec import (
            request_from_dict as metrics_request_from_dict,
            response_to_dict as metrics_response_to_dict,
        )

        mreq = metrics_request_from_dict(payload["req"])
        return metrics_response_to_dict(querier.metrics_query_range(tenant, mreq))
    if kind == "find_recent":
        tr = querier.find_trace_by_id(
            tenant, bytes.fromhex(payload["trace_id"]), query_backend=False
        )
        return {"trace": otlp_json.dumps(tr) if tr is not None else None}
    if kind == "find_blocks":
        metas = querier.db.blocklist.metas_by_id(tenant, payload["block_ids"])
        tr = querier.find_in_blocks(tenant, bytes.fromhex(payload["trace_id"]), metas)
        return {"trace": otlp_json.dumps(tr) if tr is not None else None}
    raise ValueError(f"unknown job kind {kind!r}")


class QuerierWorker:
    """Long-poll worker loops against one or more frontend addresses."""

    def __init__(self, querier: Querier, frontend_addrs: list[str],
                 token: str = "", concurrency: int = 4, poll_wait_s: float = 5.0,
                 worker_id: str = ""):
        self.querier = querier
        self.addrs = [a.rstrip("/") for a in frontend_addrs]
        self.token = token
        self.poll_wait_s = poll_wait_s
        self.worker_id = worker_id
        self._stop = threading.Event()
        self._threads = [
            threading.Thread(target=self._loop, args=(addr,), daemon=True,
                             name=f"querier-worker-{addr}-{i}")
            for addr in self.addrs
            for i in range(concurrency)
        ]
        self.jobs_executed = 0
        self.jobs_failed = 0

    def start(self) -> None:
        for t in self._threads:
            t.start()

    def stop(self) -> None:
        self._stop.set()

    # frontend-down backoff: exponential with full jitter, capped -- a
    # restarting frontend must not be thundering-herded by a fleet of
    # workers all polling again on the same fixed 1 s tick
    BACKOFF_BASE_S = 0.5
    BACKOFF_CAP_S = 5.0

    def _post(self, addr: str, path: str, payload: dict, timeout: float) -> dict | None:
        from ..chaos import plane as chaos_plane

        if chaos_plane.tap("rpc.worker", key=path) is chaos_plane.DROP:
            raise OSError("chaos: worker rpc black-holed")
        headers = {"Content-Type": "application/json"}
        if self.token:
            headers["X-Tempo-Internal-Token"] = self.token
        req = urllib.request.Request(
            addr + path, data=json.dumps(payload).encode(), headers=headers
        )
        with urllib.request.urlopen(req, timeout=timeout) as r:
            body = r.read()
            return json.loads(body) if body else None

    def _loop(self, addr: str) -> None:
        import random

        backoff = self.BACKOFF_BASE_S
        while not self._stop.is_set():
            try:
                job = self._post(addr, "/internal/jobs/poll",
                                 {"wait_s": self.poll_wait_s,
                                  "worker_id": self.worker_id},
                                 timeout=self.poll_wait_s + 10.0)
            except (urllib.error.URLError, ConnectionError, OSError):
                # full jitter: sleep U(0, backoff), then double the cap
                self._stop.wait(random.random() * backoff)
                backoff = min(backoff * 2, self.BACKOFF_CAP_S)
                continue
            backoff = self.BACKOFF_BASE_S  # frontend answered: reset
            if not job or not job.get("id"):
                continue
            # deadline propagation: the frontend stamps the caller's
            # REMAINING time budget (relative seconds, so worker and
            # frontend clocks never need to agree) on the wire job --
            # a non-positive budget means the caller already gave up
            # and dispatch cancelled the job; scanning would burn
            # device time nobody can use
            dl = job.get("deadline_in_s")
            if dl is not None and float(dl) <= 0.0:
                TEL.record_routing("worker_job", "skipped",
                                   "deadline_exceeded")
                try:
                    # skipped=True: the job never exercised the backend
                    # -- it must not feed the frontend's breaker stats
                    self._post(addr, "/internal/jobs/result",
                               {"id": job["id"], "ok": False,
                                "error": "deadline exceeded before "
                                         "execution", "retryable": False,
                                "skipped": True},
                               timeout=10.0)
                except (urllib.error.URLError, ConnectionError, OSError):
                    pass
                continue
            out = {"id": job["id"]}
            # the frontend's dequeue placement (own/steal/unowned) rides
            # the wire job so THIS process's staged-cache hits attribute
            # to owner-vs-stolen routing in its own kerneltel
            ptoken = TEL.set_affinity_placement(job.get("placement", ""))
            # self-trace propagation: the wire job's (trace_id,
            # parent_span_id) seed a recorder that catches every engine
            # span/cost hook this leg fires; the spans ship back WITH
            # the result and graft into the frontend's tree
            recorder = None
            ctx = job.get("trace")
            if ctx and ctx.get("trace_id") and ctx.get("parent_span_id"):
                try:
                    from .selftrace import RemoteSpanRecorder

                    recorder = RemoteSpanRecorder(
                        ctx["trace_id"], ctx["parent_span_id"],
                        worker_id=self.worker_id)
                except Exception:
                    recorder = None
            ttoken = TEL.set_active_trace(recorder) if recorder else None
            try:
                result = execute_job(
                    self.querier, job.get("tenant", ""), job["kind"], job["payload"]
                )
                out.update(ok=True, result=result)
                self.jobs_executed += 1
            except Exception as e:  # noqa: BLE001 - report, let frontend retry
                from .frontend import _retryable

                out.update(ok=False, error=f"{type(e).__name__}: {e}",
                           retryable=_retryable(e))
                self.jobs_failed += 1
            finally:
                if ttoken is not None:
                    TEL.reset_active_trace(ttoken)
                TEL.reset_affinity_placement(ptoken)
            if recorder is not None:
                spans = recorder.to_wire()
                if spans:
                    out["self_spans"] = spans
            try:
                self._post(addr, "/internal/jobs/result", out, timeout=10.0)
            except (urllib.error.URLError, ConnectionError, OSError):
                continue  # lease expiry re-dispatches the job
