"""Metrics-generator: span-metrics + service-graphs processors over an
active-series registry.

Reference: modules/generator -- spanmetrics (spanmetrics.go:79-96: RED
counters/histograms per (service, span_name, kind, status)),
servicegraphs (servicegraphs.go:62-80: client/server span pairing via
an expiring edge store), registry with staleness + max-active-series
(registry/registry.go).

TPU-first: spans buffer into flat column arrays and aggregate with ONE
jitted segmented reduce per collection cycle (ops/reduce.py) -- the
BASELINE config #5 "span-metrics aggregation as TPU reduce" -- instead
of the reference's per-span map updates.
"""

from __future__ import annotations

import threading
import time
from collections import defaultdict
from dataclasses import dataclass, field

import numpy as np

from ..wire.model import SpanKind, StatusCode, Trace

# seconds histogram buckets (reference spanmetrics defaults)
LATENCY_BUCKETS = (0.002, 0.004, 0.008, 0.016, 0.032, 0.064, 0.128, 0.256,
                   0.512, 1.024, 2.048, 4.096, 8.192, 16.384)


@dataclass
class SeriesKey:
    service: str
    span_name: str
    kind: int
    status: int

    def labels(self) -> str:
        return (
            f'service="{self.service}",span_name="{self.span_name}",'
            f'span_kind="{SpanKind(self.kind).name}",status_code="{StatusCode(self.status).name}"'
        )


class SpanMetricsProcessor:
    """Buffers spans as columns; a device segmented-reduce folds them
    into per-series counts/sums/bucket increments on collect()."""

    def __init__(self, max_active_series: int = 0):
        self.lock = threading.Lock()
        self.keys: dict[tuple, int] = {}  # series key -> sid
        self.key_list: list[SeriesKey] = []
        self.free_sids: list[int] = []  # evicted slots, reused on new series
        self.max_active_series = max_active_series
        self.dropped_series = 0
        # pending span columns
        self._sid: list[int] = []
        self._dur_s: list[float] = []
        # exemplars: last observed (trace_id hex, duration s) per series
        self.exemplars: dict[int, tuple[str, float]] = {}
        # aggregated state
        self.calls = np.zeros(0, dtype=np.int64)
        self.lat_sum = np.zeros(0, dtype=np.float64)
        self.lat_count = np.zeros(0, dtype=np.int64)
        self.lat_buckets = np.zeros((0, len(LATENCY_BUCKETS) + 1), dtype=np.int64)
        self.last_update: dict[int, float] = {}

    def push(self, tenant_unused: str, traces: list[Trace]) -> None:
        with self.lock:
            for tr in traces:
                for res, _, sp in tr.all_spans():
                    k = (res.service_name, sp.name, int(sp.kind), int(sp.status_code))
                    sid = self.keys.get(k)
                    if sid is None:
                        active = len(self.key_list) - len(self.free_sids)
                        if self.max_active_series and active >= self.max_active_series:
                            self.dropped_series += 1
                            continue
                        if self.free_sids:  # reuse an evicted slot
                            sid = self.free_sids.pop()
                            self.key_list[sid] = SeriesKey(*k)
                            self.keys[k] = sid
                        else:
                            sid = self.keys[k] = len(self.key_list)
                            self.key_list.append(SeriesKey(*k))
                    dur_s = max(0, sp.duration_nanos) / 1e9
                    self._sid.append(sid)
                    self._dur_s.append(dur_s)
                    self.last_update[sid] = time.time()
                    if sp.trace_id:
                        self.exemplars[sid] = (sp.trace_id.hex(), dur_s)

    def collect(self) -> None:
        """Fold pending spans into series state with the device reduce."""
        with self.lock:
            if not self._sid:
                return
            sid = np.asarray(self._sid, dtype=np.int32)
            dur = np.asarray(self._dur_s, dtype=np.float32)
            self._sid, self._dur_s = [], []
            n_series = len(self.key_list)
        from ..ops.reduce import span_metrics_reduce

        calls, lsum, buckets = span_metrics_reduce(sid, dur, n_series, LATENCY_BUCKETS)
        with self.lock:
            if len(self.calls) < n_series:
                pad = n_series - len(self.calls)
                self.calls = np.concatenate([self.calls, np.zeros(pad, np.int64)])
                self.lat_sum = np.concatenate([self.lat_sum, np.zeros(pad, np.float64)])
                self.lat_count = np.concatenate([self.lat_count, np.zeros(pad, np.int64)])
                self.lat_buckets = np.concatenate(
                    [self.lat_buckets, np.zeros((pad, self.lat_buckets.shape[1]), np.int64)]
                )
            self.calls[:n_series] += calls[:n_series]
            self.lat_sum[:n_series] += lsum[:n_series]
            self.lat_count[:n_series] += calls[:n_series]
            self.lat_buckets[:n_series] += buckets[:n_series]

    def evict_stale(self, max_idle_s: float, now: float | None = None) -> int:
        """Staleness eviction (registry.go): series with no updates for
        max_idle_s stop being exported; their key slots are freed for
        reuse so long-running processes don't grow without bound."""
        now = now or time.time()
        with self.lock:
            stale = [s for s, ts in self.last_update.items() if now - ts > max_idle_s]
            for s in stale:
                del self.last_update[s]
                key = self.key_list[s]
                self.keys.pop((key.service, key.span_name, key.kind, key.status), None)
                # zero the counter rows so a reused slot starts fresh,
                # then free the sid for the next new series
                self.exemplars.pop(s, None)
                if s < len(self.calls):
                    self.calls[s] = 0
                    self.lat_sum[s] = 0.0
                    self.lat_count[s] = 0
                    self.lat_buckets[s, :] = 0
                self.free_sids.append(s)
            return len(stale)

    def metrics_text(self) -> list[str]:
        self.collect()
        out = []
        with self.lock:
            for sid, key in enumerate(self.key_list):
                if sid >= len(self.calls) or self.calls[sid] == 0:
                    continue
                if sid not in self.last_update:
                    continue  # evicted as stale
                lab = key.labels()
                out.append(f"traces_spanmetrics_calls_total{{{lab}}} {int(self.calls[sid])}")
                out.append(
                    f"traces_spanmetrics_latency_sum{{{lab}}} {self.lat_sum[sid]:.6f}"
                )
                out.append(
                    f"traces_spanmetrics_latency_count{{{lab}}} {int(self.lat_count[sid])}"
                )
                ex = self.exemplars.get(sid)
                cum = 0
                for bi, edge in enumerate(LATENCY_BUCKETS):
                    cum += int(self.lat_buckets[sid, bi])
                    line = f'traces_spanmetrics_latency_bucket{{{lab},le="{edge}"}} {cum}'
                    if ex is not None and ex[1] <= edge and (bi == 0 or ex[1] > LATENCY_BUCKETS[bi - 1]):
                        # OpenMetrics exemplar: the trace behind this bucket
                        line += f' # {{trace_id="{ex[0]}"}} {ex[1]:.6f}'
                    out.append(line)
                cum += int(self.lat_buckets[sid, -1])
                line = f'traces_spanmetrics_latency_bucket{{{lab},le="+Inf"}} {cum}'
                if ex is not None and ex[1] > LATENCY_BUCKETS[-1]:
                    line += f' # {{trace_id="{ex[0]}"}} {ex[1]:.6f}'
                out.append(line)
        return out


@dataclass
class _Edge:
    client_service: str = ""
    server_service: str = ""
    client_dur_s: float = 0.0
    server_dur_s: float = 0.0
    failed: bool = False
    t: float = 0.0


class ServiceGraphsProcessor:
    """Pairs client/server spans by (trace_id, span_id/parent_id) through
    an expiring edge store (servicegraphs store/store.go), emitting the
    reference's full edge series (servicegraphs.go:62-80): request
    counts, failed counts, and client/server latency histograms. Like
    span-metrics, completed edges buffer as columns and fold through the
    device segmented reduce on collect()."""

    def __init__(self, wait_s: float = 10.0, max_items: int = 10_000):
        self.lock = threading.Lock()
        self.wait_s = wait_s
        self.max_items = max_items
        self.pending: dict[tuple, _Edge] = {}
        self.edge_ids: dict[tuple[str, str], int] = {}
        self.edge_list: list[tuple[str, str]] = []
        self.expired = 0
        # pending completed-edge columns
        self._eid: list[int] = []
        self._client_dur: list[float] = []
        self._server_dur: list[float] = []
        self._failed: list[bool] = []
        # aggregated state, per edge id
        self.counts = np.zeros(0, dtype=np.int64)
        self.failed_counts = np.zeros(0, dtype=np.int64)
        self.client_sum = np.zeros(0, dtype=np.float64)
        self.server_sum = np.zeros(0, dtype=np.float64)
        self.client_buckets = np.zeros((0, len(LATENCY_BUCKETS) + 1), dtype=np.int64)
        self.server_buckets = np.zeros((0, len(LATENCY_BUCKETS) + 1), dtype=np.int64)

    def push(self, tenant_unused: str, traces: list[Trace]) -> None:
        now = time.time()
        with self.lock:
            for tr in traces:
                for res, _, sp in tr.all_spans():
                    failed = int(sp.status_code) == 2
                    dur_s = max(0, sp.duration_nanos) / 1e9
                    if sp.kind == SpanKind.CLIENT:
                        key = (sp.trace_id, sp.span_id)
                        e = self.pending.setdefault(key, _Edge(t=now))
                        e.client_service = res.service_name
                        e.client_dur_s = dur_s
                        e.failed = e.failed or failed
                    elif sp.kind == SpanKind.SERVER:
                        key = (sp.trace_id, sp.parent_span_id)
                        e = self.pending.setdefault(key, _Edge(t=now))
                        e.server_service = res.service_name
                        e.server_dur_s = dur_s
                        e.failed = e.failed or failed
                    else:
                        continue
                    if e.client_service and e.server_service:
                        ek = (e.client_service, e.server_service)
                        eid = self.edge_ids.get(ek)
                        if eid is None:
                            eid = self.edge_ids[ek] = len(self.edge_list)
                            self.edge_list.append(ek)
                        self._eid.append(eid)
                        self._client_dur.append(e.client_dur_s)
                        self._server_dur.append(e.server_dur_s)
                        self._failed.append(e.failed)
                        del self.pending[key]
            self._expire(now)

    def _expire(self, now: float) -> None:
        if len(self.pending) > self.max_items:
            cutoff = now - self.wait_s
            for k in [k for k, e in self.pending.items() if e.t < cutoff]:
                del self.pending[k]
                self.expired += 1

    def collect(self) -> None:
        """Fold pending completed edges into per-edge series with the
        same segmented reduce the span-metrics processor uses."""
        with self.lock:
            if not self._eid:
                return
            eid = np.asarray(self._eid, dtype=np.int32)
            cdur = np.asarray(self._client_dur, dtype=np.float32)
            sdur = np.asarray(self._server_dur, dtype=np.float32)
            failed = np.asarray(self._failed, dtype=bool)
            self._eid, self._client_dur, self._server_dur, self._failed = [], [], [], []
            n_edges = len(self.edge_list)
        from ..ops.reduce import span_metrics_reduce

        ccalls, csum, cbuckets = span_metrics_reduce(eid, cdur, n_edges, LATENCY_BUCKETS)
        _, ssum, sbuckets = span_metrics_reduce(eid, sdur, n_edges, LATENCY_BUCKETS)
        fcounts = np.bincount(eid[failed], minlength=n_edges).astype(np.int64)
        with self.lock:
            if len(self.counts) < n_edges:
                pad = n_edges - len(self.counts)
                zb = np.zeros((pad, self.client_buckets.shape[1]), np.int64)
                self.counts = np.concatenate([self.counts, np.zeros(pad, np.int64)])
                self.failed_counts = np.concatenate([self.failed_counts, np.zeros(pad, np.int64)])
                self.client_sum = np.concatenate([self.client_sum, np.zeros(pad, np.float64)])
                self.server_sum = np.concatenate([self.server_sum, np.zeros(pad, np.float64)])
                self.client_buckets = np.concatenate([self.client_buckets, zb])
                self.server_buckets = np.concatenate([self.server_buckets, zb.copy()])
            self.counts[:n_edges] += ccalls[:n_edges]
            self.failed_counts[:n_edges] += fcounts[:n_edges]
            self.client_sum[:n_edges] += csum[:n_edges]
            self.server_sum[:n_edges] += ssum[:n_edges]
            self.client_buckets[:n_edges] += cbuckets[:n_edges]
            self.server_buckets[:n_edges] += sbuckets[:n_edges]

    def metrics_text(self) -> list[str]:
        self.collect()
        out = []
        with self.lock:
            for eid, (c, s) in enumerate(self.edge_list):
                if eid >= len(self.counts) or self.counts[eid] == 0:
                    continue
                lab = f'client="{c}",server="{s}"'
                out.append(f"traces_service_graph_request_total{{{lab}}} {int(self.counts[eid])}")
                out.append(
                    f"traces_service_graph_request_failed_total{{{lab}}} "
                    f"{int(self.failed_counts[eid])}"
                )
                for side, total, buckets in (
                    ("client", self.client_sum, self.client_buckets),
                    ("server", self.server_sum, self.server_buckets),
                ):
                    out.append(
                        f"traces_service_graph_request_{side}_seconds_sum{{{lab}}} "
                        f"{total[eid]:.6f}"
                    )
                    out.append(
                        f"traces_service_graph_request_{side}_seconds_count{{{lab}}} "
                        f"{int(self.counts[eid])}"
                    )
                    cum = 0
                    for bi, edge in enumerate(LATENCY_BUCKETS):
                        cum += int(buckets[eid, bi])
                        out.append(
                            f'traces_service_graph_request_{side}_seconds_bucket'
                            f'{{{lab},le="{edge}"}} {cum}'
                        )
                    cum += int(buckets[eid, -1])
                    out.append(
                        f'traces_service_graph_request_{side}_seconds_bucket'
                        f'{{{lab},le="+Inf"}} {cum}'
                    )
        return out


class MetricsGenerator:
    """Per-tenant processor sets, fed by the distributor tap
    (modules/generator/generator.go)."""

    def __init__(self, overrides, processors: tuple[str, ...] = ("span-metrics", "service-graphs"),
                 stale_series_s: float = 300.0):
        self.overrides = overrides
        self.default_processors = processors
        self.stale_series_s = stale_series_s
        self.lock = threading.Lock()
        self.tenants: dict[str, dict[str, object]] = {}

    def _procs(self, tenant: str) -> dict[str, object]:
        with self.lock:
            procs = self.tenants.get(tenant)
            if procs is None:
                lim = self.overrides.for_tenant(tenant)
                enabled = lim.metrics_generator_processors or self.default_processors
                procs = {}
                if "span-metrics" in enabled:
                    procs["span-metrics"] = SpanMetricsProcessor(
                        lim.metrics_generator_max_active_series
                    )
                if "service-graphs" in enabled:
                    procs["service-graphs"] = ServiceGraphsProcessor()
                self.tenants[tenant] = procs
            return procs

    def push(self, tenant: str, traces: list[Trace]) -> None:
        for p in self._procs(tenant).values():
            p.push(tenant, traces)

    def metrics_text(self) -> list[str]:
        out = []
        with self.lock:
            items = list(self.tenants.items())
        for tenant, procs in items:
            for p in procs.values():
                if isinstance(p, SpanMetricsProcessor):
                    p.evict_stale(self.stale_series_s)
                out.extend(p.metrics_text())
        return out
