"""Metrics-generator: span-metrics + service-graphs processors over an
active-series registry.

Reference: modules/generator -- spanmetrics (spanmetrics.go:79-96: RED
counters/histograms per (service, span_name, kind, status)),
servicegraphs (servicegraphs.go:62-80: client/server span pairing via
an expiring edge store), registry with staleness + max-active-series
(registry/registry.go).

TPU-first, two generations deep. The legacy processors
(SpanMetricsProcessor / ServiceGraphsProcessor) walk decoded Trace
objects in Python and fold buffered columns per collection cycle; they
remain as the differential oracle and the decoded-trace entry point.
The STREAMING processors ride the PR-16 write path: the distributor
tap hands over ColumnarIngest SpanColumns (coded inside the one proto
decode the ingest path already performs -- zero extra walks, proven by
the ColumnarIngest.decodes counter), series keys assemble as
vectorized packed-code hashing against the never-remapping LiveDict,
and every push window folds immediately through the device segmented
reduces in ops/reduce.py (span_metrics_reduce / edge_metrics_reduce),
so scrape time does no aggregation work at all.
"""

from __future__ import annotations

import threading
import time
from collections import defaultdict
from dataclasses import dataclass, field

import numpy as np

from ..ingest.columnar import LiveDict, SpanColumns, span_columns_from_trace
from ..wire.model import SpanKind, StatusCode, Trace

# seconds histogram buckets (reference spanmetrics defaults)
LATENCY_BUCKETS = (0.002, 0.004, 0.008, 0.016, 0.032, 0.064, 0.128, 0.256,
                   0.512, 1.024, 2.048, 4.096, 8.192, 16.384)


@dataclass
class SeriesKey:
    service: str
    span_name: str
    kind: int
    status: int

    def labels(self) -> str:
        return (
            f'service="{self.service}",span_name="{self.span_name}",'
            f'span_kind="{SpanKind(self.kind).name}",status_code="{StatusCode(self.status).name}"'
        )


class SpanMetricsProcessor:
    """Buffers spans as columns; a device segmented-reduce folds them
    into per-series counts/sums/bucket increments on collect()."""

    def __init__(self, max_active_series: int = 0):
        self.lock = threading.Lock()
        self.keys: dict[tuple, int] = {}  # series key -> sid
        self.key_list: list[SeriesKey] = []
        self.free_sids: list[int] = []  # evicted slots, reused on new series
        self.max_active_series = max_active_series
        self.dropped_series = 0
        # pending span columns
        self._sid: list[int] = []
        self._dur_s: list[float] = []
        # exemplars: last observed (trace_id hex, duration s) per series
        self.exemplars: dict[int, tuple[str, float]] = {}
        # aggregated state
        self.calls = np.zeros(0, dtype=np.int64)
        self.lat_sum = np.zeros(0, dtype=np.float64)
        self.lat_count = np.zeros(0, dtype=np.int64)
        self.lat_buckets = np.zeros((0, len(LATENCY_BUCKETS) + 1), dtype=np.int64)
        self.last_update: dict[int, float] = {}

    def push(self, tenant_unused: str, traces: list[Trace]) -> None:
        with self.lock:
            for tr in traces:
                for res, _, sp in tr.all_spans():
                    k = (res.service_name, sp.name, int(sp.kind), int(sp.status_code))
                    sid = self.keys.get(k)
                    if sid is None:
                        active = len(self.key_list) - len(self.free_sids)
                        if self.max_active_series and active >= self.max_active_series:
                            self.dropped_series += 1
                            continue
                        if self.free_sids:  # reuse an evicted slot
                            sid = self.free_sids.pop()
                            self.key_list[sid] = SeriesKey(*k)
                            self.keys[k] = sid
                        else:
                            sid = self.keys[k] = len(self.key_list)
                            self.key_list.append(SeriesKey(*k))
                    dur_s = max(0, sp.duration_nanos) / 1e9
                    self._sid.append(sid)
                    self._dur_s.append(dur_s)
                    self.last_update[sid] = time.time()
                    if sp.trace_id:
                        self.exemplars[sid] = (sp.trace_id.hex(), dur_s)

    def collect(self) -> None:
        """Fold pending spans into series state with the device reduce."""
        with self.lock:
            if not self._sid:
                return
            sid = np.asarray(self._sid, dtype=np.int32)
            dur = np.asarray(self._dur_s, dtype=np.float32)
            self._sid, self._dur_s = [], []
            n_series = len(self.key_list)
        from ..ops.reduce import span_metrics_reduce

        calls, lsum, buckets = span_metrics_reduce(sid, dur, n_series, LATENCY_BUCKETS)
        with self.lock:
            self._apply_fold_locked(n_series, calls, lsum, buckets)

    def _apply_fold_locked(self, n_series: int, calls, lsum, buckets) -> None:
        """Accumulate one fold's per-series outputs into the registry
        state (caller holds self.lock). Shared by the legacy collect()
        cycle and the streaming per-window path."""
        if len(self.calls) < n_series:
            pad = n_series - len(self.calls)
            self.calls = np.concatenate([self.calls, np.zeros(pad, np.int64)])
            self.lat_sum = np.concatenate([self.lat_sum, np.zeros(pad, np.float64)])
            self.lat_count = np.concatenate([self.lat_count, np.zeros(pad, np.int64)])
            self.lat_buckets = np.concatenate(
                [self.lat_buckets, np.zeros((pad, self.lat_buckets.shape[1]), np.int64)]
            )
        self.calls[:n_series] += calls[:n_series]
        self.lat_sum[:n_series] += lsum[:n_series]
        self.lat_count[:n_series] += calls[:n_series]
        self.lat_buckets[:n_series] += buckets[:n_series]

    def evict_stale(self, max_idle_s: float, now: float | None = None) -> int:
        """Staleness eviction (registry.go): series with no updates for
        max_idle_s stop being exported; their key slots are freed for
        reuse so long-running processes don't grow without bound."""
        now = now or time.time()
        with self.lock:
            stale = [s for s, ts in self.last_update.items() if now - ts > max_idle_s]
            for s in stale:
                del self.last_update[s]
                key = self.key_list[s]
                self.keys.pop((key.service, key.span_name, key.kind, key.status), None)
                # zero the counter rows so a reused slot starts fresh,
                # then free the sid for the next new series
                self.exemplars.pop(s, None)
                if s < len(self.calls):
                    self.calls[s] = 0
                    self.lat_sum[s] = 0.0
                    self.lat_count[s] = 0
                    self.lat_buckets[s, :] = 0
                self.free_sids.append(s)
            return len(stale)

    def metrics_text(self) -> list[str]:
        self.collect()
        out = []
        with self.lock:
            for sid, key in enumerate(self.key_list):
                if sid >= len(self.calls) or self.calls[sid] == 0:
                    continue
                if sid not in self.last_update:
                    continue  # evicted as stale
                lab = key.labels()
                out.append(f"traces_spanmetrics_calls_total{{{lab}}} {int(self.calls[sid])}")
                out.append(
                    f"traces_spanmetrics_latency_sum{{{lab}}} {self.lat_sum[sid]:.6f}"
                )
                out.append(
                    f"traces_spanmetrics_latency_count{{{lab}}} {int(self.lat_count[sid])}"
                )
                ex = self.exemplars.get(sid)
                cum = 0
                for bi, edge in enumerate(LATENCY_BUCKETS):
                    cum += int(self.lat_buckets[sid, bi])
                    line = f'traces_spanmetrics_latency_bucket{{{lab},le="{edge}"}} {cum}'
                    if ex is not None and ex[1] <= edge and (bi == 0 or ex[1] > LATENCY_BUCKETS[bi - 1]):
                        # OpenMetrics exemplar: the trace behind this bucket
                        line += f' # {{trace_id="{ex[0]}"}} {ex[1]:.6f}'
                    out.append(line)
                cum += int(self.lat_buckets[sid, -1])
                line = f'traces_spanmetrics_latency_bucket{{{lab},le="+Inf"}} {cum}'
                if ex is not None and ex[1] > LATENCY_BUCKETS[-1]:
                    line += f' # {{trace_id="{ex[0]}"}} {ex[1]:.6f}'
                out.append(line)
        return out


@dataclass
class _Edge:
    client_service: str = ""
    server_service: str = ""
    client_dur_s: float = 0.0
    server_dur_s: float = 0.0
    failed: bool = False
    t: float = 0.0


class ServiceGraphsProcessor:
    """Pairs client/server spans by (trace_id, span_id/parent_id) through
    an expiring edge store (servicegraphs store/store.go), emitting the
    reference's full edge series (servicegraphs.go:62-80): request
    counts, failed counts, and client/server latency histograms. Like
    span-metrics, completed edges buffer as columns and fold through the
    device segmented reduce on collect()."""

    def __init__(self, wait_s: float = 10.0, max_items: int = 10_000):
        self.lock = threading.Lock()
        self.wait_s = wait_s
        self.max_items = max_items
        self.pending: dict[tuple, _Edge] = {}
        self.edge_ids: dict[tuple[str, str], int] = {}
        self.edge_list: list[tuple[str, str]] = []
        self.expired = 0
        # pending completed-edge columns
        self._eid: list[int] = []
        self._client_dur: list[float] = []
        self._server_dur: list[float] = []
        self._failed: list[bool] = []
        # aggregated state, per edge id
        self.counts = np.zeros(0, dtype=np.int64)
        self.failed_counts = np.zeros(0, dtype=np.int64)
        self.client_sum = np.zeros(0, dtype=np.float64)
        self.server_sum = np.zeros(0, dtype=np.float64)
        self.client_buckets = np.zeros((0, len(LATENCY_BUCKETS) + 1), dtype=np.int64)
        self.server_buckets = np.zeros((0, len(LATENCY_BUCKETS) + 1), dtype=np.int64)

    def push(self, tenant_unused: str, traces: list[Trace]) -> None:
        now = time.time()
        with self.lock:
            for tr in traces:
                for res, _, sp in tr.all_spans():
                    failed = int(sp.status_code) == 2
                    dur_s = max(0, sp.duration_nanos) / 1e9
                    if sp.kind == SpanKind.CLIENT:
                        key = (sp.trace_id, sp.span_id)
                        e = self.pending.setdefault(key, _Edge(t=now))
                        e.client_service = res.service_name
                        e.client_dur_s = dur_s
                        e.failed = e.failed or failed
                    elif sp.kind == SpanKind.SERVER:
                        key = (sp.trace_id, sp.parent_span_id)
                        e = self.pending.setdefault(key, _Edge(t=now))
                        e.server_service = res.service_name
                        e.server_dur_s = dur_s
                        e.failed = e.failed or failed
                    else:
                        continue
                    if e.client_service and e.server_service:
                        ek = (e.client_service, e.server_service)
                        eid = self.edge_ids.get(ek)
                        if eid is None:
                            eid = self.edge_ids[ek] = len(self.edge_list)
                            self.edge_list.append(ek)
                        self._eid.append(eid)
                        self._client_dur.append(e.client_dur_s)
                        self._server_dur.append(e.server_dur_s)
                        self._failed.append(e.failed)
                        del self.pending[key]
            self._expire(now)

    def _expire(self, now: float) -> None:
        if len(self.pending) > self.max_items:
            cutoff = now - self.wait_s
            for k in [k for k, e in self.pending.items() if e.t < cutoff]:
                del self.pending[k]
                self.expired += 1

    def collect(self) -> None:
        """Fold pending completed edges into per-edge series with the
        same segmented reduce the span-metrics processor uses."""
        with self.lock:
            if not self._eid:
                return
            eid = np.asarray(self._eid, dtype=np.int32)
            cdur = np.asarray(self._client_dur, dtype=np.float32)
            sdur = np.asarray(self._server_dur, dtype=np.float32)
            failed = np.asarray(self._failed, dtype=bool)
            self._eid, self._client_dur, self._server_dur, self._failed = [], [], [], []
            n_edges = len(self.edge_list)
        from ..ops.reduce import span_metrics_reduce

        ccalls, csum, cbuckets = span_metrics_reduce(eid, cdur, n_edges, LATENCY_BUCKETS)
        _, ssum, sbuckets = span_metrics_reduce(eid, sdur, n_edges, LATENCY_BUCKETS)
        fcounts = np.bincount(eid[failed], minlength=n_edges).astype(np.int64)
        with self.lock:
            self._apply_fold_locked(n_edges, ccalls, fcounts, csum, ssum,
                                    cbuckets, sbuckets)

    def _apply_fold_locked(self, n_edges: int, counts, fcounts, csum, ssum,
                           cbuckets, sbuckets) -> None:
        """Accumulate one fold's per-edge outputs (caller holds
        self.lock). Shared by legacy collect() and the streaming fused
        edge reduce."""
        if len(self.counts) < n_edges:
            pad = n_edges - len(self.counts)
            zb = np.zeros((pad, self.client_buckets.shape[1]), np.int64)
            self.counts = np.concatenate([self.counts, np.zeros(pad, np.int64)])
            self.failed_counts = np.concatenate([self.failed_counts, np.zeros(pad, np.int64)])
            self.client_sum = np.concatenate([self.client_sum, np.zeros(pad, np.float64)])
            self.server_sum = np.concatenate([self.server_sum, np.zeros(pad, np.float64)])
            self.client_buckets = np.concatenate([self.client_buckets, zb])
            self.server_buckets = np.concatenate([self.server_buckets, zb.copy()])
        self.counts[:n_edges] += counts[:n_edges]
        self.failed_counts[:n_edges] += fcounts[:n_edges]
        self.client_sum[:n_edges] += csum[:n_edges]
        self.server_sum[:n_edges] += ssum[:n_edges]
        self.client_buckets[:n_edges] += cbuckets[:n_edges]
        self.server_buckets[:n_edges] += sbuckets[:n_edges]

    def metrics_text(self) -> list[str]:
        self.collect()
        out = []
        with self.lock:
            for eid, (c, s) in enumerate(self.edge_list):
                if eid >= len(self.counts) or self.counts[eid] == 0:
                    continue
                lab = f'client="{c}",server="{s}"'
                out.append(f"traces_service_graph_request_total{{{lab}}} {int(self.counts[eid])}")
                out.append(
                    f"traces_service_graph_request_failed_total{{{lab}}} "
                    f"{int(self.failed_counts[eid])}"
                )
                for side, total, buckets in (
                    ("client", self.client_sum, self.client_buckets),
                    ("server", self.server_sum, self.server_buckets),
                ):
                    out.append(
                        f"traces_service_graph_request_{side}_seconds_sum{{{lab}}} "
                        f"{total[eid]:.6f}"
                    )
                    out.append(
                        f"traces_service_graph_request_{side}_seconds_count{{{lab}}} "
                        f"{int(self.counts[eid])}"
                    )
                    cum = 0
                    for bi, edge in enumerate(LATENCY_BUCKETS):
                        cum += int(buckets[eid, bi])
                        out.append(
                            f'traces_service_graph_request_{side}_seconds_bucket'
                            f'{{{lab},le="{edge}"}} {cum}'
                        )
                    cum += int(buckets[eid, -1])
                    out.append(
                        f'traces_service_graph_request_{side}_seconds_bucket'
                        f'{{{lab},le="+Inf"}} {cum}'
                    )
        return out


class StreamingSpanMetrics(SpanMetricsProcessor):
    """Streaming variant fed by the write-path tap: consumes
    ColumnarIngest SpanColumns (coded inside the single ingest decode)
    and folds each push window through the device reduce IMMEDIATELY.
    Series keys assemble as vectorized packed-code hashing -- one int64
    per span, np.unique over the window -- so Python runs only per
    UNIQUE NEW key; registry state, eviction and exposition are the
    parent's, which is what makes the streaming-vs-legacy differential
    a like-for-like comparison."""

    # packed series key layout: (svc_code << 34) | (name_code << 6) |
    # (kind << 3) | status. kind <= 5 and status <= 2 fit 3 bits each;
    # name gets 28 bits and svc 30 -- orders of magnitude above the
    # live window's dictionary cardinality (ColumnarIngest caps cached
    # segments at 1<<16).
    _SVC_SHIFT = 34
    _NAME_SHIFT = 6
    _NAME_MASK = (1 << 28) - 1

    def __init__(self, max_active_series: int = 0):
        super().__init__(max_active_series)
        # per-source-dict packed-key -> sid cache: codes are only
        # meaningful against the LiveDict that assigned them, so keying
        # the cache by the dict object keeps the in-process tap and the
        # remote-genpush feed (different dictionaries) from colliding
        self._packed_sids: dict[object, dict[int, int]] = {}

    def push_columns(self, parts: list[SpanColumns], ldict: LiveDict,
                     now: float | None = None) -> int:
        """Fold one push window of coded span columns. Returns the span
        count folded (after series-limit shedding)."""
        parts = [p for p in parts if len(p.svc_code)]
        if not parts:
            return 0
        now = time.time() if now is None else now
        svc = np.concatenate([p.svc_code for p in parts]).astype(np.int64)
        name = np.concatenate([p.name_code for p in parts]).astype(np.int64)
        kind = np.concatenate([p.kind for p in parts]).astype(np.int64)
        status = np.concatenate([p.status for p in parts]).astype(np.int64)
        dur = np.concatenate([p.dur_s for p in parts])
        segi = np.concatenate([np.full(len(p.svc_code), i, np.int32)
                               for i, p in enumerate(parts)])
        packed = ((svc << self._SVC_SHIFT) | (name << self._NAME_SHIFT)
                  | (kind << 3) | status)
        uniq, first, inv = np.unique(packed, return_index=True,
                                     return_inverse=True)
        with self.lock:
            pmap = self._packed_sids.setdefault(ldict, {})
            usid = np.empty(len(uniq), np.int32)
            # new keys resolve strings + claim sids in first-seen SPAN
            # order: exactly the legacy per-span assignment sequence,
            # including the max-active-series shed decisions
            for ui in np.argsort(first, kind="stable").tolist():
                pk = int(uniq[ui])
                s = pmap.get(pk)
                if s is None:
                    k = (ldict.string(pk >> self._SVC_SHIFT),
                         ldict.string((pk >> self._NAME_SHIFT) & self._NAME_MASK),
                         (pk >> 3) & 7, pk & 7)
                    s = self.keys.get(k)
                    if s is None:
                        active = len(self.key_list) - len(self.free_sids)
                        if self.max_active_series and active >= self.max_active_series:
                            # shed: NOT cached, so freed capacity from a
                            # later eviction re-admits the key (legacy
                            # re-checks per span the same way)
                            usid[ui] = -1
                            continue
                        if self.free_sids:
                            s = self.free_sids.pop()
                            self.key_list[s] = SeriesKey(*k)
                            self.keys[k] = s
                        else:
                            s = self.keys[k] = len(self.key_list)
                            self.key_list.append(SeriesKey(*k))
                    pmap[pk] = s
                usid[ui] = s
            sid = usid[inv]
            shed = sid < 0
            nshed = int(shed.sum())
            if nshed:
                self.dropped_series += nshed
                keep = ~shed
                sid, dur, segi = sid[keep], dur[keep], segi[keep]
            if len(sid) == 0:
                return 0
            n_series = len(self.key_list)
            # staleness stamps + exemplars: last window occurrence per
            # series (np.unique over the reversed array finds it without
            # a per-span Python pass)
            ridx = np.unique(sid[::-1], return_index=True)[1]
            for li in (len(sid) - 1 - ridx).tolist():
                s = int(sid[li])
                self.last_update[s] = now
                tid = parts[int(segi[li])].tid_hex
                if tid:
                    self.exemplars[s] = (tid, float(dur[li]))
        from ..ops.reduce import span_metrics_reduce

        calls, lsum, buckets = span_metrics_reduce(
            sid.astype(np.int32), dur.astype(np.float32), n_series,
            LATENCY_BUCKETS)
        with self.lock:
            self._apply_fold_locked(n_series, calls, lsum, buckets)
        return int(len(sid))

    def evict_stale(self, max_idle_s: float, now: float | None = None) -> int:
        n = super().evict_stale(max_idle_s, now)
        if n:
            # evicted sids may be reassigned to different keys; the
            # packed caches hold raw sid ints, so drop them wholesale
            # (evictions are rare; each live key re-resolves once)
            with self.lock:
                for m in self._packed_sids.values():
                    m.clear()
        return n


# SpanKind value with the client edge role (mirrors ingest/columnar)
_KIND_CLIENT = int(SpanKind.CLIENT)


@dataclass
class _CodedEdge:
    """Pending edge in the coded store: service CODES plus the dict
    that assigned them (resolved to strings only at completion)."""

    t: float = 0.0
    cdict: LiveDict | None = None
    sdict: LiveDict | None = None
    csvc: int = 0  # 0 = unset (LiveDict codes "" as 0; legacy treats
    ssvc: int = 0  # an empty service name as not-set the same way)
    cdur: float = 0.0
    sdur: float = 0.0
    failed: bool = False


class StreamingServiceGraphs(ServiceGraphsProcessor):
    """Coded edge store: client/server spans pair on the uint64
    (trace-id, span-id/parent-id) hash computed inside the write-path
    decode (ingest/columnar.edge_key_client), so matching is one dict
    probe on an int. Completed edges batch-pair per push window and
    fold through ONE fused device program (ops/reduce.edge_metrics_
    reduce) instead of the legacy two span-metrics launches + host
    bincount per collection cycle."""

    def push_columns(self, parts: list[SpanColumns], ldict: LiveDict,
                     now: float | None = None) -> int:
        """Pair one window's edge-role spans and fold the completed
        edges. Returns the number of edges completed this window."""
        now = time.time() if now is None else now
        with self.lock:
            for p in parts:
                idxs = np.flatnonzero(p.edge_key)
                if len(idxs) == 0:
                    continue
                ek, kinds = p.edge_key, p.kind
                status, durs, svcs = p.status, p.dur_s, p.svc_code
                for i in idxs.tolist():
                    key = int(ek[i])
                    e = self.pending.get(key)
                    if e is None:
                        e = self.pending[key] = _CodedEdge(t=now)
                    d = float(durs[i])
                    if int(kinds[i]) == _KIND_CLIENT:
                        e.cdict, e.csvc, e.cdur = ldict, int(svcs[i]), d
                    else:
                        e.sdict, e.ssvc, e.sdur = ldict, int(svcs[i]), d
                    e.failed = e.failed or int(status[i]) == 2
                    if e.csvc and e.ssvc:
                        pair = (e.cdict.string(e.csvc), e.sdict.string(e.ssvc))
                        eid = self.edge_ids.get(pair)
                        if eid is None:
                            eid = self.edge_ids[pair] = len(self.edge_list)
                            self.edge_list.append(pair)
                        self._eid.append(eid)
                        self._client_dur.append(e.cdur)
                        self._server_dur.append(e.sdur)
                        self._failed.append(e.failed)
                        del self.pending[key]
            self._expire(now)
            if not self._eid:
                return 0
            eid = np.asarray(self._eid, dtype=np.int32)
            cdur = np.asarray(self._client_dur, dtype=np.float32)
            sdur = np.asarray(self._server_dur, dtype=np.float32)
            failed = np.asarray(self._failed, dtype=np.int32)
            self._eid, self._client_dur, self._server_dur, self._failed = [], [], [], []
            n_edges = len(self.edge_list)
        from ..ops.reduce import edge_metrics_reduce

        out = edge_metrics_reduce(eid, cdur, sdur, failed, n_edges,
                                  LATENCY_BUCKETS)
        with self.lock:
            self._apply_fold_locked(n_edges, *out)
        return int(len(eid))


class MetricsGenerator:
    """Per-tenant processor sets, fed by the distributor tap
    (modules/generator/generator.go). Two entry points: push_window
    (the streaming tap: coded columns straight from the write path's
    single decode) and push (decoded traces: remote genpush + direct
    callers), which builds columns on a generator-owned per-tenant
    LiveDict and rides the same streaming fold."""

    def __init__(self, overrides, processors: tuple[str, ...] = ("span-metrics", "service-graphs"),
                 stale_series_s: float = 300.0):
        self.overrides = overrides
        self.default_processors = processors
        self.stale_series_s = stale_series_s
        self.lock = threading.Lock()
        self.tenants: dict[str, dict[str, object]] = {}
        self._dicts: dict[str, LiveDict] = {}  # push()-path dictionaries
        self._stale: dict[str, float] = {}  # per-tenant staleness window

    def _procs(self, tenant: str) -> dict[str, object]:
        with self.lock:
            procs = self.tenants.get(tenant)
            if procs is None:
                lim = self.overrides.for_tenant(tenant)
                enabled = lim.metrics_generator_processors or self.default_processors
                procs = {}
                if "span-metrics" in enabled:
                    procs["span-metrics"] = StreamingSpanMetrics(
                        lim.metrics_generator_max_active_series
                    )
                if "service-graphs" in enabled:
                    procs["service-graphs"] = StreamingServiceGraphs()
                self.tenants[tenant] = procs
                stale = getattr(lim, "metrics_generator_stale_series_s", 0.0)
                self._stale[tenant] = stale if stale > 0 else self.stale_series_s
            return procs

    def push(self, tenant: str, traces: list[Trace]) -> None:
        with self.lock:
            ld = self._dicts.get(tenant)
            if ld is None:
                ld = self._dicts[tenant] = LiveDict()
        cols = [span_columns_from_trace(tr, ld.code) for tr in traces]
        self.push_window(tenant, cols, ld)

    def push_window(self, tenant: str, cols: list[SpanColumns],
                    ldict: LiveDict, push_ts: float | None = None) -> None:
        """Fold one push window of coded columns for `tenant`. push_ts
        (the distributor's receive time) feeds the push->series-visible
        freshness histogram; after this returns the window's series ARE
        visible to the next metrics_text()."""
        from ..util.kerneltel import TEL

        procs = self._procs(tenant)
        now = time.time()
        sm = procs.get("span-metrics")
        sg = procs.get("service-graphs")
        shed0 = sm.dropped_series if sm is not None else 0
        edges = 0
        spans = 0
        for pname, p in procs.items():
            t0 = time.perf_counter()
            r = p.push_columns(cols, ldict, now)
            TEL.record_generator_stage(pname, time.perf_counter() - t0)
            if p is sg:
                edges = r
            else:
                spans = r
        TEL.record_generator_window(
            spans, edges,
            unpaired=len(sg.pending) if sg is not None else 0,
            expired=sg.expired if sg is not None else 0)
        if sm is not None and sm.dropped_series > shed0:
            TEL.record_generator_shed(tenant, sm.dropped_series - shed0)
        if push_ts is not None:
            TEL.record_generator_freshness(time.time() - push_ts)

    def metrics_text(self) -> list[str]:
        out = []
        with self.lock:
            items = list(self.tenants.items())
            stale = dict(self._stale)
        for tenant, procs in items:
            for p in procs.values():
                if isinstance(p, SpanMetricsProcessor):
                    p.evict_stale(stale.get(tenant, self.stale_series_s))
                out.extend(p.metrics_text())
        return out
