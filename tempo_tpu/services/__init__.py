"""L5 service modules: distributor, ingester, querier, frontend,
compactor, overrides, metrics-generator -- the role layer over TempoDB
(reference: modules/*, SURVEY.md 2.2). One process hosts any subset of
roles (single-binary `all` target) or one role per process."""
