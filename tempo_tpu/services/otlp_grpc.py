"""OTLP/gRPC trace receiver: the default OTel exporter transport.

Reference: the distributor's receiver shim runs the OTLP gRPC receiver
on :4317 (modules/distributor/receiver/shim.go:95-101). Here it's a
grpc generic handler -- no generated stubs: the ExportTraceServiceRequest
wire form is `repeated ResourceSpans = 1`, byte-identical to TracesData,
so the existing hand-rolled OTLP codec (wire/otlp_pb.py) decodes it
directly, and the empty ExportTraceServiceResponse serializes to b"".

Tenancy rides the x-scope-orgid metadata key (the gRPC twin of the
X-Scope-OrgID header); push limit errors map to the canonical gRPC
codes (429 -> RESOURCE_EXHAUSTED, 400 -> INVALID_ARGUMENT), which OTel
SDK exporters understand as retryable / fatal respectively.
"""

from __future__ import annotations

from concurrent import futures


_EXPORT_METHOD = "Export"
_SERVICE = "opentelemetry.proto.collector.trace.v1.TraceService"


def push_grpc_code(e: Exception, grpc):
    """Push-exception -> canonical gRPC status, shared by every gRPC
    receiver: 429 -> RESOURCE_EXHAUSTED (retryable to OTel SDKs),
    401 -> UNAUTHENTICATED, other rejects -> INVALID_ARGUMENT (fatal),
    anything unexpected -> INTERNAL."""
    from .distributor import PushError

    if isinstance(e, PushError):
        return (grpc.StatusCode.RESOURCE_EXHAUSTED if e.status == 429
                else grpc.StatusCode.UNAUTHENTICATED if e.status == 401
                else grpc.StatusCode.INVALID_ARGUMENT)
    return grpc.StatusCode.INTERNAL


class OTLPGrpcReceiver:
    def __init__(self, app, max_workers: int = 8):
        self.app = app
        self._max_workers = max_workers
        self._server = None
        self.port = 0
        self.requests = 0
        self.failures = 0

    def start(self, port: int = 4317, host: str = "127.0.0.1") -> int:
        import grpc

        app = self.app
        recv = self

        def export(request: bytes, context) -> bytes:
            recv.requests += 1
            try:
                md = {k.lower(): v for k, v in (context.invocation_metadata() or [])}
                # gRPC metadata keys are lowercase; re-shape for tenant_of
                tenant = app.tenant_of({"X-Scope-OrgID": md.get("x-scope-orgid", "")})
                # raw fast path: native scan + byte splice, no model
                # decode on the write path (distributor.push_raw)
                app.distributor.push_raw(tenant, request)
                return b""
            except Exception as e:
                recv.failures += 1
                context.abort(push_grpc_code(e, grpc), f"{type(e).__name__}: {e}")

        handler = grpc.method_handlers_generic_handler(
            _SERVICE,
            {
                _EXPORT_METHOD: grpc.unary_unary_rpc_method_handler(
                    export,
                    request_deserializer=None,  # raw bytes in
                    response_serializer=None,  # raw bytes out
                )
            },
        )
        self._server = grpc.server(
            futures.ThreadPoolExecutor(max_workers=self._max_workers,
                                       thread_name_prefix="otlp-grpc"),
        )
        self._server.add_generic_rpc_handlers((handler,))
        self.port = self._server.add_insecure_port(f"{host}:{port}")
        self._server.start()
        return self.port

    def stop(self) -> None:
        if self._server is not None:
            self._server.stop(grace=1.0)
            self._server = None
