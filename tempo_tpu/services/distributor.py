"""Distributor: receive trace batches, rebatch by trace id, rate-limit,
replicate to ingesters via the ring.

Reference: modules/distributor/distributor.go -- PushBatches (:277),
requestsByTraceID (:451-525, hot loop 1), sendToIngestersViaBytes
(:357-408, ring.DoBatch with quorum). The transport boundary is a
client registry mapping instance addr -> Pusher; in-process for the
single binary, HTTP for multi-process.
"""

from __future__ import annotations

import time
from collections import defaultdict
from dataclasses import dataclass, field

from ..ring.ring import Ring
from ..util.hashing import ring_token
from ..wire.model import ResourceSpans, ScopeSpans, Trace
from ..wire.segment import segment_for_write
from .overrides import Overrides, RateLimiter


class PushError(Exception):
    def __init__(self, status: int, msg: str):
        super().__init__(msg)
        self.status = status  # 429 rate-limited / 400 too large / 500


@dataclass
class DistributorStats:
    spans_received: int = 0
    bytes_received: int = 0
    traces_pushed: int = 0
    push_failures: int = 0
    spans_refused_rate: int = 0
    traces_refused_size: int = 0


class Distributor:
    def __init__(self, ring: Ring, client_for, overrides: Overrides,
                 generator_forward=None, generator_ring: Ring | None = None):
        """client_for(addr) -> object with push_segments(tenant, batch);
        generator_forward(tenant, traces) optional in-process
        metrics-generator tap (single binary). generator_ring selects
        REMOTE generators instead, per-tenant shuffle-sharded
        (distributor.go:410-442: metrics_generator_ring_size members
        per tenant, traces routed within the shard by id hash)."""
        self.ring = ring
        self.client_for = client_for
        self.overrides = overrides
        self.limiter = RateLimiter(overrides)
        self.generator_forward = generator_forward
        self.generator_ring = generator_ring
        self.stats = DistributorStats()
        from ..util.metrics import Histogram

        self.push_latency = Histogram("tempo_distributor_push_duration_seconds")

    def _forward_to_generators(self, tenant: str, per_trace: dict) -> None:
        if self.generator_ring is not None:
            from ..util.hashing import fnv1a_32

            size = self.overrides.for_tenant(tenant).metrics_generator_ring_size
            shard = self.generator_ring.shuffle_shard(tenant, size)
            if not shard:
                return
            by_member: dict[str, list] = defaultdict(list)
            for tid, tr in per_trace.items():
                member = shard[fnv1a_32(tid) % len(shard)]
                by_member[member.addr].append(tr)
            for addr, traces in by_member.items():
                try:
                    self.client_for(addr).push_generator(tenant, traces)
                except Exception:
                    pass  # metrics tap must never fail ingest
        elif self.generator_forward is not None:
            try:
                self.generator_forward(tenant, list(per_trace.values()))
            except Exception:
                pass

    # ---------------------------------------------------------------- push
    def push(self, tenant: str, batches: list[ResourceSpans]) -> None:
        """One OTLP export request worth of ResourceSpans."""
        from ..util.metrics import timed

        with timed(self.push_latency):
            self._push(tenant, batches)

    def _push(self, tenant: str, batches: list[ResourceSpans]) -> None:
        now = time.time()
        n_spans = sum(len(ss.spans) for rs in batches for ss in rs.scope_spans)
        self.stats.spans_received += n_spans

        # cheap pre-gate BEFORE rebatch/serialization: if even a
        # conservative LOWER BOUND on the wire size (ids + timestamps
        # alone exceed 16 bytes/span) can't pass the bucket, refuse
        # without paying encoding CPU; the exact-bytes limiter still
        # applies below on real wire bytes
        if not self.limiter.peek(tenant, n_spans * 16, now):
            self.stats.spans_refused_rate += n_spans
            raise PushError(429, f"tenant {tenant} over ingestion rate limit")

        per_trace = self._requests_by_trace_id(batches)
        if not per_trace:
            return

        # serialize first so the limiter and bytes_received see REAL wire
        # bytes, not a guess (reference limits on actual request size,
        # distributor.go:312-319)
        max_trace = self.overrides.for_tenant(tenant).max_bytes_per_trace
        segs = {}
        nbytes = 0
        for tid, tr in per_trace.items():
            lo, hi = tr.time_range_nanos()
            seg = segment_for_write(tr, (lo or 0) // 10**9, ((hi or 0) + 10**9 - 1) // 10**9)
            nbytes += len(seg)
            segs[tid] = ((lo or 0) // 10**9, ((hi or 0) + 10**9 - 1) // 10**9, seg)
        self.stats.bytes_received += nbytes
        if not self.limiter.allow(tenant, nbytes, now):
            self.stats.spans_refused_rate += n_spans
            raise PushError(429, f"tenant {tenant} over ingestion rate limit")

        lim_filtered = {}
        for tid, (s, e, seg) in segs.items():
            if max_trace and len(seg) > max_trace:
                self.stats.traces_refused_size += 1
                continue
            lim_filtered[tid] = (s, e, seg)
        if not lim_filtered:
            return

        # group traces by replica instance (ring.DoBatch analog);
        # snapshot the healthy set once for the whole batch
        healthy = self.ring.healthy_instances()
        by_instance: dict[str, list] = defaultdict(list)
        quorum_need: dict[bytes, int] = {}
        for tid, (s, e, seg) in lim_filtered.items():
            rs = self.ring.get(ring_token(tenant, tid), instances=healthy)
            if not rs.instances:
                raise PushError(500, "no healthy ingesters in the ring")
            quorum_need[tid] = len(rs.instances) - rs.max_errors
            for inst in rs.instances:
                by_instance[inst.addr].append((tid, s, e, seg))

        ok_count: dict[bytes, int] = defaultdict(int)
        errors = []
        for addr, batch in by_instance.items():
            try:
                self.client_for(addr).push_segments(tenant, batch)
                for tid, *_ in batch:
                    ok_count[tid] += 1
            except Exception as e:  # replica failure: quorum decides below
                errors.append(e)
        failed = [tid for tid, need in quorum_need.items() if ok_count[tid] < need]
        if failed:
            self.stats.push_failures += len(failed)
            # surface the ingester's own status (429 backpressure / 400 too
            # large) instead of flattening everything to 500
            push_errs = [e for e in errors if isinstance(e, PushError)]
            if push_errs:
                raise PushError(push_errs[0].status, str(push_errs[0]))
            raise PushError(500, f"{len(failed)} traces failed quorum write: {errors[:1]}")
        self.stats.traces_pushed += len(lim_filtered)

        self._forward_to_generators(tenant, per_trace)

    # ------------------------------------------------------------ rebatch
    @staticmethod
    def _requests_by_trace_id(batches: list[ResourceSpans]) -> dict[bytes, Trace]:
        """Regroup spans by trace id keeping resource/scope structure
        (requestsByTraceID, distributor.go:451-525)."""
        out: dict[bytes, Trace] = {}
        for rs in batches:
            for ss in rs.scope_spans:
                groups: dict[bytes, list] = defaultdict(list)
                for sp in ss.spans:
                    groups[sp.trace_id].append(sp)
                for tid, spans in groups.items():
                    tr = out.get(tid)
                    if tr is None:
                        tr = out[tid] = Trace()
                    tr.resource_spans.append(
                        ResourceSpans(
                            resource=rs.resource,
                            scope_spans=[ScopeSpans(scope=ss.scope, spans=spans)],
                        )
                    )
        return out
