"""Distributor: receive trace batches, rebatch by trace id, rate-limit,
replicate to ingesters via the ring.

Reference: modules/distributor/distributor.go -- PushBatches (:277),
requestsByTraceID (:451-525, hot loop 1), sendToIngestersViaBytes
(:357-408, ring.DoBatch with quorum). The transport boundary is a
client registry mapping instance addr -> Pusher; in-process for the
single binary, HTTP for multi-process.
"""

from __future__ import annotations

import time
from collections import defaultdict
from dataclasses import dataclass, field

from ..fleet.replication import guarded_push, record_write_outcomes
from ..ring.ring import Ring
from ..util.hashing import ring_token
from ..wire.model import ResourceSpans, ScopeSpans, Trace
from ..wire.segment import segment_for_write
from .overrides import Overrides, RateLimiter


class PushError(Exception):
    def __init__(self, status: int, msg: str):
        super().__init__(msg)
        self.status = status  # 429 rate-limited / 400 too large / 500


@dataclass
class DistributorStats:
    spans_received: int = 0
    bytes_received: int = 0
    traces_pushed: int = 0
    push_failures: int = 0
    spans_refused_rate: int = 0
    traces_refused_size: int = 0
    gen_tap_dropped: int = 0  # generator-tap queue overflows (lossy tap)


class Distributor:
    def __init__(self, ring: Ring, client_for, overrides: Overrides,
                 generator_forward=None, generator_ring: Ring | None = None,
                 generator_window=None):
        """client_for(addr) -> object with push_segments(tenant, batch);
        generator_forward(tenant, traces) optional in-process
        metrics-generator tap (single binary). generator_ring selects
        REMOTE generators instead, per-tenant shuffle-sharded
        (distributor.go:410-442: metrics_generator_ring_size members
        per tenant, traces routed within the shard by id hash).
        generator_window(tenant, segs, push_ts) is the STREAMING
        in-process tap: it receives the post-filter segment bytes --
        the same objects the ingester just staged, so the generator
        reads their coded features out of ColumnarIngest's identity-
        keyed cache with zero extra proto decodes. When set it replaces
        generator_forward's decode-per-push leg."""
        self.ring = ring
        self.client_for = client_for
        self.overrides = overrides
        self.limiter = RateLimiter(overrides)
        self.generator_forward = generator_forward
        self.generator_ring = generator_ring
        self.generator_window = generator_window
        self.stats = DistributorStats()
        from ..util.metrics import Histogram

        self.push_latency = Histogram("tempo_distributor_push_duration_seconds")
        # async generator tap: the metrics leg (decode for the raw fast
        # path + shuffle-shard routing + network sends) runs OFF the
        # ingest critical path on one worker; a bounded queue keeps it
        # lossy-on-overflow, matching the tap's never-fail-ingest
        # contract (errors are already swallowed)
        import queue as _queue
        import threading as _threading

        self._gen_q: _queue.Queue = _queue.Queue(maxsize=256)
        self._gen_thread = None
        self._gen_lock = _threading.Lock()  # guards thread start + pending
        self._gen_pending = 0  # queued + in-flight tap items
        self._gen_stop = False

    def _forward_to_generators(self, tenant: str, segs, traces_fn,
                               push_ts: float) -> None:
        """segs: {tid: (s, e, segment)} for the ring and streaming legs,
        the post-filter id set for the legacy in-process leg. traces_fn()
        -> {tid: Trace} is resolved ONLY by the legacy leg -- and on the
        TAP WORKER, not the push path. The remote-ring leg ships proto
        blobs sliced straight from the segments (segment_payload) and
        the streaming leg hands the segment bytes to the generator's
        columnar tap, so neither ever decodes on the distributor."""
        if (self.generator_ring is None and self.generator_forward is None
                and self.generator_window is None):
            return
        import queue as _queue

        with self._gen_lock:
            if self._gen_thread is None:
                import threading

                self._gen_thread = threading.Thread(
                    target=self._gen_tap_loop, daemon=True, name="generator-tap")
                self._gen_thread.start()
            try:
                self._gen_q.put_nowait((tenant, segs, traces_fn, push_ts))
                self._gen_pending += 1
            except _queue.Full:
                self.stats.gen_tap_dropped += 1

    def _gen_tap_loop(self) -> None:
        import queue as _queue

        while not self._gen_stop:
            try:
                item = self._gen_q.get(timeout=0.5)
            except Exception:
                continue
            # greedy drain: everything already queued folds in THIS
            # pass, merged per tenant into one push window -- a backlog
            # amortizes to one device reduce per tenant instead of one
            # per push, so push->series-visible lag stays bounded by
            # fold time rather than queue depth under sustained load
            items = [item]
            while len(items) < 64:
                try:
                    items.append(self._gen_q.get_nowait())
                except _queue.Empty:
                    break
            try:
                self._forward_batch(items)
            except Exception:
                pass  # metrics tap must never crash its worker
            finally:
                # pending counts queued + in-flight, decremented only
                # AFTER processing: flush can't slip through the window
                # between queue pop and the work happening
                with self._gen_lock:
                    self._gen_pending -= len(items)

    def _forward_batch(self, items: list) -> None:
        """Forward one drained tap batch. The streaming-window leg
        merges items per tenant (segment lists concatenate -- the same
        trace may continue across pushes, so never dedupe by id) and
        stamps the merged window with its OLDEST push_ts, keeping the
        freshness histogram an honest upper bound. The ring and legacy
        legs keep per-item semantics."""
        use_window = (self.generator_window is not None
                      and self.generator_ring is None)
        if not use_window or len(items) == 1:
            for tenant, segs, traces_fn, push_ts in items:
                try:
                    self._forward_now(tenant, segs, traces_fn, push_ts)
                except Exception:
                    pass
            return
        merged: dict[str, tuple[list, float]] = {}
        for tenant, segs, _fn, push_ts in items:
            ent = merged.get(tenant)
            if ent is None:
                merged[tenant] = ([seg for _, _, seg in segs.values()], push_ts)
            else:
                ent[0].extend(seg for _, _, seg in segs.values())
                merged[tenant] = (ent[0], min(ent[1], push_ts))
        for tenant, (seg_list, ts) in merged.items():
            try:
                self.generator_window(tenant, seg_list, ts)
            except Exception:
                pass

    def flush_generator_tap(self, timeout_s: float = 5.0) -> None:
        """Drain the tap queue (tests / graceful shutdown)."""
        deadline = time.time() + timeout_s
        while time.time() < deadline:
            with self._gen_lock:
                if self._gen_pending == 0:
                    return
            time.sleep(0.005)

    def stop(self) -> None:
        self.flush_generator_tap(timeout_s=2.0)
        self._gen_stop = True

    def _forward_now(self, tenant: str, segs, traces_fn,
                     push_ts: float) -> None:
        if self.generator_ring is not None:
            from ..util.hashing import fnv1a_32
            from ..wire.segment import segment_payload

            size = self.overrides.for_tenant(tenant).metrics_generator_ring_size
            shard = self.generator_ring.shuffle_shard(tenant, size)
            if not shard:
                return
            by_member: dict[str, list] = defaultdict(list)
            for tid, (_, _, seg) in segs.items():
                member = shard[fnv1a_32(tid) % len(shard)]
                by_member[member.addr].append(segment_payload(seg))
            for addr, blobs in by_member.items():
                try:
                    self.client_for(addr).push_generator_blobs(tenant, blobs)
                except Exception:
                    pass  # metrics tap must never fail ingest
        elif self.generator_window is not None:
            try:
                self.generator_window(
                    tenant, [seg for _, _, seg in segs.values()], push_ts)
            except Exception:
                pass  # metrics tap must never fail ingest
        elif self.generator_forward is not None and traces_fn is not None:
            try:
                # restrict to the post-filter set: segs is lim_filtered,
                # traces_fn() may also hold size-refused traces
                per = traces_fn()
                self.generator_forward(
                    tenant, [tr for tid, tr in per.items() if tid in segs])
            except Exception:
                pass

    # ---------------------------------------------------------------- push
    def push(self, tenant: str, batches: list[ResourceSpans]) -> None:
        """One OTLP export request worth of ResourceSpans."""
        from ..util.metrics import timed

        with timed(self.push_latency):
            self._push(tenant, batches)

    def push_raw(self, tenant: str, payload: bytes) -> int:
        """One OTLP export request as RAW proto bytes: the fast write
        path. The native structural scanner + byte splicer regroup spans
        by trace id without building model objects or re-encoding
        (wire/otlp_splice.py); the reference's analog keeps pre-marshaled
        per-trace bytes end to end (PushBytes, sendToIngestersViaBytes).
        Falls back to decode + the model path when the native layer is
        unavailable or the payload doesn't scan cleanly; a payload
        neither path can read raises PushError(400) so receivers can
        classify it as poison rather than transient. Returns the span
        count."""
        from ..util.metrics import timed

        with timed(self.push_latency):
            out = None
            try:
                from ..wire.otlp_splice import split_by_trace

                out = split_by_trace(payload)
            except Exception:
                out = None  # scanner edge case: the model path decides
            if out is None:
                from ..wire.otlp_pb import decode_trace

                try:
                    tr = decode_trace(payload)
                except Exception as e:
                    raise PushError(400, f"undecodable OTLP payload: {e}")
                return self._push(tenant, tr.resource_spans)
            segs, n_spans = out
            now = time.time()
            self.stats.spans_received += n_spans
            if not self.limiter.peek(tenant, n_spans * 16, now):
                self.stats.spans_refused_rate += n_spans
                raise PushError(429, f"tenant {tenant} over ingestion rate limit")
            if not segs:
                return 0

            def lazy_traces() -> dict:
                from ..wire.segment import segment_to_trace

                return {tid: segment_to_trace(seg)
                        for tid, (_, _, seg) in segs.items()}

            self._send_segments(tenant, segs, n_spans, lazy_traces, now)
            return n_spans

    def _push(self, tenant: str, batches: list[ResourceSpans]) -> int:
        now = time.time()
        n_spans = sum(len(ss.spans) for rs in batches for ss in rs.scope_spans)
        self.stats.spans_received += n_spans

        # cheap pre-gate BEFORE rebatch/serialization: if even a
        # conservative LOWER BOUND on the wire size (ids + timestamps
        # alone exceed 16 bytes/span) can't pass the bucket, refuse
        # without paying encoding CPU; the exact-bytes limiter still
        # applies below on real wire bytes
        if not self.limiter.peek(tenant, n_spans * 16, now):
            self.stats.spans_refused_rate += n_spans
            raise PushError(429, f"tenant {tenant} over ingestion rate limit")

        per_trace = self._requests_by_trace_id(batches)
        if not per_trace:
            return 0

        # serialize first so the limiter and bytes_received see REAL wire
        # bytes, not a guess (reference limits on actual request size,
        # distributor.go:312-319)
        segs = {}
        for tid, tr in per_trace.items():
            lo, hi = tr.time_range_nanos()
            seg = segment_for_write(tr, (lo or 0) // 10**9, ((hi or 0) + 10**9 - 1) // 10**9)
            segs[tid] = ((lo or 0) // 10**9, ((hi or 0) + 10**9 - 1) // 10**9, seg)
        self._send_segments(tenant, segs, n_spans, lambda: per_trace, now)
        return n_spans

    def _send_segments(self, tenant: str, segs: dict, n_spans: int,
                       traces_fn, now: float) -> None:
        """Limit, replicate and quorum-write prepared per-trace segments
        (the shared tail of the model and raw push paths)."""
        nbytes = sum(len(seg) for _, _, seg in segs.values())
        self.stats.bytes_received += nbytes
        if not self.limiter.allow(tenant, nbytes, now):
            self.stats.spans_refused_rate += n_spans
            raise PushError(429, f"tenant {tenant} over ingestion rate limit")
        max_trace = self.overrides.for_tenant(tenant).max_bytes_per_trace

        lim_filtered = {}
        for tid, (s, e, seg) in segs.items():
            if max_trace and len(seg) > max_trace:
                self.stats.traces_refused_size += 1
                continue
            lim_filtered[tid] = (s, e, seg)
        if not lim_filtered:
            return

        # group traces by replica instance (ring.DoBatch analog);
        # snapshot the healthy set once for the whole batch
        healthy = self.ring.healthy_instances()
        if not healthy:
            raise PushError(500, "no healthy ingesters in the ring")
        by_instance: dict[str, list] = defaultdict(list)
        quorum_need: dict[bytes, int] = {}
        replicated = self.ring.rf > 1
        if len(healthy) == 1 and not replicated:
            # single-ingester fast path (the single-binary topology):
            # every token resolves to the one instance with quorum 1, so
            # skip the per-trace ring walk -- on large push windows the
            # hash+bisect loop is real write-path time. MUST stay gated
            # on rf<=1: at RF>1 the ring walk still yields one replica
            # when only one is healthy, but only the walk path records
            # the write as under-replicated ("partial") instead of
            # silently degrading replication to RF=1.
            addr = healthy[0].addr
            by_instance[addr] = [(tid, s, e, seg)
                                 for tid, (s, e, seg) in lim_filtered.items()]
            quorum_need = dict.fromkeys(lim_filtered, 1)
        else:
            for tid, (s, e, seg) in lim_filtered.items():
                rs = self.ring.get(ring_token(tenant, tid), instances=healthy)
                if not rs.instances:
                    raise PushError(500, "no healthy ingesters in the ring")
                quorum_need[tid] = len(rs.instances) - rs.max_errors
                for inst in rs.instances:
                    by_instance[inst.addr].append((tid, s, e, seg))

        ok_count: dict[bytes, int] = defaultdict(int)
        errors = []
        for addr, batch in by_instance.items():
            try:
                if replicated:
                    # per-replica breaker: a flapping replica sheds its
                    # own leg fast; the quorum math below absorbs it
                    guarded_push(self.client_for(addr), addr, tenant, batch)
                else:
                    self.client_for(addr).push_segments(tenant, batch)
                for tid, *_ in batch:
                    ok_count[tid] += 1
            except Exception as e:  # replica failure: quorum decides below
                errors.append(e)
        if replicated:
            record_write_outcomes(quorum_need, ok_count,
                                  desired=max(self.ring.rf, 1))
        failed = [tid for tid, need in quorum_need.items() if ok_count[tid] < need]
        if failed:
            self.stats.push_failures += len(failed)
            # surface the ingester's own status (429 backpressure / 400 too
            # large) instead of flattening everything to 500
            push_errs = [e for e in errors if isinstance(e, PushError)]
            if push_errs:
                raise PushError(push_errs[0].status, str(push_errs[0]))
            raise PushError(500, f"{len(failed)} traces failed quorum write: {errors[:1]}")
        self.stats.traces_pushed += len(lim_filtered)

        # forward the POST-filter set (a trace refused from storage must
        # not produce span metrics). The ring and streaming legs ship
        # the segment bytes (the streaming tap NEEDS the exact objects
        # the ingester staged -- ColumnarIngest's feature cache is
        # identity-keyed, and holding the refs pins the cache entries
        # until the tap reads them); the legacy in-process leg only
        # needs the post-filter id SET plus the model closure, resolved
        # on the tap worker
        use_window = (self.generator_window is not None
                      and self.generator_ring is None)
        self._forward_to_generators(
            tenant,
            lim_filtered if (self.generator_ring is not None or use_window)
            else frozenset(lim_filtered),
            traces_fn if (self.generator_forward is not None
                          and not use_window) else None,
            now)

    # ------------------------------------------------------------ rebatch
    @staticmethod
    def _requests_by_trace_id(batches: list[ResourceSpans]) -> dict[bytes, Trace]:
        """Regroup spans by trace id keeping resource/scope structure
        (requestsByTraceID, distributor.go:451-525)."""
        out: dict[bytes, Trace] = {}
        for rs in batches:
            for ss in rs.scope_spans:
                groups: dict[bytes, list] = defaultdict(list)
                for sp in ss.spans:
                    groups[sp.trace_id].append(sp)
                for tid, spans in groups.items():
                    tr = out.get(tid)
                    if tr is None:
                        tr = out[tid] = Trace()
                    tr.resource_spans.append(
                        ResourceSpans(
                            resource=rs.resource,
                            scope_spans=[ScopeSpans(scope=ss.scope, spans=spans)],
                        )
                    )
        return out
