"""Anonymous usage statistics (reference: pkg/usagestats).

The reference generates a persistent cluster seed (a UUID stored as a
backend object so every module of a cluster reports under one identity)
and periodically reports counters. This deployment-local variant keeps
the same seed protocol and report shape but never leaves the process:
the report is served at /status/usage-stats (operators can forward it
themselves; a tracing backend should not phone home by default).
"""

from __future__ import annotations

import json
import time
import uuid

from ..backend.base import DoesNotExist, RawBackend

_SEED_TENANT = "__cluster__"  # tenant-level object, like the reference's seed file
_SEED_NAME = "usage-stats-seed.json"


class UsageReporter:
    def __init__(self, backend: RawBackend, target: str):
        self.backend = backend
        self.target = target
        self.started_at = time.time()
        self._seed: dict | None = None

    def seed(self) -> dict:
        """Load-or-create the cluster seed (reference: usagestats seed
        object with leader election by first-writer-wins; a lost race
        just means re-reading the winner's seed)."""
        if self._seed is not None:
            return self._seed
        try:
            self._seed = json.loads(
                self.backend.read_tenant_object(_SEED_TENANT, _SEED_NAME)
            )
        except DoesNotExist:
            seed = {"UID": str(uuid.uuid4()), "created_at": time.time()}
            self.backend.write_tenant_object(
                _SEED_TENANT, _SEED_NAME, json.dumps(seed).encode()
            )
            try:  # re-read: another module may have won the write race
                self._seed = json.loads(
                    self.backend.read_tenant_object(_SEED_TENANT, _SEED_NAME)
                )
            except DoesNotExist:
                self._seed = seed
        return self._seed

    def report(self, app) -> dict:
        """The reference's report shape: seed + edition + target +
        uptime + coarse counters."""
        out = {
            "clusterID": self.seed().get("UID", ""),
            "edition": "tpu-oss",
            "target": self.target,
            "uptime_s": round(time.time() - self.started_at, 1),
            "metrics": {},
        }
        m = out["metrics"]
        if app.distributor is not None:
            m["spans_received"] = app.distributor.stats.spans_received
            m["bytes_received"] = app.distributor.stats.bytes_received
        if app.ingester is not None:
            m["blocks_flushed"] = sum(
                i.blocks_flushed for i in app.ingester.instances.values()
            )
        if app.compactor is not None:
            m["blocks_compacted"] = app.compactor.stats.blocks_compacted
        if app.querier is not None:
            m["searches"] = app.querier.stats.searches
            m["traces_found"] = app.querier.stats.traces_found
        m["tenants"] = len(app.db.blocklist.tenants())
        m["blocklist_length"] = sum(
            len(app.db.blocklist.metas(t)) for t in app.db.blocklist.tenants()
        )
        return out
