"""App runtime: config root, module wiring, HTTP server, targets.

Reference: cmd/tempo/app -- module DAG (modules.go:360-414), single
binary running any role or `all` (config.go Target), HTTP API routes
(pkg/api/http.go:56-60). The single-binary target wires every module
in-process over an in-memory ring, exactly the topology the reference
uses for tests (cmd/tempo/main.go:186-194).

Run: python -m tempo_tpu.services.app --target=all --storage.path=DIR
"""

from __future__ import annotations

import argparse
import json
import os
import re
import threading
import time
from dataclasses import dataclass, field
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlparse

from ..db.search import SearchRequest
from ..db.tempodb import TempoDB, TempoDBConfig
from ..db.wal import WAL
from ..ring.ring import InMemoryKV, Lifecycler, Ring
from ..util.traceid import parse_trace_id
from ..wire import otlp_json
from ..wire.model import Trace
from .compactor import Compactor
from .distributor import Distributor, PushError
from .frontend import Frontend, TooManyRequests
from .ingester import Ingester, IngesterConfig
from .overrides import Overrides
from .querier import Querier

DEFAULT_TENANT = "single-tenant"
TENANT_HEADER = "X-Scope-OrgID"  # reference: shared orgid header

INGESTER_RING = "ingester-ring"
COMPACTOR_RING = "compactor-ring"
GENERATOR_RING = "generator-ring"
QUERIER_RING = "querier-ring"  # blocklist-poll sharding (fleet/)


@dataclass
class AppConfig:
    target: str = "all"  # all | distributor | ingester | querier | ...
    http_port: int = 3200
    storage_path: str = "./tempo-data"
    wal_path: str = ""
    overrides_path: str = ""
    multitenancy: bool = False
    instance_id: str = ""  # empty = derive tempo-<http_port>
    replication_factor: int = 1
    ingester: IngesterConfig = field(default_factory=IngesterConfig)
    compaction_cycle_s: float = 30.0
    enable_generator: bool = True
    # multi-process topology: shared ring-KV directory + the address other
    # processes reach this one at (http://host:port). Empty = single binary
    # with an in-memory ring.
    kv_dir: str = ""
    # OR true multi-host membership: gossip bind addr (host:port, 0 port =
    # ephemeral) + comma-separated seed peers (reference: memberlist)
    gossip_bind: str = ""
    gossip_seeds: str = ""
    gossip_advertise: str = ""  # addr peers dial (wildcard binds need it)
    advertise_addr: str = ""
    http_host: str = ""  # default: loopback, or 0.0.0.0 when advertising non-loopback
    # shared secret for /internal/* and remote /flush//shutdown when the
    # server is reachable beyond loopback
    internal_token: str = ""
    # standalone querier: comma-separated frontend addresses to attach to
    # and pull jobs from (reference: querier.frontend-address)
    frontend_addr: str = ""
    frontend_workers: int = 8  # in-process worker threads (0 = dispatcher-only)
    # OTLP gRPC receiver port (reference receiver default 4317);
    # 0 = disabled, -1 = ephemeral (tests)
    otlp_grpc_port: int = 0
    # OpenCensus gRPC receiver port (reference shim.go:98; OC agent
    # convention 55678); 0 = disabled, -1 = ephemeral (tests)
    opencensus_grpc_port: int = 0
    # Jaeger gRPC collector port (reference shim.go:95-101; jaeger
    # collector convention 14250); 0 = disabled, -1 = ephemeral (tests)
    jaeger_grpc_port: int = 0
    # Jaeger agent UDP ports (client-SDK emitBatch; 6831 thrift-compact,
    # 6832 thrift-binary); 0 = disabled, -1 = ephemeral (tests). One
    # flag enables both sockets.
    jaeger_agent_port: int = 0
    # Kafka receiver (reference shim.go:100): host:port of a broker, ""
    # = disabled; messages are OTLP-proto ExportTraceServiceRequest
    kafka_brokers: str = ""
    kafka_topic: str = ""
    kafka_tenant: str = ""  # required when multitenancy is on
    # self-tracing: query operations emit spans into this tenant through
    # the local distributor ("" = off); reference: the app traces its own
    # handlers and ships them like any tenant's (SURVEY.md 5.1)
    self_tracing_tenant: str = ""
    # metrics-generator remote-write target ("" = expose on /metrics only)
    remote_write_url: str = ""
    remote_write_interval_s: float = 15.0
    # comma-separated serverless search endpoints: block-shard jobs POST
    # there with hedging, local execution as fallback (reference:
    # querier.search.external_endpoints, querier.go:401-458)
    search_external_endpoints: str = ""
    search_external_hedge_after_s: float = 4.0
    # persistent XLA compilation cache dir ("" = TEMPO_COMPILE_CACHE_DIR
    # env, or off): restarts deserialize compiled kernels from disk
    # instead of re-paying the first-compile storm (util/costmodel)
    compile_cache_dir: str = ""
    # measured-crossover CostLedger artifact ("" = TEMPO_COST_LEDGER
    # env, else <storage_path>/cost_ledger.json): find/live-search/
    # block-scan routing seeds from it at startup (util/costledger)
    cost_ledger_path: str = ""
    # chaos plane (tempo_tpu/chaos): fault-injection rules as inline
    # JSON or a file path ("" = TEMPO_CHAOS env, else off). Armed
    # processes also accept runtime rule swaps via POST /internal/chaos.
    chaos_rules: str = ""
    # AOT warmup: compile the CostLedger's recorded (op, shape-bucket)
    # corpus through the persistent compile cache BEFORE serving, so
    # the first query stops paying the XLA compile storm (util/warmup)
    warmup_shapes: bool = False
    # fleet knobs (tempo_tpu/fleet): ring liveness window in seconds
    # (0 = ring.HEARTBEAT_TIMEOUT_S); lifecyclers also PRUNE peers past
    # it, so a SIGKILLed ingester leaves the write ring within about
    # one heartbeat period of the timeout instead of soaking doomed
    # replica writes until every reader's local filter catches up
    ring_heartbeat_timeout: float = 0.0
    # per-RPC deadline for remote ingester clients (replica writes,
    # quorum-read snapshots): the replica-leg timeout the quorum
    # arithmetic absorbs
    rpc_deadline_s: float = 10.0
    # standalone-querier worker threads against the frontend job API
    # (reference: querier.max-concurrent-queries)
    worker_concurrency: int = 4


class App:
    """All modules of one process, wired per target."""

    VALID_TARGETS = ("all", "distributor", "ingester", "querier", "query-frontend",
                     "compactor", "metrics-generator")

    def __init__(self, cfg: AppConfig):
        shared_ring = bool(cfg.kv_dir or cfg.gossip_bind)
        if cfg.target == "distributor" and not shared_ring:
            raise ValueError(
                "standalone distributor needs a shared ring (--kv.dir for a "
                "shared filesystem, --memberlist.bind/--memberlist.join for "
                "multi-host gossip) to reach remote ingesters; or run "
                "-target=all (single binary)"
            )
        if cfg.target not in self.VALID_TARGETS:
            raise ValueError(f"unknown target {cfg.target!r}; one of {self.VALID_TARGETS}")
        if not cfg.instance_id:
            cfg.instance_id = f"tempo-{cfg.http_port}"
        self.cfg = cfg

        # chaos plane: arm BEFORE any backend/TempoDB exists so the
        # object-store seam gets its injection wrapper; an explicit
        # --chaos.rules wins over (and replaces) the TEMPO_CHAOS env
        from ..chaos import plane as chaos_plane

        if cfg.chaos_rules:
            chaos_plane.configure_spec(cfg.chaos_rules)

        def has(role: str) -> bool:
            return cfg.target in ("all", role)

        if shared_ring and cfg.target in ("all", "ingester") and not cfg.advertise_addr.startswith(
            ("http://", "https://")
        ):
            raise ValueError(
                "an ingester joining a shared ring (--kv.dir or --memberlist.*) "
                "must advertise an http(s):// address (--advertise.addr) for "
                "peers to reach it"
            )
        # device cost plane wiring BEFORE the first TempoDB (it seeds
        # routing from the ledger at init): persistent compile cache +
        # the measured-crossover CostLedger artifact. Explicit env vars
        # win over the storage-path default -- the operator aimed them.
        from ..util import costledger, costmodel

        if cfg.compile_cache_dir:
            costmodel.enable_compile_cache(cfg.compile_cache_dir)
        else:
            costmodel.maybe_enable_compile_cache_from_env()
        if not os.environ.get(costledger.LEDGER_ENV, ""):
            costledger.configure(
                cfg.cost_ledger_path
                or os.path.join(cfg.storage_path, "cost_ledger.json"))
        # continuous profiling plane (util/profiler): the bounded
        # profile-artifact store lives under the storage path (an
        # explicit TEMPO_PROFILE_DIR env wins inside configure)
        from ..util import profiler as _profiler

        _profiler.PROF.configure_artifacts(
            os.path.join(cfg.storage_path, "profiles"))

        # per-instance WAL dir: ingesters sharing --storage.path must never
        # replay (and delete) each other's live WAL files
        default_wal_layout = not cfg.wal_path
        wal_path = cfg.wal_path or os.path.join(cfg.storage_path, "wal", cfg.instance_id)
        self.db = TempoDB(
            TempoDBConfig(
                backend={"backend": "local", "path": cfg.storage_path},
                wal_path=os.path.join(cfg.storage_path, "db-wal"),
            )
        )
        self.db.poll_now()
        self.overrides = Overrides(path=cfg.overrides_path)
        if cfg.gossip_bind:
            from ..transport.gossip import GossipKV

            self.kv = GossipKV(
                cfg.gossip_bind,
                seeds=[s.strip() for s in cfg.gossip_seeds.split(",") if s.strip()],
                advertise=cfg.gossip_advertise,
            )
        elif cfg.kv_dir:
            from ..transport import FileKV

            self.kv = FileKV(cfg.kv_dir)
        else:
            self.kv = InMemoryKV()
        from ..ring.ring import HEARTBEAT_TIMEOUT_S

        hb_timeout = cfg.ring_heartbeat_timeout or HEARTBEAT_TIMEOUT_S
        # heartbeat fast enough that a live instance never looks dead
        # inside its own liveness window (harnesses run 2 s windows)
        hb_period = min(5.0, max(0.2, hb_timeout / 4.0))
        self._hb_timeout, self._hb_period = hb_timeout, hb_period
        self.ring = Ring(self.kv, INGESTER_RING,
                         replication_factor=cfg.replication_factor,
                         heartbeat_timeout=hb_timeout)

        # addr -> client: in-process registry + HTTP for remote addrs
        from ..transport import client_registry

        self._clients: dict[str, object] = {}
        self.client_for = client_registry(self._clients, token=cfg.internal_token,
                                          timeout=cfg.rpc_deadline_s)

        self.ingester = self.lifecycler = None
        if has("ingester"):
            self.ingester = Ingester(
                WAL(wal_path, fsync_interval_s=cfg.ingester.wal_fsync_interval_s),
                self.db, self.overrides, cfg.ingester)
            self.ingester.replay_wal()
            if default_wal_layout:
                # only the per-instance layout has meaningful siblings; an
                # explicit --wal.path may live beside unrelated directories
                self._warn_orphan_wals(os.path.dirname(wal_path), cfg.instance_id)
            self.lifecycler = Lifecycler(self.kv, INGESTER_RING, cfg.instance_id,
                                         addr=cfg.advertise_addr,
                                         heartbeat_period=hb_period,
                                         prune_timeout=hb_timeout)
            self._clients[self.lifecycler.desc.addr] = self.ingester

        self.generator = self.generator_lifecycler = None
        gen_forward = None
        if cfg.enable_generator and (has("metrics-generator") or cfg.target == "all"):
            from .generator import MetricsGenerator

            self.generator = MetricsGenerator(self.overrides)
            gen_forward = self.generator.push
            if shared_ring and cfg.target == "metrics-generator":
                # standalone generator joins its own ring so distributors
                # shuffle-shard tenants across the generator fleet
                self.generator_lifecycler = Lifecycler(
                    self.kv, GENERATOR_RING, cfg.instance_id, addr=cfg.advertise_addr
                )

        self.distributor = None
        if has("distributor"):
            # local generator -> in-process tap; shared-KV topology with
            # no local generator -> shuffle-sharded remote generator ring
            gen_ring = (
                Ring(self.kv, GENERATOR_RING)
                if shared_ring and self.generator is None
                else None
            )
            # streaming tap: when the generator AND the ingester share
            # this process, the tap reads coded span columns out of the
            # ingester's ColumnarIngest cache (the write path already
            # decoded them) instead of re-decoding traces
            gen_window = (
                self._generator_window
                if self.generator is not None and self.ingester is not None
                else None
            )
            self.distributor = Distributor(
                self.ring, self.client_for, self.overrides,
                generator_forward=gen_forward, generator_ring=gen_ring,
                generator_window=gen_window,
            )

        self.querier = self.frontend = self.querier_worker = None
        if has("querier") or has("query-frontend"):
            # with a shared KV the ring may hold remote ingesters even when
            # this process hosts none
            ingester_ring = self.ring if (self._clients or shared_ring) else None
            ext = [e.strip() for e in cfg.search_external_endpoints.split(",")
                   if e.strip()]
            self.querier = Querier(
                self.db, ingester_ring, self.client_for,
                external_endpoints=ext,
                external_hedge_after_s=cfg.search_external_hedge_after_s,
            )
            # a standalone query-frontend with remote queriers attached is
            # dispatcher-only (v1/frontend.go); every other shape keeps
            # in-process workers draining the same queue
            n_workers = cfg.frontend_workers
            if cfg.target == "query-frontend" and shared_ring:
                n_workers = 0
            self.frontend = Frontend(self.querier, n_workers=n_workers,
                                     overrides=self.overrides)
            if self.frontend.result_cache is not None and self.ingester is not None:
                # live-head generation feed: result-cache entries over
                # ranges touching the live window key on it, so every
                # push/cut/flush invalidates them naturally. Without a
                # local ingester those ranges stay uncacheable (the
                # extension prefix never includes the live window).
                self.frontend.result_cache.live_gen = self.ingester.live_generation
            if cfg.target == "querier" and cfg.frontend_addr:
                from .worker import QuerierWorker

                self.querier_worker = QuerierWorker(
                    self.querier,
                    [a.strip() for a in cfg.frontend_addr.split(",") if a.strip()],
                    token=cfg.internal_token,
                    concurrency=cfg.worker_concurrency,
                    worker_id=cfg.instance_id,
                )

        # blocklist-poll sharding (fleet/poller_shard): standalone
        # queriers on a shared ring join the querier ring and each polls
        # only the tenants it owns, reading peers' indexes for the rest
        self.querier_lifecycler = self.poller_shard = None
        if shared_ring and cfg.target == "querier":
            from ..fleet.poller_shard import PollerShard

            self.querier_lifecycler = Lifecycler(
                self.kv, QUERIER_RING, cfg.instance_id,
                heartbeat_period=hb_period, prune_timeout=hb_timeout)
            self.poller_shard = PollerShard(
                Ring(self.kv, QUERIER_RING, heartbeat_timeout=hb_timeout),
                cfg.instance_id)
            self.poller_shard.install(self.db)

        self.compactor = self.compactor_lifecycler = None
        if has("compactor"):
            # compactors own jobs via their OWN ring (the reference's
            # compactor ring, modules/compactor/compactor.go:36-38) -- an
            # ingester-ring membership test would never match a standalone
            # compactor process
            self.compactor_lifecycler = Lifecycler(self.kv, COMPACTOR_RING, cfg.instance_id)
            comp_ring = Ring(self.kv, COMPACTOR_RING)
            self.compactor = Compactor(self.db, comp_ring, cfg.instance_id,
                                       cycle_s=cfg.compaction_cycle_s)
        if (cfg.self_tracing_tenant and self.frontend is not None
                and self.distributor is not None):
            from .selftrace import SelfTracer

            self.frontend.self_tracer = SelfTracer(
                self.distributor.push, tenant=cfg.self_tracing_tenant
            )

        # SLO plane (util/slo): declarative objectives over the metrics
        # this process already collects, evaluated as multi-window burn
        # rates on /status/slo + tempo_slo_burn_rate gauges. Query-
        # serving roles only -- a standalone compactor has no read SLIs.
        self.slo = (build_default_slo(self.frontend, self.generator)
                    if (self.frontend or self.generator) else None)

        from .usagestats import UsageReporter

        self.usage = UsageReporter(self.db.backend, cfg.target)
        self.warmup_report: dict | None = None
        self._started = False
        self.otlp_grpc = None
        self.opencensus = None
        self.jaeger_grpc = None
        self.jaeger_agent = None
        self.kafka = None
        self.remote_writer = None
        self.http_server: ThreadingHTTPServer | None = None
        self._profile_lock = threading.Lock()  # one /debug/profile at a time

    def _generator_window(self, tenant: str, segs: list, push_ts: float) -> None:
        """Streaming generator tap (runs on the distributor's tap
        worker): resolve each segment's coded span columns from the
        tenant instance's ColumnarIngest -- the staging path filled
        that identity-keyed cache before the tap item was enqueued, so
        this is a pure cache read with ZERO extra proto decodes
        (ColumnarIngest.decodes proves it) -- and fold the window."""
        col = self.ingester.instance(tenant).columnar
        cols = []
        for seg in segs:
            feat = col.features_for(seg)
            if feat.spans is not None:
                cols.append(feat.spans)
        if cols:
            self.generator.push_window(tenant, cols, col.dict, push_ts)

    # ------------------------------------------------------------ lifecycle
    def start(self) -> None:
        if self.lifecycler:
            self.lifecycler.start()
        if self.compactor_lifecycler:
            self.compactor_lifecycler.start()
        if self.generator_lifecycler:
            self.generator_lifecycler.start()
        if self.querier_lifecycler:
            self.querier_lifecycler.start()
        if self.ingester:
            self.ingester.start_sweeper()
        if self.compactor:
            self.compactor.start()
        if self.querier_worker:
            self.querier_worker.start()
        self.overrides.start_reloader()  # hot-reload per-tenant limits
        if self.generator is not None and self.cfg.remote_write_url:
            from .remotewrite import RemoteWriter

            self.remote_writer = RemoteWriter(
                self.generator, self.cfg.remote_write_url,
                interval_s=self.cfg.remote_write_interval_s,
            )
            self.remote_writer.start()
        if self.distributor is not None and self.cfg.otlp_grpc_port != 0:
            from .otlp_grpc import OTLPGrpcReceiver

            self.otlp_grpc = OTLPGrpcReceiver(self)
            port = max(0, self.cfg.otlp_grpc_port)  # -1 -> ephemeral
            self.cfg.otlp_grpc_port = self.otlp_grpc.start(
                port, host=self._bind_host())
        if self.distributor is not None and self.cfg.opencensus_grpc_port != 0:
            from .opencensus_grpc import OpenCensusReceiver

            self.opencensus = OpenCensusReceiver(self)
            port = max(0, self.cfg.opencensus_grpc_port)  # -1 -> ephemeral
            self.cfg.opencensus_grpc_port = self.opencensus.start(
                port, host=self._bind_host())
        if self.distributor is not None and self.cfg.jaeger_grpc_port != 0:
            from .jaeger_grpc import JaegerGrpcReceiver

            self.jaeger_grpc = JaegerGrpcReceiver(self)
            port = max(0, self.cfg.jaeger_grpc_port)  # -1 -> ephemeral
            self.cfg.jaeger_grpc_port = self.jaeger_grpc.start(
                port, host=self._bind_host())
        if self.distributor is not None and self.cfg.jaeger_agent_port != 0:
            if self.cfg.multitenancy:
                # UDP datagrams cannot carry X-Scope-OrgID: every push
                # would 401 and silently vanish -- fail the config loudly
                raise ValueError(
                    "jaeger_agent_port requires multitenancy off "
                    "(UDP carries no tenant header)")
            from .jaeger_agent import JaegerAgentReceiver

            self.jaeger_agent = JaegerAgentReceiver(self)
            want = max(0, self.cfg.jaeger_agent_port)
            cport, _bport = self.jaeger_agent.start(
                want, want + 1 if want else 0, host=self._bind_host())
            self.cfg.jaeger_agent_port = cport
        if self.distributor is not None and self.cfg.kafka_brokers:
            from .kafka_receiver import DEFAULT_TOPIC, KafkaReceiver

            if self.cfg.multitenancy and not self.cfg.kafka_tenant:
                # fail at startup, not by silently dropping every message
                raise ValueError(
                    "the kafka receiver needs --distributor.kafka-tenant "
                    "when multitenancy is enabled (messages carry no "
                    "X-Scope-OrgID)"
                )
            self.kafka = KafkaReceiver(
                self, self.cfg.kafka_brokers,
                topic=self.cfg.kafka_topic or DEFAULT_TOPIC,
                tenant=self.cfg.kafka_tenant or DEFAULT_TENANT,
            )
            self.kafka.start()
        if self.slo is not None:
            try:
                slo_interval = float(os.environ.get("TEMPO_SLO_EVAL_S", "")
                                     or 15)
            except ValueError:
                slo_interval = 15.0  # a typo'd env must not abort startup
            self.slo.start(interval_s=slo_interval)
        # always-on attributed sampler (TEMPO_PROFILE_HZ, 0 = strict
        # no-op) + the Go-runtime-equivalent GC/thread/RSS gauges
        from ..util import profiler as _profiler
        from ..util import runtimestats as _runtimestats

        _profiler.PROF.ensure_sampler()
        _runtimestats.install()
        if self.cfg.warmup_shapes:
            # pre-serve AOT warmup: compile the ledger's recorded
            # (op, bucket) corpus (through the persistent compile
            # cache when enabled) before the first query arrives
            from ..util.warmup import run_warmup

            self.warmup_report = run_warmup()
        self.db.enable_polling()
        self._started = True

    def stop(self) -> None:
        if self.distributor is not None:
            self.distributor.stop()  # drain the async generator tap
        if self.remote_writer is not None:
            self.remote_writer.stop()
        self.overrides.stop()
        if self.otlp_grpc is not None:
            self.otlp_grpc.stop()
        if self.opencensus is not None:
            self.opencensus.stop()
        if self.jaeger_grpc is not None:
            self.jaeger_grpc.stop()
        if self.jaeger_agent is not None:
            self.jaeger_agent.stop()
        if self.kafka is not None:
            self.kafka.stop()
        if self.slo is not None:
            self.slo.stop()
        if self.querier_worker:
            self.querier_worker.stop()
        if self.compactor:
            self.compactor.stop()
        if self.ingester:
            self.ingester.stop()
        if self.frontend:
            self.frontend.stop()
        if self.lifecycler:
            self.lifecycler.leave()
        if self.compactor_lifecycler:
            self.compactor_lifecycler.leave()
        if self.generator_lifecycler:
            self.generator_lifecycler.leave()
        if self.querier_lifecycler:
            self.querier_lifecycler.leave()
        self.db.close()
        if hasattr(self.kv, "close"):
            self.kv.close()  # gossip mode: stop the server + sync loop
        if self.http_server:
            self.http_server.shutdown()

    def ready(self) -> bool:
        if not self._started:
            return False
        if self.ingester is not None:
            return bool(self.ring.healthy_instances())
        return True

    @staticmethod
    def _warn_orphan_wals(wal_root: str, instance_id: str) -> None:
        """WAL dirs are per --instance.id; a renamed instance would silently
        strand its predecessor's unflushed data, so surface any sibling
        WAL dir that still holds files."""
        from ..util.log import get_logger

        try:
            entries = os.listdir(wal_root)
        except OSError:
            return
        for name in entries:
            p = os.path.join(wal_root, name)
            if name != instance_id and os.path.isdir(p) and os.listdir(p):
                get_logger("app").warning(
                    "orphaned WAL dir %s holds unreplayed files from instance %r; "
                    "restart with --instance.id %s to replay it",
                    p, name, name,
                )

    # ------------------------------------------------------------ tenant
    def tenant_of(self, headers, read: bool = False) -> str:
        if not self.cfg.multitenancy:
            t = headers.get(TENANT_HEADER, "")
            if read and t and t == self.cfg.self_tracing_tenant:
                # READ-only carve-out: the self-tracing tenant stays
                # queryable in single-tenant mode so the dogfood loop
                # (tempo-cli self-trace) works against the plain dev
                # app. Ingest never honors the header here -- a client
                # must not be able to push spoofed spans into the
                # system's own diagnostic tenant.
                return t
            return DEFAULT_TENANT
        t = headers.get(TENANT_HEADER, "")
        if not t:
            raise PushError(401, f"missing {TENANT_HEADER} header")
        return t

    # ------------------------------------------------------------ http
    def _bind_host(self) -> str:
        """Bind policy shared by the HTTP server and every gRPC
        receiver: explicit http_host wins; else a non-loopback advertise
        addr implies peers connect from other hosts (bind all
        interfaces), else stay loopback-only."""
        if self.cfg.http_host:
            return self.cfg.http_host
        adv = self.cfg.advertise_addr
        local = ("127.0.0.1" in adv) or ("localhost" in adv) or not adv
        return "127.0.0.1" if local else "0.0.0.0"

    def serve_http(self, port: int | None = None, background: bool = False):
        handler = _make_handler(self)
        host = self._bind_host()
        self.http_server = ThreadingHTTPServer((host, port or self.cfg.http_port), handler)
        if background:
            t = threading.Thread(target=self.http_server.serve_forever, daemon=True)
            t.start()
            return self.http_server
        self.http_server.serve_forever()


def _make_handler(app: App):
    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, *a):  # quiet
            pass

        def _send(self, code: int, body: bytes | str, ctype="application/json",
                  headers: dict | None = None):
            if isinstance(body, str):
                body = body.encode()
            self.send_response(code)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            for k, v in (headers or {}).items():
                self.send_header(k, v)
            self.end_headers()
            self.wfile.write(body)

        @staticmethod
        def _cache_headers() -> dict:
            """X-Tempo-Cache: hit|miss|extend for the query routes --
            how soak's --repeat-zipf and the vulture cached_vs_fresh
            probes classify responses client-side."""
            from .resultcache import LAST_OUTCOME

            outcome = LAST_OUTCOME.get()
            LAST_OUTCOME.set(None)
            return {"X-Tempo-Cache": outcome} if outcome else {}

        def _err(self, code: int, msg: str):
            self._send(code, json.dumps({"error": msg}))

        def _stream_json(self, events, sse: bool) -> None:
            """Write an event iterator as a chunked HTTP/1.1 response:
            SSE `data:` frames or NDJSON lines, one flush per event so
            the client sees each partial the moment its shard lands.
            The first event is pulled BEFORE the headers go out, so
            admission errors (QoS 429) still surface as real statuses."""
            import itertools

            close = getattr(events, "close", None)  # BEFORE chain rebinds
            try:
                first = next(events)
            except StopIteration:
                first = None
            else:
                events = itertools.chain([first], events)
            self.send_response(200)
            self.send_header(
                "Content-Type",
                "text/event-stream" if sse else "application/x-ndjson")
            self.send_header("Cache-Control", "no-cache")
            self.send_header("Transfer-Encoding", "chunked")
            self.end_headers()

            def chunk(payload: bytes) -> bytes:
                return b"%X\r\n%s\r\n" % (len(payload), payload)

            try:
                if first is not None:
                    for obj in events:
                        data = json.dumps(obj)
                        payload = (f"data: {data}\n\n"
                                   if sse else data + "\n").encode()
                        self.wfile.write(chunk(payload))
                        self.wfile.flush()
                self.wfile.write(b"0\r\n\r\n")
            except (BrokenPipeError, ConnectionResetError):
                # client went away mid-stream: close the generator so it
                # cancels its jobs and releases its QoS charge
                if close is not None:
                    close()
            except Exception:
                # headers are already out: propagating would let do_GET
                # write a SECOND status line into the chunked body. Close
                # the generator (cancels jobs, releases QoS) and end the
                # chunked stream so the client sees clean termination.
                if close is not None:
                    close()
                try:
                    self.wfile.write(b"0\r\n\r\n")
                except OSError:
                    pass

        def _authorized_internal(self) -> bool:
            """Operational + internal endpoints: loopback peers are always
            trusted; remote peers must present the shared token."""
            if self.client_address[0] in ("127.0.0.1", "::1"):
                return True
            tok = app.cfg.internal_token
            return bool(tok) and self.headers.get("X-Tempo-Internal-Token", "") == tok

        # ----------------------------------------------------------- GET
        def do_GET(self):
            u = urlparse(self.path)
            q = {k: v[0] for k, v in parse_qs(u.query).items()}
            try:
                # operational endpoints never need a tenant (probes/scrapes
                # carry no X-Scope-OrgID)
                if u.path == "/api/echo":
                    return self._send(200, "echo", "text/plain")
                if u.path == "/ready":
                    return self._send(200 if app.ready() else 503, "ready" if app.ready() else "starting", "text/plain")
                if u.path == "/metrics":
                    # OpenMetrics: exemplars on histogram buckets are only
                    # legal in this format (classic text parsers reject
                    # the `# {...}` suffix), and it requires the EOF marker
                    return self._send(
                        200, _metrics_text(app) + "# EOF\n",
                        "application/openmetrics-text; version=1.0.0; charset=utf-8",
                    )
                if u.path == "/status/config":
                    # ?mode=defaults -> the built-in config; ?mode=diff
                    # -> only fields differing from it (the reference's
                    # /status/config?mode= variants)
                    mode = q.get("mode", "")
                    if mode not in ("", "diff", "defaults"):
                        return self._err(
                            400, f"unknown mode {mode!r}; one of diff, defaults")
                    cfg_d = _config_dict(app.cfg)
                    if mode == "defaults":
                        cfg_d = _config_dict(AppConfig())
                    elif mode == "diff":
                        defaults = _config_dict(AppConfig())
                        cfg_d = {k: v for k, v in cfg_d.items()
                                 if v != defaults.get(k)}
                    return self._send(200, json.dumps(cfg_d, indent=2))
                if u.path == "/status/kernels":
                    # kernel telemetry: compile/cache-hit table, staged-
                    # cache contents, routing reasons, slow-query log
                    return self._send(200, json.dumps(_kernel_status(app), indent=2))
                if u.path == "/status/cost":
                    # device cost plane (util/costmodel): per-(op,bucket)
                    # FLOPs/bytes/utilization vs roofline, collective
                    # comm bytes, the HBM ledger, the crossover ledger
                    # and compile-cache state
                    from ..util.costmodel import COST

                    return self._send(
                        200, json.dumps(COST.status_snapshot(), indent=2))
                if u.path == "/status/chaos":
                    # chaos + resilience surface: active fault rules
                    # with call/fire counts, the recent injection log,
                    # circuit-breaker legs, retry-budget + hedge
                    # counters, and the warmup report when --warmup.
                    # shapes ran
                    from ..chaos import plane as chaos_plane
                    from ..util.breaker import breakers_snapshot
                    from ..util.kerneltel import TEL

                    out = chaos_plane.status()
                    out["breakers"] = breakers_snapshot()
                    out["retries"] = TEL.retry_stats()
                    out["hedging"] = TEL.hedge_stats()
                    if app.warmup_report is not None:
                        out["warmup"] = app.warmup_report
                    return self._send(200, json.dumps(out, indent=2))
                if u.path == "/status/fleet":
                    # the cluster operator's one-stop view: ring members
                    # with heartbeat ages, RF + quorum arithmetic,
                    # replica push-leg breaker health, replication write
                    # outcomes, the poller shard map and per-tenant
                    # queue depths
                    return self._send(
                        200, json.dumps(_fleet_status(app), indent=2))
                if u.path == "/status/slo":
                    # the SLO plane's verdict surface: every objective
                    # with its multi-window burn rates (util/slo),
                    # re-evaluated at request time so the payload is
                    # never staler than the ask
                    if app.slo is None:
                        return self._err(
                            404, f"target {app.cfg.target} serves no "
                                 "query SLOs")
                    return self._send(
                        200, json.dumps(app.slo.evaluate(), indent=2))
                if u.path == "/status/usage-stats":
                    return self._send(200, json.dumps(app.usage.report(app), indent=2))
                if u.path == "/status/profile":
                    # continuous profiling plane (util/profiler):
                    # sampler state + per-component sample counts +
                    # top-stack summaries, lock-contention table,
                    # slow-capture count and the artifact index
                    from ..util.profiler import PROF

                    return self._send(
                        200, json.dumps(PROF.status_snapshot(), indent=2))
                if u.path == "/debug/threads":
                    # every thread's current stack (the role the
                    # reference's pprof goroutine dump plays): first stop
                    # for "what is this process stuck on". Same trust
                    # gate as /internal/*: loopback or shared token
                    # (stacks leak code paths; see _authorized_internal)
                    if not self._authorized_internal():
                        return self._err(403, "forbidden")
                    import sys
                    import traceback as _tb

                    names = {t.ident: t.name for t in threading.enumerate()}
                    parts = []
                    for tid, frame in sys._current_frames().items():
                        parts.append(f"--- thread {names.get(tid, tid)}\n")
                        parts.extend(_tb.format_stack(frame))
                    return self._send(200, "".join(parts), "text/plain")
                if u.path == "/debug/profile":
                    # on-demand burst CPU profile over ?seconds=N
                    # (default 2, capped): the pprof profile endpoint
                    # analog (util/profiler.sample_cpu). Samples
                    # sys._current_frames() across ALL threads at
                    # ?hz= (default 200; a tracing profiler would only
                    # see this handler's thread). ?format=text renders
                    # the hottest stacks; ?format=folded streams the
                    # flamegraph-collapsed table. One at a time:
                    # overlapping scrapes get a 409. Gated like
                    # /internal/*: a repeatable multi-second CPU burn
                    # must not be open to unauthenticated remote peers.
                    if not self._authorized_internal():
                        return self._err(403, "forbidden")
                    from ..util.profiler import PROF

                    fmt = q.get("format", "text")
                    if fmt not in ("text", "folded"):
                        return self._err(
                            400, f"unknown format {fmt!r}; text or folded")
                    try:
                        secs = min(max(float(q.get("seconds", 2.0)), 0.1), 30.0)
                        hz = float(q.get("hz", 200.0))
                    except ValueError:
                        return self._err(400, "seconds/hz must be numbers")
                    if not app._profile_lock.acquire(blocking=False):
                        return self._err(409, "a profile is already running")
                    try:
                        return self._send(200, PROF.sample_cpu(secs, hz, fmt),
                                          "text/plain")
                    finally:
                        app._profile_lock.release()
                if u.path == "/debug/profile/device":
                    # device profile: record jax.profiler trace events
                    # for ?seconds=N while serving continues and publish
                    # the zipped trace directory as an artifact (fetch
                    # via /debug/profile/artifact/<id> or
                    # `tempo-tpu-cli profile device`)
                    if not self._authorized_internal():
                        return self._err(403, "forbidden")
                    from ..util.profiler import PROF, ProfilerUnavailable

                    try:
                        secs = min(max(float(q.get("seconds", 2.0)), 0.1), 60.0)
                    except ValueError:
                        return self._err(400, "seconds must be a number")
                    if not app._profile_lock.acquire(blocking=False):
                        return self._err(409, "a profile is already running")
                    try:
                        aid, summary = PROF.capture_device_profile(secs)
                    except ProfilerUnavailable as e:
                        return self._err(503, f"device profiler: {e}")
                    finally:
                        app._profile_lock.release()
                    return self._send(
                        200, json.dumps({"artifact_id": aid, **summary}))
                m = re.fullmatch(r"/debug/profile/artifact/([^/]+)", u.path)
                if m:
                    # download one profile artifact (slow-query folded
                    # snapshots, device trace zips) from the bounded
                    # store -- ids come from the slow-query log,
                    # /status/profile, or the device endpoint
                    if not self._authorized_internal():
                        return self._err(403, "forbidden")
                    from ..util.profiler import PROF

                    data = PROF.artifact_bytes(m.group(1))
                    if data is None:
                        return self._err(404, f"no artifact {m.group(1)!r}")
                    ctype = ("text/plain" if m.group(1).endswith(".folded")
                             else "application/octet-stream")
                    return self._send(200, data, ctype)
                if app.querier is None:
                    return self._err(404, f"target {app.cfg.target} serves no query API")
                tenant = app.tenant_of(self.headers, read=True)
                m = re.fullmatch(r"/api/traces/([0-9a-fA-F]+)", u.path)
                if m:
                    return self._trace_by_id(tenant, m.group(1), q)
                m = re.fullmatch(r"/jaeger/api/traces/([0-9a-fA-F]+)", u.path)
                if m:  # tempo-query shim: Jaeger UI JSON
                    from ..util.traceid import parse_trace_id
                    from ..wire.jaeger import trace_to_jaeger

                    tr = app.frontend.find_trace_by_id(tenant, parse_trace_id(m.group(1)))
                    if tr is None:
                        return self._err(404, "trace not found")
                    return self._send(200, json.dumps(trace_to_jaeger(tr)))
                if u.path == "/api/search":
                    return self._search(tenant, q)
                if u.path == "/api/metrics/query_range":
                    return self._metrics_query_range(tenant, q)
                if u.path == "/api/search/tags":
                    tags = app.querier.search_tags(tenant)
                    return self._send(200, json.dumps({"tagNames": tags}))
                m = re.fullmatch(r"/api/search/tag/([^/]+)/values", u.path)
                if m:
                    vals = app.querier.search_tag_values(tenant, m.group(1))
                    return self._send(200, json.dumps({"tagValues": vals}))
                return self._err(404, f"no route {u.path}")
            except PushError as e:
                return self._err(e.status, str(e))
            except TooManyRequests as e:
                return self._err(429, str(e))
            except Exception as e:
                return self._err(500, f"{type(e).__name__}: {e}")

        def _trace_by_id(self, tenant: str, hex_id: str, q: dict):
            tid = parse_trace_id(hex_id)
            start = int(q.get("start", 0))
            end = int(q.get("end", 0))
            tr = app.frontend.find_trace_by_id(tenant, tid, start, end)
            hdrs = self._cache_headers()
            if tr is None:
                return self._err(404, "trace not found")
            return self._send(200, otlp_json.dumps(tr), headers=hdrs)

        def _metrics_query_range(self, tenant: str, q: dict):
            """GET /api/metrics/query_range?q=...&start=...&end=...&step=...
            -- TraceQL metrics over the backend (Prometheus-style matrix
            JSON; start/end unix seconds, step a Go duration or
            seconds). The step grid is aligned (metrics_exec
            align_params), so any client polling cadence yields stable
            buckets."""
            from ..db.metrics_exec import (
                align_params,
                parse_metrics_query,
                to_prometheus,
            )
            from ..traceql.ast import ParseError
            from ..traceql.parser import _parse_duration_ns

            query = q.get("q") or q.get("query", "")
            if not query:
                return self._err(400, "missing q parameter")
            try:
                parse_metrics_query(query)
            except ParseError as e:
                return self._err(400, f"invalid TraceQL metrics query: {e}")
            try:
                end = float(q["end"]) if "end" in q else time.time()
                start = float(q["start"]) if "start" in q else end - 3600.0
                if end <= start:
                    raise ValueError("end must be after start")
                sv = q.get("step", "")
                if sv:
                    try:
                        step = float(sv)
                    except ValueError:
                        step = _parse_duration_ns(sv) / 1e9
                    if step <= 0:
                        raise ValueError(f"invalid step {sv!r}")
                else:
                    # default: ~60 points over the range, 1s floor
                    step = max(1.0, round((end - start) / 60.0))
                req = align_params(query, start, end, step)
            except (ValueError, OverflowError) as e:
                return self._err(400, f"bad query_range parameter: {e}")
            try:
                resp = app.frontend.metrics_query_range(tenant, req)
            except ValueError as e:
                # execution-time request errors (e.g. by() cardinality
                # over the accumulator budget) are the caller's to fix
                return self._err(400, f"query_range failed: {e}")
            return self._send(200, json.dumps(to_prometheus(resp)),
                              headers=self._cache_headers())

        def _search(self, tenant: str, q: dict):
            tags = {}
            if "tags" in q:  # logfmt-ish k=v space separated
                for part in q["tags"].split():
                    if "=" in part:
                        k, v = part.split("=", 1)
                        tags[k] = v.strip('"')
            query = q.get("q", "")
            if query:
                # parse + type-check once at the API boundary so a bad
                # query is a 400, not a per-block failure downstream
                from ..traceql.ast import MetricsQuery, ParseError
                from ..traceql.parser import parse as parse_traceql

                try:
                    parsed = parse_traceql(query)
                except ParseError as e:
                    return self._err(400, f"invalid TraceQL: {e}")
                if isinstance(parsed, MetricsQuery):
                    return self._err(
                        400, "metrics queries (rate(), *_over_time()) belong "
                             "on /api/metrics/query_range, not /api/search")
            def dur_ms(name: str) -> int:
                """Go-style duration params ('300ms', '1m30s', '2h') per
                the reference's time.ParseDuration-based API
                (pkg/api ParseSearchRequest); bare numbers keep this
                API's original plain-seconds reading."""
                v = q.get(name, "")
                if not v:
                    return 0
                try:
                    ms = int(float(v) * 1000)
                except ValueError:
                    from ..traceql.parser import _parse_duration_ns

                    ns = _parse_duration_ns(v)
                    if ns <= 0:
                        raise ValueError(f"invalid duration {name}={v!r}")
                    ms = ns // 1_000_000
                if ms <= 0:
                    # this filter API is ms-granularity; silently mapping
                    # '500us' to 0 would DROP the filter (0 = unset)
                    raise ValueError(
                        f"{name}={v!r} is below this API's 1ms granularity")
                return ms

            try:
                req = SearchRequest(
                    tags=tags,
                    query=query,
                    min_duration_ms=dur_ms("minDuration"),
                    max_duration_ms=dur_ms("maxDuration"),
                    start=int(q.get("start", 0)),
                    end=int(q.get("end", 0)),
                    limit=int(q.get("limit", 20)),
                )
            except (ValueError, OverflowError) as e:
                return self._err(400, f"bad search parameter: {e}")
            stream = q.get("stream", "").lower()
            if stream in ("true", "1", "sse"):
                # progressive delivery: newest-first partial result
                # snapshots flush as ingester/backend shards complete
                # (the reference's streaming search direction). SSE when
                # asked (stream=sse or an event-stream Accept header),
                # newline-delimited JSON otherwise; the final event is
                # the exact blocking-response body plus done=true.
                sse = (stream == "sse"
                       or "text/event-stream" in self.headers.get("Accept", ""))
                return self._stream_json(
                    app.frontend.search_stream(tenant, req), sse)
            resp = app.frontend.search(tenant, req)
            return self._send(
                200,
                json.dumps(
                    {
                        "traces": [t.to_dict() for t in resp.traces],
                        "metrics": {
                            "inspectedBytes": str(resp.inspected_bytes),
                            "inspectedSpans": str(resp.inspected_spans),
                        },
                    }
                ),
                headers=self._cache_headers(),
            )

        # ---------------------------------------------------------- POST
        def do_POST(self):
            u = urlparse(self.path)
            ln = int(self.headers.get("Content-Length", 0))
            body = self.rfile.read(ln) if ln else b""
            try:
                if u.path.startswith("/internal/"):
                    if not self._authorized_internal():
                        return self._err(401, "missing or wrong internal token")
                    from ..transport.client import handle_internal
                    from ..transport.frames import CONTENT_TYPE as FRAMES_CT

                    ctype = self.headers.get("Content-Type", "")
                    payload = ({} if ctype.startswith(FRAMES_CT)
                               else json.loads(body or b"{}"))
                    code, out = handle_internal(
                        app, u.path, payload, raw_body=body, content_type=ctype,
                        accept=self.headers.get("Accept", ""),
                    )
                    if isinstance(out, tuple):  # (bytes, content_type)
                        return self._send(code, out[0], out[1])
                    return self._send(code, json.dumps(out))
                if u.path == "/v1/traces":  # OTLP HTTP ingest
                    if app.distributor is None:
                        return self._err(404, f"target {app.cfg.target} does not ingest")
                    tenant = app.tenant_of(self.headers)
                    ctype = self.headers.get("Content-Type", "")
                    if "json" in ctype:
                        tr = otlp_json.loads(body)
                        app.distributor.push(tenant, tr.resource_spans)
                    else:
                        # proto bodies take the raw fast path (native
                        # scan + splice; 400 if undecodable)
                        app.distributor.push_raw(tenant, body)
                    return self._send(200, "{}")
                if u.path == "/api/traces":  # Jaeger collector thrift ingest
                    if app.distributor is None:
                        return self._err(404, f"target {app.cfg.target} does not ingest")
                    from ..wire import jaeger_thrift

                    tenant = app.tenant_of(self.headers)
                    try:
                        rs = jaeger_thrift.decode_batch(body)
                    except jaeger_thrift.ThriftError as e:
                        return self._err(400, f"bad thrift payload: {e}")
                    app.distributor.push(tenant, [rs])
                    return self._send(202, "")
                if u.path == "/api/v2/spans":  # Zipkin v2 JSON ingest
                    if app.distributor is None:
                        return self._err(404, f"target {app.cfg.target} does not ingest")
                    from ..wire import zipkin

                    tenant = app.tenant_of(self.headers)
                    app.distributor.push(tenant, zipkin.decode_spans(body))
                    return self._send(202, "")
                if u.path == "/flush":
                    if not self._authorized_internal():
                        return self._err(401, "missing or wrong internal token")
                    if app.ingester:
                        app.ingester.flush_all()
                    return self._send(204, "")
                if u.path == "/shutdown":
                    if not self._authorized_internal():
                        return self._err(401, "missing or wrong internal token")
                    if app.ingester:
                        app.ingester.flush_all()
                    threading.Thread(target=app.stop, daemon=True).start()
                    return self._send(204, "")
                return self._err(404, f"no route {u.path}")
            except PushError as e:
                return self._err(e.status, str(e))
            except Exception as e:
                return self._err(500, f"{type(e).__name__}: {e}")

    return Handler


def build_default_slo(frontend, generator=None):
    """The serving objectives every query-capable target ships with
    (util/slo): availability over the frontend's per-class outcome
    counters (QoS sheds excluded -- admission refusing work is the
    budget system functioning), p99-under-threshold latency per query
    class from the frontend latency histogram, and live-head freshness
    from the push->device-visible staging-lag histogram. Targets that
    host a metrics-generator additionally carry the push->series-
    visible generator-freshness objective. Thresholds sit on bucket
    edges; TEMPO_SLO_<CLASS>_P99_S env overrides let an operator
    retune without code."""
    from ..util import slo as slomod
    from ..util.kerneltel import TEL

    def _thr(env: str, default: float) -> float:
        try:
            return float(os.environ.get(env, "") or default)
        except ValueError:
            return default

    engine = slomod.SLOEngine()

    if frontend is not None:
        def outcomes_sli():
            # resolve the instrument through TEL at call time:
            # TEL.reset() (tests) swaps the counter object under us
            return slomod.counter_sli(
                TEL.query_outcomes,
                good=lambda l: 'outcome="ok"' in l,
                bad=lambda l: 'outcome="error"' in l)()

        engine.register(slomod.Objective(
            name="read-availability", kind="availability", target=0.999,
            sli=outcomes_sli,
            description="queries served without error across every query "
                        "class (429 QoS sheds excluded)"))

        for op, env, default in (("traces", "TEMPO_SLO_TRACES_P99_S", 1.0),
                                 ("search", "TEMPO_SLO_SEARCH_P99_S", 2.5),
                                 ("search_stream", "TEMPO_SLO_STREAM_P99_S", 5.0),
                                 ("metrics", "TEMPO_SLO_METRICS_P99_S", 10.0)):
            thr = _thr(env, default)
            engine.register(slomod.Objective(
                name=f"latency-{op}", kind="latency", target=0.99,
                sli=slomod.histogram_sli(
                    frontend.query_latency, thr,
                    labels_pred=lambda l, _op=op: f'op="{_op}"' in l),
                description=f"{op} queries completing within {thr:g}s"))

        fresh_thr = _thr("TEMPO_SLO_FRESHNESS_P99_S", 2.5)
        engine.register(slomod.freshness_objective(
            "live-freshness", lambda: TEL.livestage_lag, fresh_thr,
            description=f"pushes device-visible to live search within "
                        f"{fresh_thr:g}s (livestage staging lag)"))

    if generator is not None:
        gen_thr = _thr("TEMPO_SLO_GENERATOR_FRESHNESS_P99_S", 2.5)
        engine.register(slomod.freshness_objective(
            "generator-freshness", lambda: TEL.generator_freshness, gen_thr,
            description=f"pushed spans reflected in generated series "
                        f"within {gen_thr:g}s (streaming tap fold lag)"))
    return engine


def _kernel_status(app: App) -> dict:
    """The /status/kernels payload: everything an operator needs to
    answer "why was that query slow" one layer below HTTP -- per-op
    compile/cache-hit counts and device time, the staged device-column
    cache's contents, engine routing reasons, and the slowest recent
    queries with their self-trace ids."""
    from ..ops.stage import staged_cache_stats
    from ..util.kerneltel import TEL

    out = TEL.snapshot()
    out["staged_cache"] = staged_cache_stats()
    out["staged_cache"]["budget_note"] = (
        "device HBM budget for staged block columns (ops/stage)")
    # the tiered cache plane: Tier A (frontend result cache) + Tier B
    # (host-RAM compressed column-chunk pool under the HBM staged LRU)
    from ..ops import chunkpool

    rc = app.frontend.result_cache if app.frontend is not None else None
    out["caching"] = {
        "result_cache": rc.stats() if rc is not None else {"enabled": False},
        "chunk_pool": chunkpool.stats(),
    }
    return out


# point-in-time gauges, set at scrape (the reference's promauto GaugeFunc)
from ..util.metrics import Gauge as _Gauge  # noqa: E402
from ..util.metrics import escape_label as _esc  # noqa: E402

_JIT_CACHE_GAUGE = _Gauge("tempo_kernel_jit_cache_entries",
                          help="distinct compiled kernel signatures resident")
_BLOCKLIST_GAUGE = _Gauge("tempo_blocklist_length",
                          help="blocks across all tenants in the blocklist")
_WAL_DEPTH_GAUGE = _Gauge("tempo_ingester_wal_bytes",
                          help="bytes buffered in open WAL head blocks")
_QUEUE_DEPTH_GAUGE = _Gauge(
    "tempo_query_queue_depth",
    help="queued query jobs per tenant (the querier-pool autoscaling "
         "SLI: sustained depth means too few queriers for the load)")

# family -> help for the OpenMetrics renderer (families not listed get a
# generated default; TYPE is inferred from the suffix conventions)
_METRIC_HELP = {
    "tempo_distributor_spans_received": "spans accepted by the distributor",
    "tempo_distributor_push_failures": "quorum write failures (data loss)",
    "tempo_frontend_query_duration_seconds": "frontend query latency by op",
    "tempo_kernel_compiles": "XLA program compiles by op and shape bucket",
    "tempo_kernel_cache_hits": "jit-cache hits by op and shape bucket",
    "tempo_kernel_device_seconds": "per-op device wall time",
    "tempo_engine_routing": "engine routing decisions (layer/engine/reason)",
    "tempo_stage_transfer_bytes": "host->device staging upload bytes",
    "tempo_replication_writes_total":
        "replicated write outcomes per trace (quorum/partial/failed)",
    "tempo_query_queue_depth":
        "queued query jobs per tenant (querier-pool autoscaling SLI)",
}


def _metrics_text(app: App) -> str:
    lines = []
    if app.distributor:
        d = app.distributor.stats
        lines += [
            f"tempo_distributor_spans_received_total {d.spans_received}",
            f"tempo_distributor_bytes_received_total {d.bytes_received}",
            f"tempo_distributor_push_failures_total {d.push_failures}",
            f"tempo_distributor_spans_refused_rate_total {d.spans_refused_rate}",
            f"tempo_distributor_traces_refused_size_total {d.traces_refused_size}",
            f"tempo_distributor_gen_tap_dropped_total {d.gen_tap_dropped}",
        ]
        lines += app.distributor.push_latency.text()
    if app.kafka is not None:
        lines += [
            f"tempo_kafka_receiver_messages_total {app.kafka.messages}",
            f"tempo_kafka_receiver_spans_total {app.kafka.spans}",
            f"tempo_kafka_receiver_failures_total {app.kafka.failures}",
        ]
    if app.opencensus is not None:
        lines += [
            f"tempo_opencensus_receiver_requests_total {app.opencensus.requests}",
            f"tempo_opencensus_receiver_spans_total {app.opencensus.spans}",
            f"tempo_opencensus_receiver_failures_total {app.opencensus.failures}",
        ]
    if app.ingester:
        from .ingester import FLUSH_DURATION, FLUSH_FAILURES, WAL_REPLAYS

        lines += [
            f"tempo_ingester_blocks_flushed_total "
            f"{sum(i.blocks_flushed for i in app.ingester.instances.values())}",
            f"tempo_ingester_live_traces "
            f"{sum(len(i.live) for i in app.ingester.instances.values())}",
        ]
        lines += FLUSH_DURATION.text() + FLUSH_FAILURES.text() + WAL_REPLAYS.text()
    if app.querier is not None:
        q = app.querier.stats
        lines += [
            f"tempo_querier_searches_total {q.searches}",
            f"tempo_querier_traces_found_total {q.traces_found}",
            f"tempo_querier_metrics_queries_total {q.metrics_queries}",
            f"tempo_querier_external_searches_total {q.external_searches}",
            f"tempo_querier_external_failures_total {q.external_failures}",
        ]
    if app.compactor:
        lines += [
            f"tempo_compactor_runs_total {app.compactor.stats.runs}",
            f"tempo_compactor_blocks_compacted_total {app.compactor.stats.blocks_compacted}",
            f"tempo_compactor_blocks_retained_total {app.compactor.stats.blocks_retained}",
            f"tempo_compactor_errors_total {len(app.compactor.stats.errors)}",
        ]
        lines += app.compactor.compaction_duration.text()
    # storage-engine + backend-wrapper metrics (poller, cache, hedging)
    lines += app.db.polls.text() + app.db.poll_errors.text() + app.db.poll_duration.text()
    _BLOCKLIST_GAUGE.set(
        sum(len(app.db.blocklist.metas(t)) for t in app.db.blocklist.tenants()))
    lines += _BLOCKLIST_GAUGE.text()
    b = app.db.backend
    while b is not None:
        if hasattr(b, "hits"):
            lines.append(f"tempo_cache_hits_total {b.hits}")
        if hasattr(b, "hedged_requests"):
            lines.append(f"tempo_backend_hedged_requests_total {b.hedged_requests}")
        b = getattr(b, "inner", None)
    if app.frontend:
        lines += app.frontend.query_latency.text()
    if app.querier_worker:
        lines += [
            f"tempo_querier_worker_jobs_executed_total {app.querier_worker.jobs_executed}",
            f"tempo_querier_worker_jobs_failed_total {app.querier_worker.jobs_failed}",
        ]
    if app.frontend:
        lines += [
            f"tempo_frontend_jobs_local_total {app.frontend.stats_jobs_local}",
            f"tempo_frontend_jobs_remote_total {app.frontend.stats_jobs_remote}",
        ]
        # per-tenant queue depth, zeroing tenants that drained since the
        # last scrape so the gauge never freezes on a stale depth
        depths = app.frontend.queue.depths()
        # unlabeled aggregate always exists, so the queue-depth alert
        # has a series to evaluate even on an idle frontend
        _QUEUE_DEPTH_GAUGE.set(sum(depths.values()))
        stale = getattr(app, "_queue_depth_tenants", set()) - set(depths)
        for t in stale:
            _QUEUE_DEPTH_GAUGE.set(0, labels=f'tenant="{_esc(t)}"')
        for t, n in depths.items():
            _QUEUE_DEPTH_GAUGE.set(n, labels=f'tenant="{_esc(t)}"')
        app._queue_depth_tenants = set(depths) | stale
        lines += _QUEUE_DEPTH_GAUGE.text()
    if app.distributor:
        from ..fleet import replication as _replication

        lines += _replication.metrics_lines()
    if app.generator is not None:
        lines.extend(app.generator.metrics_text())
    # kernel telemetry (compiles, cache hits, device time, staging,
    # routing) + point-in-time gauges
    from ..util.kerneltel import TEL
    from ..util.metrics import render_openmetrics

    lines += TEL.metrics_lines()
    _JIT_CACHE_GAUGE.set(TEL.jit_cache_size())
    lines += _JIT_CACHE_GAUGE.text()
    if app.slo is not None:
        # burn-rate + verdict gauges refresh at scrape time: alert
        # rules must never fire on an evaluator that stalled
        try:
            app.slo.evaluate()
        except Exception:
            pass  # scrape keeps the last published gauges
        lines += app.slo.metrics_lines()
    if app.ingester:
        try:
            _WAL_DEPTH_GAUGE.set(sum(
                inst.head.size_bytes()
                for inst in list(app.ingester.instances.values())))
        except Exception:
            pass  # scrape raced a head-block cut; keep the last value
        lines += _WAL_DEPTH_GAUGE.text()
    helps = dict(_METRIC_HELP)
    helps.update(TEL.help_entries())
    if app.slo is not None:
        helps.update(app.slo.help_entries())
    return render_openmetrics(lines, helps=helps)


def _fleet_status(app: App) -> dict:
    """The /status/fleet payload: ring view with heartbeat ages, RF and
    quorum arithmetic, replica-push breaker health, replication write
    outcomes, the blocklist-poll shard map and per-tenant queue depths."""
    import time as _time

    from ..fleet.replication import replication_snapshot
    from ..util.breaker import breakers_snapshot

    now = _time.time()
    members = [{
        "instance_id": d.instance_id,
        "addr": d.addr,
        "state": d.state.value,
        "heartbeat_age_s": round(now - d.heartbeat_ts, 3),
        "healthy": d.healthy(now, app.ring.heartbeat_timeout),
    } for d in app.ring.instances()]
    rf = app.ring.rf
    # mirror ring.ReplicationSet: majority quorum, except RF=2's
    # eventually-consistent minSuccess=1 (see ring/ring.py)
    write_quorum = 1 if rf <= 2 else rf - (rf - 1) // 2
    brs = breakers_snapshot()
    out = {
        "target": app.cfg.target,
        "instance_id": app.cfg.instance_id,
        "ring": {
            "key": INGESTER_RING,
            "replication_factor": rf,
            "write_quorum": write_quorum,
            "heartbeat_timeout_s": app.ring.heartbeat_timeout,
            "members": members,
            "healthy": sum(1 for m in members if m["healthy"]),
        },
        "replication": {
            "writes": replication_snapshot(),
            "push_breakers": {k: v for k, v in brs.items()
                              if k.startswith("ingester-push:")},
            "read_breakers": {k: v for k, v in brs.items()
                              if k.startswith("ingester:")},
        },
    }
    if app.frontend is not None:
        out["queue_depths"] = app.frontend.queue.depths()
    if app.poller_shard is not None:
        out["poller_shard"] = app.poller_shard.status(
            sorted(set(app.db.blocklist.tenants())
                   | set(app.db.poller.last_shard.get("owned", []))
                   | set(app.db.poller.last_shard.get("deferred", []))))
    else:
        out["poller_shard"] = {"instance_id": app.cfg.instance_id,
                               "solo": True, **app.db.poller.last_shard}
    return out


def _config_dict(cfg: AppConfig) -> dict:
    from dataclasses import asdict

    return asdict(cfg)


def load_config_file(path: str, expand_env: bool = False) -> dict:
    """YAML config root. Precedence: YAML supplies the base, explicitly
    set command-line flags override it. Keys mirror AppConfig fields;
    unknown keys are rejected so typos fail loudly like the reference's
    strict YAML. expand_env substitutes ${VAR} / ${VAR:-default}
    references BEFORE parsing (the reference's --config.expand-env,
    cmd/tempo/main.go envsubst) -- the secrets-from-environment pattern
    for credentials in checked-in config files. Names follow the shell
    grammar [A-Za-z_]\\w* (anything else passes through verbatim), and
    `$$` escapes a literal dollar, so a value that legitimately
    contains ${...} is written `$${...}` -- envsubst behavior."""
    import yaml
    from dataclasses import fields as dc_fields

    with open(path) as f:
        text = f.read()
    if expand_env:
        import os as _os
        import re as _re

        def sub(m):
            if m.group(0) == "$$":
                # envsubst escape: $$ -> literal $, so $${FOO} survives
                # expansion as the literal text ${FOO}
                return "$"
            ref = m.group(1)
            name, has_def, default = ref.partition(":-")
            val = _os.environ.get(name)
            if has_def:
                # shell ':-' semantics: default applies when unset OR empty
                return val if val else default
            if val is None:
                # no default and unset: fail at config load with the real
                # cause, not later as a None field deep in startup
                raise ValueError(
                    f"config references ${{{name}}} but it is not set "
                    f"(use ${{{name}:-default}} for an optional value)")
            return val

        # one alternation pass: the $$ alternative consumes its dollars
        # BEFORE the ${...} branch can see them, which is exactly the
        # escape semantics (names outside [A-Za-z_]\w* never match and
        # pass through verbatim)
        text = _re.sub(r"\$\$|\$\{([A-Za-z_]\w*(?::-[^}]*)?)\}", sub, text)
    data = yaml.safe_load(text) or {}
    valid = {f.name for f in dc_fields(AppConfig)}
    unknown = set(data) - valid - {"ingester"}
    if unknown:
        raise ValueError(f"unknown config keys: {sorted(unknown)}")
    if "ingester" in data:
        data["ingester"] = IngesterConfig(**(data["ingester"] or {}))
    return data


def main(argv=None):
    ap = argparse.ArgumentParser(prog="tempo-tpu")
    # None defaults = "flag not given"; a flag the user set ALWAYS overrides
    # the config file, even when set to the built-in default value
    ap.add_argument("--config.file", dest="config_file", default="")
    ap.add_argument("--config.expand-env", dest="config_expand_env",
                    action="store_true",
                    help="substitute ${VAR} / ${VAR:-default} in the config file")
    ap.add_argument("--target", default=None)
    ap.add_argument("--http.port", dest="port", type=int, default=None)
    ap.add_argument("--storage.path", dest="storage", default=None)
    ap.add_argument("--overrides.path", dest="overrides", default=None)
    ap.add_argument("--multitenancy", action="store_const", const=True, default=None)
    ap.add_argument("--kv.dir", dest="kv_dir", default=None,
                    help="shared ring-KV directory for multi-process topologies")
    ap.add_argument("--memberlist.bind", dest="gossip_bind", default=None,
                    help="gossip bind addr host:port for multi-HOST rings")
    ap.add_argument("--memberlist.join", dest="gossip_seeds", default=None,
                    help="comma-separated gossip seed peers")
    ap.add_argument("--memberlist.advertise", dest="gossip_advertise", default=None,
                    help="gossip addr peers dial (needed for 0.0.0.0 binds)")
    ap.add_argument("--advertise.addr", dest="advertise", default=None,
                    help="address other processes reach this one at (http://host:port)")
    ap.add_argument("--instance.id", dest="instance_id", default=None)
    ap.add_argument("--replication.factor", dest="rf", type=int, default=None)
    ap.add_argument("--internal.token", dest="internal_token", default=None,
                    help="shared secret for /internal/* when bound beyond loopback")
    ap.add_argument("--querier.frontend-address", dest="frontend_addr", default=None,
                    help="frontend addr(s) a standalone querier pulls jobs from")
    ap.add_argument("--distributor.otlp-grpc-port", dest="otlp_grpc_port", type=int,
                    default=None, help="OTLP gRPC receiver port (0=off, -1=ephemeral)")
    ap.add_argument("--distributor.opencensus-grpc-port", dest="opencensus_grpc_port",
                    type=int, default=None,
                    help="OpenCensus gRPC receiver port (0=off, -1=ephemeral)")
    ap.add_argument("--distributor.jaeger-grpc-port", dest="jaeger_grpc_port",
                    type=int, default=None,
                    help="Jaeger gRPC collector port (0=off, -1=ephemeral)")
    ap.add_argument("--distributor.jaeger-agent-port", dest="jaeger_agent_port",
                    type=int, default=None,
                    help="Jaeger agent UDP compact port; binary opens at +1 "
                         "(0=off, -1=ephemeral)")
    ap.add_argument("--self-tracing.tenant", dest="self_tracing_tenant",
                    default=None,
                    help="tenant the app's own query timelines ship into "
                         "('' = off); inspect with tempo-cli self-trace")
    ap.add_argument("--compile-cache.dir", dest="compile_cache_dir", default=None,
                    help="persistent XLA compilation cache directory "
                         "(default: TEMPO_COMPILE_CACHE_DIR env, else off)")
    ap.add_argument("--cost-ledger.path", dest="cost_ledger_path", default=None,
                    help="measured-crossover CostLedger artifact (default: "
                         "TEMPO_COST_LEDGER env, else "
                         "<storage.path>/cost_ledger.json)")
    ap.add_argument("--chaos.rules", dest="chaos_rules", default=None,
                    help="fault-injection rules: inline JSON or a rules "
                         "file path (default: TEMPO_CHAOS env, else off)")
    ap.add_argument("--warmup.shapes", dest="warmup_shapes",
                    action="store_const", const=True, default=None,
                    help="AOT-compile the CostLedger's recorded (op, "
                         "shape-bucket) corpus before serving")
    ap.add_argument("--querier.search-external-endpoints", dest="search_external",
                    default=None,
                    help="comma-separated serverless search handler URLs")
    ap.add_argument("--distributor.kafka-brokers", dest="kafka_brokers", default=None,
                    help="Kafka broker host:port for the kafka receiver ('' = off)")
    ap.add_argument("--distributor.kafka-topic", dest="kafka_topic", default=None)
    ap.add_argument("--distributor.kafka-tenant", dest="kafka_tenant", default=None,
                    help="tenant kafka messages ingest into (required with multitenancy)")
    ap.add_argument("--ring.heartbeat-timeout", dest="ring_heartbeat_timeout",
                    type=float, default=None,
                    help="ring liveness window in seconds; lifecyclers "
                         "also prune peers past it (0 = default 60s)")
    ap.add_argument("--rpc.deadline", dest="rpc_deadline", type=float,
                    default=None,
                    help="per-RPC deadline for remote ingester clients")
    ap.add_argument("--querier.worker-concurrency", dest="worker_concurrency",
                    type=int, default=None,
                    help="standalone-querier worker threads pulling "
                         "frontend jobs")
    args = ap.parse_args(argv)
    base = (load_config_file(args.config_file, args.config_expand_env)
            if args.config_file else {})
    flag_vals = {
        "target": args.target,
        "http_port": args.port,
        "storage_path": args.storage,
        "overrides_path": args.overrides,
        "multitenancy": args.multitenancy,
        "kv_dir": args.kv_dir,
        "gossip_bind": args.gossip_bind,
        "gossip_seeds": args.gossip_seeds,
        "gossip_advertise": args.gossip_advertise,
        "advertise_addr": args.advertise,
        "instance_id": args.instance_id,
        "replication_factor": args.rf,
        "internal_token": args.internal_token,
        "frontend_addr": args.frontend_addr,
        "otlp_grpc_port": args.otlp_grpc_port,
        "opencensus_grpc_port": args.opencensus_grpc_port,
        "jaeger_grpc_port": args.jaeger_grpc_port,
        "jaeger_agent_port": args.jaeger_agent_port,
        "self_tracing_tenant": args.self_tracing_tenant,
        "compile_cache_dir": args.compile_cache_dir,
        "cost_ledger_path": args.cost_ledger_path,
        "chaos_rules": args.chaos_rules,
        "warmup_shapes": args.warmup_shapes,
        "search_external_endpoints": args.search_external,
        "kafka_brokers": args.kafka_brokers,
        "kafka_topic": args.kafka_topic,
        "kafka_tenant": args.kafka_tenant,
        "ring_heartbeat_timeout": args.ring_heartbeat_timeout,
        "rpc_deadline_s": args.rpc_deadline,
        "worker_concurrency": args.worker_concurrency,
    }
    base.update({k: v for k, v in flag_vals.items() if v is not None})
    cfg = AppConfig(**base)
    if not cfg.advertise_addr:
        cfg.advertise_addr = f"http://127.0.0.1:{cfg.http_port}"
    app = App(cfg)
    app.start()
    print(f"tempo-tpu target={cfg.target} listening on :{cfg.http_port}")
    try:
        app.serve_http()
    except KeyboardInterrupt:
        app.stop()


if __name__ == "__main__":
    main()
