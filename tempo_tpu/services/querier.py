"""Querier: fan trace-ID lookups and searches out to ingesters (ring
replication set) and the backend (TempoDB), combine partials.

Reference: modules/querier/querier.go -- FindTraceByID (:181-266),
forGivenIngesters (:269-293), SearchRecent (:295), SearchBlock (:401).
The ingester boundary is the same client registry the distributor uses.
"""

from __future__ import annotations

import contextvars

from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass

from ..db.search import SearchRequest, SearchResponse
from ..db.tempodb import TempoDB
from ..ring.ring import Ring
from ..wire.combine import combine_traces, sort_trace
from ..wire.model import Trace


@dataclass
class QuerierStats:
    traces_found: int = 0
    searches: int = 0
    metrics_queries: int = 0  # metrics_query_range jobs executed
    external_searches: int = 0  # shard jobs served by serverless endpoints
    external_failures: int = 0  # external legs that fell back to local


class _BreakerLeg:
    """Circuit-breaker proxy over one remote ingester client: every
    method call asks the breaker first (CircuitOpen when shedding --
    the caller's existing failed-leg tolerance absorbs it) and records
    its outcome after."""

    def __init__(self, inner, br):
        self._inner = inner
        self._br = br

    def __getattr__(self, name):
        fn = getattr(self._inner, name)
        if not callable(fn):
            return fn
        br = self._br

        def call(*args, **kwargs):
            from ..util.breaker import CircuitOpen

            if not br.allow():
                raise CircuitOpen("ingester leg breaker open")
            try:
                out = fn(*args, **kwargs)
            except Exception as e:
                # breaker food is TRANSIENT failures only (same filter
                # as the frontend's backend leg): a deterministic 400/
                # 429 PushError from a healthy ingester must not open
                # the leg for every other tenant
                from .frontend import _retryable

                if _retryable(e):
                    br.record(False)
                raise
            br.record(True)
            return out

        return call


class Querier:
    def __init__(self, db: TempoDB, ring: Ring | None, client_for, workers: int = 8,
                 external_endpoints: list[str] | None = None,
                 external_hedge_after_s: float = 4.0):
        """client_for(addr) -> object with ingester read methods
        (find_trace_by_id / search). external_endpoints: serverless
        search handlers (tempo_tpu.serverless HTTP mode); block-shard
        jobs POST there with hedged re-dispatch and fall back to local
        execution (querier.go:401-458 searchExternalEndpoints)."""
        self.db = db
        self.ring = ring
        self.client_for = client_for
        self.pool = ThreadPoolExecutor(max_workers=workers, thread_name_prefix="querier")
        self.stats = QuerierStats()
        self.external_endpoints = list(external_endpoints or [])
        self.external_hedge_after_s = external_hedge_after_s
        self._external_rr = 0
        # per-endpoint circuit breaker: N consecutive failures skip the
        # endpoint for a cooldown instead of paying the hedge window on
        # every shard (reference: hedged client + endpoint weighting)
        self._external_fails: dict[str, int] = {}
        self._external_skip_until: dict[str, float] = {}
        self.external_breaker_fails = 3
        self.external_breaker_cooldown_s = 30.0

    def _submit(self, fn, *args):
        """pool.submit carrying the caller's contextvars: kerneltel's
        ambient attribution (affinity dequeue placement, active
        self-trace) must follow a query's legs into the pool threads,
        or pooled staged-cache probes would all attribute to "none"."""
        ctx = contextvars.copy_context()
        return self.pool.submit(ctx.run, fn, *args)

    def _ingester_legs(self):
        """(addr, client) for every healthy ring instance. Remote
        (HTTP) legs come back wrapped in a per-addr circuit breaker:
        a leg that keeps failing is shed fast (degrading that leg's
        coverage, exactly like the existing failed-leg tolerance)
        instead of paying its timeout on every query, with half-open
        probes re-admitting it when it recovers. In-process clients
        cannot partition and stay bare."""
        if self.ring is None:
            return []
        from ..transport.client import HTTPIngesterClient

        out = []
        for d in self.ring.healthy_instances():
            try:
                c = self.client_for(d.addr)
            except KeyError:
                continue  # unresolvable addr degrades that leg, not the query
            if isinstance(c, HTTPIngesterClient):
                # type check, not addr check: the single binary registers
                # its in-process ingester under its http advertise addr
                from ..util.breaker import get_breaker

                c = _BreakerLeg(c, get_breaker(f"ingester:{d.addr}"))
            out.append((d.addr, c))
        return out

    def _ingester_clients(self):
        return [c for _, c in self._ingester_legs()]

    # ----------------------------------------------------------- trace by id
    def find_trace_by_id(self, tenant: str, trace_id: bytes,
                         time_start: int = 0, time_end: int = 0,
                         query_ingesters: bool = True,
                         query_backend: bool = True) -> Trace | None:
        """Both legs by default; the frontend's sharded pipeline sets
        query_backend=False for the ingester-leg job (backend blocks go
        through its own find_blocks shard jobs)."""
        if query_ingesters and self.ring is not None and self.ring.rf > 1:
            return self._quorum_find(tenant, trace_id, time_start, time_end,
                                     query_backend)
        futures = []
        if query_ingesters:
            for c in self._ingester_clients():
                futures.append(self._submit(c.find_trace_by_id, tenant, trace_id))
        if query_backend:
            futures.append(self._submit(
                self.db.find_trace_by_id, tenant, trace_id, time_start, time_end
            ))
        partials = []
        for f in futures:
            try:
                t = f.result()
            except Exception:
                continue  # tolerate failed legs like TolerateFailedBlocks
            if t is not None:
                partials.append(t)
        if not partials:
            return None
        self.stats.traces_found += 1
        return sort_trace(combine_traces(partials)) if len(partials) > 1 else partials[0]

    @staticmethod
    def _leg_snapshot(c, tenant: str, trace_id: bytes):
        """One leg of a quorum read: ("snap", [(digest, seg)]) from a
        snapshot-capable replica, ("trace", Trace|None) from a
        pre-upgrade ingester that only speaks /internal/find."""
        from ..transport.client import TransportError

        try:
            return "snap", c.trace_snapshot(tenant, trace_id)
        except AttributeError:
            pass  # in-process client without the snapshot API
        except TransportError as e:
            if e.status != 404:
                raise  # real failure: the leg did NOT answer
        return "trace", c.find_trace_by_id(tenant, trace_id)

    def _quorum_find(self, tenant: str, trace_id: bytes, time_start: int,
                     time_end: int, query_backend: bool) -> Trace | None:
        """RF>1 live read: fan snapshots to every healthy leg, dedupe by
        (trace id, segment digest), and require R answers from the
        OWNING replica set -- the same quorum arithmetic the write path
        used, so a successful read always intersects an acked write and
        one dead ingester is invisible to readers. Non-replica legs are
        read too (membership churn strands segments off-set) but only
        replicas count toward R."""
        from ..fleet.quorum import (ReadQuorumError, merge_snapshots,
                                    read_quorum_need)
        from ..util.hashing import ring_token
        from ..wire.segment import segment_to_trace

        healthy = self.ring.healthy_instances()
        rs = self.ring.get(ring_token(tenant, trace_id), instances=healthy)
        replica_addrs = {d.addr for d in rs.instances}
        futures = {self._submit(self._leg_snapshot, c, tenant, trace_id): addr
                   for addr, c in self._ingester_legs()}
        backend_fut = (self._submit(self.db.find_trace_by_id, tenant,
                                    trace_id, time_start, time_end)
                       if query_backend else None)
        snapshots, partials = [], []
        replica_ok = 0
        for f, addr in futures.items():
            try:
                kind, val = f.result()
            except Exception:
                continue  # failed leg: absorbed by the quorum check
            if addr in replica_addrs:
                replica_ok += 1  # an empty snapshot is still an answer
            if kind == "snap":
                snapshots.append(val)
            elif val is not None:
                partials.append(val)
        need = read_quorum_need(len(rs.instances), rs.max_errors)
        if rs.instances and replica_ok < need:
            raise ReadQuorumError(
                f"read quorum not met for {trace_id.hex()}: "
                f"{replica_ok}/{need} replicas answered")
        partials.extend(segment_to_trace(s) for s in merge_snapshots(snapshots))
        if backend_fut is not None:
            try:
                t = backend_fut.result()
                if t is not None:
                    partials.append(t)
            except Exception:
                pass  # backend leg tolerance unchanged
        if not partials:
            return None
        self.stats.traces_found += 1
        return sort_trace(combine_traces(partials)) if len(partials) > 1 else partials[0]

    # ---------------------------------------------------------------- search
    def search_recent(self, tenant: str, req: SearchRequest) -> SearchResponse:
        """Recent (unflushed) data: all ingesters (querier.go:295)."""
        resp = SearchResponse()
        futs = [self._submit(c.search, tenant, req) for c in self._ingester_clients()]
        for f in futs:
            try:
                resp.merge(f.result(), req.limit or 20)
            except Exception:
                continue
        return resp

    def search_block_shard(self, tenant: str, meta, req: SearchRequest, groups) -> SearchResponse:
        """One backend search job: a row-group range of one block
        (the reference's SearchBlock page-shard job, querier.go:401-458).
        With external endpoints configured, the shard ships to a
        serverless handler (hedged); local execution is the fallback."""
        self.stats.searches += 1
        if self.external_endpoints:
            resp = self._search_external(tenant, meta, req, groups)
            if resp is not None:
                return resp
        return self.db.search_block_shard(tenant, meta, req, groups)

    def search_block_shard_multi(self, items: list) -> list:
        """Many shard jobs at once (the frontend's batch-aware dequeue):
        local execution goes through the coalescing db API; external
        serverless dispatch stays per-job (each leg hedges on its own)."""
        if self.external_endpoints:
            # search_block_shard counts its own stats per job
            return [self.search_block_shard(*it) for it in items]
        self.stats.searches += len(items)
        return self.db.search_block_shard_multi(items)

    def _external_candidates(self) -> list[str]:
        """Endpoints not in breaker cooldown (all of them when every
        breaker is open -- a dead fleet still gets probed)."""
        import time

        now = time.monotonic()
        ok = [e for e in self.external_endpoints
              if self._external_skip_until.get(e, 0.0) <= now]
        return ok or self.external_endpoints

    def _note_external(self, endpoint: str, ok: bool) -> None:
        import time

        if ok:
            self._external_fails[endpoint] = 0
            return
        n = self._external_fails.get(endpoint, 0) + 1
        self._external_fails[endpoint] = n
        if n >= self.external_breaker_fails:
            self._external_skip_until[endpoint] = (
                time.monotonic() + self.external_breaker_cooldown_s)

    def _search_external(self, tenant: str, meta, req: SearchRequest,
                         groups) -> SearchResponse | None:
        """POST the shard job to a serverless endpoint; if no response
        within external_hedge_after_s, hedge to the NEXT endpoint and
        take the first success. None -> caller runs locally."""
        from ..db.search import request_to_dict

        event = {
            "backend": self.db.cfg.backend,
            "tenant": tenant,
            "block_id": meta.block_id,
            "groups": ([int(groups[0]), int(groups[-1]) + 1]
                       if groups is not None and len(groups) else None),
            "search": request_to_dict(req),
        }
        eps = self._external_candidates()
        first = eps[self._external_rr % len(eps)]
        self._external_rr += 1
        futs = {self._submit(self._post_external, first, event): first}
        try:
            out = next(iter(futs)).result(timeout=self.external_hedge_after_s)
            self._note_external(first, out is not None)
            if out is not None:
                self.stats.external_searches += 1
                return out
        except TimeoutError:
            if len(eps) > 1:  # hedge on a different endpoint
                second = eps[self._external_rr % len(eps)]
                self._external_rr += 1
                futs[self._submit(self._post_external, second, event)] = second
            # await ALL legs up to one more hedge window: a slow first
            # leg failing must not discard a still-pending hedge winner
            from concurrent.futures import as_completed

            try:
                for f in as_completed(futs, timeout=self.external_hedge_after_s):
                    out = f.exception() is None and f.result()
                    self._note_external(futs[f], bool(out))
                    if out:
                        self.stats.external_searches += 1
                        return out
            except TimeoutError:
                for f, ep in futs.items():
                    if not f.done():
                        self._note_external(ep, False)
        except Exception:
            self._note_external(first, False)
        self.stats.external_failures += 1
        return None

    def _post_external(self, endpoint: str, event: dict) -> SearchResponse | None:
        import json
        import urllib.request

        from ..chaos import plane as chaos_plane
        from ..db.search import response_from_dict

        if chaos_plane.tap("rpc.external", key=endpoint) is chaos_plane.DROP:
            return None  # endpoint black-holed: hedge/failover takes over
        try:
            r = urllib.request.urlopen(
                urllib.request.Request(
                    endpoint, data=json.dumps(event).encode(),
                    headers={"Content-Type": "application/json"},
                ),
                timeout=max(self.external_hedge_after_s * 4, 10.0),
            )
            return response_from_dict(json.loads(r.read()))
        except Exception:
            return None

    def search_blocks(self, tenant: str, metas: list, req: SearchRequest) -> SearchResponse:
        """One block-BATCH job: many whole blocks searched as one fused
        device program (db/search.search_blocks_fused) -- the job shape
        that amortizes a device sync across the batch, where the
        reference dispatches one 10-MiB page-shard job per querier
        round trip."""
        self.stats.searches += 1
        return self.db.search_blocks(tenant, metas, req)

    def search_blocks_multi(self, items: list) -> list:
        """Many block-batch jobs at once: eligible single-block jobs
        coalesce into fused multi-query launches (db/batchexec)."""
        self.stats.searches += len(items)
        return self.db.search_blocks_multi(items)

    def find_in_blocks_multi(self, items: list) -> list:
        """Many explicit-block lookups at once: jobs sharing a candidate
        partition share one batched bisection (db/batchexec)."""
        return self.db.find_in_blocks_multi(items)

    def metrics_query_range(self, tenant: str, req):
        """One metrics time-shard job: a step-aligned sub-range of the
        query_range axis, executed over the backend blocklist
        (db/metrics_exec) MERGED with every ingester's live-head leg
        (exact host-twin fold over live/cut/flushing traces) -- so
        recent unflushed spans are visible to TraceQL metrics, closing
        the blocks-only gap. Time-shard jobs cover disjoint sub-ranges,
        so the per-shard ingester legs never double-count. Failed legs
        degrade coverage (the search_recent tolerance), never the
        query."""
        from ..util.kerneltel import TEL

        self.stats.metrics_queries += 1
        futs = []
        for c in self._ingester_clients():
            fn = getattr(c, "metrics_query_range", None)
            if fn is not None:  # pre-upgrade remote ingesters: skip
                futs.append(self._submit(fn, tenant, req))
        resp = self.db.metrics_query_range(tenant, req)
        for f in futs:
            try:
                part = f.result()
            except Exception:
                TEL.record_routing("metrics_live", "ingester", "leg_failed")
                continue
            if part is not None and part.series:
                TEL.record_routing("metrics_live", "ingester", "merged")
                resp.merge(part)
        return resp

    def find_in_blocks(self, tenant: str, trace_id: bytes, metas: list):
        """One frontend ID-shard job: lookup restricted to a partition
        of the candidate blocks (tracebyidsharding.go analog)."""
        return self.db.find_in_blocks(tenant, trace_id, metas)

    def search_tags(self, tenant: str, max_bytes: int = 0) -> list[str]:
        return self.db.search_tags(tenant, max_bytes)

    def search_tag_values(self, tenant: str, tag: str, max_bytes: int = 0) -> list[str]:
        return self.db.search_tag_values(tenant, tag, max_bytes)
