"""Querier: fan trace-ID lookups and searches out to ingesters (ring
replication set) and the backend (TempoDB), combine partials.

Reference: modules/querier/querier.go -- FindTraceByID (:181-266),
forGivenIngesters (:269-293), SearchRecent (:295), SearchBlock (:401).
The ingester boundary is the same client registry the distributor uses.
"""

from __future__ import annotations

from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass

from ..db.search import SearchRequest, SearchResponse
from ..db.tempodb import TempoDB
from ..ring.ring import Ring
from ..wire.combine import combine_traces, sort_trace
from ..wire.model import Trace


@dataclass
class QuerierStats:
    traces_found: int = 0
    searches: int = 0


class Querier:
    def __init__(self, db: TempoDB, ring: Ring | None, client_for, workers: int = 8):
        """client_for(addr) -> object with ingester read methods
        (find_trace_by_id / search)."""
        self.db = db
        self.ring = ring
        self.client_for = client_for
        self.pool = ThreadPoolExecutor(max_workers=workers, thread_name_prefix="querier")
        self.stats = QuerierStats()

    def _ingester_clients(self):
        if self.ring is None:
            return []
        out = []
        for d in self.ring.healthy_instances():
            try:
                out.append(self.client_for(d.addr))
            except KeyError:
                continue  # unresolvable addr degrades that leg, not the query
        return out

    # ----------------------------------------------------------- trace by id
    def find_trace_by_id(self, tenant: str, trace_id: bytes,
                         time_start: int = 0, time_end: int = 0,
                         query_ingesters: bool = True,
                         query_backend: bool = True) -> Trace | None:
        """Both legs by default; the frontend's sharded pipeline sets
        query_backend=False for the ingester-leg job (backend blocks go
        through its own find_blocks shard jobs)."""
        futures = []
        if query_ingesters:
            for c in self._ingester_clients():
                futures.append(self.pool.submit(c.find_trace_by_id, tenant, trace_id))
        if query_backend:
            futures.append(self.pool.submit(
                self.db.find_trace_by_id, tenant, trace_id, time_start, time_end
            ))
        partials = []
        for f in futures:
            try:
                t = f.result()
            except Exception:
                continue  # tolerate failed legs like TolerateFailedBlocks
            if t is not None:
                partials.append(t)
        if not partials:
            return None
        self.stats.traces_found += 1
        return sort_trace(combine_traces(partials)) if len(partials) > 1 else partials[0]

    # ---------------------------------------------------------------- search
    def search_recent(self, tenant: str, req: SearchRequest) -> SearchResponse:
        """Recent (unflushed) data: all ingesters (querier.go:295)."""
        resp = SearchResponse()
        futs = [self.pool.submit(c.search, tenant, req) for c in self._ingester_clients()]
        for f in futs:
            try:
                resp.merge(f.result(), req.limit or 20)
            except Exception:
                continue
        return resp

    def search_block_shard(self, tenant: str, meta, req: SearchRequest, groups) -> SearchResponse:
        """One backend search job: a row-group range of one block
        (the reference's SearchBlock page-shard job, querier.go:401-458)."""
        self.stats.searches += 1
        return self.db.search_block_shard(tenant, meta, req, groups)

    def search_blocks(self, tenant: str, metas: list, req: SearchRequest) -> SearchResponse:
        """One block-BATCH job: many whole blocks searched as one fused
        device program (db/search.search_blocks_fused) -- the job shape
        that amortizes a device sync across the batch, where the
        reference dispatches one 10-MiB page-shard job per querier
        round trip."""
        self.stats.searches += 1
        return self.db.search_blocks(tenant, metas, req)

    def find_in_blocks(self, tenant: str, trace_id: bytes, metas: list):
        """One frontend ID-shard job: lookup restricted to a partition
        of the candidate blocks (tracebyidsharding.go analog)."""
        return self.db.find_in_blocks(tenant, trace_id, metas)

    def search_tags(self, tenant: str, max_bytes: int = 0) -> list[str]:
        return self.db.search_tags(tenant, max_bytes)

    def search_tag_values(self, tenant: str, tag: str, max_bytes: int = 0) -> list[str]:
        return self.db.search_tag_values(tenant, tag, max_bytes)
