"""OpenCensus gRPC trace receiver (the pre-OTel agent protocol).

Reference: the receiver shim registers an OpenCensus receiver factory
(modules/distributor/receiver/shim.go:98). The OC agent protocol is a
BIDI STREAM: `opencensus.proto.agent.trace.v1.TraceService/Export`
carries a stream of ExportTraceServiceRequest messages where the first
message must carry the Node and Resource, and later messages that omit
them inherit the stream's last-seen values (sticky per-stream state) --
that statefulness is the protocol's defining quirk and the reason it
needs its own handler rather than the OTLP unary path
(services/otlp_grpc.py).

Same deployment shape as the OTLP receiver: a generic grpc handler (no
generated stubs; wire decode in wire/oc_pb.py), tenancy from the
x-scope-orgid stream metadata, push-limit errors mapped to canonical
gRPC codes. One empty ExportTraceServiceResponse is yielded per request
message as an ack.
"""

from __future__ import annotations

from concurrent import futures

from ..wire import oc_pb

_SERVICE = "opencensus.proto.agent.trace.v1.TraceService"
_METHOD = "Export"


class OpenCensusReceiver:
    def __init__(self, app, max_workers: int = 8):
        self.app = app
        self._max_workers = max_workers
        self._server = None
        self.port = 0
        self.requests = 0
        self.spans = 0
        self.failures = 0

    def start(self, port: int = 55678, host: str = "127.0.0.1") -> int:
        """55678 is the OC agent's conventional port."""
        import grpc

        app = self.app
        recv = self

        def export(request_iter, context):
            md = {k.lower(): v for k, v in (context.invocation_metadata() or [])}
            try:
                tenant = app.tenant_of(
                    {"X-Scope-OrgID": md.get("x-scope-orgid", "")})
            except Exception as e:
                recv.failures += 1
                context.abort(grpc.StatusCode.UNAUTHENTICATED,
                              f"{type(e).__name__}: {e}")
                return
            node: dict | None = None  # sticky per-stream identity
            resource: dict | None = None
            for request in request_iter:
                recv.requests += 1
                try:
                    n, r, spans = oc_pb.decode_export_request(request)
                    if n is not None:
                        node = n
                    if r is not None:
                        resource = r
                    if spans:
                        tr = oc_pb.to_trace(node, resource, spans)
                        app.distributor.push(tenant, tr.resource_spans)
                        # counted only after a successful push (the
                        # kafka receiver's convention): rejected batches
                        # show up in failures, not spans_total
                        recv.spans += sum(
                            len(ss.spans) for rs in tr.resource_spans
                            for ss in rs.scope_spans)
                    yield b""
                except Exception as e:
                    recv.failures += 1
                    from .otlp_grpc import push_grpc_code

                    # AT-LEAST-ONCE on errors: aborting mid-stream (incl.
                    # transient 429s) makes the agent reconnect and resend
                    # the whole stream, re-ingesting batches acked before
                    # the error; duplicates collapse at query-time span
                    # merge. The alternative -- ack-and-drop -- would lose
                    # spans silently with no backpressure signal, since
                    # the OC export stream has no per-message status.
                    context.abort(push_grpc_code(e, grpc),
                                  f"{type(e).__name__}: {e}")
                    return

        handler = grpc.method_handlers_generic_handler(
            _SERVICE,
            {
                _METHOD: grpc.stream_stream_rpc_method_handler(
                    export,
                    request_deserializer=None,  # raw bytes in
                    response_serializer=None,  # raw bytes out
                )
            },
        )
        self._server = grpc.server(
            futures.ThreadPoolExecutor(max_workers=self._max_workers,
                                       thread_name_prefix="oc-grpc"),
        )
        self._server.add_generic_rpc_handlers((handler,))
        self.port = self._server.add_insecure_port(f"{host}:{port}")
        self._server.start()
        return self.port

    def stop(self) -> None:
        if self._server is not None:
            self._server.stop(grace=0.5)
            self._server = None
