"""Jaeger AGENT UDP ingest: the client-library emitBatch ports.

Reference: the receiver shim's jaeger factory also opens the agent UDP
sockets (modules/distributor/receiver/shim.go; jaeger convention 6831 =
thrift compact, 6832 = thrift binary). Jaeger client SDKs fire
agent.thrift `emitBatch` datagrams at these ports; one datagram is one
complete message (no framing). Decode (wire/jaeger_thrift, compact and
strict-binary auto-detect) feeds the same distributor push path as the
collector endpoints. UDP is fire-and-forget: malformed or over-limit
datagrams increment counters and drop -- there is nothing to answer.
"""

from __future__ import annotations

import socket
import threading

_MAX_DGRAM = 65536


class JaegerAgentReceiver:
    def __init__(self, app):
        self.app = app
        self._socks: list[socket.socket] = []
        self._threads: list[threading.Thread] = []
        self.compact_port = 0
        self.binary_port = 0
        self.packets = 0
        self.spans = 0
        self.failures = 0
        self._stop = threading.Event()

    def start(self, compact_port: int = 6831, binary_port: int = 6832,
              host: str = "127.0.0.1") -> tuple[int, int]:
        ports = []
        for want in (compact_port, binary_port):
            s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
            s.bind((host, max(0, want)))  # -1/-0 -> ephemeral
            s.settimeout(0.5)  # lets the serve loop observe _stop
            self._socks.append(s)
            ports.append(s.getsockname()[1])
            t = threading.Thread(target=self._serve, args=(s,),
                                 name=f"jaeger-agent-{ports[-1]}", daemon=True)
            self._threads.append(t)
            t.start()
        self.compact_port, self.binary_port = ports
        return self.compact_port, self.binary_port

    def _serve(self, sock: socket.socket) -> None:
        from ..wire.jaeger_thrift import decode_agent_message

        app = self.app
        while not self._stop.is_set():
            try:
                data, _ = sock.recvfrom(_MAX_DGRAM)
            except socket.timeout:
                continue
            except OSError:
                return  # socket closed
            self.packets += 1
            try:
                rs = decode_agent_message(data)
                if rs is None:
                    continue  # other agent methods (emitZipkinBatch)
                tenant = app.tenant_of({})  # UDP carries no tenant header
                app.distributor.push(tenant, [rs])
                self.spans += sum(len(ss.spans) for ss in rs.scope_spans)
            except Exception:
                self.failures += 1  # fire-and-forget: count and drop

    def stop(self) -> None:
        self._stop.set()
        for s in self._socks:
            try:
                s.close()
            except OSError:
                pass
        for t in self._threads:
            t.join(timeout=2.0)
        self._socks = []
        self._threads = []
