"""Compactor service: ring-sharded ownership of compaction + retention
loops over TempoDB.

Reference: modules/compactor/compactor.go -- Owns (:187, fnv32 of the
job hash vs ring tokens), wrapping tempodb's compaction/retention
drivers (tempodb/compactor.go:66-132, retention.go:14-90).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field

from ..db.tempodb import TempoDB
from ..ring.ring import Ring


@dataclass
class CompactorStats:
    runs: int = 0
    blocks_compacted: int = 0
    blocks_retained: int = 0
    errors: list = field(default_factory=list)


class Compactor:
    def __init__(self, db: TempoDB, ring: Ring | None = None, instance_id: str = "",
                 cycle_s: float = 30.0):
        self.db = db
        self.ring = ring
        self.instance_id = instance_id
        self.cycle_s = cycle_s
        self.stats = CompactorStats()
        from ..util.metrics import Histogram

        self.compaction_duration = Histogram(
            "tempo_compactor_cycle_duration_seconds",
            buckets=(0.1, 0.5, 1.0, 5.0, 15.0, 60.0, 300.0),
        )
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        # install ring ownership into the db's compaction driver
        if ring is not None and instance_id:
            self.db.owns_job = lambda h: ring.owns(instance_id, h)

    def run_once(self) -> None:
        from ..db.compact_pipeline import resolve_concurrency
        from ..util.metrics import timed

        self.stats.runs += 1
        if resolve_concurrency(self.db.cfg.compaction) > 1:
            self._run_once_pipelined()
            return
        for tenant in self.db.tenants():
            try:
                with timed(self.compaction_duration):
                    results = self.db.compact_once(tenant)
                self.stats.blocks_compacted += sum(len(r.compacted_ids) for r in results)
                ret = self.db.retention_once(tenant)
                self.stats.blocks_retained += len(ret.deleted) if ret else 0
            except Exception as e:
                self.stats.errors.append(e)

    def _run_once_pipelined(self) -> None:
        """Concurrent sweep: every tenant's owned jobs run through the
        compaction pipeline (TEMPO_COMPACT_CONCURRENCY workers, host-RAM
        admission gate, per-tenant round-robin); retention stays
        per-tenant sequential -- it's marker/delete IO, not a hot path,
        and ring ownership filtering is identical either way."""
        from ..util.metrics import timed

        try:
            with timed(self.compaction_duration):
                outcomes = self.db.compact_tenants()
            for oc in outcomes:
                if oc.error is not None:
                    self.stats.errors.append(oc.error)
                else:
                    self.stats.blocks_compacted += len(oc.result.compacted_ids)
        except Exception as e:
            self.stats.errors.append(e)
        for tenant in self.db.tenants():
            try:
                ret = self.db.retention_once(tenant)
                self.stats.blocks_retained += len(ret.deleted) if ret else 0
            except Exception as e:
                self.stats.errors.append(e)

    def start(self) -> None:
        def loop():
            while not self._stop.wait(self.cycle_s):
                self.run_once()

        self._thread = threading.Thread(target=loop, daemon=True, name="compactor")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
