"""S3-compatible object-store backend over plain HTTP with AWS SigV4.

The role of the reference's minio-based backend (tempodb/backend/s3),
implemented against the public S3 REST API directly (PUT/GET/DELETE
object, ranged GET, ListObjectsV2) so it needs no SDK: works with AWS
S3, MinIO, and GCS's S3-interoperability endpoint (the `gcs` backend
selection routes here with storage.googleapis.com + HMAC keys).
Path-style addressing for MinIO compatibility. SigV4 is implemented
from the published algorithm (hmac/sha256 canonical requests).
"""

from __future__ import annotations

import datetime
import hashlib
import hmac
import urllib.error
import urllib.parse
import urllib.request
import xml.etree.ElementTree as ET

from .base import BackendError, DoesNotExist, RawBackend, block_object_path

_EMPTY_SHA = hashlib.sha256(b"").hexdigest()


class SigV4:
    def __init__(self, access_key: str, secret_key: str, region: str, service: str = "s3"):
        self.access_key = access_key
        self.secret_key = secret_key
        self.region = region
        self.service = service

    def signing_key(self, datestamp: str) -> bytes:
        """kSigning = HMAC-chain over date/region/service/aws4_request
        (verified against the AWS-documented derived-key vector in
        tests/test_backend_auth.py)."""

        def _hmac(key: bytes, msg: str) -> bytes:
            return hmac.new(key, msg.encode(), hashlib.sha256).digest()

        k = _hmac(("AWS4" + self.secret_key).encode(), datestamp)
        k = _hmac(k, self.region)
        k = _hmac(k, self.service)
        return _hmac(k, "aws4_request")

    def sign(self, method: str, url: str, payload_sha: str, now=None,
             extra_headers: dict[str, str] | None = None) -> dict[str, str]:
        """extra_headers: additional x-amz-* request headers to SIGN and
        send (e.g. x-amz-copy-source for server-side CopyObject); names
        must be lowercase."""
        u = urllib.parse.urlsplit(url)
        now = now or datetime.datetime.now(datetime.timezone.utc)
        amz_date = now.strftime("%Y%m%dT%H%M%SZ")
        datestamp = now.strftime("%Y%m%d")
        # sort by encoded NAME then value (spec order) -- sorting whole
        # "k=v" strings would misorder names that prefix each other
        # ('%' < '=' puts "a%20x=" before "a=1")
        canonical_query = "&".join(
            f"{k}={v}" for k, v in sorted(
                (urllib.parse.quote(k, safe=""), urllib.parse.quote(v, safe=""))
                for k, v in urllib.parse.parse_qsl(u.query, keep_blank_values=True)
            )
        )
        headers = {"host": u.netloc, "x-amz-content-sha256": payload_sha, "x-amz-date": amz_date}
        headers.update(extra_headers or {})
        signed_headers = ";".join(sorted(headers))
        canonical_headers = "".join(f"{k}:{headers[k]}\n" for k in sorted(headers))
        # u.path is already percent-encoded by the caller (_url); re-quoting
        # would double-encode and break the signature for keys with spaces etc.
        canonical = "\n".join(
            [method, u.path or "/", canonical_query,
             canonical_headers, signed_headers, payload_sha]
        )
        scope = f"{datestamp}/{self.region}/{self.service}/aws4_request"
        to_sign = "\n".join(
            ["AWS4-HMAC-SHA256", amz_date, scope,
             hashlib.sha256(canonical.encode()).hexdigest()]
        )

        k = self.signing_key(datestamp)
        sig = hmac.new(k, to_sign.encode(), hashlib.sha256).hexdigest()
        return {
            "x-amz-content-sha256": payload_sha,
            "x-amz-date": amz_date,
            **(extra_headers or {}),
            "Authorization": (
                f"AWS4-HMAC-SHA256 Credential={self.access_key}/{scope}, "
                f"SignedHeaders={signed_headers}, Signature={sig}"
            ),
        }


class S3Backend(RawBackend):
    def __init__(self, endpoint: str, bucket: str, access_key: str = "",
                 secret_key: str = "", region: str = "us-east-1", prefix: str = "",
                 timeout: float = 30.0):
        self.endpoint = endpoint.rstrip("/")
        self.bucket = bucket
        self.prefix = prefix.strip("/")
        self.signer = SigV4(access_key, secret_key, region) if access_key else None
        self.timeout = timeout

    # ------------------------------------------------------------- http
    def _key(self, path: str) -> str:
        return f"{self.prefix}/{path}" if self.prefix else path

    def _url(self, key: str = "", query: str = "") -> str:
        base = f"{self.endpoint}/{self.bucket}"
        if key:
            base += "/" + urllib.parse.quote(key)
        if query:
            base += "?" + query
        return base

    def _request(self, method: str, url: str, data: bytes | None = None,
                 range_hdr: str | None = None,
                 extra_headers: dict[str, str] | None = None) -> tuple[int, bytes]:
        payload_sha = hashlib.sha256(data).hexdigest() if data else _EMPTY_SHA
        headers = dict(extra_headers or {})
        if self.signer:
            headers.update(self.signer.sign(method, url, payload_sha,
                                            extra_headers=extra_headers))
        if range_hdr:
            headers["Range"] = range_hdr
        req = urllib.request.Request(url, data=data, headers=headers, method=method)
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as r:
                return r.status, r.read()
        except urllib.error.HTTPError as e:
            if e.code == 404:
                raise DoesNotExist(url)
            raise BackendError(f"s3 {method} {url}: {e.code} {e.read()[:200]!r}")
        except urllib.error.URLError as e:
            raise BackendError(f"s3 {method} {url}: {e}")

    # ------------------------------------------------------------ write
    def write(self, tenant: str, block_id: str, name: str, data: bytes) -> None:
        self._request("PUT", self._url(self._key(block_object_path(tenant, block_id, name))), data)

    def write_tenant_object(self, tenant: str, name: str, data: bytes) -> None:
        self._request("PUT", self._url(self._key(f"{tenant}/{name}")), data)

    def copy_object(self, tenant: str, src_block_id: str, name: str,
                    dst_block_id: str) -> int:
        """True server-side CopyObject: PUT with a signed
        x-amz-copy-source header, zero payload -- the part bytes never
        transit the client. Returns -1 (size unknown without a HEAD;
        no caller needs it). S3 reports copy errors either as non-2xx
        or as a 200 carrying an <Error> document -- both raise."""
        src_key = self._key(block_object_path(tenant, src_block_id, name))
        dst_url = self._url(self._key(block_object_path(tenant, dst_block_id, name)))
        src_hdr = urllib.parse.quote(f"/{self.bucket}/{src_key}")
        status, body = self._request(
            "PUT", dst_url, extra_headers={"x-amz-copy-source": src_hdr})
        if b"<Error>" in body:
            raise BackendError(f"s3 copy {src_key}: {body[:200]!r}")
        return -1

    # ------------------------------------------------------------- read
    def read(self, tenant: str, block_id: str, name: str) -> bytes:
        return self._request("GET", self._url(self._key(block_object_path(tenant, block_id, name))))[1]

    def read_range(self, tenant: str, block_id: str, name: str, offset: int, length: int) -> bytes:
        _, body = self._request(
            "GET",
            self._url(self._key(block_object_path(tenant, block_id, name))),
            range_hdr=f"bytes={offset}-{offset + length - 1}",
        )
        return body

    def read_tenant_object(self, tenant: str, name: str) -> bytes:
        return self._request("GET", self._url(self._key(f"{tenant}/{name}")))[1]

    # ------------------------------------------------------------- list
    def _list_prefixes(self, prefix: str) -> list[str]:
        """ListObjectsV2 common prefixes directly under `prefix`."""
        out = []
        token = ""
        while True:
            q = {
                "list-type": "2",
                "delimiter": "/",
                "prefix": prefix,
            }
            if token:
                q["continuation-token"] = token
            query = urllib.parse.urlencode(sorted(q.items()))
            _, body = self._request("GET", self._url(query=query))
            root = ET.fromstring(body)
            ns = root.tag.split("}")[0] + "}" if root.tag.startswith("{") else ""
            for cp in root.findall(f"{ns}CommonPrefixes/{ns}Prefix"):
                p = cp.text or ""
                p = p[len(prefix):].strip("/")
                if p:
                    out.append(p)
            trunc = root.findtext(f"{ns}IsTruncated") == "true"
            token = root.findtext(f"{ns}NextContinuationToken") or ""
            if not trunc or not token:
                return out

    def tenants(self) -> list[str]:
        return self._list_prefixes(f"{self.prefix}/" if self.prefix else "")

    def blocks(self, tenant: str) -> list[str]:
        return self._list_prefixes(self._key(f"{tenant}/") )

    # ----------------------------------------------------------- delete
    def _delete_object(self, tenant: str, block_id: str, name: str) -> None:
        try:
            self._request("DELETE", self._url(self._key(block_object_path(tenant, block_id, name))))
        except DoesNotExist:
            pass

    def delete_block(self, tenant: str, block_id: str) -> None:
        # enumerate the block's objects then delete each
        prefix = self._key(f"{tenant}/{block_id}/")
        token = ""
        while True:
            q = {"list-type": "2", "prefix": prefix}
            if token:
                q["continuation-token"] = token
            query = urllib.parse.urlencode(sorted(q.items()))
            _, body = self._request("GET", self._url(query=query))
            root = ET.fromstring(body)
            ns = root.tag.split("}")[0] + "}" if root.tag.startswith("{") else ""
            keys = [k.text for k in root.findall(f"{ns}Contents/{ns}Key") if k.text]
            for key in keys:
                try:
                    self._request("DELETE", self._url(key))
                except DoesNotExist:
                    pass
            trunc = root.findtext(f"{ns}IsTruncated") == "true"
            token = root.findtext(f"{ns}NextContinuationToken") or ""
            if not trunc or not token:
                return

    def delete_tenant_object(self, tenant: str, name: str) -> None:
        try:
            self._request("DELETE", self._url(self._key(f"{tenant}/{name}")))
        except DoesNotExist:
            pass
