"""Azure Blob Storage backend over the public REST API with SharedKey
auth (no SDK) -- the role of the reference's azure backend
(tempodb/backend/azure). Works against Azure and Azurite.

Operations used: Put Blob (BlockBlob), Get Blob (with Range), Delete
Blob, List Blobs (flat + delimiter). SharedKey signing follows the
published authorization scheme (HMAC-SHA256 over the canonicalized
string-to-sign).
"""

from __future__ import annotations

import base64
import datetime
import hashlib
import hmac
import urllib.error
import urllib.parse
import urllib.request
import xml.etree.ElementTree as ET

from .base import BackendError, DoesNotExist, RawBackend, block_object_path

_API_VERSION = "2021-08-06"


class AzureBackend(RawBackend):
    def __init__(self, account: str, container: str, key: str = "",
                 endpoint: str = "", prefix: str = "", timeout: float = 30.0):
        """endpoint default: https://<account>.blob.core.windows.net; for
        Azurite pass e.g. http://127.0.0.1:10000/<account>."""
        self.account = account
        self.container = container
        self.key = base64.b64decode(key) if key else b""
        self.endpoint = (endpoint or f"https://{account}.blob.core.windows.net").rstrip("/")
        self.prefix = prefix.strip("/")
        self.timeout = timeout

    # ---------------------------------------------------------------- auth
    def _sign(self, method: str, url: str, headers: dict, content_len: str,
              content_type: str) -> str:
        u = urllib.parse.urlsplit(url)
        canon_headers = "".join(
            f"{k}:{headers[k]}\n" for k in sorted(h for h in headers if h.startswith("x-ms-"))
        )
        # canonicalized resource: /account/<path>; an azurite-style endpoint
        # already carries the account as the first path segment
        if u.path.startswith(f"/{self.account}/"):
            canon_res = u.path
        else:
            canon_res = f"/{self.account}{u.path}"
        for k, v in sorted(urllib.parse.parse_qsl(u.query)):
            canon_res += f"\n{k}:{v}"
        # string-to-sign, 2015-04-05+ scheme: VERB, Content-Encoding,
        # Content-Language, Content-Length (empty when 0), Content-MD5,
        # Content-Type, Date, If-*, Range
        to_sign = "\n".join([
            method, "", "", content_len, "", content_type, "", "", "", "", "",
            headers.get("x-ms-range", ""),
        ]) + "\n" + canon_headers + canon_res
        sig = base64.b64encode(hmac.new(self.key, to_sign.encode(), hashlib.sha256).digest()).decode()
        return f"SharedKey {self.account}:{sig}"

    def _request(self, method: str, url: str, data: bytes | None = None,
                 extra: dict | None = None) -> tuple[int, bytes]:
        headers = {
            "x-ms-date": datetime.datetime.now(datetime.timezone.utc).strftime(
                "%a, %d %b %Y %H:%M:%S GMT"
            ),
            "x-ms-version": _API_VERSION,
        }
        content_type = ""
        if data is not None:
            # pin the type urllib would otherwise inject unsigned
            content_type = "application/octet-stream"
            headers["Content-Type"] = content_type
        if extra:
            headers.update(extra)
        content_len = str(len(data)) if data else ""
        if self.key:
            headers["Authorization"] = self._sign(method, url, headers, content_len, content_type)
        req = urllib.request.Request(url, data=data, headers=headers, method=method)
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as r:
                return r.status, r.read()
        except urllib.error.HTTPError as e:
            if e.code == 404:
                raise DoesNotExist(url)
            raise BackendError(f"azure {method} {url}: {e.code} {e.read()[:200]!r}")
        except urllib.error.URLError as e:
            raise BackendError(f"azure {method} {url}: {e}")

    # ------------------------------------------------------------- helpers
    def _key_path(self, path: str) -> str:
        return f"{self.prefix}/{path}" if self.prefix else path

    def _blob_url(self, key: str) -> str:
        return f"{self.endpoint}/{self.container}/{urllib.parse.quote(key)}"

    def _container_url(self, query: dict) -> str:
        return f"{self.endpoint}/{self.container}?" + urllib.parse.urlencode(sorted(query.items()))

    # -------------------------------------------------------------- write
    def write(self, tenant, block_id, name, data):
        self._request("PUT", self._blob_url(self._key_path(block_object_path(tenant, block_id, name))),
                      data, {"x-ms-blob-type": "BlockBlob"})

    def write_tenant_object(self, tenant, name, data):
        self._request("PUT", self._blob_url(self._key_path(f"{tenant}/{name}")),
                      data, {"x-ms-blob-type": "BlockBlob"})

    # --------------------------------------------------------------- read
    def read(self, tenant, block_id, name):
        return self._request("GET", self._blob_url(self._key_path(block_object_path(tenant, block_id, name))))[1]

    def read_range(self, tenant, block_id, name, offset, length):
        return self._request(
            "GET",
            self._blob_url(self._key_path(block_object_path(tenant, block_id, name))),
            extra={"x-ms-range": f"bytes={offset}-{offset + length - 1}"},
        )[1]

    def read_tenant_object(self, tenant, name):
        return self._request("GET", self._blob_url(self._key_path(f"{tenant}/{name}")))[1]

    # --------------------------------------------------------------- list
    def _list_prefixes(self, prefix: str) -> list[str]:
        out, marker = [], ""
        while True:
            q = {"restype": "container", "comp": "list", "delimiter": "/", "prefix": prefix}
            if marker:
                q["marker"] = marker
            _, body = self._request("GET", self._container_url(q))
            root = ET.fromstring(body)
            for bp in root.iter("BlobPrefix"):
                name = bp.findtext("Name") or ""
                name = name[len(prefix):].strip("/")
                if name:
                    out.append(name)
            marker = root.findtext("NextMarker") or ""
            if not marker:
                return out

    def tenants(self):
        return self._list_prefixes(f"{self.prefix}/" if self.prefix else "")

    def blocks(self, tenant):
        return self._list_prefixes(self._key_path(f"{tenant}/"))

    # ------------------------------------------------------------- delete
    def _delete_object(self, tenant, block_id, name):
        try:
            self._request("DELETE", self._blob_url(self._key_path(block_object_path(tenant, block_id, name))))
        except DoesNotExist:
            pass

    def delete_block(self, tenant, block_id):
        prefix = self._key_path(f"{tenant}/{block_id}/")
        marker = ""
        while True:
            q = {"restype": "container", "comp": "list", "prefix": prefix}
            if marker:
                q["marker"] = marker
            _, body = self._request("GET", self._container_url(q))
            root = ET.fromstring(body)
            for b in root.iter("Blob"):
                name = b.findtext("Name")
                if name:
                    try:
                        self._request("DELETE", self._blob_url(name))
                    except DoesNotExist:
                        pass
            marker = root.findtext("NextMarker") or ""
            if not marker:
                return

    def delete_tenant_object(self, tenant, name):
        try:
            self._request("DELETE", self._blob_url(self._key_path(f"{tenant}/{name}")))
        except DoesNotExist:
            pass
