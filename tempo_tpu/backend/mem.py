"""In-memory object store for unit tests (MockRawReader/Writer analog,
tempodb/backend/mocks.go:1-176) with optional fault injection."""

from __future__ import annotations

import threading

from .base import COMPACTED_META_NAME, META_NAME, DoesNotExist, RawBackend


class MemBackend(RawBackend):
    is_remote = False
    def __init__(self):
        self._lock = threading.Lock()
        self._objects: dict[tuple[str, str, str], bytes] = {}
        self._tenant_objects: dict[tuple[str, str], bytes] = {}
        self.fail_reads = 0  # >0: next N reads raise (fault injection)
        self.read_count = 0
        self.bytes_read = 0

    def _maybe_fail(self):
        if self.fail_reads > 0:
            self.fail_reads -= 1
            raise DoesNotExist("injected read failure")

    def write(self, tenant, block_id, name, data):
        with self._lock:
            self._objects[(tenant, block_id, name)] = bytes(data)

    def write_tenant_object(self, tenant, name, data):
        with self._lock:
            self._tenant_objects[(tenant, name)] = bytes(data)

    def read(self, tenant, block_id, name):
        with self._lock:
            self._maybe_fail()
            self.read_count += 1
            try:
                data = self._objects[(tenant, block_id, name)]
            except KeyError:
                raise DoesNotExist(f"{tenant}/{block_id}/{name}") from None
            self.bytes_read += len(data)
            return data

    def read_range(self, tenant, block_id, name, offset, length):
        with self._lock:
            self._maybe_fail()
            self.read_count += 1
            try:
                data = self._objects[(tenant, block_id, name)]
            except KeyError:
                raise DoesNotExist(f"{tenant}/{block_id}/{name}") from None
            out = data[offset : offset + length]
            self.bytes_read += len(out)
            return out

    def read_tenant_object(self, tenant, name):
        with self._lock:
            self._maybe_fail()
            try:
                return self._tenant_objects[(tenant, name)]
            except KeyError:
                raise DoesNotExist(f"{tenant}/{name}") from None

    def tenants(self):
        with self._lock:
            ts = {t for (t, _, _) in self._objects} | {t for (t, _) in self._tenant_objects}
            return sorted(ts)

    def blocks(self, tenant):
        with self._lock:
            out = set()
            for (t, b, name) in self._objects:
                if t == tenant and name in (META_NAME, COMPACTED_META_NAME):
                    out.add(b)
            return sorted(out)

    def delete_block(self, tenant, block_id):
        # prefix-recursive like the cloud backends: a compound block's
        # parts live under "<block_id>/pN" ids
        with self._lock:
            for key in [k for k in self._objects
                        if k[0] == tenant and (
                            k[1] == block_id or k[1].startswith(block_id + "/"))]:
                del self._objects[key]

    def delete_tenant_object(self, tenant, name):
        with self._lock:
            self._tenant_objects.pop((tenant, name), None)

    def _delete_object(self, tenant, block_id, name):
        with self._lock:
            self._objects.pop((tenant, block_id, name), None)
