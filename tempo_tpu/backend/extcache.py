"""External cache clients: memcached and redis over raw sockets.

The role of pkg/cache's memcached/redis clients in the reference: a
querier FLEET shares one cache tier for blooms/dictionaries/footers, so
a block's control objects are fetched from object storage once per
cluster instead of once per process. No SDKs: the memcached text
protocol and RESP are both line protocols a few dozen lines long.

CachedBackend takes one of these as its second tier: local LRU ->
external cache -> object store, populating both on the way back (the
reference's cache.NewCache composition, tempodb/backend/cache/cache.go).
Failures degrade to the store -- a cache outage must never fail reads.
"""

from __future__ import annotations

import socket
import threading

from ..util.hashing import fnv1a_32


class _SocketPool:
    """One pooled connection per address; callers borrow under a lock
    (these protocols are request/response, one in flight per conn)."""

    def __init__(self, addr: tuple[str, int], timeout: float):
        self.addr = addr
        self.timeout = timeout
        self._lock = threading.Lock()
        self._sock: socket.socket | None = None

    def __enter__(self):
        self._lock.acquire()
        try:
            if self._sock is None:
                self._sock = socket.create_connection(self.addr, timeout=self.timeout)
            return self._sock
        except BaseException:
            # __exit__ never runs when __enter__ raises: release here or
            # the pool deadlocks forever after one failed connect
            self._lock.release()
            raise

    def __exit__(self, exc_type, *a):
        if exc_type is not None and self._sock is not None:
            try:
                self._sock.close()
            finally:
                self._sock = None
        self._lock.release()
        return False


def _recv_line(sock: socket.socket) -> bytes:
    out = bytearray()
    while not out.endswith(b"\r\n"):
        b = sock.recv(1)
        if not b:
            raise ConnectionError("cache connection closed")
        out += b
    return bytes(out[:-2])


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    out = bytearray()
    while len(out) < n:
        chunk = sock.recv(n - len(out))
        if not chunk:
            raise ConnectionError("cache connection closed")
        out += chunk
    return bytes(out)


class MemcachedCache:
    """Text-protocol client; keys shard across servers by fnv32 (the
    reference's memcached client uses consistent jump-hashing; modulo
    keeps the same one-server-owns-one-key property)."""

    def __init__(self, addrs: list[str], timeout: float = 0.5,
                 ttl_s: int = 3600, max_item_bytes: int = 1 << 20):
        self.pools = []
        for a in addrs:
            host, _, port = a.partition(":")
            self.pools.append(_SocketPool((host, int(port or 11211)), timeout))
        self.ttl_s = ttl_s
        self.max_item_bytes = max_item_bytes

    def _pool(self, key: str) -> _SocketPool:
        return self.pools[fnv1a_32(key.encode()) % len(self.pools)]

    @staticmethod
    def _safe_key(key: str) -> str:
        """Memcached keys must be <=250 printable-ASCII bytes with no
        whitespace; anything else desyncs the text protocol (a CRLF in a
        key turns the value bytes into commands -- cross-key cache
        poisoning). Unsafe or oversized keys map to a stable hash."""
        if len(key) <= 240 and all(33 <= ord(c) <= 126 for c in key):
            return key
        import hashlib

        return "h:" + hashlib.sha256(key.encode()).hexdigest()

    def get(self, key: str) -> bytes | None:
        key = self._safe_key(key)
        try:
            with self._pool(key) as sock:
                sock.sendall(f"get {key}\r\n".encode())
                line = _recv_line(sock)
                if not line.startswith(b"VALUE"):
                    return None  # END
                n = int(line.rsplit(b" ", 1)[1])
                data = _recv_exact(sock, n)
                _recv_exact(sock, 2)  # \r\n
                end = _recv_line(sock)
                if end != b"END":
                    raise ConnectionError(f"bad memcached tail {end!r}")
                return data
        except (OSError, ValueError, ConnectionError):
            return None

    def set(self, key: str, value: bytes) -> None:
        if len(value) > self.max_item_bytes:
            return
        key = self._safe_key(key)
        try:
            with self._pool(key) as sock:
                sock.sendall(
                    f"set {key} 0 {self.ttl_s} {len(value)}\r\n".encode()
                    + value + b"\r\n"
                )
                _recv_line(sock)  # STORED
        except (OSError, ConnectionError):
            pass


class RedisCache:
    """RESP client: GET/SETEX only."""

    def __init__(self, addr: str, timeout: float = 0.5, ttl_s: int = 3600,
                 max_item_bytes: int = 1 << 20):
        host, _, port = addr.partition(":")
        self.pool = _SocketPool((host, int(port or 6379)), timeout)
        self.ttl_s = ttl_s
        self.max_item_bytes = max_item_bytes

    @staticmethod
    def _cmd(parts: list[bytes]) -> bytes:
        out = b"*%d\r\n" % len(parts)
        for p in parts:
            out += b"$%d\r\n%s\r\n" % (len(p), p)
        return out

    def get(self, key: str) -> bytes | None:
        try:
            with self.pool as sock:
                sock.sendall(self._cmd([b"GET", key.encode()]))
                line = _recv_line(sock)
                if not line.startswith(b"$") or line == b"$-1":
                    return None
                n = int(line[1:])
                data = _recv_exact(sock, n)
                _recv_exact(sock, 2)
                return data
        except (OSError, ValueError, ConnectionError):
            return None

    def set(self, key: str, value: bytes) -> None:
        if len(value) > self.max_item_bytes:
            return
        try:
            with self.pool as sock:
                sock.sendall(self._cmd(
                    [b"SETEX", key.encode(), str(self.ttl_s).encode(), value]
                ))
                _recv_line(sock)  # +OK
        except (OSError, ConnectionError):
            pass


class BackgroundWriteCache:
    """Write-behind wrapper (reference: pkg/cache/background.go:22-80):
    set() enqueues onto a byte-bounded queue drained by background
    writer threads, so a slow or stalled cache tier can never block the
    read path that populates it. When the queue is full the write is
    DROPPED (counted), exactly like the reference -- cache writes are
    best-effort by definition."""

    def __init__(self, inner, max_queued_bytes: int = 16 << 20, writers: int = 2):
        import queue

        self.inner = inner
        self.max_queued_bytes = max_queued_bytes
        self._q: queue.Queue = queue.Queue()
        self._queued_bytes = 0
        self._lock = threading.Lock()
        self.dropped = 0
        self._threads = [
            threading.Thread(target=self._drain, name=f"cache-writeback-{i}",
                             daemon=True)
            for i in range(writers)
        ]
        for t in self._threads:
            t.start()

    def _drain(self) -> None:
        while True:
            item = self._q.get()
            try:
                if item is None:
                    return
                key, value = item
                with self._lock:
                    self._queued_bytes -= len(value)
                try:
                    self.inner.set(key, value)
                except Exception:
                    pass  # cache writes are best-effort
            finally:
                self._q.task_done()

    def flush(self) -> None:
        """Block until every queued write has been attempted (tests /
        orderly shutdown)."""
        self._q.join()

    def get(self, key: str) -> bytes | None:
        return self.inner.get(key)

    def set(self, key: str, value: bytes) -> None:
        with self._lock:
            if self._queued_bytes + len(value) > self.max_queued_bytes:
                self.dropped += 1
                return
            self._queued_bytes += len(value)
        self._q.put((key, value))

    def stop(self) -> None:
        for _ in self._threads:
            self._q.put(None)
        for t in self._threads:
            t.join(timeout=2.0)


def open_external_cache(cfg: dict):
    """Config -> client: {"kind": "memcached", "addrs": [...]} or
    {"kind": "redis", "addr": "host:port"}. Writes go through the
    write-behind queue unless "background": false."""
    kind = cfg.get("kind", "")
    if kind == "memcached":
        client = MemcachedCache(cfg["addrs"], ttl_s=int(cfg.get("ttl_s", 3600)))
    elif kind == "redis":
        client = RedisCache(cfg["addr"], ttl_s=int(cfg.get("ttl_s", 3600)))
    else:
        raise ValueError(f"unknown external cache kind {kind!r}")
    if cfg.get("background", True):
        return BackgroundWriteCache(
            client,
            max_queued_bytes=int(cfg.get("background_queue_bytes", 16 << 20)),
            writers=int(cfg.get("background_writers", 2)),
        )
    return client
