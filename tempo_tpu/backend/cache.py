"""Caching + hedging backend wrappers.

CachedBackend interposes an LRU on reads keyed tenant:block:name[:off:len]
(the reference's cache interposer, tempodb/backend/cache/cache.go:22-113)
with the same policy seam as tempodb's shouldCache: only hot control
objects (blooms, dictionary, footers / small ranges) are cached, never
bulk column data.

HedgedBackend launches a backup read if the primary hasn't answered
within a delay -- first result wins (the reference hedges every object
backend via cristalhq/hedgedhttp).
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from concurrent.futures import FIRST_COMPLETED, ThreadPoolExecutor, wait

from .base import RawBackend

# NEVER meta.json: it is the one mutable object (deleted by another
# process's compactor); caching it would pin dead blocks on the blocklist
_CACHEABLE_NAMES = ("bloom-", "dictionary")
MAX_CACHED_RANGE = 1 << 20  # ranges above 1 MiB are bulk column reads


class CachedBackend(RawBackend):
    def __init__(self, inner: RawBackend, max_bytes: int = 256 * 1024 * 1024,
                 external=None):
        """external: optional shared cache tier (backend/extcache.py
        memcached/redis client) between the local LRU and the store, so
        a querier fleet fetches each control object from object storage
        once per cluster, not once per process."""
        self.inner = inner
        self.is_remote = getattr(inner, "is_remote", True)
        self.max_bytes = max_bytes
        self.external = external
        self._lock = threading.Lock()
        self._lru: OrderedDict[tuple, bytes] = OrderedDict()
        self._bytes = 0
        self.hits = 0
        self.misses = 0
        self.external_hits = 0

    @staticmethod
    def _ext_key(key: tuple) -> str:
        return ":".join(str(p) for p in key)

    # ------------------------------------------------------------- cache
    @staticmethod
    def _cacheable(name: str, length: int | None = None) -> bool:
        if length is not None and length > MAX_CACHED_RANGE:
            return False
        return any(t in name for t in _CACHEABLE_NAMES) or (length is not None)

    def _get(self, key: tuple) -> bytes | None:
        with self._lock:
            data = self._lru.get(key)
            if data is not None:
                self._lru.move_to_end(key)
                self.hits += 1
            else:
                self.misses += 1
            return data

    def _put(self, key: tuple, data: bytes) -> None:
        if len(data) > self.max_bytes:
            return
        with self._lock:
            old = self._lru.pop(key, None)
            if old is not None:
                self._bytes -= len(old)
            self._lru[key] = data
            self._bytes += len(data)
            while self._bytes > self.max_bytes and self._lru:
                _, evicted = self._lru.popitem(last=False)
                self._bytes -= len(evicted)

    def _invalidate_block(self, tenant: str, block_id: str) -> None:
        with self._lock:
            for k in [k for k in self._lru if k[0] == tenant and k[1] == block_id]:
                self._bytes -= len(self._lru.pop(k))

    # ------------------------------------------------------------ passthru
    def write(self, tenant, block_id, name, data):
        self.inner.write(tenant, block_id, name, data)
        self._invalidate_block(tenant, block_id)

    def open_append(self, tenant, block_id, name):
        self._invalidate_block(tenant, block_id)
        return self.inner.open_append(tenant, block_id, name)

    def write_tenant_object(self, tenant, name, data):
        self.inner.write_tenant_object(tenant, name, data)

    def _read_tiered(self, key: tuple, fetch):
        """local LRU -> external cache -> store, back-filling each
        tier above the one that answered."""
        data = self._get(key)
        if data is not None:
            return data
        if self.external is not None:
            data = self.external.get(self._ext_key(key))
            if data is not None:
                self.external_hits += 1
                self._put(key, data)
                return data
        data = fetch()
        self._put(key, data)
        if self.external is not None:
            self.external.set(self._ext_key(key), data)
        return data

    def read(self, tenant, block_id, name):
        key = (tenant, block_id, name)
        if not self._cacheable(name):
            return self.inner.read(tenant, block_id, name)
        return self._read_tiered(key, lambda: self.inner.read(tenant, block_id, name))

    def read_range(self, tenant, block_id, name, offset, length):
        key = (tenant, block_id, name, offset, length)
        if not self._cacheable(name, length):
            return self.inner.read_range(tenant, block_id, name, offset, length)
        return self._read_tiered(
            key, lambda: self.inner.read_range(tenant, block_id, name, offset, length)
        )

    def read_tenant_object(self, tenant, name):
        return self.inner.read_tenant_object(tenant, name)

    def tenants(self):
        return self.inner.tenants()

    def blocks(self, tenant):
        return self.inner.blocks(tenant)

    def delete_block(self, tenant, block_id):
        self.inner.delete_block(tenant, block_id)
        self._invalidate_block(tenant, block_id)

    def delete_tenant_object(self, tenant, name):
        self.inner.delete_tenant_object(tenant, name)

    def _delete_object(self, tenant, block_id, name):
        self.inner._delete_object(tenant, block_id, name)
        self._invalidate_block(tenant, block_id)

    def mark_compacted(self, tenant, block_id):
        self.inner.mark_compacted(tenant, block_id)
        self._invalidate_block(tenant, block_id)


class HedgedBackend(RawBackend):
    """Issues a backup read when the primary is slow; first reply wins."""

    def __init__(self, inner: RawBackend, hedge_after_s: float = 0.5, workers: int = 16):
        self.inner = inner
        self.is_remote = getattr(inner, "is_remote", True)
        self.hedge_after_s = hedge_after_s
        self.pool = ThreadPoolExecutor(max_workers=workers, thread_name_prefix="hedge")
        self.hedged_requests = 0

    def _hedged(self, fn, *args):
        f1 = self.pool.submit(fn, *args)
        done, _ = wait([f1], timeout=self.hedge_after_s, return_when=FIRST_COMPLETED)
        if done:
            return f1.result()
        self.hedged_requests += 1
        futures = {f1, self.pool.submit(fn, *args)}
        last_err: Exception | None = None
        while futures:
            done, futures = wait(futures, return_when=FIRST_COMPLETED)
            # any success among the completed set wins, even if another
            # completed leg errored in the same instant
            for f in done:
                try:
                    return f.result()
                except Exception as e:
                    last_err = e
        raise last_err

    def read(self, tenant, block_id, name):
        return self._hedged(self.inner.read, tenant, block_id, name)

    def read_range(self, tenant, block_id, name, offset, length):
        return self._hedged(self.inner.read_range, tenant, block_id, name, offset, length)

    def read_tenant_object(self, tenant, name):
        return self._hedged(self.inner.read_tenant_object, tenant, name)

    # writes/lists/deletes pass through unhedged
    def write(self, tenant, block_id, name, data):
        self.inner.write(tenant, block_id, name, data)

    def open_append(self, tenant, block_id, name):
        return self.inner.open_append(tenant, block_id, name)

    def write_tenant_object(self, tenant, name, data):
        self.inner.write_tenant_object(tenant, name, data)

    def tenants(self):
        return self.inner.tenants()

    def blocks(self, tenant):
        return self.inner.blocks(tenant)

    def delete_block(self, tenant, block_id):
        self.inner.delete_block(tenant, block_id)

    def delete_tenant_object(self, tenant, name):
        self.inner.delete_tenant_object(tenant, name)

    def _delete_object(self, tenant, block_id, name):
        self.inner._delete_object(tenant, block_id, name)

    def mark_compacted(self, tenant, block_id):
        self.inner.mark_compacted(tenant, block_id)
