"""Filesystem object store: <path>/<tenant>/<block>/<name>.

Same layout role as the reference's local backend
(tempodb/backend/local/local.go); doubles as the in-test object store so
no cloud credentials are ever needed for the full engine test suite.
Writes are atomic (tmp file + rename) so a crashed writer never leaves a
half-written meta visible to pollers.
"""

from __future__ import annotations

import os
import tempfile

from .base import COMPACTED_META_NAME, META_NAME, Appender, DoesNotExist, RawBackend

_TENANT_OBJECT_DIR = "__tenant__"


class _FileAppender(Appender):
    """True incremental append: parts stream to a temp file, atomically
    renamed into place on close (keeps the crash-safety of write())."""

    def __init__(self, backend: "LocalBackend", tenant: str, block_id: str, name: str):
        super().__init__(backend, tenant, block_id, name)
        path = backend._obj_path(tenant, block_id, name)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        fd, self._tmp = tempfile.mkstemp(dir=os.path.dirname(path), prefix=".tmp-")
        self._f = os.fdopen(fd, "wb")
        self._path = path

    def append(self, data: bytes) -> None:
        self._f.write(data)
        self.bytes_written += len(data)

    def close(self) -> None:
        try:
            self._f.close()
            os.replace(self._tmp, self._path)
        except BaseException:
            try:
                os.unlink(self._tmp)
            except OSError:
                pass
            raise

    def abort(self) -> None:
        try:
            self._f.close()
        finally:
            try:
                os.unlink(self._tmp)
            except OSError:
                pass


class LocalBackend(RawBackend):
    is_remote = False
    def __init__(self, path: str):
        self.path = os.path.abspath(path)
        os.makedirs(self.path, exist_ok=True)

    # ---- helpers
    def _obj_path(self, tenant: str, block_id: str, name: str) -> str:
        return os.path.join(self.path, tenant, block_id, name)

    def _write_file(self, path: str, data: bytes) -> None:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        fd, tmp = tempfile.mkstemp(dir=os.path.dirname(path), prefix=".tmp-")
        try:
            with os.fdopen(fd, "wb") as f:
                f.write(data)
            os.replace(tmp, path)
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise

    def _read_file(self, path: str) -> bytes:
        try:
            with open(path, "rb") as f:
                return f.read()
        except FileNotFoundError:
            raise DoesNotExist(path) from None

    # ---- write
    def write(self, tenant: str, block_id: str, name: str, data: bytes) -> None:
        self._write_file(self._obj_path(tenant, block_id, name), data)

    def open_append(self, tenant: str, block_id: str, name: str) -> Appender:
        return _FileAppender(self, tenant, block_id, name)

    def copy_object(self, tenant: str, src_block_id: str, name: str,
                    dst_block_id: str) -> int:
        """Server-side copy as a hardlink: block objects are immutable
        and writes replace directory entries (tmp + rename), never
        inodes, so sharing the inode is safe -- and the concat
        compactor's part copies become pure metadata ops. Falls back to
        the read+write default when the filesystem refuses (cross-device
        links, exotic mounts)."""
        src = self._obj_path(tenant, src_block_id, name)
        dst = self._obj_path(tenant, dst_block_id, name)
        try:
            os.makedirs(os.path.dirname(dst), exist_ok=True)
            os.link(src, dst)
            return os.path.getsize(dst)
        except FileNotFoundError:
            raise DoesNotExist(src) from None
        except OSError:
            return super().copy_object(tenant, src_block_id, name, dst_block_id)

    def write_tenant_object(self, tenant: str, name: str, data: bytes) -> None:
        self._write_file(os.path.join(self.path, tenant, _TENANT_OBJECT_DIR, name), data)

    # ---- read
    def read(self, tenant: str, block_id: str, name: str) -> bytes:
        return self._read_file(self._obj_path(tenant, block_id, name))

    def read_range(self, tenant: str, block_id: str, name: str, offset: int, length: int) -> bytes:
        path = self._obj_path(tenant, block_id, name)
        try:
            with open(path, "rb") as f:
                f.seek(offset)
                return f.read(length)
        except FileNotFoundError:
            raise DoesNotExist(path) from None

    def read_tenant_object(self, tenant: str, name: str) -> bytes:
        return self._read_file(os.path.join(self.path, tenant, _TENANT_OBJECT_DIR, name))

    # ---- list
    def tenants(self) -> list[str]:
        try:
            return sorted(
                d for d in os.listdir(self.path) if os.path.isdir(os.path.join(self.path, d))
            )
        except FileNotFoundError:
            return []

    def blocks(self, tenant: str) -> list[str]:
        tdir = os.path.join(self.path, tenant)
        out = []
        try:
            entries = os.listdir(tdir)
        except FileNotFoundError:
            return []
        for d in entries:
            if d == _TENANT_OBJECT_DIR:
                continue
            bdir = os.path.join(tdir, d)
            if not os.path.isdir(bdir):
                continue
            if os.path.exists(os.path.join(bdir, META_NAME)) or os.path.exists(
                os.path.join(bdir, COMPACTED_META_NAME)
            ):
                out.append(d)
        return sorted(out)

    # ---- delete
    def delete_block(self, tenant: str, block_id: str) -> None:
        import shutil

        bdir = os.path.join(self.path, tenant, block_id)
        if not os.path.isdir(bdir):
            return

        def _onexc(fn, path, exc):
            # concurrent deletion is fine; anything else (permissions,
            # read-only fs) must surface -- retention reports this block
            # as reclaimed based on the outcome
            if not isinstance(exc, FileNotFoundError):
                raise exc

        # recursive: compound blocks (db/concat_compact.py) nest their
        # parts as subdirectories of the block dir
        shutil.rmtree(bdir, onexc=_onexc)

    def delete_tenant_object(self, tenant: str, name: str) -> None:
        try:
            os.unlink(os.path.join(self.path, tenant, _TENANT_OBJECT_DIR, name))
        except FileNotFoundError:
            pass

    def _delete_object(self, tenant: str, block_id: str, name: str) -> None:
        try:
            os.unlink(self._obj_path(tenant, block_id, name))
        except FileNotFoundError:
            pass
