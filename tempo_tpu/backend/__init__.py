from .base import (
    BackendError,
    CompactedMarker,
    DoesNotExist,
    RawBackend,
    block_object_path,
    meta_name,
)
from .local import LocalBackend
from .mem import MemBackend


def open_backend(cfg: dict) -> RawBackend:
    """Select a backend by config, like the reference's string-keyed
    selection (tempodb/tempodb.go:141-152)."""
    kind = cfg.get("backend", "local")
    if kind == "local":
        return LocalBackend(cfg.get("path", "./tempo-data"))
    if kind in ("mem", "memory"):
        return MemBackend()
    if kind == "gcs":
        # native JSON-API backend (the primary TPU-VM store); HMAC keys
        # select the S3-interoperability endpoint instead
        if cfg.get("access_key"):
            from .s3 import S3Backend

            inner = S3Backend(
                endpoint=cfg.get("endpoint") or "https://storage.googleapis.com",
                bucket=cfg["bucket"],
                access_key=cfg.get("access_key", ""),
                secret_key=cfg.get("secret_key", ""),
                region=cfg.get("region", "us-east-1"),
                prefix=cfg.get("prefix", ""),
            )
        else:
            from .gcs import GCSBackend

            inner = GCSBackend(
                bucket=cfg["bucket"],
                prefix=cfg.get("prefix", ""),
                endpoint=cfg.get("endpoint", ""),
                token=cfg.get("token", ""),
            )
        return _wrap(inner, cfg)
    if kind == "s3":
        from .s3 import S3Backend

        inner = S3Backend(
            endpoint=cfg.get("endpoint") or "https://s3.amazonaws.com",
            bucket=cfg["bucket"],
            access_key=cfg.get("access_key", ""),
            secret_key=cfg.get("secret_key", ""),
            region=cfg.get("region", "us-east-1"),
            prefix=cfg.get("prefix", ""),
        )
        return _wrap(inner, cfg)
    if kind == "azure":
        from .azure import AzureBackend

        inner = AzureBackend(
            account=cfg["account"],
            container=cfg["container"],
            key=cfg.get("key", ""),
            endpoint=cfg.get("endpoint", ""),
            prefix=cfg.get("prefix", ""),
        )
        return _wrap(inner, cfg)
    raise ValueError(f"unknown backend {kind!r}")


def _wrap(inner: RawBackend, cfg: dict) -> RawBackend:
    """Optional cache + hedging interposers (reference: backend/cache
    wrapper + hedged requests on every object backend)."""
    from .cache import CachedBackend, HedgedBackend

    if cfg.get("hedge_requests_after_s"):
        inner = HedgedBackend(inner, hedge_after_s=float(cfg["hedge_requests_after_s"]))
    if cfg.get("cache", True) and cfg.get("cache_max_bytes", 1) != 0:
        external = None
        if cfg.get("external_cache"):
            from .extcache import open_external_cache

            external = open_external_cache(cfg["external_cache"])
        inner = CachedBackend(inner, max_bytes=int(cfg.get("cache_max_bytes", 256 << 20)),
                              external=external)
    return inner
