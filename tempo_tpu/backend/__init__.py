from .base import (
    BackendError,
    CompactedMarker,
    DoesNotExist,
    RawBackend,
    block_object_path,
    meta_name,
)
from .local import LocalBackend
from .mem import MemBackend


def open_backend(cfg: dict) -> RawBackend:
    """Select a backend by config, like the reference's string-keyed
    selection (tempodb/tempodb.go:141-152)."""
    kind = cfg.get("backend", "local")
    if kind == "local":
        return LocalBackend(cfg.get("path", "./tempo-data"))
    if kind in ("mem", "memory"):
        return MemBackend()
    if kind in ("gcs", "s3", "azure"):
        raise NotImplementedError(
            f"backend {kind!r} requires cloud SDKs not present in this build; "
            "use 'local' (works for all single-host and test deployments)"
        )
    raise ValueError(f"unknown backend {kind!r}")
