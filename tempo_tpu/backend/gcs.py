"""Native GCS backend over the JSON API.

The role of the reference's GCS backend (tempodb/backend/gcs/gcs.go:
1-298): media uploads, ranged reads, delimiter listing, the
compacted-marker protocol (read+stamp+write+delete, carrying the mark
time like the reference's CompactedBlockMeta), and RESUMABLE streamed
uploads for the appender so a block's data object never buffers whole
in memory (gcs.go's writer is a streaming pipe for the same reason).

Auth modes: explicit OAuth bearer token, the GCE/TPU-VM metadata server
(tokens fetched lazily and refreshed before expiry -- the natural mode
on TPU VMs, which carry a service account), or anonymous (fake servers,
public buckets). No SDK: the JSON API is plain HTTP.

Hedged reads + caching come from the shared wrappers (backend/cache.py)
applied by open_backend, like every other object backend here. GCS's
S3-interoperability endpoint remains reachable through the `s3` backend
with HMAC keys; this native backend is the primary TPU-VM path
(SURVEY.md 7.1).
"""

from __future__ import annotations

import json
import threading
import time
import urllib.error
import urllib.parse
import urllib.request

from .base import Appender, BackendError, DoesNotExist, RawBackend, block_object_path

_METADATA_TOKEN_URL = (
    "http://metadata.google.internal/computeMetadata/v1/"
    "instance/service-accounts/default/token"
)
_RESUMABLE_CHUNK = 8 << 20  # multiple of the required 256 KiB granularity


class _MetadataTokenSource:
    """Lazy bearer tokens from the GCE metadata server, refreshed 60 s
    before expiry."""

    def __init__(self, timeout: float = 5.0):
        self._timeout = timeout
        self._lock = threading.Lock()
        self._token = ""
        self._expires = 0.0

    def token(self) -> str:
        with self._lock:
            if self._token and time.time() < self._expires - 60:
                return self._token
            req = urllib.request.Request(
                _METADATA_TOKEN_URL, headers={"Metadata-Flavor": "Google"}
            )
            try:
                with urllib.request.urlopen(req, timeout=self._timeout) as r:
                    body = json.loads(r.read())
            except (urllib.error.URLError, OSError) as e:
                raise BackendError(f"gcs metadata token: {e}")
            self._token = body.get("access_token", "")
            self._expires = time.time() + float(body.get("expires_in", 0))
            return self._token


class GCSBackend(RawBackend):
    def __init__(self, bucket: str, prefix: str = "", endpoint: str = "",
                 token: str = "", use_metadata_auth: bool | None = None,
                 timeout: float = 30.0):
        """endpoint overrides https://storage.googleapis.com (fake
        servers); token is a static bearer token; use_metadata_auth
        defaults to True only when neither endpoint nor token is given
        (i.e. talking to real GCS from a GCP VM)."""
        self.bucket = bucket
        self.prefix = prefix.strip("/")
        self.endpoint = (endpoint or "https://storage.googleapis.com").rstrip("/")
        self._static_token = token
        if use_metadata_auth is None:
            use_metadata_auth = not endpoint and not token
        self._meta_tokens = _MetadataTokenSource() if use_metadata_auth else None
        self.timeout = timeout

    # ------------------------------------------------------------- http
    def _key(self, path: str) -> str:
        return f"{self.prefix}/{path}" if self.prefix else path

    def _obj_url(self, key: str, query: dict | None = None) -> str:
        u = (f"{self.endpoint}/storage/v1/b/{urllib.parse.quote(self.bucket, safe='')}"
             f"/o/{urllib.parse.quote(key, safe='')}")
        if query:
            u += "?" + urllib.parse.urlencode(query)
        return u

    def _headers(self, extra: dict | None = None) -> dict:
        h = dict(extra or {})
        tok = self._static_token or (self._meta_tokens.token() if self._meta_tokens else "")
        if tok:
            h["Authorization"] = f"Bearer {tok}"
        return h

    def _request(self, method: str, url: str, data: bytes | None = None,
                 headers: dict | None = None, ok_statuses=(200, 204, 206, 308)):
        req = urllib.request.Request(
            url, data=data, headers=self._headers(headers), method=method
        )
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as r:
                return r.status, r.read(), dict(r.headers)
        except urllib.error.HTTPError as e:
            if e.code in ok_statuses:  # 308 = resumable "continue"
                return e.code, e.read(), dict(e.headers)
            if e.code == 404:
                raise DoesNotExist(url)
            raise BackendError(f"gcs {method} {url}: {e.code} {e.read()[:200]!r}")
        except urllib.error.URLError as e:
            raise BackendError(f"gcs {method} {url}: {e}")

    # ------------------------------------------------------------ write
    def write(self, tenant: str, block_id: str, name: str, data: bytes) -> None:
        self._write_key(self._key(block_object_path(tenant, block_id, name)), data)

    def write_tenant_object(self, tenant: str, name: str, data: bytes) -> None:
        self._write_key(self._key(f"{tenant}/{name}"), data)

    def _write_key(self, key: str, data: bytes) -> None:
        url = (f"{self.endpoint}/upload/storage/v1/b/"
               f"{urllib.parse.quote(self.bucket, safe='')}/o?"
               + urllib.parse.urlencode({"uploadType": "media", "name": key}))
        self._request("POST", url, data=data,
                      headers={"Content-Type": "application/octet-stream"})

    def open_append(self, tenant: str, block_id: str, name: str) -> Appender:
        return _ResumableAppender(self, self._key(block_object_path(tenant, block_id, name)))

    # ------------------------------------------------------------- read
    def read(self, tenant: str, block_id: str, name: str) -> bytes:
        key = self._key(block_object_path(tenant, block_id, name))
        return self._request("GET", self._obj_url(key, {"alt": "media"}))[1]

    def read_range(self, tenant: str, block_id: str, name: str, offset: int, length: int) -> bytes:
        key = self._key(block_object_path(tenant, block_id, name))
        _, body, _ = self._request(
            "GET", self._obj_url(key, {"alt": "media"}),
            headers={"Range": f"bytes={offset}-{offset + length - 1}"},
        )
        return body

    def read_tenant_object(self, tenant: str, name: str) -> bytes:
        return self._request("GET", self._obj_url(self._key(f"{tenant}/{name}"), {"alt": "media"}))[1]

    # ------------------------------------------------------------- list
    def _list(self, prefix: str, delimiter: str = "/") -> tuple[list[str], list[str]]:
        """(common prefixes under `prefix`, object names)."""
        prefixes: list[str] = []
        names: list[str] = []
        token = ""
        while True:
            q = {"prefix": prefix}
            if delimiter:
                q["delimiter"] = delimiter
            if token:
                q["pageToken"] = token
            url = (f"{self.endpoint}/storage/v1/b/"
                   f"{urllib.parse.quote(self.bucket, safe='')}/o?"
                   + urllib.parse.urlencode(q))
            _, body, _ = self._request("GET", url)
            out = json.loads(body or b"{}")
            for p in out.get("prefixes", []):
                p = p[len(prefix):].strip("/")
                if p:
                    prefixes.append(p)
            for item in out.get("items", []):
                names.append(item.get("name", ""))
            token = out.get("nextPageToken", "")
            if not token:
                return prefixes, names

    def tenants(self) -> list[str]:
        return self._list(f"{self.prefix}/" if self.prefix else "")[0]

    def blocks(self, tenant: str) -> list[str]:
        return self._list(self._key(f"{tenant}/"))[0]

    # ----------------------------------------------------------- delete
    def _delete_key(self, key: str) -> None:
        try:
            self._request("DELETE", self._obj_url(key))
        except DoesNotExist:
            pass

    def _delete_object(self, tenant: str, block_id: str, name: str) -> None:
        self._delete_key(self._key(block_object_path(tenant, block_id, name)))

    def delete_block(self, tenant: str, block_id: str) -> None:
        _, names = self._list(self._key(f"{tenant}/{block_id}/"), delimiter="")
        for n in names:
            self._delete_key(n)

    def delete_tenant_object(self, tenant: str, name: str) -> None:
        self._delete_key(self._key(f"{tenant}/{name}"))

    # compacted-marker rename: the base read+stamp+write+delete path
    # applies (the reference's gcs MarkBlockCompacted likewise rewrites
    # the meta content to carry CompactedTime).


class _ResumableAppender(Appender):
    """Streamed object writer over a GCS resumable-upload session:
    chunks flush at 256 KiB-aligned boundaries, memory stays bounded at
    one chunk (gcs.go's streaming writer role)."""

    def __init__(self, backend: GCSBackend, key: str):
        self._b = backend
        self._key = key
        self._session: str | None = None
        self._buf = bytearray()
        self._flushed = 0
        self.bytes_written = 0
        self._aborted = False

    def _ensure_session(self) -> None:
        if self._session is not None:
            return
        url = (f"{self._b.endpoint}/upload/storage/v1/b/"
               f"{urllib.parse.quote(self._b.bucket, safe='')}/o?"
               + urllib.parse.urlencode({"uploadType": "resumable", "name": self._key}))
        _, _, headers = self._b._request(
            "POST", url, data=b"",
            headers={"Content-Type": "application/octet-stream",
                     "X-Upload-Content-Type": "application/octet-stream"},
        )
        loc = headers.get("Location") or headers.get("location")
        if not loc:
            raise BackendError("gcs resumable upload: no session Location")
        self._session = loc

    def append(self, data: bytes) -> None:
        self._buf.extend(data)
        self.bytes_written += len(data)
        while len(self._buf) >= _RESUMABLE_CHUNK:
            self._flush_chunk(final_total=None)

    def _flush_chunk(self, final_total: int | None) -> None:
        self._ensure_session()
        if final_total is None:
            take = (len(self._buf) // (256 << 10)) * (256 << 10)
            chunk = bytes(self._buf[:take])
            total = "*"
        else:
            chunk = bytes(self._buf)
            total = str(final_total)
        start = self._flushed
        hdrs = {"Content-Type": "application/octet-stream"}
        if chunk:
            hdrs["Content-Range"] = f"bytes {start}-{start + len(chunk) - 1}/{total}"
        else:
            hdrs["Content-Range"] = f"bytes */{total}"
        self._b._request("PUT", self._session, data=chunk, headers=hdrs)
        self._flushed += len(chunk)
        del self._buf[: len(chunk)]

    def close(self) -> None:
        if self._aborted:
            return
        self._flush_chunk(final_total=self._flushed + len(self._buf))

    def abort(self) -> None:
        self._aborted = True
        self._buf.clear()
        if self._session:
            try:  # cancel the session; orphaned sessions expire anyway
                self._b._request("DELETE", self._session, ok_statuses=(200, 204, 499))
            except BackendError:
                pass
