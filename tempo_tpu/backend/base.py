"""Object-store backend abstraction.

One flat interface per the reference's RawReader/RawWriter/RawCompactor
seam (tempodb/backend/raw.go:55-133, backend.go:22-66): named objects
under <tenant>/<block uuid>/<name>, plus tenant-level objects (the
per-tenant blocklist index), list operations, and the compacted-marker
protocol (meta.json renamed to meta.compacted.json, as the local/gcs
compactors do).
"""

from __future__ import annotations

import abc
from dataclasses import dataclass

META_NAME = "meta.json"
COMPACTED_META_NAME = "meta.compacted.json"
TENANT_INDEX_NAME = "index.json.gz"


class BackendError(Exception):
    pass


class DoesNotExist(BackendError):
    pass


@dataclass(frozen=True)
class CompactedMarker:
    block_id: str
    compacted_at_unix: float


def block_object_path(tenant: str, block_id: str, name: str) -> str:
    return f"{tenant}/{block_id}/{name}"


def meta_name(compacted: bool = False) -> str:
    return COMPACTED_META_NAME if compacted else META_NAME


class Appender:
    """Incremental object writer (reference: backend.AppendTracker,
    tempodb/backend/raw.go). Default buffers parts and issues one write
    on close; backends with native append (local files) override
    open_append for true streamed flushes."""

    def __init__(self, backend: "RawBackend", tenant: str, block_id: str, name: str):
        self._backend = backend
        self._tenant = tenant
        self._block_id = block_id
        self._name = name
        self._parts: list[bytes] = []
        self.bytes_written = 0

    def append(self, data: bytes) -> None:
        self._parts.append(data)
        self.bytes_written += len(data)

    def close(self) -> None:
        self._backend.write(self._tenant, self._block_id, self._name, b"".join(self._parts))
        self._parts = []

    def abort(self) -> None:
        """Discard everything appended so far; nothing is written."""
        self._parts = []


class RawBackend(abc.ABC):
    """Reader+writer+compactor over raw named objects."""

    # True when reads cross a network (object stores): IO waits release
    # the GIL, so thread-pool fan-out overlaps them even on one core.
    # Local/mem backends override to False -- there a 1-core box gains
    # nothing from pool handoffs (db/search gates its pools on this).
    is_remote = True

    # ---- write
    @abc.abstractmethod
    def write(self, tenant: str, block_id: str, name: str, data: bytes) -> None: ...

    def open_append(self, tenant: str, block_id: str, name: str) -> Appender:
        return Appender(self, tenant, block_id, name)

    def copy_object(self, tenant: str, src_block_id: str, name: str,
                    dst_block_id: str) -> int:
        """Copy one immutable object between blocks of the same tenant,
        backend-side where the store supports it (local backend:
        hardlink; S3: CopyObject; others fall back here). Default: read
        + write through the client. Returns bytes copied, or -1 when
        the backend copied server-side without learning the size. The
        concat compactor's verbatim part copies ride this, so
        "compacting" a small block never moves its bytes through Python
        when the backend can copy server-side."""
        data = self.read(tenant, src_block_id, name)
        self.write(tenant, dst_block_id, name, data)
        return len(data)

    @abc.abstractmethod
    def write_tenant_object(self, tenant: str, name: str, data: bytes) -> None: ...

    # ---- read
    @abc.abstractmethod
    def read(self, tenant: str, block_id: str, name: str) -> bytes: ...

    @abc.abstractmethod
    def read_range(self, tenant: str, block_id: str, name: str, offset: int, length: int) -> bytes: ...

    @abc.abstractmethod
    def read_tenant_object(self, tenant: str, name: str) -> bytes: ...

    # ---- list
    @abc.abstractmethod
    def tenants(self) -> list[str]: ...

    @abc.abstractmethod
    def blocks(self, tenant: str) -> list[str]:
        """Block UUIDs that have either a live or a compacted meta."""

    # ---- delete
    @abc.abstractmethod
    def delete_block(self, tenant: str, block_id: str) -> None: ...

    @abc.abstractmethod
    def delete_tenant_object(self, tenant: str, name: str) -> None: ...

    # ---- compacted-marker protocol
    def mark_compacted(self, tenant: str, block_id: str) -> None:
        """Rename meta.json -> meta.compacted.json, stamping the mark
        time (reference: CompactedBlockMeta.CompactedTime) so
        compacted-retention measures from when the block was marked,
        not from its data window."""
        import json
        import time as _time

        try:
            data = self.read(tenant, block_id, META_NAME)
        except DoesNotExist:
            # idempotent: a concurrent compactor/retention sweep (or a
            # grace-window double-selection) already marked this block
            if self.has_object(tenant, block_id, COMPACTED_META_NAME):
                return
            # parts of a compound block carry no meta.json of their own
            # (their meta lives in the compound's parts list): marking
            # one writes a minimal stamped marker the poller's expansion
            # understands (db/blocklist.py). ONLY parts: fabricating a
            # marker for an ordinary missing block would resurrect a
            # fully-deleted block as a phantom grace-searchable entry.
            if "/" in block_id:
                data = json.dumps({"block_id": block_id, "tenant_id": tenant,
                                   "compacted_at_unix": _time.time()},
                                  separators=(",", ":")).encode()
                self.write(tenant, block_id, COMPACTED_META_NAME, data)
                return
            raise
        try:
            d = json.loads(data)
            d["compacted_at_unix"] = _time.time()
            data = json.dumps(d, separators=(",", ":")).encode()
        except (ValueError, TypeError):
            pass  # unparseable meta: keep the verbatim-copy rename
        self.write(tenant, block_id, COMPACTED_META_NAME, data)
        self._delete_object(tenant, block_id, META_NAME)

    def has_object(self, tenant: str, block_id: str, name: str) -> bool:
        try:
            self.read(tenant, block_id, name)
            return True
        except DoesNotExist:
            return False

    @abc.abstractmethod
    def _delete_object(self, tenant: str, block_id: str, name: str) -> None: ...
