"""Search execution: tag / TraceQL queries -> device filter plan -> results.

The per-block pipeline (analog of vparquet/block_search.go:78-116 +
block_traceql.go Fetch): the traceql planner resolves strings through
the block dictionary (a miss prunes the whole block -- the dictionary IS
the page-dictionary pre-filter of parquetquery predicates.go:38-89) and
emits a trace-level condition tree; ops.filter evaluates it over staged
columns; surviving trace candidates are exactly re-verified host-side
for time/duration (device encodings are conservative)."""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from ..block.reader import BackendBlock
from ..ops.filter import Operands, eval_block, required_columns
from ..ops.stage import stage_block
from ..traceql.plan import plan_search_request
from ..util.distinct import DistinctStringCollector

DEFAULT_LIMIT = 20
_STREAM_MIN_GROUPS = 8  # blocks larger than this stream chunks through device

_INTRINSIC_NAME = "name"
_WELL_KNOWN_RES = {
    "service.name": "res.service_id",
    "k8s.cluster.name": "res.cluster_id",
    "k8s.namespace.name": "res.namespace_id",
    "k8s.pod.name": "res.pod_id",
    "k8s.container.name": "res.container_id",
}


@dataclass
class SearchRequest:
    tags: dict[str, str] = field(default_factory=dict)
    min_duration_ms: int = 0
    max_duration_ms: int = 0
    start: int = 0  # unix seconds, 0 = unbounded
    end: int = 0
    limit: int = DEFAULT_LIMIT
    query: str = ""  # TraceQL spanset filter


@dataclass
class SearchResult:
    trace_id: str  # hex
    root_service_name: str
    root_trace_name: str
    start_time_unix_nano: int
    duration_ms: int
    matched_spans: int = 0

    def to_dict(self) -> dict:
        return {
            "traceID": self.trace_id,
            "rootServiceName": self.root_service_name,
            "rootTraceName": self.root_trace_name,
            "startTimeUnixNano": str(self.start_time_unix_nano),
            "durationMs": self.duration_ms,
        }


@dataclass
class SearchResponse:
    traces: list[SearchResult] = field(default_factory=list)
    inspected_bytes: int = 0
    inspected_spans: int = 0

    def merge(self, other: "SearchResponse", limit: int) -> None:
        seen = {t.trace_id for t in self.traces}
        for t in other.traces:
            if t.trace_id not in seen and len(self.traces) < limit:
                self.traces.append(t)
                seen.add(t.trace_id)
        self.inspected_bytes += other.inspected_bytes
        self.inspected_spans += other.inspected_spans


def _plan_for_block(blk: BackendBlock, req: SearchRequest):
    start_rel = None
    if req.start or req.end:
        base_ms = blk.meta.start_time_unix_nano // 1_000_000
        lo = (req.start * 1000 - base_ms - 1) if req.start else -(2**31)
        hi = (req.end * 1000 - base_ms + 1) if req.end else 2**31 - 1
        start_rel = (
            int(np.clip(lo, -(2**31), 2**31 - 1)),
            int(np.clip(hi, -(2**31), 2**31 - 1)),
        )
    return plan_search_request(
        blk.dictionary,
        req.tags,
        query=req.query,
        min_duration_ms=req.min_duration_ms,
        max_duration_ms=req.max_duration_ms,
        start_rel_ms=start_rel,
    )


def _verify_and_build(
    blk: BackendBlock, req: SearchRequest, sids: np.ndarray, counts: np.ndarray
) -> list[SearchResult]:
    """Exact host re-check of time/duration + result materialization from
    the cached trace-level index."""
    ti = blk.trace_index
    d = blk.dictionary
    out = []
    for sid in sids:
        start_ns = int(ti["trace.start_ns"][sid])
        end_ns = int(ti["trace.end_ns"][sid])
        dur_ms = max(0, (end_ns - start_ns) // 1_000_000)
        if req.min_duration_ms and dur_ms < req.min_duration_ms:
            continue
        if req.max_duration_ms and dur_ms > req.max_duration_ms:
            continue
        if req.start and start_ns < req.start * 1_000_000_000:
            continue
        if req.end and start_ns > req.end * 1_000_000_000:
            continue
        out.append(
            SearchResult(
                trace_id=ti["trace.id"][sid].tobytes().hex(),
                root_service_name=d.string(int(ti["trace.root_service_id"][sid])),
                root_trace_name=d.string(int(ti["trace.root_name_id"][sid])),
                start_time_unix_nano=start_ns,
                duration_ms=dur_ms,
                matched_spans=int(counts[sid]),
            )
        )
    return out


def search_block(
    blk: BackendBlock,
    req: SearchRequest,
    groups_range: list[int] | None = None,
) -> SearchResponse:
    """Search one block (optionally one row-group shard of it)."""
    resp = SearchResponse()
    if not blk.meta.overlaps_time(req.start, req.end):
        return resp
    planned = _plan_for_block(blk, req)
    if planned.prune:
        return resp
    operands = Operands.build(planned.rows, planned.tables or None)
    needed = required_columns(planned.conds)
    span_ax = blk.pack.axes.get("span")
    n_groups = len(groups_range) if groups_range is not None else (
        span_ax.n_groups if span_ax else 1
    )
    if n_groups > _STREAM_MIN_GROUPS:
        # large scan: stream row-group chunks, prefetching the next chunk's
        # IO while the device filters the current one (ops/stream.py)
        from ..ops.stream import eval_block_streamed

        trace_mask, counts, n_spans_seen = eval_block_streamed(
            blk, needed, (planned.tree, planned.conds), operands, groups=groups_range
        )
        sids = np.nonzero(trace_mask)[0]
    else:
        staged = stage_block(blk, needed, groups=groups_range)
        _, trace_mask, counts = eval_block(
            (planned.tree, planned.conds),
            staged.cols,
            operands,
            staged.n_spans,
            staged.n_traces,
            staged.n_spans_b,
            staged.n_res_b,
            staged.n_traces_b,
        )
        counts = np.asarray(counts)
        n_spans_seen = staged.n_spans
        sids = np.nonzero(np.asarray(trace_mask)[: staged.n_traces])[0]
    if planned.needs_verify and req.query and len(sids):
        # device filter was conservative (clamped encodings / mixed OR):
        # exact host re-check of each candidate (hosteval.py)
        from ..traceql.hosteval import trace_matches
        from ..traceql.parser import parse

        q = parse(req.query)
        traces = blk.materialize_traces([int(s) for s in sids])
        sids = np.asarray(
            [s for s, tr in zip(sids, traces) if tr is not None and trace_matches(q, tr)],
            dtype=np.int64,
        )
    results = _verify_and_build(blk, req, sids, counts)
    results.sort(key=lambda r: -r.start_time_unix_nano)
    resp.traces = results[: req.limit]
    resp.inspected_spans = n_spans_seen
    resp.inspected_bytes = blk.pack.bytes_read
    return resp


# ---- tag name/value discovery (reference: /api/search/tags endpoints)


def search_tags(blk: BackendBlock, collector: DistinctStringCollector) -> None:
    d = blk.dictionary
    for col in ("sattr.key_id", "rattr.key_id"):
        codes = np.unique(blk.pack.read(col))
        for c in codes:
            if c >= 0:
                collector.collect(d.string(int(c)))
    # well-known resource attrs live only in dedicated columns
    for tag, col in _WELL_KNOWN_RES.items():
        if blk.pack.has(col) and (blk.pack.read(col) >= 0).any():
            collector.collect(tag)


def search_tag_values(blk: BackendBlock, tag: str, collector: DistinctStringCollector) -> None:
    d = blk.dictionary
    kcode = d.lookup(tag)
    if tag == _INTRINSIC_NAME:
        for c in np.unique(blk.pack.read("span.name_id")):
            if c >= 0:
                collector.collect(d.string(int(c)))
        return
    ded = _WELL_KNOWN_RES.get(tag)
    if ded and blk.pack.has(ded):
        for c in np.unique(blk.pack.read(ded)):
            if c >= 0:
                collector.collect(d.string(int(c)))
    if kcode < 0:
        return
    for pre in ("sattr", "rattr"):
        keys = blk.pack.read(f"{pre}.key_id")
        mask = keys == kcode
        if not mask.any():
            continue
        vt = blk.pack.read(f"{pre}.vtype")[mask]
        sid = blk.pack.read(f"{pre}.str_id")[mask]
        i64 = blk.pack.read(f"{pre}.int64")[mask]
        for j in range(len(vt)):
            if vt[j] == 0:
                collector.collect(d.string(int(sid[j])))
            elif vt[j] == 1:
                collector.collect(str(int(i64[j])))
            elif vt[j] == 3:
                collector.collect("true" if i64[j] else "false")
