"""Search execution: tag queries -> device filter plan -> results.

The per-block pipeline (analog of vparquet/block_search.go:78-116 +
makePipelineWithRowGroups): resolve strings through the block dictionary
(a miss prunes the whole block -- the dictionary IS the page-level
dictionary pre-filter of parquetquery predicates.go:38-89), build
condition groups (each tag ORs across span attrs / resource attrs /
dedicated columns), run ops.filter.eval_block over staged columns, then
exactly re-verify time/duration on host trace columns (device encodings
are conservative; see ops/filter.py).
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from ..block.reader import BackendBlock
from ..ops.filter import Cond, Operands, eval_block, required_columns
from ..ops.stage import stage_block
from ..util.distinct import DistinctStringCollector

DEFAULT_LIMIT = 20


@dataclass
class SearchRequest:
    tags: dict[str, str] = field(default_factory=dict)
    min_duration_ms: int = 0
    max_duration_ms: int = 0
    start: int = 0  # unix seconds, 0 = unbounded
    end: int = 0
    limit: int = DEFAULT_LIMIT
    query: str = ""  # TraceQL (planned by traceql/ when set)


@dataclass
class SearchResult:
    trace_id: str  # hex
    root_service_name: str
    root_trace_name: str
    start_time_unix_nano: int
    duration_ms: int

    def to_dict(self) -> dict:
        return {
            "traceID": self.trace_id,
            "rootServiceName": self.root_service_name,
            "rootTraceName": self.root_trace_name,
            "startTimeUnixNano": str(self.start_time_unix_nano),
            "durationMs": self.duration_ms,
        }


@dataclass
class SearchResponse:
    traces: list[SearchResult] = field(default_factory=list)
    inspected_bytes: int = 0
    inspected_spans: int = 0

    def merge(self, other: "SearchResponse", limit: int) -> None:
        seen = {t.trace_id for t in self.traces}
        for t in other.traces:
            if t.trace_id not in seen and len(self.traces) < limit:
                self.traces.append(t)
                seen.add(t.trace_id)
        self.inspected_bytes += other.inspected_bytes
        self.inspected_spans += other.inspected_spans


_INTRINSIC_NAME = "name"
_WELL_KNOWN_SPAN_STR = {"http.method": "span.http_method_id", "http.url": "span.http_url_id"}
_WELL_KNOWN_RES = {
    "service.name": "res.service_id",
    "k8s.cluster.name": "res.cluster_id",
    "k8s.namespace.name": "res.namespace_id",
    "k8s.pod.name": "res.pod_id",
    "k8s.container.name": "res.container_id",
}


def plan_tags(blk: BackendBlock, req: SearchRequest):
    """-> (groups, operand_rows) or None when the block can be pruned."""
    d = blk.dictionary
    groups: list[tuple[Cond, ...]] = []
    rows: list[tuple[int, int, int, float, float]] = []

    for key, value in req.tags.items():
        alts: list[Cond] = []
        arows: list[tuple] = []
        if key == _INTRINSIC_NAME:
            code = d.lookup(value)
            if code >= 0:
                alts.append(Cond(target="span", col="span.name_id", op="eq"))
                arows.append((0, code, 0, 0.0, 0.0))
        else:
            scode = d.lookup(value)
            kcode = d.lookup(key)
            if scode >= 0:
                ded = _WELL_KNOWN_SPAN_STR.get(key)
                if ded:
                    alts.append(Cond(target="span", col=ded, op="eq"))
                    arows.append((0, scode, 0, 0.0, 0.0))
                dedr = _WELL_KNOWN_RES.get(key)
                if dedr:
                    alts.append(Cond(target="res", col=dedr, op="eq"))
                    arows.append((0, scode, 0, 0.0, 0.0))
            if kcode >= 0:
                if scode >= 0:
                    alts.append(Cond(target="sattr", col="str", op="eq"))
                    arows.append((kcode, scode, 0, 0.0, 0.0))
                    alts.append(Cond(target="rattr", col="str", op="eq"))
                    arows.append((kcode, scode, 0, 0.0, 0.0))
                # numeric / bool forms of the value
                try:
                    iv = int(value)
                    alts.append(Cond(target="sattr", col="int", op="eq"))
                    arows.append((kcode, iv, 0, 0.0, 0.0))
                    alts.append(Cond(target="rattr", col="int", op="eq"))
                    arows.append((kcode, iv, 0, 0.0, 0.0))
                except ValueError:
                    pass
                if value in ("true", "false"):
                    bv = 1 if value == "true" else 0
                    alts.append(Cond(target="sattr", col="bool", op="eq"))
                    arows.append((kcode, bv, 0, 0.0, 0.0))
                    alts.append(Cond(target="rattr", col="bool", op="eq"))
                    arows.append((kcode, bv, 0, 0.0, 0.0))
        if not alts:
            return None  # no way this block matches this tag
        groups.append(tuple(alts))
        rows.extend(arows)

    # coarse duration / time-range conditions (exact-verified host-side)
    if req.min_duration_ms or req.max_duration_ms:
        lo = req.min_duration_ms * 1000 if req.min_duration_ms else 0
        hi = req.max_duration_ms * 1000 if req.max_duration_ms else 2**31 - 1
        groups.append((Cond(target="trace", col="trace.dur_us", op="range", needs_verify=True),))
        rows.append((0, max(0, lo - 1), min(2**31 - 1, hi + 1), 0.0, 0.0))
    if req.start or req.end:
        base_ms = blk.meta.start_time_unix_nano // 1_000_000
        lo = (req.start * 1000 - base_ms - 1) if req.start else -(2**31)
        hi = (req.end * 1000 - base_ms + 1) if req.end else 2**31 - 1
        lo = int(np.clip(lo, -(2**31), 2**31 - 1))
        hi = int(np.clip(hi, -(2**31), 2**31 - 1))
        groups.append((Cond(target="trace", col="trace.start_ms", op="range", needs_verify=True),))
        rows.append((0, lo, hi, 0.0, 0.0))

    return tuple(groups), rows


def _verify_and_build(blk: BackendBlock, req: SearchRequest, sids: np.ndarray) -> list[SearchResult]:
    """Exact host re-check of time/duration + result materialization from
    the cached trace-level index."""
    ti = blk.trace_index
    d = blk.dictionary
    out = []
    for sid in sids:
        start_ns = int(ti["trace.start_ns"][sid])
        end_ns = int(ti["trace.end_ns"][sid])
        dur_ms = max(0, (end_ns - start_ns) // 1_000_000)
        if req.min_duration_ms and dur_ms < req.min_duration_ms:
            continue
        if req.max_duration_ms and dur_ms > req.max_duration_ms:
            continue
        if req.start and start_ns < req.start * 1_000_000_000:
            continue
        if req.end and start_ns > req.end * 1_000_000_000:
            continue
        out.append(
            SearchResult(
                trace_id=ti["trace.id"][sid].tobytes().hex(),
                root_service_name=d.string(int(ti["trace.root_service_id"][sid])),
                root_trace_name=d.string(int(ti["trace.root_name_id"][sid])),
                start_time_unix_nano=start_ns,
                duration_ms=dur_ms,
            )
        )
    return out


def search_block(
    blk: BackendBlock,
    req: SearchRequest,
    groups_range: list[int] | None = None,
) -> SearchResponse:
    """Search one block (optionally one row-group shard of it)."""
    resp = SearchResponse()
    if not blk.meta.overlaps_time(req.start, req.end):
        return resp
    plan = plan_tags(blk, req)
    if plan is None:
        return resp
    cond_groups, rows = plan
    staged = stage_block(blk, required_columns(cond_groups), groups=groups_range)
    operands = Operands.build(rows)
    _, trace_mask, _ = eval_block(
        cond_groups,
        "and",
        staged.cols,
        operands,
        staged.n_spans,
        staged.n_traces,
        staged.n_spans_b,
        staged.n_res_b,
        staged.n_traces_b,
    )
    sids = np.nonzero(np.asarray(trace_mask)[: staged.n_traces])[0]
    results = _verify_and_build(blk, req, sids)
    results.sort(key=lambda r: -r.start_time_unix_nano)
    resp.traces = results[: req.limit]
    resp.inspected_spans = staged.n_spans
    resp.inspected_bytes = blk.pack.bytes_read
    return resp


# ---- tag name/value discovery (reference: /api/search/tags endpoints)


def search_tags(blk: BackendBlock, collector: DistinctStringCollector) -> None:
    d = blk.dictionary
    for col in ("sattr.key_id", "rattr.key_id"):
        codes = np.unique(blk.pack.read(col))
        for c in codes:
            if c >= 0:
                collector.collect(d.string(int(c)))
    # well-known resource attrs live only in dedicated columns
    for tag, col in _WELL_KNOWN_RES.items():
        if blk.pack.has(col) and (blk.pack.read(col) >= 0).any():
            collector.collect(tag)


def search_tag_values(blk: BackendBlock, tag: str, collector: DistinctStringCollector) -> None:
    d = blk.dictionary
    kcode = d.lookup(tag)
    if tag == _INTRINSIC_NAME:
        for c in np.unique(blk.pack.read("span.name_id")):
            if c >= 0:
                collector.collect(d.string(int(c)))
        return
    ded = _WELL_KNOWN_RES.get(tag)
    if ded and blk.pack.has(ded):
        for c in np.unique(blk.pack.read(ded)):
            if c >= 0:
                collector.collect(d.string(int(c)))
    if kcode < 0:
        return
    for pre in ("sattr", "rattr"):
        keys = blk.pack.read(f"{pre}.key_id")
        mask = keys == kcode
        if not mask.any():
            continue
        vt = blk.pack.read(f"{pre}.vtype")[mask]
        sid = blk.pack.read(f"{pre}.str_id")[mask]
        i64 = blk.pack.read(f"{pre}.int64")[mask]
        for j in range(len(vt)):
            if vt[j] == 0:
                collector.collect(d.string(int(sid[j])))
            elif vt[j] == 1:
                collector.collect(str(int(i64[j])))
            elif vt[j] == 3:
                collector.collect("true" if i64[j] else "false")
