"""Search execution: tag / TraceQL queries -> filter plan -> results.

The per-block pipeline (analog of vparquet/block_search.go:78-116 +
block_traceql.go Fetch): the traceql planner resolves strings through
the block dictionary (a miss prunes the whole block -- the dictionary IS
the page-dictionary pre-filter of parquetquery predicates.go:38-89) and
emits a trace-level condition tree; the filter evaluates it over the
block's columns; the top `limit` candidates BY TRACE START TIME are
selected before any host materialization (ops/select.py), and only
those are exactly re-verified (device encodings are conservative).

Two execution engines share the plan + verify contract:
  - device (ops/filter + ops/stage): staged padded columns, jit kernel,
    on-device top-k -- ONE small fetch per query. The production path
    for hot (cached/pinned) blocks; cost is O(limit), not O(matches).
  - host (ops/hostfilter): vectorized numpy over raw columns, for cold
    one-shot scans where upload + dispatch round trips exceed the scan.
"""

from __future__ import annotations

import os
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass, field

import numpy as np

_N_CPU = os.cpu_count() or 2

from ..block import schema as S
from ..block.reader import BackendBlock
from ..ops.filter import (
    Operands,
    T_RATTR,
    T_RES,
    T_SPAN,
    T_TRACE,
    _ATTR_VALUE_COL,
    eval_block,
    required_columns,
)
from ..ops.hostfilter import eval_block_host
from ..ops.select import (
    k_bucket,
    select_topk_device,
    select_topk_device_multi,
    select_topk_host,
    select_topk_host_multi,
)
from ..ops.stage import stage_block
from ..traceql.plan import plan_search_request
from ..util.distinct import DistinctStringCollector

DEFAULT_LIMIT = 20
# stream row-group chunks only when the staged columns would exceed this
# (bounds device memory); below it a single staged eval wins -- one kernel
# dispatch + one result transfer instead of one per chunk, which matters
# when host<->device latency is high
_STREAM_MIN_STAGE_BYTES = 512 << 20

_INTRINSIC_NAME = "name"
_WELL_KNOWN_RES = {
    "service.name": "res.service_id",
    "k8s.cluster.name": "res.cluster_id",
    "k8s.namespace.name": "res.namespace_id",
    "k8s.pod.name": "res.pod_id",
    "k8s.container.name": "res.container_id",
}

# column IO for the host evaluation path (reads overlap across columns;
# shared across queries -- each read is one ranged GET + zstd decode)
_host_io_pool = ThreadPoolExecutor(max_workers=8, thread_name_prefix="search-io")

# ---------------------------------------------------------------- engine cost
# The device engine costs ~one link round trip per query (fused select's
# single fetch) regardless of block count; the host engine costs
# bytes/rate with ZERO round trips (cost model shared with the
# generator's reduce: util/linkcost.py). A host-rate EMA updated by
# every cold host-engine block scan completes the estimate.
from ..util.linkcost import link_rtt_ms as _link_rtt_ms

_HOST_RATE_BPS: float = 1.5e9  # EMA, seeded at DDR-ish single-core scan rate
_HOST_RATE_SEEDED = False  # ledger seed applied (once per process)


def _note_host_rate(n_bytes: int, seconds: float) -> None:
    global _HOST_RATE_BPS
    if seconds > 1e-5 and n_bytes > (1 << 20):
        # lossy EMA on the hot host-scan path: racing writers converge
        # on the same steady state and a lock would serialize every scan
        # tempo: ignore[global-mutation-unlocked] intentional lock-free EMA
        _HOST_RATE_BPS = 0.7 * _HOST_RATE_BPS + 0.3 * (n_bytes / seconds)


def seed_host_rate_from_ledger() -> None:
    """Seed the cold-scan host-rate EMA from the CostLedger's measured
    block_scan entry (tempo-tpu-cli calibrate) instead of the DDR-ish
    constant -- the first routing decisions of a fresh process then
    start from THIS box's measured scan rate. Later scans keep updating
    the EMA as before; called once by TempoDB init (idempotent)."""
    global _HOST_RATE_BPS, _HOST_RATE_SEEDED
    if _HOST_RATE_SEEDED:
        return
    # racing initializers write the same ledger value
    # tempo: ignore[global-mutation-unlocked] once-at-init seed
    _HOST_RATE_SEEDED = True
    try:
        from ..util.costledger import KEY_BLOCK_SCAN, ledger

        entry = ledger().get(KEY_BLOCK_SCAN)
        rate = float(entry.get("host_rate_bps", 0.0)) if entry else 0.0
        if rate > 0:
            # tempo: ignore[global-mutation-unlocked] same seed-once write
            _HOST_RATE_BPS = rate
    except Exception:
        pass  # routing falls back to the constant seed


@dataclass
class SearchRequest:
    tags: dict[str, str] = field(default_factory=dict)
    min_duration_ms: int = 0
    max_duration_ms: int = 0
    start: int = 0  # unix seconds, 0 = unbounded
    end: int = 0
    limit: int = DEFAULT_LIMIT
    query: str = ""  # TraceQL spanset filter


@dataclass
class SearchResult:
    trace_id: str  # hex
    root_service_name: str
    root_trace_name: str
    start_time_unix_nano: int
    duration_ms: int
    matched_spans: int = 0

    def to_dict(self) -> dict:
        return {
            "traceID": self.trace_id,
            "rootServiceName": self.root_service_name,
            "rootTraceName": self.root_trace_name,
            "startTimeUnixNano": str(self.start_time_unix_nano),
            "durationMs": self.duration_ms,
        }


@dataclass
class SearchResponse:
    traces: list[SearchResult] = field(default_factory=list)
    inspected_bytes: int = 0
    inspected_spans: int = 0

    def merge(self, other: "SearchResponse", limit: int) -> None:
        seen = {t.trace_id for t in self.traces}
        for t in other.traces:
            if t.trace_id not in seen and len(self.traces) < limit:
                self.traces.append(t)
                seen.add(t.trace_id)
        self.inspected_bytes += other.inspected_bytes
        self.inspected_spans += other.inspected_spans


# ---- wire forms (the internal-API serialization both the remote job
# plane and the ingester client speak)


def request_to_dict(req: SearchRequest) -> dict:
    return {
        "tags": req.tags,
        "query": req.query,
        "min_duration_ms": req.min_duration_ms,
        "max_duration_ms": req.max_duration_ms,
        "start": req.start,
        "end": req.end,
        "limit": req.limit,
    }


def request_from_dict(d: dict) -> SearchRequest:
    return SearchRequest(
        tags=d.get("tags", {}),
        query=d.get("query", ""),
        min_duration_ms=d.get("min_duration_ms", 0),
        max_duration_ms=d.get("max_duration_ms", 0),
        start=d.get("start", 0),
        end=d.get("end", 0),
        limit=d.get("limit", DEFAULT_LIMIT),
    )


def response_to_dict(resp: SearchResponse) -> dict:
    return {
        "traces": [
            {**t.to_dict(), "matchedSpans": t.matched_spans} for t in resp.traces
        ],
        "inspectedBytes": resp.inspected_bytes,
        "inspectedSpans": resp.inspected_spans,
    }


def response_from_dict(d: dict) -> SearchResponse:
    resp = SearchResponse()
    resp.inspected_bytes = d.get("inspectedBytes", 0)
    resp.inspected_spans = d.get("inspectedSpans", 0)
    for t in d.get("traces", []):
        resp.traces.append(
            SearchResult(
                trace_id=t["traceID"],
                root_service_name=t.get("rootServiceName", ""),
                root_trace_name=t.get("rootTraceName", ""),
                start_time_unix_nano=int(t.get("startTimeUnixNano", "0")),
                duration_ms=t.get("durationMs", 0),
                matched_spans=t.get("matchedSpans", 0),
            )
        )
    return resp


def _plan_for_block(blk: BackendBlock, req: SearchRequest, allow_struct: bool = True):
    start_rel = None
    if req.start or req.end:
        base_ms = blk.meta.start_time_unix_nano // 1_000_000
        lo = (req.start * 1000 - base_ms - 1) if req.start else -(2**31)
        hi = (req.end * 1000 - base_ms + 1) if req.end else 2**31 - 1
        start_rel = (
            int(np.clip(lo, -(2**31), 2**31 - 1)),
            int(np.clip(hi, -(2**31), 2**31 - 1)),
        )
    # struct nodes need the block to carry the parent-row column
    # (pre-upgrade blocks don't)
    allow_struct = allow_struct and blk.pack.has("span.parent_idx")
    return plan_search_request(
        blk.dictionary,
        req.tags,
        query=req.query,
        min_duration_ms=req.min_duration_ms,
        max_duration_ms=req.max_duration_ms,
        start_rel_ms=start_rel,
        allow_struct=allow_struct,
    )


# --------------------------------------------------- candidate selection


def _start_key_host(blk: BackendBlock) -> np.ndarray:
    """trace.start_ms column (the top-k selection key), cached on the
    immutable block."""
    key = getattr(blk, "_start_key_host", None)
    if key is None:
        key = blk._start_key_host = blk.pack.read("trace.start_ms")
    return key


def _start_key_dev(blk: BackendBlock, nb: int):
    key = getattr(blk, "_start_key_dev", None)
    if key is None or key.shape[0] != nb:
        import jax.numpy as jnp

        from ..ops.device import pad_rows

        key = jnp.asarray(pad_rows(_start_key_host(blk), nb, np.int32(0)))
        blk._start_key_dev = key
    return key


def _verify_candidates(blk: BackendBlock, req: SearchRequest, sids, needs_verify: bool):
    """Exact host re-check of TraceQL candidates when the device filter
    was conservative. Bounded: callers pass at most the escalation k."""
    if not (needs_verify and req.query and len(sids)):
        return sids
    import time as _time

    from ..traceql.hosteval import trace_matches
    from ..traceql.parser import parse
    from ..util.kerneltel import TEL

    t0_wall = _time.time()
    q = parse(req.query)
    traces = blk.materialize_traces([int(s) for s in sids])
    out = np.asarray(
        [s for s, tr in zip(sids, traces) if tr is not None and trace_matches(q, tr)],
        dtype=np.int64,
    )
    # timeline + cost: the exact-verify leg (conservative device mask ->
    # host re-check) of this block's evaluation
    TEL.child_span("verify", t0_wall, _time.time(),
                   {"block": blk.meta.block_id[:8],
                    "rows": int(len(sids)), "kept": int(out.shape[0])})
    TEL.add_query_cost("rows_verified", int(len(sids)))
    return out


def _candidates(
    blk: BackendBlock, req: SearchRequest, sids: list[int], counts: dict[int, int]
) -> list[tuple]:
    """Exact host re-check of time/duration + LIGHTWEIGHT candidate
    records (start_ns, trace_id hex, dur_ms, matched, blk, sid):
    everything the global merge sorts/dedupes on, with the dictionary
    lookups + SearchResult construction deferred to the winners
    (_materialize). O(len(sids)) -- callers cap it at the escalation k,
    never the full match count."""
    ti = blk.search_index
    if not len(sids):
        return []
    # vectorized over the candidate set (up to the escalation k): the
    # per-sid scalar loop cost more than the selection it followed
    sa = np.asarray(sids, dtype=np.int64)
    start_ns = ti["trace.start_ns"][sa].astype(np.int64)
    end_ns = ti["trace.end_ns"][sa].astype(np.int64)
    dur_ms = np.maximum(0, (end_ns - start_ns) // 1_000_000)
    keep = np.ones(sa.shape[0], dtype=bool)
    if req.min_duration_ms:
        keep &= dur_ms >= req.min_duration_ms
    if req.max_duration_ms:
        keep &= dur_ms <= req.max_duration_ms
    if req.start:
        keep &= start_ns >= req.start * 1_000_000_000
    if req.end:
        keep &= start_ns <= req.end * 1_000_000_000
    ka = sa[keep]
    # one hex() over the packed id rows, sliced per 16-byte id
    blob = np.ascontiguousarray(ti["trace.id"][ka]).tobytes().hex()
    ids_hex = [blob[i * 32 : (i + 1) * 32] for i in range(ka.shape[0])]
    return [
        (s, h, d, int(counts.get(sid, 0)), blk, sid)
        for s, h, d, sid in zip(start_ns[keep].tolist(), ids_hex,
                                dur_ms[keep].tolist(), ka.tolist())
    ]


def _materialize(cand: tuple) -> SearchResult:
    """One candidate record -> wire SearchResult (the deferred
    dictionary/materialization half of _candidates)."""
    start_ns, tid_hex, dur_ms, cnt, blk, sid = cand
    ti = blk.search_index
    d = blk.dictionary
    return SearchResult(
        trace_id=tid_hex,
        root_service_name=d.string(int(ti["trace.root_service_id"][sid])),
        root_trace_name=d.string(int(ti["trace.root_name_id"][sid])),
        start_time_unix_nano=start_ns,
        duration_ms=dur_ms,
        matched_spans=cnt,
    )




def _collect_topk(blk: BackendBlock, req: SearchRequest, needs_verify: bool,
                  selector, limit: int, materialize: bool = True):
    """Escalating top-k collect: select k candidates (newest first),
    verify exactly, and only widen k when verification rejected enough
    to fall short of the limit. selector(k) -> (sids, counts, n_match).
    materialize=False returns candidate records (_candidates) for a
    caller doing its own global merge -- the fused engine materializes
    only the cross-block winners."""
    nt = blk.meta.total_traces
    if nt == 0:
        return []
    k = min(k_bucket(max(2 * limit, 32)), nt)
    out: list = []
    seen: set[int] = set()
    while True:
        sids, cnts, n_match = selector(k)
        fresh = [(int(s), int(c)) for s, c in zip(sids, cnts) if int(s) not in seen]
        seen.update(s for s, _ in fresh)
        if fresh:
            ok = _verify_candidates(
                blk, req, np.asarray([s for s, _ in fresh], dtype=np.int64), needs_verify
            )
            okset = {int(s) for s in ok}
            out.extend(
                _candidates(blk, req, [s for s, _ in fresh if s in okset], dict(fresh))
            )
        if len(out) >= limit or len(seen) >= n_match or k >= nt:
            return [_materialize(c) for c in out] if materialize else out
        k = min(k_bucket(k * 4), nt)


# ---------------------------------------------------- per-block search


def _tres_eligible(blk: BackendBlock, p) -> bool:
    """Res/trace-only condition trees can evaluate over the tres
    membership axis (one row per (trace, resource) pair, builder.py
    build_tres) instead of the span axis: identical trace mask and
    matched-span counts from a ~10x smaller decode."""
    return (blk.pack.has("tres.res") and bool(p.conds)
            and not getattr(p, "has_struct", False)  # struct needs span rows
            and all(c.target in (T_RES, T_RATTR, T_TRACE) for c in p.conds))


def _tres_needed(conds) -> list[str]:
    need = {"tres.res", "tres.nspans", "trace.tres_off"}
    for c in conds:
        if c.target in (T_TRACE, T_RES):
            need.add(c.col)
        elif c.target == T_RATTR:
            need.update({"rattr.res", "rattr.key_id", "rattr.vtype", "res.service_id"})
            if c.col in _ATTR_VALUE_COL:
                need.add(f"rattr.{_ATTR_VALUE_COL[c.col]}")
    return sorted(need)


def _host_plan(blk: BackendBlock, p, groups_range) -> tuple[list[str], bool]:
    """(columns the host engine will read, tres-mode flag). tres mode is
    whole-block only -- row-group shards slice the span axis."""
    if groups_range is None and _tres_eligible(blk, p):
        return _tres_needed(p.conds), True
    needed = required_columns(p.conds) + list(getattr(p, "extra_cols", ()))
    host_needed = ([n for n in needed if n != "span.trace_sid"]
                   if "trace.span_off" in needed else needed)
    return host_needed, False


def _host_eval(blk: BackendBlock, p, operands, groups_range, plan=None):
    """Run the host engine under the chosen axis: returns
    (trace_mask, counts, cols_read). Covered spans are the caller's to
    report: tres mode still inspects every span's data (via its
    membership summary), so inspected_spans stays the span-axis count.
    plan: a precomputed _host_plan result (callers that already built it
    for warm_columns pass it through)."""
    host_needed, tres = plan if plan is not None else _host_plan(blk, p, groups_range)
    cols = _host_cols(blk, host_needed, groups_range)
    if tres:
        # evaluate the same condition tree over the tres axis: entries
        # play the role of spans (res conds LUT through tres.res), and
        # per-entry span counts weight the segment fold so matched-span
        # counts stay exact
        ecols = dict(cols)
        ecols["span.res_idx"] = cols["tres.res"]
        ecols["trace.span_off"] = cols["trace.tres_off"]
        ecols["@seg_weights"] = cols["tres.nspans"]
        tm, counts = eval_block_host(
            (p.tree, p.conds), ecols, operands,
            int(cols["tres.res"].shape[0]), blk.meta.total_traces,
        )
        return tm, counts, cols
    span_ax = blk.pack.axes.get(S.AX_SPAN)
    if groups_range is not None and span_ax is not None:
        n_rows = sum(span_ax.offsets[g + 1] - span_ax.offsets[g] for g in groups_range)
    else:
        n_rows = span_ax.n_rows if span_ax else 0
    tm, counts = eval_block_host(
        (p.tree, p.conds), cols, operands, n_rows, blk.meta.total_traces
    )
    return tm, counts, cols


def _host_cols(blk: BackendBlock, needed: list[str], groups_range):
    """Raw (unpadded) host columns for the numpy evaluator; span/sattr
    axis columns cover only groups_range when given, with sattr owners
    rebased to the local span rows (same contract as ops/stage.py)."""
    pack = blk.pack
    span_ax = pack.axes.get(S.AX_SPAN)
    sliced = groups_range is not None and span_ax is not None and span_ax.n_groups > 0
    span_base = span_ax.offsets[groups_range[0]] if sliced and groups_range else 0

    def read(name):
        pref = name.split(".", 1)[0]
        if sliced and pref in ("span", "sattr"):
            return name, pack.read_groups(name, groups_range)
        return name, pack.read(name)

    wanted = [n for n in needed if not n.startswith("span@") and pack.has(n)]
    # warm blocks: every column is an array-cache hit, and pool dispatch
    # would cost more than the dict lookups it parallelizes. The check
    # races concurrent evictions (check-then-act): losing it only
    # degrades to serial re-reads of columns that were cached a moment
    # ago -- a cache already thrashing at that point.
    serial = all(pack.has_cached_array(n) for n in wanted) or (
        _N_CPU == 1 and not getattr(blk.backend, "is_remote", True)
    )
    if wanted and serial:
        cols = dict(read(n) for n in wanted)
    else:
        cols = dict(_host_io_pool.map(read, wanted))
    if "sattr.span" in cols and span_base:
        cols["sattr.span"] = cols["sattr.span"] - span_base
    if "trace.span_off" in cols and sliced:
        hi = span_ax.offsets[groups_range[-1] + 1] if groups_range else 0
        cols["trace.span_off"] = (
            np.clip(cols["trace.span_off"], span_base, hi) - span_base
        ).astype(np.int32)
    return cols


def search_block(
    blk: BackendBlock,
    req: SearchRequest,
    groups_range: list[int] | None = None,
    mode: str = "auto",
) -> SearchResponse:
    """Search one block (optionally one row-group shard of it).

    mode: 'device' | 'host' | 'auto'. auto picks the device engine for
    blocks the storage layer keeps hot (TempoDB.open_block pins its
    cached readers) or that already hold staged device columns, and the
    host engine for cold one-shot readers, where column upload + a
    dispatch round trip would dominate a single scan."""
    resp = SearchResponse()
    if not blk.meta.overlaps_time(req.start, req.end):
        return resp
    planned = _plan_for_block(blk, req)
    if planned.prune:
        return resp
    if groups_range is not None and planned.has_struct:
        # struct nodes resolve parent links by GLOBAL row index; a
        # row-group slice would sever links across group boundaries, so
        # shards take the conservative plan (trace-AND + host verify)
        planned = _plan_for_block(blk, req, allow_struct=False)
        if planned.prune:  # the conservative fold may prove "no match"
            return resp
    limit = req.limit or DEFAULT_LIMIT
    operands = Operands.build(planned.rows, planned.tables or None)
    needed = required_columns(planned.conds) + list(planned.extra_cols)
    pack = blk.pack
    io0 = pack.bytes_read  # per-query IO delta (pack counts lifetime bytes)
    span_ax = pack.axes.get(S.AX_SPAN)
    if groups_range is not None and span_ax is not None:
        n_rows = sum(span_ax.offsets[g + 1] - span_ax.offsets[g] for g in groups_range)
    else:
        n_rows = span_ax.n_rows if span_ax else 0

    n_span_cols = max(1, sum(1 for n in needed if n.startswith(("span.", "sattr."))))

    def _host_cheaper() -> bool:
        """Auto mode weighs one device round trip against the host scan,
        with the SAME cost model as search_blocks_fused: the tres plan
        and cached host arrays scan at memory speed (serverless +
        row-group shard jobs land here). Called only after the cheap
        pinned/staged gate passed -- the RTT probe's first use inits the
        device backend."""
        host_cols_n, tres = _host_plan(blk, planned, groups_range)
        if tres or all(blk.pack.has_cached_array(n) for n in host_cols_n
                       if blk.pack.has(n)):
            est_bytes = blk.meta.total_traces * 4 * 12 if tres else 0
        else:
            est_bytes = n_rows * 4 * n_span_cols
        return est_bytes / _HOST_RATE_BPS * 1e3 < _link_rtt_ms()

    from ..util.kerneltel import TEL

    hot = (getattr(blk, "device_pinned", False)
           or getattr(blk, "_staged_cache", None) is not None)
    if mode != "auto":
        use_device, reason = mode == "device", "forced"
    elif not hot:
        use_device, reason = False, "cold_block"
    elif _host_cheaper():
        use_device, reason = False, "host_scan_cheaper"
    else:
        use_device, reason = True, "hot_block"
    TEL.record_routing("search_block", "device" if use_device else "host", reason)
    import time as _time

    t0_wall = _time.time()
    compiles0 = TEL.totals()[0]  # delta covers every chunk of a streamed eval

    if use_device:
        if n_rows * 4 * n_span_cols > _STREAM_MIN_STAGE_BYTES:
            # large scan: stream row-group chunks, prefetching the next
            # chunk's IO while the device filters the current one
            if planned.has_struct:  # streaming slices the span axis too
                planned = _plan_for_block(blk, req, allow_struct=False)
                if planned.prune:
                    return resp
                operands = Operands.build(planned.rows, planned.tables or None)
                needed = required_columns(planned.conds)
            from ..ops.stream import eval_block_streamed

            tm, counts, n_spans_seen = eval_block_streamed(
                blk, needed, (planned.tree, planned.conds), operands,
                groups=groups_range, return_device=True,
            )
            key = _start_key_dev(blk, tm.shape[0])
        else:
            staged = stage_block(blk, needed + ["trace.start_ms"], groups=groups_range)
            tm, counts = eval_block(
                (planned.tree, planned.conds),
                staged.cols,
                operands,
                staged.n_spans,
                staged.n_traces,
                staged.n_spans_b,
                staged.n_res_b,
                staged.n_traces_b,
                span_out=False,
            )
            key = staged.cols["trace.start_ms"]
            n_spans_seen = staged.n_spans

        def selector(k):
            return select_topk_device(tm, key, counts, k)
    else:
        # span_off carries the span->trace grouping: the full-length
        # trace_sid column never needs to leave disk on the host path
        plan = None
        if groups_range is None:
            from ..ops.stream import staged_warm

            plan = _host_plan(blk, planned, None)
            # single-unit form of the cold pipeline: coalesced ranged
            # fetch + one threaded decode, with per-stage kerneltel
            staged_warm(
                blk, plan[0] + list(blk.SEARCH_TRACE_COLS) + ["trace.start_ms"])
        tm, counts, _ = _host_eval(blk, planned, operands, groups_range, plan=plan)
        n_spans_seen = n_rows
        key = _start_key_host(blk)

        def selector(k):
            return select_topk_host(tm, key, counts, k)

    # per-block self-trace span with kernel attrs: a slow query's flame
    # view shows which block ran where and whether it recompiled
    info = TEL.last_launch() if use_device else None
    TEL.child_span(
        f"block:{blk.meta.block_id[:8]}", t0_wall, _time.time(),
        {"engine": "device" if use_device else "host",
         "bucket": (int(info[1]) if info and info[0] == "filter" and use_device
                    else n_rows),
         "compile": use_device and TEL.totals()[0] > compiles0,
         "reason": reason},
    )
    results = _collect_topk(blk, req, planned.needs_verify, selector, limit)
    results.sort(key=lambda r: -r.start_time_unix_nano)
    resp.traces = results[:limit]
    resp.inspected_spans = n_spans_seen
    resp.inspected_bytes = pack.bytes_read - io0
    return resp


# ---- fused multi-block device search (single chip)
# (the cross-block ordering key trace@gkey_s is a derived staged column;
# its origin constant lives in ops/stage.GKEY_ORIGIN_S)


def _staged_hit(blk: BackendBlock, needed: tuple) -> bool:
    store = getattr(blk, "_staged_cache", None)
    return store is not None and (needed, None) in store


def search_blocks_fused(
    blocks: list[BackendBlock],
    req: SearchRequest,
    pool=None,
    default_limit: int = DEFAULT_LIMIT,
    promote_touches: int = 2,
) -> SearchResponse | None:
    """Search many blocks with at most ONE device sync.

    Engine choice is per block, by temperature: a block whose staged
    device columns are already resident (or that has been searched
    promote_touches times -- provably hot, worth the one-time staging
    upload) evaluates on device; everything colder evaluates on host
    with the vectorized numpy engine, which costs ZERO device round
    trips -- the right trade on a high-latency link where each sync is
    a fixed ~100 ms. Device blocks share one fused cross-block top-k
    (one sync covers the whole group); host blocks run per-block
    top-k collects in the IO pool. A cold one-shot scan therefore never
    touches the device, and a hot working set costs ~one RTT per query
    regardless of block count -- the single-chip counterpart of the mesh
    program in parallel/search.py, and the production engine behind
    TempoDB.search_blocks / the frontend's block-batch jobs.

    Returns None only when the combined staged footprint of the
    device-eligible blocks exceeds the device budget -- the caller
    falls back to per-block (streamed) search."""
    resp = SearchResponse()
    limit = req.limit or default_limit
    in_range = [b for b in blocks if b.meta.overlaps_time(req.start, req.end)]
    # TempoDB already gates its io_pool on core count + backend locality;
    # this covers direct callers handing in an ungated pool
    if (pool is not None and _N_CPU == 1 and in_range
            and not getattr(in_range[0].backend, "is_remote", True)):
        pool = None
    plans = (
        list(pool.map(lambda b: _plan_for_block(b, req), in_range))
        if pool is not None
        else [_plan_for_block(b, req) for b in in_range]
    )
    live = [(blk, p) for blk, p in zip(in_range, plans) if not p.prune]
    if not live:
        return resp

    # whole-query engine choice first: if scanning every live block on
    # host is estimated cheaper than ONE device round trip, promotion is
    # a loss no matter how hot the blocks are (the tunnel-latency case);
    # per-block temperature only matters when the device can win at all
    scan_bytes = 0
    for blk, p in live:
        host_cols_n, tres = _host_plan(blk, p, None)
        # a block whose host columns sit in the array cache scans at
        # memory speed -- its bytes don't count against the host engine
        if all(blk.pack.has_cached_array(n) for n in host_cols_n
               if blk.pack.has(n)):
            continue
        if tres:
            # tres axis rows ~= resources-per-trace * traces, tiny next
            # to the span axis; 3 int32 columns is the honest estimate
            scan_bytes += blk.meta.total_traces * 4 * 12
        else:
            n_span = sum(1 for n in host_cols_n if n.startswith(("span.", "sattr.")))
            scan_bytes += blk.pack.axes[S.AX_SPAN].n_rows * 4 * max(1, n_span)
    host_est_ms = scan_bytes / _HOST_RATE_BPS * 1e3
    prefer_host = host_est_ms < _link_rtt_ms()

    from ..util.kerneltel import TEL

    self_trace = TEL.active_trace()  # pool threads lose the contextvar
    dev_items: list[tuple[BackendBlock, object]] = []
    host_items: list[tuple[BackendBlock, object]] = []
    decisions: list[tuple[str, str]] = []  # recorded only if we RUN here
    est = 0
    for blk, p in live:
        blk.search_touches = getattr(blk, "search_touches", 0) + 1
        needed = (tuple(required_columns(p.conds)) + tuple(p.extra_cols)
                  + ("trace@gkey_s",))
        staged_hit = _staged_hit(blk, needed)
        hot = not prefer_host and (staged_hit or blk.search_touches >= promote_touches)
        if hot:
            n_span_cols = max(1, sum(
                1 for n in needed if n.startswith(("span.", "sattr."))
            ))
            est += blk.pack.axes[S.AX_SPAN].n_rows * 4 * n_span_cols
            dev_items.append((blk, p))
            decisions.append(("device", "staged_hit" if staged_hit else "promoted"))
        else:
            # hot is false either because the whole query prefers host or
            # because this block is cold (staged miss, below promotion)
            host_items.append((blk, p))
            decisions.append(("host", "host_scan_cheaper" if prefer_host
                              else "cold_block"))
    if est > _DEVICE_SEARCH_MAX_BYTES:
        # caller falls back to per-block (streamed) search, which records
        # its own per-block decisions -- recording the per-block choices
        # above too would double-count every evaluation
        TEL.record_routing("search_fused", "fallback", "pre_io_budget",
                           n=len(dev_items))
        return None
    for engine, reason in decisions:
        TEL.record_routing("search_fused", engine, reason)

    io0 = {id(blk): blk.pack.bytes_read for blk, _ in live}
    results: list[tuple] = []  # _candidates records until the final merge

    # cold host blocks run through the streaming read pipeline: block
    # N+1's ranged reads and threaded decompress are in flight while
    # block N's host engine evaluates -- the read-side analog of the
    # compaction pipeline's input prefetch, depth/byte-budget bounded
    # (ops/stream). Results are unaffected: the pipeline only moves the
    # fetch+decode of exactly the columns host_eval_collect would read.
    host_plans: dict[int, tuple] = {}
    cold_ids: set[int] = set()
    cold_wants: list[tuple[BackendBlock, list[str]]] = []
    for blk, p in host_items:
        plan = _host_plan(blk, p, None)
        host_plans[id(blk)] = plan
        if not all(blk.pack.has_cached_array(n) for n in plan[0]
                   if blk.pack.has(n)):
            cold_ids.add(id(blk))
            cold_wants.append((blk, plan[0] + list(blk.SEARCH_TRACE_COLS)
                               + ["trace.start_ms"]))
    prefetch = None
    if len(cold_wants) > 1:  # a lone cold block has nothing to overlap
        from ..ops.stream import HostPrefetch

        prefetch = HostPrefetch(cold_wants)

    def stage_and_eval(item):
        import time as _time

        blk, p = item
        t0w = _time.time()
        operands = Operands.build(p.rows, p.tables or None)
        needed = required_columns(p.conds) + list(p.extra_cols) + ["trace@gkey_s"]
        staged = stage_block(blk, needed)
        tm, counts = eval_block(
            (p.tree, p.conds), staged.cols, operands,
            staged.n_spans, staged.n_traces,
            staged.n_spans_b, staged.n_res_b, staged.n_traces_b,
            span_out=False,
        )
        if self_trace is not None:
            info = TEL.last_launch()
            self_trace.child(
                f"block:{blk.meta.block_id[:8]}", t0w, _time.time(),
                {"engine": "device", "bucket": staged.n_spans_b,
                 "compile": bool(info and info[0] == "filter" and info[2])})
        return tm, counts, staged.cols["trace@gkey_s"], staged.n_spans

    def host_eval_collect(item):
        import time as _time

        blk, p = item
        t0w = _time.time()
        operands = Operands.build(p.rows, p.tables or None)
        # cold-scan detection from the PRE-prefetch snapshot (a pipeline
        # hit still runs the host engine as a cold scan), but the rate
        # EMA only learns from scans that paid their own IO: a block the
        # prefetch served (fully or partly) times at somewhere between
        # memory and IO speed and would inflate _HOST_RATE_BPS,
        # misrouting the next lone cold block toward the host engine
        plan = host_plans[id(blk)]
        host_needed = plan[0]
        cold = id(blk) in cold_ids
        paid_io = False
        t0 = _time.perf_counter()
        if cold:
            # one coalesced ranged read + one threaded decompress batch
            # for EVERYTHING this query touches (eval columns + the
            # candidate/result trace columns): a cold scan's cost is
            # per-column fixed overheads, not bytes. The pipeline ran
            # (or is running) those stages ahead; wait for them, and do
            # the read here only if the pipeline was skipped/cancelled.
            if prefetch is None or not prefetch.wait(blk):
                paid_io = True
                blk.pack.warm_columns(
                    host_needed + list(blk.SEARCH_TRACE_COLS) + ["trace.start_ms"])
        tm, counts, cols = _host_eval(blk, p, operands, None, plan=plan)
        if paid_io:
            _note_host_rate(sum(a.nbytes for a in cols.values()),
                            _time.perf_counter() - t0)
        key = _start_key_host(blk)
        n_spans = blk.pack.axes[S.AX_SPAN].n_rows
        if self_trace is not None:
            self_trace.child(
                f"block:{blk.meta.block_id[:8]}", t0w, _time.time(),
                {"engine": "host", "bucket": int(n_spans), "compile": False,
                 "cold": cold})

        if not p.needs_verify:
            # exact plans skip the per-block escalating collect: ONE
            # global host selection covers every such block (the host
            # twin of the fused device select). Key = the cross-block
            # seconds-granularity gkey (shared definition with the
            # staged device column); the final merge sorts winners by
            # exact start_ns anyway.
            from ..ops.stage import gkey_from_start_ms

            return ("raw", tm, counts, gkey_from_start_ms(blk.meta, key), n_spans)

        def selector(k):
            return select_topk_host(tm, key, counts, k)

        return ("cand", _collect_topk(blk, req, p.needs_verify, selector, limit,
                                      materialize=False), n_spans)

    # device staging IO + host scans overlap across one pool pass;
    # device kernel dispatches are async, so nothing blocks until the
    # fused select's single fetch
    tagged = [("dev", it) for it in dev_items] + [("host", it) for it in host_items]

    def run_item(t):
        tag, item = t
        try:
            return tag, (stage_and_eval(item) if tag == "dev" else host_eval_collect(item))
        except Exception as e:
            # pool futures re-raise with the OUTER stack; carry the real
            # one along so truncated logs still show the root cause
            import traceback

            e.add_note(f"search {tag} item on block "
                       f"{item[0].meta.block_id}: {traceback.format_exc()}")
            raise

    try:
        outs = list(pool.map(run_item, tagged)) if pool is not None else [
            run_item(t) for t in tagged
        ]
    finally:
        if prefetch is not None:
            prefetch.close()  # an errored item mustn't leak pipeline work
    evald = [o for tag, o in outs if tag == "dev"]
    host_out = [(o, it) for (tag, o), (htag, it) in zip(outs, tagged) if tag == "host"]

    host_raw: list[tuple] = []
    for (o, item) in host_out:
        if o[0] == "cand":
            _, out, n_spans = o
            results.extend(out)
        else:
            _, tm, counts, gkey, n_spans = o
            host_raw.append((item[0], item[1], tm, counts, gkey))
        resp.inspected_spans += int(n_spans)
    if host_raw:
        h_blocks = [b for b, _, _, _, _ in host_raw]
        h_plans = [p for _, p, _, _, _ in host_raw]
        h_tms = [t for _, _, t, _, _ in host_raw]
        h_cnts = [c for _, _, _, c, _ in host_raw]
        h_keys = [k for _, _, _, _, k in host_raw]
        h_offsets = np.cumsum([0] + [int(t.shape[0]) for t in h_tms])

        def h_selector(k):
            return select_topk_host_multi(h_tms, h_keys, h_cnts, k)

        results.extend(_collect_topk_multi(
            h_blocks, h_plans, h_offsets, req, h_selector, limit,
            materialize=False,
        ))

    if evald:
        tms = [e[0] for e in evald]
        cnts = [e[1] for e in evald]
        keys = [e[2] for e in evald]
        resp.inspected_spans += int(sum(e[3] for e in evald))
        offsets = np.cumsum([0] + [int(t.shape[0]) for t in tms])

        def selector(k):
            return select_topk_device_multi(tms, keys, cnts, k)

        results.extend(_collect_topk_multi(
            [blk for blk, _ in dev_items], [p for _, p in dev_items],
            offsets, req, selector, limit, materialize=False,
        ))

    # global merge over lightweight candidates; only the winning `limit`
    # pay dictionary lookups + SearchResult construction
    results.sort(key=lambda c: -c[0])
    seen: set[str] = set()
    resp.traces = []
    for c in results:
        if c[1] in seen:
            continue
        seen.add(c[1])
        resp.traces.append(_materialize(c))
        if len(resp.traces) >= limit:
            break
    resp.inspected_bytes = sum(
        blk.pack.bytes_read - io0[id(blk)] for blk, _ in live
    )
    return resp


def _collect_topk_multi(blocks, plans, offsets, req: SearchRequest, selector,
                        limit: int, materialize: bool = True):
    """Escalating cross-block top-k collect: global winners map back to
    (block, sid) via the padded part offsets, then per-block exact
    verification + result building -- the multi-block twin of
    _collect_topk (same materialize contract)."""
    total = int(offsets[-1])
    if total == 0:
        return []
    k = min(k_bucket(max(2 * limit, 32)), total)
    out: list = []
    seen: set[int] = set()
    while True:
        gids, gcnts, n_match = selector(k)
        per_block: dict[int, list[tuple[int, int]]] = {}
        fresh = 0
        for g, c in zip(gids, gcnts):
            g = int(g)
            if g in seen:
                continue
            seen.add(g)
            fresh += 1
            bi = int(np.searchsorted(offsets, g, side="right")) - 1
            per_block.setdefault(bi, []).append((g - int(offsets[bi]), int(c)))
        for bi, pairs in per_block.items():
            blk, p = blocks[bi], plans[bi]
            sids = np.asarray([s for s, _ in pairs], dtype=np.int64)
            ok = _verify_candidates(blk, req, sids, p.needs_verify)
            okset = {int(s) for s in ok}
            out.extend(
                _candidates(blk, req, [s for s, c in pairs if s in okset], dict(pairs))
            )
        if len(out) >= limit or len(seen) >= n_match or k >= total or fresh == 0:
            return [_materialize(c) for c in out] if materialize else out
        k = min(k_bucket(k * 4), total)


# ---- stacked multi-block device search (parallel/search.py)

_DEVICE_SEARCH_MAX_BYTES = 512 << 20  # stacked-column budget before falling back


def _count_struct_nodes(tree) -> int:
    """Struct ('>' / '>>' / '~') nodes in a condition tree. Each one
    costs its own span-axis lhs-mask all_gather on the mesh, so the
    pre-IO budget estimate must scale with the count, not a boolean.
    ('struct', op, lhs, rhs): t[1] is the op STRING, never recursed."""
    if not isinstance(tree, tuple):
        return 0
    n = 1 if tree[0] == "struct" else 0
    children = tree[2:] if tree[0] == "struct" else tree[1:]
    return n + sum(_count_struct_nodes(ch) for ch in children
                   if isinstance(ch, tuple))


def _has_deep_struct(tree) -> bool:
    """True when any '>>' or '~' node is present: those relations walk
    the REPLICATED parent table, so the mesh program hoists one
    parent/validity gather per launch on top of the per-node masks
    ('>' runs off the local parent column and needs neither)."""
    if not isinstance(tree, tuple):
        return False
    if tree[0] == "struct" and tree[1] in (">>", "~"):
        return True
    children = tree[2:] if tree[0] == "struct" else tree[1:]
    return any(_has_deep_struct(ch) for ch in children
               if isinstance(ch, tuple))


def _stacked_words_est(items, needed: list[str], tree, sp: int,
                       S_b: int, NT_b: int, attr_b: dict[str, int]) -> int:
    """Per-block stacked-column words the mesh program will hold on
    device, estimated BEFORE any column IO (an over-budget group must
    fall back without paying the cold reads). Per-axis products plus
    the struct-node replication, priced to the SHRUNK mesh program
    (parallel/search): each node replicates its (bit-packed on the
    wire, unpacked bool on device) lhs mask onto every chip, and a
    tree with any '>>' / '~' node additionally hoists ONE
    parent/validity gather (+ pointer-doubling temps) per launch --
    the costmodel comm walker prices the same collectives on the wire
    and tests cross-check the two counts."""
    from ..ops.device import bucket

    span_cols = [n for n in needed if n.startswith("span.")]
    est = S_b * max(1, len(span_cols))
    # trace-axis tables (span_off at NT_b+1 plus any trace.* conds) and
    # res-axis columns ride every block too; their row counts come from
    # footer metadata (pack.n_rows_of), so trace-heavy groups near the
    # budget are no longer understated (ADVICE round 5)
    n_trace_cols = sum(1 for n in needed if n.startswith("trace."))
    est += NT_b * n_trace_cols
    res_cols = [n for n in needed if n.startswith("res.")]
    if res_cols:
        r_rows = max((blk.pack.n_rows_of(n) for blk, _ in items for n in res_cols),
                     default=1)
        est += bucket(max(r_rows, 1)) * len(res_cols)
    for pre, a_b in attr_b.items():
        n_val_cols = sum(
            1 for n in needed if n.startswith(f"{pre}.") and not n.endswith((".span", ".res"))
        )
        est += a_b * n_val_cols + (S_b + 1 if pre == "sattr" else 0)  # values + off
    from ..parallel.search import struct_pack_enabled

    if struct_pack_enabled():
        est += S_b * sp * _count_struct_nodes(tree)  # per-node replicated mask
        if _has_deep_struct(tree):
            est += 4 * S_b * sp  # hoisted pid/valid + closure temps, once
    else:
        # legacy escape hatch (TEMPO_STRUCT_PACK=0): every node gathers
        # lm/pid/valid + temps -- the budget must price what will run
        est += 6 * S_b * sp * _count_struct_nodes(tree)
    return est


def search_blocks_device(
    blocks: list[BackendBlock],
    req: SearchRequest,
    mesh,
    default_limit: int = DEFAULT_LIMIT,
    pool=None,
) -> SearchResponse | None:
    """Search many blocks as ONE stacked mesh program: blocks shard over
    'dp', span rows AND generic-attr rows over 'sp', per-block operands
    resolved through each block's dictionary (parallel/search.py). The
    multi-chip analog of the reference's per-block job fan-out
    (modules/frontend/searchsharding.go + tempodb/pool), including the
    generic attribute iterators (vparquet/block_traceql.go:682-763) and
    structural ops (>, >>, ~: parent tables all_gather along sp).
    Pre-upgrade blocks without span.parent_idx never reach a struct
    tree -- their planner falls back to the conservative force-verify
    plan, which runs on the mesh like any other. Returns None only when
    the stacked columns (plus struct all_gather replication) exceed the
    device budget -- the caller falls back to per-block search_block."""
    resp = SearchResponse()
    in_range = [b for b in blocks if b.meta.overlaps_time(req.start, req.end)]
    # plan fan-out pulls each block's dictionary + footer: overlap the IO
    plans = (
        list(pool.map(lambda b: _plan_for_block(b, req), in_range))
        if pool is not None
        else [_plan_for_block(b, req) for b in in_range]
    )
    live: list[tuple[BackendBlock, object]] = []
    for blk, p in zip(in_range, plans):
        if p.prune:
            continue
        live.append((blk, p))
    if not live:
        return resp

    # identical plan structure -> one compiled mesh program per group
    groups: dict[tuple, list[tuple[BackendBlock, object]]] = {}
    for blk, p in live:
        groups.setdefault((p.tree, p.conds), []).append((blk, p))

    limit = req.limit or default_limit
    results: list[SearchResult] = []
    for (tree, conds), items in groups.items():
        got = _search_group_device(items, tree, conds, req, mesh, resp, pool)
        if got is None:
            return None
        results.extend(got)
    results.sort(key=lambda r: -r.start_time_unix_nano)
    # replicated partials hit in several blocks: dedupe by trace id, same
    # as the per-block path's SearchResponse.merge
    seen: set[str] = set()
    deduped = []
    for r in results:
        if r.trace_id not in seen:
            seen.add(r.trace_id)
            deduped.append(r)
    resp.traces = deduped[:limit]
    return resp


def _search_group_device(items, tree, conds, req: SearchRequest, mesh, resp: SearchResponse,
                         pool=None):
    from ..ops.device import PAD_I32, bucket
    from ..parallel.search import sharded_search

    dp, sp = mesh.shape["dp"], mesh.shape["sp"]
    # span@ materialization is a staged-cache concept; the stacked path
    # reads and stacks raw columns only. extra_cols carries tree-level
    # needs (span.parent_idx for struct nodes).
    needed = [n for n in required_columns(conds) + list(items[0][1].extra_cols)
              if not n.startswith("span@")]
    B = len(items)
    Bp = ((B + dp - 1) // dp) * dp
    s_max = max(blk.pack.axes[S.AX_SPAN].n_rows for blk, _ in items)
    S_b = sp * bucket(max(1, -(-max(s_max, 1) // sp)))
    NT_b = bucket(max(max(blk.meta.total_traces for blk, _ in items), 1))
    # generic-attr rows ride the sp axis like span rows; their buckets
    # come from the widest block in the group (axis metadata -- no IO)
    attr_b: dict[str, int] = {}
    for pre, ax in (("sattr", S.AX_SATTR), ("rattr", S.AX_RATTR)):
        if f"{pre}.key_id" in needed:
            a_max = max(
                blk.pack.axes[ax].n_rows if ax in blk.pack.axes else 0 for blk, _ in items
            )
            attr_b[pre] = sp * bucket(max(1, -(-max(a_max, 1) // sp)))
    est = _stacked_words_est(items, needed, tree, sp, S_b, NT_b, attr_b)
    if Bp * est * 4 > _DEVICE_SEARCH_MAX_BYTES:
        from ..util.kerneltel import TEL

        TEL.record_routing("search_mesh", "fallback", "pre_io_budget",
                           n=len(items))
        return None

    host: dict[str, np.ndarray] = {}
    io0 = [blk.pack.bytes_read for blk, _ in items]

    def read_block_cols(blk):
        return {n: blk.pack.read(n) for n in needed}

    if pool is not None:  # overlap per-block column IO, like the host path
        per_block = list(pool.map(read_block_cols, [blk for blk, _ in items]))
    else:
        per_block = [read_block_cols(blk) for blk, _ in items]
    n_res_per = [
        max((a.shape[0] for n, a in cols.items() if n.startswith("res.")), default=1)
        for cols in per_block
    ]
    R_b = bucket(max(max(n_res_per), 1))
    for n in needed:
        pre = n.split(".", 1)[0]
        if n == "trace.span_off":
            # (NT_b+1,) offsets per block; padded trace rows collapse to
            # empty segments by repeating the final offset
            out = np.zeros((Bp, NT_b + 1), dtype=np.int32)
            for bi, cols in enumerate(per_block):
                a = cols[n]
                out[bi, : a.shape[0]] = a
                out[bi, a.shape[0]:] = a[-1] if a.size else 0
            host[n] = out
            continue
        if n in ("sattr.span", "rattr.res"):
            # owner rows (grouped by owner) -> per-owner offset column,
            # replicated along sp; the kernel aggregates with cumsum +
            # offset gathers (parallel/search.owner_counts). Mirrors
            # ops/stage.py's single-device offsetting.
            n_seg_b = S_b if n == "sattr.span" else R_b
            out = np.zeros((Bp, n_seg_b + 1), dtype=np.int32)
            for bi, cols in enumerate(per_block):
                owners = cols[n]
                n_seg = (
                    items[bi][0].pack.axes[S.AX_SPAN].n_rows
                    if n == "sattr.span"
                    else n_res_per[bi]
                )
                cnt = np.bincount(
                    np.clip(owners, 0, max(n_seg, 1) - 1), minlength=max(n_seg, 1)
                ) if owners.size else np.zeros(max(n_seg, 1), dtype=np.int64)
                off = np.concatenate([[0], np.cumsum(cnt)]).astype(np.int32)
                out[bi, : off.shape[0]] = off
                out[bi, off.shape[0]:] = off[-1]
            host[f"{pre}.off"] = out
            continue
        if n.startswith("span."):
            shape, fill = (Bp, S_b), PAD_I32
        elif pre in attr_b:
            shape, fill = (Bp, attr_b[pre]), PAD_I32
        elif n.startswith("res."):
            shape, fill = (Bp, R_b), PAD_I32
        elif n.startswith("trace."):
            shape, fill = (Bp, NT_b), PAD_I32
        else:
            return None
        first = per_block[0][n]
        if first.dtype not in (np.int32, np.float32):
            return None
        out = np.full(shape, fill if first.dtype == np.int32 else np.float32(0), dtype=first.dtype)
        for bi, cols in enumerate(per_block):
            a = cols[n]
            out[bi, : a.shape[0]] = a
        host[n] = out

    n_spans = np.zeros((Bp,), dtype=np.int32)
    for bi, (blk, _) in enumerate(items):
        n_spans[bi] = blk.pack.axes[S.AX_SPAN].n_rows
    operands = [Operands.build(p.rows, p.tables or None) for _, p in items]
    operands += [Operands.build([(0, 0, 0, 0.0, 0.0)] * len(conds))] * (Bp - B)
    tm, sc = sharded_search(mesh, tree, conds, operands, host, n_spans, nt=NT_b)

    limit = req.limit or DEFAULT_LIMIT
    results: list[SearchResult] = []
    for bi, (blk, p) in enumerate(items):
        nt = blk.meta.total_traces
        mask, cnt = tm[bi][:nt], sc[bi][:nt]
        key = _start_key_host(blk)[:nt]

        def selector(k, mask=mask, cnt=cnt, key=key):
            return select_topk_host(mask, key, cnt, k)

        results.extend(_collect_topk(blk, req, p.needs_verify, selector, limit))
        resp.inspected_spans += int(n_spans[bi])
        resp.inspected_bytes += blk.pack.bytes_read - io0[bi]
    return results


# ---- tag name/value discovery (reference: /api/search/tags endpoints)


def search_tags(blk: BackendBlock, collector: DistinctStringCollector) -> None:
    d = blk.dictionary
    for col in ("sattr.key_id", "rattr.key_id"):
        codes = np.unique(blk.pack.read(col))
        for c in codes:
            if c >= 0:
                collector.collect(d.string(int(c)))
    # well-known resource attrs live only in dedicated columns
    for tag, col in _WELL_KNOWN_RES.items():
        if blk.pack.has(col) and (blk.pack.read(col) >= 0).any():
            collector.collect(tag)


def search_tag_values(blk: BackendBlock, tag: str, collector: DistinctStringCollector) -> None:
    d = blk.dictionary
    kcode = d.lookup(tag)
    if tag == _INTRINSIC_NAME:
        for c in np.unique(blk.pack.read("span.name_id")):
            if c >= 0:
                collector.collect(d.string(int(c)))
        return
    ded = _WELL_KNOWN_RES.get(tag)
    if ded and blk.pack.has(ded):
        for c in np.unique(blk.pack.read(ded)):
            if c >= 0:
                collector.collect(d.string(int(c)))
    if kcode < 0:
        return
    for pre in ("sattr", "rattr"):
        keys = blk.pack.read(f"{pre}.key_id")
        mask = keys == kcode
        if not mask.any():
            continue
        vt = blk.pack.read(f"{pre}.vtype")[mask]
        sid = blk.pack.read(f"{pre}.str_id")[mask]
        i64 = blk.pack.read(f"{pre}.int64")[mask]
        for j in range(len(vt)):
            if vt[j] == 0:
                collector.collect(d.string(int(sid[j])))
            elif vt[j] == 1:
                collector.collect(str(int(i64[j])))
            elif vt[j] == 3:
                collector.collect("true" if i64[j] else "false")
