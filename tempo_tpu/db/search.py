"""Search execution: tag / TraceQL queries -> device filter plan -> results.

The per-block pipeline (analog of vparquet/block_search.go:78-116 +
block_traceql.go Fetch): the traceql planner resolves strings through
the block dictionary (a miss prunes the whole block -- the dictionary IS
the page-dictionary pre-filter of parquetquery predicates.go:38-89) and
emits a trace-level condition tree; ops.filter evaluates it over staged
columns; surviving trace candidates are exactly re-verified host-side
for time/duration (device encodings are conservative)."""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from ..block import schema as S
from ..block.reader import BackendBlock
from ..ops.filter import Operands, T_RES, T_SPAN, T_TRACE, eval_block, required_columns
from ..ops.stage import stage_block
from ..traceql.plan import plan_search_request
from ..util.distinct import DistinctStringCollector

DEFAULT_LIMIT = 20
# stream row-group chunks only when the staged columns would exceed this
# (bounds device memory); below it a single staged eval wins -- one kernel
# dispatch + one result transfer instead of one per chunk, which matters
# when host<->device latency is high
_STREAM_MIN_STAGE_BYTES = 512 << 20

_INTRINSIC_NAME = "name"
_WELL_KNOWN_RES = {
    "service.name": "res.service_id",
    "k8s.cluster.name": "res.cluster_id",
    "k8s.namespace.name": "res.namespace_id",
    "k8s.pod.name": "res.pod_id",
    "k8s.container.name": "res.container_id",
}


@dataclass
class SearchRequest:
    tags: dict[str, str] = field(default_factory=dict)
    min_duration_ms: int = 0
    max_duration_ms: int = 0
    start: int = 0  # unix seconds, 0 = unbounded
    end: int = 0
    limit: int = DEFAULT_LIMIT
    query: str = ""  # TraceQL spanset filter


@dataclass
class SearchResult:
    trace_id: str  # hex
    root_service_name: str
    root_trace_name: str
    start_time_unix_nano: int
    duration_ms: int
    matched_spans: int = 0

    def to_dict(self) -> dict:
        return {
            "traceID": self.trace_id,
            "rootServiceName": self.root_service_name,
            "rootTraceName": self.root_trace_name,
            "startTimeUnixNano": str(self.start_time_unix_nano),
            "durationMs": self.duration_ms,
        }


@dataclass
class SearchResponse:
    traces: list[SearchResult] = field(default_factory=list)
    inspected_bytes: int = 0
    inspected_spans: int = 0

    def merge(self, other: "SearchResponse", limit: int) -> None:
        seen = {t.trace_id for t in self.traces}
        for t in other.traces:
            if t.trace_id not in seen and len(self.traces) < limit:
                self.traces.append(t)
                seen.add(t.trace_id)
        self.inspected_bytes += other.inspected_bytes
        self.inspected_spans += other.inspected_spans


def _plan_for_block(blk: BackendBlock, req: SearchRequest):
    start_rel = None
    if req.start or req.end:
        base_ms = blk.meta.start_time_unix_nano // 1_000_000
        lo = (req.start * 1000 - base_ms - 1) if req.start else -(2**31)
        hi = (req.end * 1000 - base_ms + 1) if req.end else 2**31 - 1
        start_rel = (
            int(np.clip(lo, -(2**31), 2**31 - 1)),
            int(np.clip(hi, -(2**31), 2**31 - 1)),
        )
    return plan_search_request(
        blk.dictionary,
        req.tags,
        query=req.query,
        min_duration_ms=req.min_duration_ms,
        max_duration_ms=req.max_duration_ms,
        start_rel_ms=start_rel,
    )


def _verify_and_build(
    blk: BackendBlock, req: SearchRequest, sids: np.ndarray, counts: np.ndarray
) -> list[SearchResult]:
    """Exact host re-check of time/duration + result materialization from
    the cached trace-level index."""
    ti = blk.trace_index
    d = blk.dictionary
    out = []
    for sid in sids:
        start_ns = int(ti["trace.start_ns"][sid])
        end_ns = int(ti["trace.end_ns"][sid])
        dur_ms = max(0, (end_ns - start_ns) // 1_000_000)
        if req.min_duration_ms and dur_ms < req.min_duration_ms:
            continue
        if req.max_duration_ms and dur_ms > req.max_duration_ms:
            continue
        if req.start and start_ns < req.start * 1_000_000_000:
            continue
        if req.end and start_ns > req.end * 1_000_000_000:
            continue
        out.append(
            SearchResult(
                trace_id=ti["trace.id"][sid].tobytes().hex(),
                root_service_name=d.string(int(ti["trace.root_service_id"][sid])),
                root_trace_name=d.string(int(ti["trace.root_name_id"][sid])),
                start_time_unix_nano=start_ns,
                duration_ms=dur_ms,
                matched_spans=int(counts[sid]),
            )
        )
    return out


def search_block(
    blk: BackendBlock,
    req: SearchRequest,
    groups_range: list[int] | None = None,
) -> SearchResponse:
    """Search one block (optionally one row-group shard of it)."""
    resp = SearchResponse()
    if not blk.meta.overlaps_time(req.start, req.end):
        return resp
    planned = _plan_for_block(blk, req)
    if planned.prune:
        return resp
    operands = Operands.build(planned.rows, planned.tables or None)
    needed = required_columns(planned.conds)
    span_ax = blk.pack.axes.get("span")
    if groups_range is not None:
        n_rows = sum(
            span_ax.offsets[g + 1] - span_ax.offsets[g] for g in groups_range
        ) if span_ax else 0
    else:
        n_rows = span_ax.n_rows if span_ax else 0
    n_span_cols = max(1, sum(1 for n in needed if n.startswith(("span.", "sattr."))))
    if n_rows * 4 * n_span_cols > _STREAM_MIN_STAGE_BYTES:
        # large scan: stream row-group chunks, prefetching the next chunk's
        # IO while the device filters the current one (ops/stream.py)
        from ..ops.stream import eval_block_streamed

        trace_mask, counts, n_spans_seen = eval_block_streamed(
            blk, needed, (planned.tree, planned.conds), operands, groups=groups_range
        )
        sids = np.nonzero(trace_mask)[0]
    else:
        staged = stage_block(blk, needed, groups=groups_range)
        _, trace_mask, counts = eval_block(
            (planned.tree, planned.conds),
            staged.cols,
            operands,
            staged.n_spans,
            staged.n_traces,
            staged.n_spans_b,
            staged.n_res_b,
            staged.n_traces_b,
        )
        counts = np.asarray(counts)
        n_spans_seen = staged.n_spans
        sids = np.nonzero(np.asarray(trace_mask)[: staged.n_traces])[0]
    # device filter may be conservative (clamped encodings / mixed OR):
    # exact host re-check of each candidate (hosteval.py)
    sids = _verify_candidates(blk, req, sids, planned.needs_verify)
    results = _verify_and_build(blk, req, sids, counts)
    results.sort(key=lambda r: -r.start_time_unix_nano)
    resp.traces = results[: req.limit]
    resp.inspected_spans = n_spans_seen
    resp.inspected_bytes = blk.pack.bytes_read
    return resp


# ---- stacked multi-block device search (parallel/search.py)

_DEVICE_SEARCH_MAX_BYTES = 512 << 20  # stacked-column budget before falling back


def _verify_candidates(blk: BackendBlock, req: SearchRequest, sids, needs_verify: bool):
    """Exact host re-check of TraceQL candidates when the device filter
    was conservative (same step as search_block's verify leg)."""
    if not (needs_verify and req.query and len(sids)):
        return sids
    from ..traceql.hosteval import trace_matches
    from ..traceql.parser import parse

    q = parse(req.query)
    traces = blk.materialize_traces([int(s) for s in sids])
    return np.asarray(
        [s for s, tr in zip(sids, traces) if tr is not None and trace_matches(q, tr)],
        dtype=np.int64,
    )


def search_blocks_device(
    blocks: list[BackendBlock],
    req: SearchRequest,
    mesh,
    default_limit: int = DEFAULT_LIMIT,
    pool=None,
) -> SearchResponse | None:
    """Search many blocks as ONE stacked mesh program: blocks shard over
    'dp', span rows over 'sp', per-block operands resolved through each
    block's dictionary (parallel/search.py). The multi-chip analog of the
    reference's per-block job fan-out (modules/frontend/searchsharding.go
    + tempodb/pool). Returns None when the query needs the per-block
    generic-attr path or the stacked columns exceed the device budget --
    the caller falls back to per-block search_block."""
    resp = SearchResponse()
    in_range = [b for b in blocks if b.meta.overlaps_time(req.start, req.end)]
    # plan fan-out pulls each block's dictionary + footer: overlap the IO
    plans = (
        list(pool.map(lambda b: _plan_for_block(b, req), in_range))
        if pool is not None
        else [_plan_for_block(b, req) for b in in_range]
    )
    live: list[tuple[BackendBlock, object]] = []
    for blk, p in zip(in_range, plans):
        if p.prune:
            continue
        if any(c.target not in (T_SPAN, T_RES, T_TRACE) for c in p.conds):
            return None  # generic-attr tables stay on the per-block path
        live.append((blk, p))
    if not live:
        return resp

    # identical plan structure -> one compiled mesh program per group
    groups: dict[tuple, list[tuple[BackendBlock, object]]] = {}
    for blk, p in live:
        groups.setdefault((p.tree, p.conds), []).append((blk, p))

    limit = req.limit or default_limit
    results: list[SearchResult] = []
    for (tree, conds), items in groups.items():
        got = _search_group_device(items, tree, conds, req, mesh, resp, pool)
        if got is None:
            return None
        results.extend(got)
    results.sort(key=lambda r: -r.start_time_unix_nano)
    # replicated partials hit in several blocks: dedupe by trace id, same
    # as the per-block path's SearchResponse.merge
    seen: set[str] = set()
    deduped = []
    for r in results:
        if r.trace_id not in seen:
            seen.add(r.trace_id)
            deduped.append(r)
    resp.traces = deduped[:limit]
    return resp


def _search_group_device(items, tree, conds, req: SearchRequest, mesh, resp: SearchResponse,
                         pool=None):
    from ..ops.device import PAD_I32, bucket
    from ..parallel.search import sharded_search

    dp, sp = mesh.shape["dp"], mesh.shape["sp"]
    needed = required_columns(conds)
    span_cols = [n for n in needed if n.startswith("span.")]
    B = len(items)
    Bp = ((B + dp - 1) // dp) * dp
    s_max = max(blk.pack.axes[S.AX_SPAN].n_rows for blk, _ in items)
    S_b = sp * bucket(max(1, -(-max(s_max, 1) // sp)))
    if Bp * S_b * 4 * max(1, len(span_cols)) > _DEVICE_SEARCH_MAX_BYTES:
        return None
    NT_b = bucket(max(max(blk.meta.total_traces for blk, _ in items), 1))

    host: dict[str, np.ndarray] = {}

    def read_block_cols(blk):
        return {n: blk.pack.read(n) for n in needed}

    if pool is not None:  # overlap per-block column IO, like the host path
        per_block = list(pool.map(read_block_cols, [blk for blk, _ in items]))
    else:
        per_block = [read_block_cols(blk) for blk, _ in items]
    n_res_per = [
        max((a.shape[0] for n, a in cols.items() if n.startswith("res.")), default=1)
        for cols in per_block
    ]
    R_b = bucket(max(max(n_res_per), 1))
    for n in needed:
        if n.startswith("span."):
            shape, fill = (Bp, S_b), PAD_I32
        elif n.startswith("res."):
            shape, fill = (Bp, R_b), PAD_I32
        elif n.startswith("trace."):
            shape, fill = (Bp, NT_b), PAD_I32
        else:
            return None  # attr tables never reach here (guarded above)
        first = per_block[0][n]
        if first.dtype not in (np.int32, np.float32):
            return None
        out = np.full(shape, fill if first.dtype == np.int32 else np.float32(0), dtype=first.dtype)
        for bi, cols in enumerate(per_block):
            a = cols[n]
            out[bi, : a.shape[0]] = a
        host[n] = out

    n_spans = np.zeros((Bp,), dtype=np.int32)
    for bi, (blk, _) in enumerate(items):
        n_spans[bi] = blk.pack.axes[S.AX_SPAN].n_rows
    operands = [Operands.build(p.rows, p.tables or None) for _, p in items]
    operands += [Operands.build([(0, 0, 0, 0.0, 0.0)] * len(conds))] * (Bp - B)
    tm, sc = sharded_search(mesh, tree, conds, operands, host, n_spans, nt=NT_b)

    results: list[SearchResult] = []
    for bi, (blk, p) in enumerate(items):
        nt = blk.meta.total_traces
        sids = np.nonzero(tm[bi][:nt])[0]
        sids = _verify_candidates(blk, req, sids, p.needs_verify)
        results.extend(_verify_and_build(blk, req, sids, sc[bi]))
        resp.inspected_spans += int(n_spans[bi])
        resp.inspected_bytes += blk.pack.bytes_read
    return results


# ---- tag name/value discovery (reference: /api/search/tags endpoints)


def search_tags(blk: BackendBlock, collector: DistinctStringCollector) -> None:
    d = blk.dictionary
    for col in ("sattr.key_id", "rattr.key_id"):
        codes = np.unique(blk.pack.read(col))
        for c in codes:
            if c >= 0:
                collector.collect(d.string(int(c)))
    # well-known resource attrs live only in dedicated columns
    for tag, col in _WELL_KNOWN_RES.items():
        if blk.pack.has(col) and (blk.pack.read(col) >= 0).any():
            collector.collect(tag)


def search_tag_values(blk: BackendBlock, tag: str, collector: DistinctStringCollector) -> None:
    d = blk.dictionary
    kcode = d.lookup(tag)
    if tag == _INTRINSIC_NAME:
        for c in np.unique(blk.pack.read("span.name_id")):
            if c >= 0:
                collector.collect(d.string(int(c)))
        return
    ded = _WELL_KNOWN_RES.get(tag)
    if ded and blk.pack.has(ded):
        for c in np.unique(blk.pack.read(ded)):
            if c >= 0:
                collector.collect(d.string(int(c)))
    if kcode < 0:
        return
    for pre in ("sattr", "rattr"):
        keys = blk.pack.read(f"{pre}.key_id")
        mask = keys == kcode
        if not mask.any():
            continue
        vt = blk.pack.read(f"{pre}.vtype")[mask]
        sid = blk.pack.read(f"{pre}.str_id")[mask]
        i64 = blk.pack.read(f"{pre}.int64")[mask]
        for j in range(len(vt)):
            if vt[j] == 0:
                collector.collect(d.string(int(sid[j])))
            elif vt[j] == 1:
                collector.collect(str(int(i64[j])))
            elif vt[j] == 3:
                collector.collect("true" if i64[j] else "false")
