"""Multi-chip metrics orchestration: stack blocks onto the mesh fold.

Glue between db/metrics_exec and parallel/timeseries: plans every
in-range block, GLOBALIZES the per-block group keys (the union of all
blocks' label tuples becomes the shared group axis -- per-block
dictionary codes never cross a block boundary), stacks padded per-block
columns, and runs the sharded fold whose psum lands the combined
[num_groups, num_buckets] accumulators on every chip.

Falls back (returns False) whenever any block needs the exact engine,
a cond target needs the generic attr tables, the stacked footprint
exceeds the device budget, or fewer than two blocks survive pruning --
the per-block engines in metrics_exec then take over unchanged.
"""

from __future__ import annotations

import numpy as np

from ..block import schema as S
from ..ops.device import PAD_I32, bucket
from ..ops.filter import Operands, required_columns
from ..traceql.plan import plan_metrics_filter

_MESH_MAX_BYTES = 512 << 20  # stacked-column budget (shared with search)


def _fallback(reason: str, n: int = 1) -> bool:
    """Record WHY the stacked mesh fold bowed out (the per-block engines
    take over) and return False for the caller to propagate."""
    from ..util.kerneltel import TEL

    TEL.record_routing("metrics_mesh", "fallback", reason, n)
    return False


def try_metrics_mesh(mesh, blocks, q, req, resp) -> bool:
    """Attempt the stacked mesh fold; True when resp now holds the
    complete answer for `blocks`, False to fall back per-block."""
    from ..parallel.timeseries import MESH_TARGETS, sharded_timeseries
    from .metrics_exec import (
        _block_axis,
        _outs_to_series,
        _value_column,
        resolve_groups,
    )

    if req.step_ms >= 2**31:
        return _fallback("i32_step")  # the mesh kernel buckets in int32 ms
    has_val = q.agg.field is not None
    items = []
    for blk in blocks:
        planned = plan_metrics_filter(q, blk.dictionary)
        if planned.prune:
            continue
        if planned.needs_verify:
            return _fallback("lossy_plan")
        if any(c.target not in MESH_TARGETS for c in planned.conds):
            return _fallback("attr_targets")
        groups = resolve_groups(blk, q.agg.by)
        if groups is None:
            return _fallback("unplannable_by")
        vals = _value_column(blk, q.agg.field) if has_val else None
        if has_val and vals is None:
            return _fallback("unplannable_value")
        _, nb, t0_rel = _block_axis(blk, req)
        if nb == 0:
            continue
        # the stacked fold uses one shared bucket axis: the full request
        # origin must stay within the block's int32-relative-ms range
        t0_full = req.start_ms - blk.meta.start_time_unix_nano // 1_000_000
        if not -(2**31) < t0_full < 2**31:
            return _fallback("i32_origin")
        items.append((blk, planned, groups, vals, t0_full))
    if len(items) < 2:
        return _fallback("too_few_blocks")

    # global group table: label tuples are the cross-block join key
    label_index: dict[tuple, int] = {}
    for _, _, (_gid, labels), _, _ in items:
        for lab in labels:
            label_index.setdefault(lab, len(label_index))
    glabels = list(label_index)
    if not glabels:
        for blk, _, _, _, _ in items:
            resp.inspected_spans += blk.pack.axes[S.AX_SPAN].n_rows
        return True
    from .metrics_exec import MAX_ACC_CELLS

    NB = req.n_buckets
    if bucket(len(glabels)) * bucket(NB) > MAX_ACC_CELLS:
        return _fallback("cardinality")

    ndev = int(mesh.devices.size)
    by_plan: dict[tuple, list] = {}
    for it in items:
        by_plan.setdefault((it[1].tree, it[1].conds), []).append(it)

    io0 = {id(blk): blk.pack.bytes_read for blk, _, _, _, _ in items}
    # two phases: EVERY plan group must stack and pass its budget/dtype
    # checks before ANY fold merges into resp -- a late fallback after a
    # partial merge would double-count those blocks when the per-block
    # engines re-run the full set
    runs = []
    for (tree, conds), its in by_plan.items():
        needed = [n for n in required_columns(conds)
                  if not n.startswith("span@") and n != "trace.span_off"]
        if "span.start_ms" not in needed:
            needed.append("span.start_ms")
        B = len(its)
        Bp = -(-B // ndev) * ndev
        s_max = max(blk.pack.axes[S.AX_SPAN].n_rows for blk, *_ in its)
        S_b = bucket(max(s_max, 1))
        NT_b = bucket(max(max(blk.meta.total_traces for blk, *_ in its), 1))
        # budget estimate BEFORE any column IO (footer row counts via
        # pack.n_rows_of, the same pre-read discipline as the search
        # group estimate): an over-budget attempt must fall back without
        # paying the cold reads it would then throw away
        res_cols = [n for n in needed if n.startswith("res.")]
        r_max = max((blk.pack.n_rows_of(n) for blk, *_ in its
                     for n in res_cols), default=1)
        R_b = bucket(max(r_max, 1))
        n_span_cols = sum(1 for n in needed if n.startswith("span."))
        n_trace_cols = sum(1 for n in needed if n.startswith("trace."))
        est = Bp * 4 * (S_b * (n_span_cols + 2 + (1 if has_val else 0))
                        + R_b * max(1, len(res_cols)) + NT_b * n_trace_cols)
        if est > _MESH_MAX_BYTES:
            return _fallback("pre_io_budget", n=len(its))
        per_block = [{n: blk.pack.read(n) for n in needed if blk.pack.has(n)}
                     for blk, *_ in its]

        host: dict[str, np.ndarray] = {}
        for n in needed:
            if n.startswith("span."):
                shape = (Bp, S_b)
            elif n.startswith("res."):
                shape = (Bp, R_b)
            elif n.startswith("trace."):
                shape = (Bp, NT_b)
            else:
                return _fallback("axis_shape")
            first = next((c[n] for c in per_block if n in c), None)
            if first is None or first.dtype not in (np.int32, np.float32):
                return _fallback("dtype")
            fill = PAD_I32 if first.dtype == np.int32 else np.float32(0)
            out = np.full(shape, fill, dtype=first.dtype)
            for bi, cols in enumerate(per_block):
                a = cols.get(n)
                if a is not None:
                    out[bi, : a.shape[0]] = a
            host[n] = out

        n_spans = np.zeros(Bp, np.int32)
        t0_arr = np.zeros(Bp, np.int32)
        gid = np.full((Bp, S_b), -1, np.int32)
        val = np.zeros((Bp, S_b), np.float32) if has_val else None
        pres = np.zeros((Bp, S_b), bool) if has_val else None
        operands = []
        for bi, (blk, planned, (bgid, blabels), vals, t0_full) in enumerate(its):
            ns = blk.pack.axes[S.AX_SPAN].n_rows
            n_spans[bi] = ns
            t0_arr[bi] = t0_full
            remap = np.asarray([label_index[lab] for lab in blabels], np.int32)
            if remap.size:
                gid[bi, :ns] = np.where(bgid >= 0,
                                        remap[np.clip(bgid, 0, remap.size - 1)],
                                        np.int32(-1))
            if has_val:
                v, p = vals
                val[bi, :ns] = v.astype(np.float32)
                pres[bi, :ns] = p
            operands.append(Operands.build(planned.rows, planned.tables or None))
        operands += [Operands.build([(0, 0, 0, 0.0, 0.0)] * len(conds))] * (Bp - B)
        runs.append((tree, tuple(conds), operands, host, n_spans, t0_arr,
                     gid, val, pres))

    # every group passed: fold and merge (no fallback past this point)
    from ..util.kerneltel import TEL

    TEL.record_routing("metrics_mesh", "device", "stacked", n=len(items))
    for (tree, conds, operands, host, n_spans, t0_arr, gid, val, pres) in runs:
        outs = sharded_timeseries(
            mesh, tree, conds, operands, host, n_spans, t0_arr,
            gid, val, pres, req.step_ms, NB, len(glabels))
        _outs_to_series(outs, q.agg.fn, glabels, 0, resp)
        resp.inspected_spans += int(n_spans.sum())
    resp.inspected_bytes += sum(
        blk.pack.bytes_read - io0[id(blk)] for blk, *_ in items)
    return True
