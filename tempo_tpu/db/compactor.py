"""Compaction: block selection, N-way merge, retention.

Selector follows the reference's time-window policy
(tempodb/compaction_block_selector.go:29-47): blocks bucket by time
window; inside the active window (default 24h) only same-level blocks
compact together, older windows compact anything. Each chosen job gets a
deterministic hash string (`tenant-level-window-...`) so a compactor
ring can assign ownership (services/compactor).

Merge strategy: blocks are id-sorted, so compaction is a K-way sorted
merge. Unique-id traces (the overwhelming majority) take the columnar
fast path -- their span/attr rows are gathered block-by-block in sorted
runs without decoding; duplicate ids are materialized to the wire model,
combined with span dedupe (wire/combine.py), and re-flattened. Bloom
filters are NOT re-built key-by-key: when input geometries match, the
output bloom is the device bitwise-OR union (ops/bloom_ops.py), the
north-star sketch-union.
"""

from __future__ import annotations

import hashlib
import time
from dataclasses import dataclass, field

from ..backend.base import RawBackend
from ..block.builder import BlockBuilder, write_block
from ..block.meta import BlockMeta
from ..block.reader import BackendBlock
from ..wire.combine import combine_traces

DEFAULT_ACTIVE_WINDOW_S = 24 * 3600
DEFAULT_WINDOW_S = 3600
DEFAULT_MAX_INPUT_BLOCKS = 4
DEFAULT_MAX_BLOCK_BYTES = 100 * 1024 * 1024 * 1024


@dataclass
class CompactionJob:
    tenant: str
    blocks: list[BlockMeta]
    hash: str = ""

    def __post_init__(self):
        if not self.hash and self.blocks:
            ids = "-".join(sorted(b.block_id for b in self.blocks))
            level = self.blocks[0].compaction_level
            self.hash = f"{self.tenant}-{level}-{hashlib.sha1(ids.encode()).hexdigest()[:16]}"


@dataclass
class CompactorConfig:
    window_s: int = DEFAULT_WINDOW_S
    active_window_s: int = DEFAULT_ACTIVE_WINDOW_S
    max_input_blocks: int = DEFAULT_MAX_INPUT_BLOCKS
    min_input_blocks: int = 2
    max_block_bytes: int = DEFAULT_MAX_BLOCK_BYTES
    max_compaction_level: int = 4
    retention_s: int = 14 * 24 * 3600
    compacted_retention_s: int = 3600
    row_group_spans: int = 1 << 16
    columnar: bool = True  # numpy-level merge fast path (columnar_compact.py)
    target_block_bytes: int = 0  # output size cut; 0 -> max_block_bytes
    # output zstd level: compaction rewrites every byte, so the fast
    # level keeps the compactor ahead of ingest (the reference trades
    # the same way: snappy on the write-heavy v2 path); ingest-time
    # block builds keep level 3
    zstd_level: int = 1
    # blocks below the final compaction level are REWRITTEN again soon,
    # so they get zstd's fast negative mode: ~30% faster compress AND
    # ~60% faster decompress on the next job's read side, for ~20% more
    # bytes held only until the next merge. Final-level outputs (the
    # long-lived, query-serving blocks) keep zstd_level.
    zstd_level_intermediate: int = -3
    # level-0 jobs whose inputs are ALL at most this size take the
    # no-decode concat path into a compound block (concat_compact.py);
    # 0 disables. Parts surface one level up, where the ordinary
    # columnar rewrite merges them for real.
    concat_small_input_bytes: int = 8 << 20
    # ---- pipelined concurrent execution (db/compact_pipeline.py) ----
    # worker threads running jobs concurrently; None resolves from the
    # TEMPO_COMPACT_CONCURRENCY env (default 1 = sequential). Jobs own
    # disjoint input block sets, so they are safe to run in parallel.
    concurrency: int | None = None
    # host-RAM admission budget for in-flight jobs; None resolves from
    # TEMPO_COMPACT_MEM_BUDGET (bytes, default 1 GiB). A job's estimated
    # peak is sum(input size_bytes) * pipeline_expansion; jobs above the
    # remaining budget wait at the admission gate (one always admits, so
    # an oversized job stalls the pipeline rather than deadlocking it).
    pipeline_mem_budget_bytes: int | None = None
    # decoded-columns + merge-scratch expansion over compressed input
    # bytes, for the admission estimate
    pipeline_expansion: float = 3.0
    # how many not-yet-admitted jobs the prefetch stage may run ahead of
    # the workers (ranged-read pack preloads; 0 disables prefetch)
    prefetch_depth: int = 2

    def level_for(self, out_level: int) -> int:
        """Output zstd level for a block produced at out_level: final
        (long-lived, query-serving) blocks get zstd_level, blocks still
        below max_compaction_level get the fast intermediate mode."""
        return (self.zstd_level if out_level >= self.max_compaction_level
                else self.zstd_level_intermediate)


def select_jobs(tenant: str, metas: list[BlockMeta], cfg: CompactorConfig, now: float | None = None) -> list[CompactionJob]:
    """Group by (window, level-in-active-window); emit jobs of
    min..max_input_blocks."""
    now = now or time.time()
    buckets: dict[tuple, list[BlockMeta]] = {}
    for m in metas:
        if m.compacted_at_unix:
            # the blocklist keeps freshly-compacted blocks SEARCHABLE for
            # a grace window (blocklist.COMPACTED_GRACE_S); they are not
            # compaction inputs -- their data already lives in an output
            continue
        if m.compaction_level >= cfg.max_compaction_level:
            continue
        end_s = m.end_time_unix_nano / 1e9
        window = int(end_s // cfg.window_s)
        active = (now - end_s) < cfg.active_window_s
        key = (window, m.compaction_level) if active else (window, -1)
        buckets.setdefault(key, []).append(m)

    jobs = []
    for key in sorted(buckets):
        group = sorted(buckets[key], key=lambda m: m.size_bytes)
        batch: list[BlockMeta] = []
        size = 0
        for m in group:
            if m.size_bytes > cfg.max_block_bytes:
                # already over the output cap on its own: merging it with
                # ANY neighbor exceeds max_block_bytes, so it never joins
                # a batch -- skip it WITHOUT cutting the batch in
                # progress, so its neighbors still compact
                continue
            if len(batch) >= cfg.max_input_blocks or (batch and size + m.size_bytes > cfg.max_block_bytes):
                if len(batch) >= cfg.min_input_blocks:
                    jobs.append(CompactionJob(tenant, batch))
                batch, size = [], 0
            batch.append(m)
            size += m.size_bytes
        if len(batch) >= cfg.min_input_blocks:
            jobs.append(CompactionJob(tenant, batch))
    return jobs


@dataclass
class CompactionResult:
    new_blocks: list[BlockMeta] = field(default_factory=list)
    compacted_ids: list[str] = field(default_factory=list)
    traces_out: int = 0
    spans_out: int = 0


def _union_input_blooms(blocks: list[BackendBlock]):
    """Device OR-union of the inputs' bloom filters when geometries match
    (the north-star sketch union, ops/bloom_ops.py). Valid because the
    output block's trace-id set is exactly the union of the inputs';
    duplicate ids merge but never vanish. Returns None on geometry
    mismatch (caller re-inserts ids instead)."""
    geos = {(b.meta.bloom_shards, b.meta.bloom_shard_bits) for b in blocks}
    if len(geos) != 1:
        return None
    n_shards, bits = geos.pop()
    if not n_shards:
        return None
    # capacity check: the union holds the SUM of the inputs' id sets in the
    # inputs' geometry. Only union while that stays within the geometry's
    # design load (~bits_per_item at the target fp rate), else the filter
    # saturates across compaction levels -- rebuild sized for the merged
    # count instead (like the reference's compactor bloom rebuild).
    import math

    import numpy as np

    from ..block.bloom import DEFAULT_FP_RATE

    bits_per_item = max(1.0, -math.log(DEFAULT_FP_RATE) / (math.log(2) ** 2))
    total_ids = sum(b.meta.total_traces for b in blocks)
    if total_ids * bits_per_item > n_shards * bits:
        return None

    from ..block.bloom import ShardedBloom
    from ..ops.bloom_ops import union_blooms

    sbs = []
    for b in blocks:
        sb = ShardedBloom(n_shards, bits)
        sb.words = np.stack([b.bloom_shard(i) for i in range(n_shards)])
        sbs.append(sb)
    return union_blooms(sbs)


def concat_eligible(job: CompactionJob, cfg: CompactorConfig) -> bool:
    """True when the job takes the no-decode concat path (all-small
    level-0 inputs). Shared with the pipeline executor so both drivers
    route identically."""
    return bool(cfg.concat_small_input_bytes
                and len(job.blocks) >= 2
                and all(m.compaction_level == 0
                        and m.version in ("vtpu1", "vtpu2")
                        and 0 < m.size_bytes <= cfg.concat_small_input_bytes
                        for m in job.blocks))


def compact(backend: RawBackend, job: CompactionJob, cfg: CompactorConfig) -> CompactionResult:
    """Run one compaction job: no-decode CONCAT for all-small level-0
    inputs (concat_compact.py: verbatim copies into one compound block
    at backend IO speed), the columnar numpy-level merge
    (columnar_compact.py) otherwise, falling back to the wire-level
    merge only when the inputs aren't columnar-mergeable."""
    if concat_eligible(job, cfg):
        from .concat_compact import compact_concat

        return compact_concat(backend, job, cfg)
    if cfg.columnar:
        from .columnar_compact import UnsupportedColumnar, compact_columnar

        try:
            return compact_columnar(backend, job, cfg)
        except UnsupportedColumnar:
            pass
    return _compact_wire(backend, job, cfg)


def _compact_wire(backend: RawBackend, job: CompactionJob, cfg: CompactorConfig) -> CompactionResult:
    """Wire-model merge: every trace decodes to the wire model and
    re-encodes through the builder. Correct for any inputs; used as the
    columnar fast path's fallback."""
    from ..block.versioned import open_block_versioned

    blocks = [open_block_versioned(backend, m) for m in job.blocks]
    out_level = max(m.compaction_level for m in job.blocks) + 1
    builder = BlockBuilder(
        job.tenant,
        row_group_spans=cfg.row_group_spans,
        compaction_level=out_level,
    )

    # K-way merge over each block's sorted trace-id index
    cursors = []
    for bi, blk in enumerate(blocks):
        ids = blk.trace_index["trace.id"]
        if ids.shape[0]:
            cursors.append([ids, 0, bi])

    import heapq

    heap = [(c[0][c[1]].tobytes(), i) for i, c in enumerate(cursors)]
    heapq.heapify(heap)
    result = CompactionResult()
    while heap:
        tid, ci = heap[0]
        # collect all cursors positioned at this id
        same: list[tuple[int, int]] = []  # (cursor idx, sid)
        while heap and heap[0][0] == tid:
            _, ci = heapq.heappop(heap)
            ids, pos, bi = cursors[ci]
            same.append((ci, pos))
            pos += 1
            cursors[ci][1] = pos
            if pos < ids.shape[0]:
                heapq.heappush(heap, (ids[pos].tobytes(), ci))
        traces = [blocks[cursors[ci][2]].materialize_traces([sid])[0] for ci, sid in same]
        combined = combine_traces(traces) if len(traces) > 1 else traces[0]
        builder.add_trace(tid, combined)
        result.traces_out += 1

    fin = builder.finalize(bloom=_union_input_blooms(blocks))
    result.spans_out = fin.meta.total_spans
    meta = write_block(backend, fin, level=cfg.level_for(out_level))
    result.new_blocks = [meta]
    result.compacted_ids = [m.block_id for m in job.blocks]
    for m in job.blocks:
        backend.mark_compacted(job.tenant, m.block_id)
    return result


@dataclass
class RetentionResult:
    marked: list[str] = field(default_factory=list)
    deleted: list[str] = field(default_factory=list)


def apply_retention(
    backend: RawBackend,
    tenant: str,
    metas: list[BlockMeta],
    compacted: list[BlockMeta],
    cfg: CompactorConfig,
    now: float | None = None,
    owns=lambda h: True,
) -> RetentionResult:
    """Mark live blocks past retention as compacted, delete compacted
    blocks past compacted-retention (reference: tempodb/retention.go:37-90)."""
    now = now or time.time()
    out = RetentionResult()
    cutoff_ns = (now - cfg.retention_s) * 1e9
    for m in metas:
        if m.compacted_at_unix:
            continue  # grace-listed (already compacted): not a live block
        if m.end_time_unix_nano < cutoff_ns and owns(m.block_id):
            backend.mark_compacted(tenant, m.block_id)
            out.marked.append(m.block_id)
    for m in compacted:
        if m.compacted_at_unix:
            # delete only once compacted_retention has elapsed SINCE THE
            # MARK (retention.go:70-90): a block compacted long after its
            # data window still gets its full grace period. Never sooner
            # than the blocklist's searchable-grace window, or a search
            # could open a block retention just deleted.
            from .blocklist import COMPACTED_GRACE_S

            hold = max(cfg.compacted_retention_s, COMPACTED_GRACE_S)
            expired = m.compacted_at_unix < now - hold
        else:  # legacy marker without a stamp: fall back to block end
            expired = m.end_time_unix_nano < (
                now - cfg.retention_s - cfg.compacted_retention_s
            ) * 1e9
        if "/" in m.block_id:
            # a PART of a compound block: its bytes are reclaimed when
            # the whole compound ages out (deleting a part's directory
            # would also delete its compacted marker, resurrecting the
            # part as a live-but-dataless block at the next poll)
            continue
        if expired and owns(m.block_id):
            backend.delete_block(tenant, m.block_id)
            out.deleted.append(m.block_id)
    return out
