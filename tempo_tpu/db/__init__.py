from .tempodb import TempoDB, TempoDBConfig
from .search import SearchRequest, SearchResult, SearchResponse
