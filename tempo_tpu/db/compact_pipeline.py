"""Pipelined concurrent compaction: overlap fetch, merge, assemble and
write across jobs and output blocks.

The sequential driver (db/compactor.compact) runs one job, one stage at
a time: backend IO, the numpy/native merge, and zstd+write never overlap
even though jobs own disjoint block sets. This executor turns the
compactor into a bounded, memory-budgeted pipeline (the write-side
analog of PR 3's admission-window batching on the query side):

  * job-level concurrency: TEMPO_COMPACT_CONCURRENCY worker threads run
    whole jobs in parallel. Compaction is IO + C-extension work (ranged
    reads, memcpy gathers, zstd/zlib), all of which drops the GIL, so
    even the 1-2 core compactor box overlaps one job's reads with
    another's compress+write.
  * admission gate: a job's estimated peak host RAM is
    sum(input size_bytes) x pipeline_expansion; jobs wait at the gate
    while the in-flight estimate would exceed the budget. One job always
    admits, so an oversized job stalls the pipeline instead of
    deadlocking it.
  * per-tenant round-robin fairness: the admission order interleaves
    tenants (the RequestQueue rotation shape, applied to a fixed job
    set), so one tenant's backlog can't starve the others.
  * input prefetch: while admitted jobs merge, a prefetch thread runs up
    to prefetch_depth jobs ahead, opening readers and preloading small
    packs via the existing one-ranged-read path (_Source.PRELOAD_MAX
    _BYTES), charged against the same memory budget.
  * assemble/write double-buffering: within a multi-output columnar job,
    output block k+1 assembles while block k compresses and streams
    through write_block's ordered writer thread -- a bounded queue of
    depth 1, so at most one finalized block waits in memory.

Crash/ordering safety: outputs are written with defer_meta=True and
their meta.json objects publish only after EVERY output's data is
durable; input blocks are mark_compacted strictly after the last
publish. A crash anywhere before the publish point (the whole
fetch/merge/assemble/write span) leaves nothing visible to blocklist
polling and no input consumed, so a re-run converges. The publish loop
itself is the one narrow window left: a crash between meta publishes
surfaces some outputs with inputs unmarked -- the rerun duplicates
those traces, which query-time dedupe (wire/combine) already renders
harmless (the same double-visibility the poller's swap-window grace
relies on) until the next level folds them. Output bytes are
bit-identical to a sequential run: the pipeline reorders WORK, never
data.

Everything observable lands in util/kerneltel.TEL: per-stage wall-time
histograms, jobs/bytes in flight, admission queue depth, prefetch
hit/miss/waste, and the per-run overlap ratio -- surfaced through
/metrics and /status/kernels.
"""

from __future__ import annotations

import os
import queue as _queue
import threading
import time
from collections import deque
from dataclasses import dataclass

from ..backend.base import RawBackend
from ..block.builder import publish_block_meta, write_block
from ..util.kerneltel import TEL
from .compactor import (
    CompactionJob,
    CompactionResult,
    CompactorConfig,
    _compact_wire,
    compact,
    concat_eligible,
)

DEFAULT_MEM_BUDGET_BYTES = 1 << 30


def resolve_concurrency(cfg: CompactorConfig) -> int:
    """Worker count: config wins, then TEMPO_COMPACT_CONCURRENCY, then 1
    (sequential)."""
    if cfg.concurrency is not None:
        return max(1, int(cfg.concurrency))
    try:
        return max(1, int(os.environ.get("TEMPO_COMPACT_CONCURRENCY", "") or 1))
    except ValueError:
        return 1


def resolve_mem_budget(cfg: CompactorConfig) -> int:
    """Admission budget in bytes: config, then TEMPO_COMPACT_MEM_BUDGET,
    then 1 GiB."""
    if cfg.pipeline_mem_budget_bytes is not None:
        return max(1, int(cfg.pipeline_mem_budget_bytes))
    try:
        return max(1, int(os.environ.get("TEMPO_COMPACT_MEM_BUDGET", "")
                          or DEFAULT_MEM_BUDGET_BYTES))
    except ValueError:
        return DEFAULT_MEM_BUDGET_BYTES


@dataclass
class JobOutcome:
    """One job's terminal state; exactly one of result/error is set."""

    tenant: str
    job: CompactionJob
    result: CompactionResult | None = None
    error: Exception | None = None


@dataclass
class _Ticket:
    """One scheduled job plus its pipeline bookkeeping. All mutable
    fields are read/written under the pipeline's condition variable."""

    tenant: str
    job: CompactionJob
    est_bytes: int
    fetch_claimed: bool = False  # someone (prefetcher or worker) owns the fetch
    pf_accounted: bool = False  # est_bytes already charged by the prefetcher
    pf_failed: bool = False  # prefetch errored; the worker refetches
    blocks: list | None = None  # opened readers, packs preloaded
    fetch_seconds: float = 0.0


class CompactionPipeline:
    """Bounded pipeline executor over a fixed set of compaction jobs.

    One instance runs one job set (`run`); construct per sweep. Ring
    ownership is the CALLER's concern -- pass only owned jobs. Results
    surface in admission order; `on_result` (blocklist update hook)
    fires from worker threads as each job commits."""

    def __init__(self, backend: RawBackend, cfg: CompactorConfig,
                 concurrency: int | None = None):
        self.backend = backend
        self.cfg = cfg
        self.concurrency = max(1, concurrency if concurrency is not None
                               else resolve_concurrency(cfg))
        self.budget = resolve_mem_budget(cfg)
        self.expansion = max(1.0, float(cfg.pipeline_expansion))
        self.prefetch_depth = max(0, int(cfg.prefetch_depth))
        self._cv = threading.Condition()
        # ---- guarded by _cv ----
        self._tickets: list[_Ticket] = []
        self._next = 0  # admission cursor into _tickets
        self._inflight_jobs = 0
        self._inflight_bytes = 0  # admitted + prefetch-charged estimates
        self._stop = False

    # ------------------------------------------------------------ schedule
    def _round_robin(self, jobs_by_tenant: dict[str, list[CompactionJob]]
                     ) -> list[_Ticket]:
        """Deterministic admission order: tenants rotate, jobs FIFO
        within a tenant (the RequestQueue fairness pattern over a fixed
        job set)."""
        order = sorted(t for t, jobs in jobs_by_tenant.items() if jobs)
        queues: dict[str, deque] = {t: deque(jobs_by_tenant[t]) for t in order}
        out: list[_Ticket] = []
        while order:
            for t in list(order):
                q = queues[t]
                job = q.popleft()
                est = int(sum(m.size_bytes for m in job.blocks) * self.expansion)
                out.append(_Ticket(t, job, est_bytes=max(1, est)))
                if not q:
                    order.remove(t)
        return out

    # ---------------------------------------------------------------- run
    def run(self, jobs_by_tenant: dict[str, list[CompactionJob]],
            on_result=None) -> list[JobOutcome]:
        """Execute every job; returns outcomes in admission order.
        on_result(tenant, job, result) runs on the worker thread right
        after a job's commit point (outputs published, inputs marked) --
        an exception there converts the outcome to an error."""
        tickets = self._round_robin(jobs_by_tenant)
        if not tickets:
            return []
        TEL.begin_compact_run()
        t_run = time.perf_counter()
        with self._cv:
            self._tickets = tickets
            self._next = 0
            self._inflight_jobs = 0
            self._inflight_bytes = 0
            self._stop = False
        outcomes: list[JobOutcome | None] = [None] * len(tickets)
        n_workers = min(self.concurrency, len(tickets))
        workers = [
            threading.Thread(target=self._worker, args=(outcomes, on_result),
                             name=f"compact-worker-{i}", daemon=True)
            for i in range(n_workers)
        ]
        prefetcher = None
        if (self.prefetch_depth > 0 and len(tickets) > 1
                and any(self._prefetchable(t) for t in tickets)):
            # all-concat sweeps (the many-tiny-blocks shape) have nothing
            # to prefetch; don't run a thread that would only poll the cv
            prefetcher = threading.Thread(
                target=self._prefetcher, name="compact-prefetch", daemon=True)
            prefetcher.start()
        for w in workers:
            w.start()
        for w in workers:
            w.join()
        with self._cv:
            self._stop = True
            self._cv.notify_all()
        if prefetcher is not None:
            prefetcher.join()
        TEL.compact_inflight(0, 0, 0)
        TEL.record_compact_run(time.perf_counter() - t_run)
        return [oc for oc in outcomes if oc is not None]

    # ------------------------------------------------------------- workers
    def _worker(self, outcomes: list, on_result) -> None:
        while True:
            with self._cv:
                while True:
                    if self._next >= len(self._tickets):
                        return
                    t = self._tickets[self._next]
                    extra = 0 if t.pf_accounted else t.est_bytes
                    if (self._inflight_jobs == 0
                            or self._inflight_bytes + extra <= self.budget):
                        i = self._next
                        self._next += 1
                        self._inflight_jobs += 1
                        self._inflight_bytes += extra
                        break
                    # re-check on release notifications; the timeout only
                    # guards against a lost wakeup, not correctness
                    self._cv.wait(0.1)
                jobs_now = self._inflight_jobs
                bytes_now = self._inflight_bytes
                queued = len(self._tickets) - self._next
            TEL.compact_inflight(jobs_now, bytes_now, queued)
            in_bytes = sum(m.size_bytes for m in t.job.blocks)
            try:
                res = self._run_job(t)
                if on_result is not None:
                    on_result(t.tenant, t.job, res)
                outcomes[i] = JobOutcome(t.tenant, t.job, result=res)
                TEL.record_compact_job(in_bytes, ok=True)
            except Exception as e:  # noqa: BLE001 - one job must not kill the sweep
                outcomes[i] = JobOutcome(t.tenant, t.job, error=e)
                TEL.record_compact_job(in_bytes, ok=False)
            finally:
                with self._cv:
                    self._inflight_jobs -= 1
                    self._inflight_bytes -= t.est_bytes
                    jobs_now = self._inflight_jobs
                    bytes_now = self._inflight_bytes
                    queued = len(self._tickets) - self._next
                    self._cv.notify_all()
                # re-publish on release too, or the gauges overstate
                # occupancy for the whole drain tail of a run
                TEL.compact_inflight(jobs_now, bytes_now, queued)

    # ------------------------------------------------------------ prefetch
    def _prefetcher(self) -> None:
        """Run ahead of the admission cursor, opening readers and
        preloading small packs (one ranged read per pack) for jobs the
        workers will pick up next. Lookahead and bytes are bounded: at
        most prefetch_depth jobs past the active window, charged against
        the same admission budget."""
        while True:
            with self._cv:
                if self._stop:
                    return
                if self._next >= len(self._tickets):
                    return
                target = None
                hi = min(len(self._tickets),
                         self._next + self.concurrency + self.prefetch_depth)
                for j in range(self._next, hi):
                    c = self._tickets[j]
                    if c.fetch_claimed or not self._prefetchable(c):
                        continue
                    if self._inflight_bytes + c.est_bytes > self.budget:
                        # budget full: don't pile decode RAM ahead. No
                        # one-job exemption here -- skipping a prefetch
                        # can't deadlock (workers fetch for themselves),
                        # while exempting it would let charges stack past
                        # the budget whenever workers are between jobs
                        continue
                    c.fetch_claimed = True
                    c.pf_accounted = True
                    self._inflight_bytes += c.est_bytes
                    target = c
                    break
                if target is None:
                    self._cv.wait(0.05)
                    continue
            try:
                blocks, dt = self._fetch(target)
            except Exception:  # noqa: BLE001 - worker refetches and surfaces it
                blocks, dt = None, 0.0
                # the IO done before the failure is thrown away: the
                # worker refetches from scratch
                TEL.record_compact_prefetch("waste")
            with self._cv:
                if blocks is None:
                    target.pf_failed = True
                else:
                    target.blocks = blocks
                    target.fetch_seconds = dt
                self._cv.notify_all()

    def _prefetchable(self, t: _Ticket) -> bool:
        """Only columnar jobs consume opened readers; concat jobs copy
        raw objects and wire-merge jobs are the rare fallback."""
        return self.cfg.columnar and not concat_eligible(t.job, self.cfg)

    def _fetch(self, t: _Ticket) -> tuple[list, float]:
        """The IO stage: open every input's reader; small packs preload
        with one ranged read (idempotent -- _Source.from_block's own
        preload becomes a no-op)."""
        from ..block.versioned import open_block_versioned
        from .columnar_compact import _Source

        t0 = time.perf_counter()
        blocks = []
        for m in t.job.blocks:
            b = open_block_versioned(self.backend, m)
            pack = getattr(b, "pack", None)
            if (pack is not None and m.size_bytes
                    and m.size_bytes <= _Source.PRELOAD_MAX_BYTES):
                pack.preload()
            blocks.append(b)
        return blocks, time.perf_counter() - t0

    def _take_fetched(self, t: _Ticket) -> list:
        """Fetch stage from the worker's side: use the prefetched
        readers (hit), wait for an in-flight prefetch, or do the IO
        here (miss)."""
        with self._cv:
            wait_for_pf = t.fetch_claimed
            if not t.fetch_claimed:
                t.fetch_claimed = True
            while wait_for_pf and t.blocks is None and not t.pf_failed:
                self._cv.wait(0.05)
            blocks = t.blocks
            # drop the ticket's reference: tickets outlive their jobs
            # (the whole run), and a retained reader pins its preloaded
            # pack bytes -- the admission budget must be the only thing
            # holding job memory alive
            t.blocks = None
        if blocks is not None:
            TEL.record_compact_stage("fetch", t.fetch_seconds)
            TEL.record_compact_prefetch("hit")
            return blocks
        blocks, dt = self._fetch(t)
        TEL.record_compact_stage("fetch", dt)
        TEL.record_compact_prefetch("miss")
        return blocks

    # ------------------------------------------------------------ job body
    def _run_job(self, t: _Ticket) -> CompactionResult:
        """One job through the staged path. Concat and wire-merge jobs
        run their existing (already meta-last, mark-after-durable)
        bodies -- job-level concurrency is the win there; columnar jobs
        additionally overlap assemble with compress+write."""
        job, cfg = t.job, self.cfg
        is_concat = concat_eligible(job, cfg)
        if not cfg.columnar or is_concat:
            # unstaged job bodies get their own stage labels so the
            # per-stage histogram doesn't misattribute concat IO (ranged
            # reads + object copies) to the columnar write stage
            stage = "concat" if is_concat else "wire"
            t0 = time.perf_counter()
            res = compact(self.backend, job, cfg)
            TEL.record_compact_stage(stage, time.perf_counter() - t0)
            return res
        blocks = self._take_fetched(t)
        from .columnar_compact import UnsupportedColumnar, plan_columnar

        t0 = time.perf_counter()
        try:
            plan = plan_columnar(self.backend, job, cfg, blocks=blocks)
        except UnsupportedColumnar:
            TEL.record_compact_stage("merge", time.perf_counter() - t0)
            # rare fallback, straight to the wire merge: re-entering
            # compact() would re-fetch and re-decode every input just to
            # raise the same refusal again before landing there anyway
            t1 = time.perf_counter()
            res = _compact_wire(self.backend, job, cfg)
            TEL.record_compact_stage("wire", time.perf_counter() - t1)
            return res
        TEL.record_compact_stage("merge", time.perf_counter() - t0)
        try:
            return self._write_outputs(plan)
        except UnsupportedColumnar:
            # _assemble can refuse LATE (e.g. unknown column family).
            # Go STRAIGHT to the wire merge: re-entering the columnar
            # driver via compact() would publish early outputs
            # (defer_meta=False there) before deterministically refusing
            # again -- orphaned duplicates. _write_outputs already
            # reclaimed its unpublished outputs and no input is marked.
            t1 = time.perf_counter()
            res = _compact_wire(self.backend, job, cfg)
            TEL.record_compact_stage("wire", time.perf_counter() - t1)
            return res

    def _write_outputs(self, plan) -> CompactionResult:
        """Assemble/write double-buffer with an atomic commit: data for
        ALL outputs lands (defer_meta) before the first meta.json
        publishes; inputs mark_compacted only after every publish. The
        depth-1 queue bounds memory to one finalized block waiting."""
        from .columnar_compact import iter_outputs, write_output

        cfg = self.cfg
        result = CompactionResult()
        metas: list = []
        fins: _queue.Queue = _queue.Queue(maxsize=1)
        werr: list[BaseException] = []

        def _writer():
            # keep draining after a failure so the assembler never
            # deadlocks on put(); the error surfaces after join
            while True:
                fin = fins.get()
                if fin is None:
                    return
                if werr:
                    continue
                t0 = time.perf_counter()
                try:
                    metas.append(write_output(
                        self.backend, fin, cfg, plan.out_level,
                        defer_meta=True))
                except BaseException as e:  # noqa: BLE001 - surfaced after join
                    werr.append(e)
                finally:
                    TEL.record_compact_stage("write", time.perf_counter() - t0)

        wt = threading.Thread(target=_writer, name="compact-block-writer",
                              daemon=True)
        wt.start()
        aerr: BaseException | None = None
        try:
            it = iter_outputs(plan, cfg)
            while True:
                t0 = time.perf_counter()
                try:
                    fin = next(it)
                except StopIteration:
                    break
                TEL.record_compact_stage("assemble", time.perf_counter() - t0)
                if werr:
                    break
                fins.put(fin)
        except BaseException as e:  # noqa: BLE001 - re-raised below
            aerr = e
        finally:
            fins.put(None)
            wt.join()
        if werr or aerr is not None:
            # unpublished outputs (no meta.json) are invisible to
            # pollers; reclaim their data objects best-effort
            for m in metas:
                try:
                    self.backend.delete_block(m.tenant_id, m.block_id)
                except Exception:  # noqa: BLE001 - cleanup only
                    pass
            raise werr[0] if werr else aerr
        # ---- commit point ----
        for m in metas:
            publish_block_meta(self.backend, m)
            result.new_blocks.append(m)
            result.traces_out += m.total_traces
            result.spans_out += m.total_spans
        result.compacted_ids = [m.block_id for m in plan.job.blocks]
        for m in plan.job.blocks:
            self.backend.mark_compacted(plan.tenant, m.block_id)
        return result
