"""Columnar compaction: numpy-level K-way merge of vtpu blocks.

The fast path the reference takes at parquet.Row level (no proto decode,
vparquet/compactor.go:23-80) re-expressed for the vtpu SoA layout: blocks
are id-sorted, so the merge order is one lexsort over the stacked 128-bit
trace ids; maximal runs of consecutive traces from one block move as
COLUMN SLICES -- span rows, attr rows, events, links all come along via
their sorted owner columns with two searchsorteds per table. No wire
model anywhere on the unique-id path. Only colliding ids (replicated
partial traces) are materialized, combined with span dedupe
(wire/combine.py, the reference's combiner.go analog), and re-flattened
through a one-trace builder.

Output blocks cut at a size target estimated from input bytes/trace
(reference: tempodb/compactor.go:21-30 flush/size targets) and stream to
the backend through the appender (v2/streaming_block.go role): one
column's chunks in memory at a time, never the serialized block.

Dictionaries merge as a sorted string union; every code column remaps
through one gather. The output bloom is the device OR-union of the
inputs' filters when a single output block is cut and the union stays
within design capacity (ops/bloom_ops.py), else rebuilt batch-native.
"""

from __future__ import annotations

import os
import uuid
from dataclasses import dataclass

import numpy as np

from ..backend.base import DoesNotExist, RawBackend
from ..block import schema as S
from ..block.bloom import ShardedBloom
from ..block.builder import (
    BLOOM_PREFIX,
    DATA_NAME,
    DICT_NAME,
    BlockBuilder,
    FinalizedBlock,
    compute_row_groups,
    write_block,
)
from ..block.colio import is_broadcast
from ..block.dictionary import Dictionary, apply_remap
from ..block.meta import BlockMeta
from ..block.reader import BackendBlock
from ..wire.combine import combine_traces
from .compactor import (
    CompactionJob,
    CompactionResult,
    CompactorConfig,
    _union_input_blooms,
)


class UnsupportedColumnar(Exception):
    """Inputs this merge can't handle columnar-ly; caller falls back to
    the wire-level merge."""


# dict-code columns (remapped into the merged dictionary at load)
_DICT_COLS = frozenset(
    {
        "span.name_id", "span.service_id", "span.http_method_id", "span.http_url_id",
        "span.trace_state_id", "span.status_msg_id",
        "trace.root_service_id", "trace.root_name_id",
        "scope.name_id", "scope.version_id",
        "ev.name_id", "ln.state_id",
    }
    | set(S.WELL_KNOWN_RES_ATTRS.values())
    | {f"{p}.key_id" for p in ("sattr", "rattr", "evattr", "lnattr")}
    | {f"{p}.str_id" for p in ("sattr", "rattr", "evattr", "lnattr")}
)


_AXIS_PREFIXES = frozenset({"span", "trace", "sattr", "ev", "ln", "evattr", "lnattr"})


class _Source:
    """One input block (or one combined collision trace) as raw columns."""

    def __init__(self, cols: dict[str, np.ndarray], dictionary: Dictionary):
        self.cols = cols
        self.dictionary = dictionary
        self.span_off = cols["trace.span_off"]
        self.remap: np.ndarray | None = None
        self.fused_remap = False

    # below this size the whole pack is fetched with ONE ranged read
    # before decode (kills the per-chunk open/read fixed costs that
    # dominate the many-tiny-blocks compaction shape)
    PRELOAD_MAX_BYTES = 32 << 20

    @classmethod
    def from_block(cls, blk: BackendBlock, independent: bool = True) -> "_Source":
        if blk.meta.size_bytes and blk.meta.size_bytes <= cls.PRELOAD_MAX_BYTES:
            blk.pack.preload()
        # const columns arrive as stride-0 broadcast views: zero decode,
        # zero memory, and _assemble forwards them const when every
        # source agrees (the dominant case -- absent optional columns).
        # independent=True: _assemble's consume-as-you-go frees each
        # column after its output pass; views over one shared buffer
        # would pin the whole thing for as long as any column lived.
        # Multi-output jobs never consume, so the caller skips the copy.
        return cls(blk.pack.read_all(broadcast_const=True, independent=independent),
                   blk.dictionary)

    def remap_codes(self, remap: np.ndarray, fused: bool = False) -> None:
        """Re-encode dict-code columns into the merged dictionary. With
        fused=True (native available), axis columns skip the pre-pass:
        _assemble's copy kernel applies the remap in-flight, saving a
        full read+write pass over every code column."""
        self.remap = np.ascontiguousarray(remap, dtype=np.int32)
        self.fused_remap = fused
        for name in self.cols:
            if name in _DICT_COLS and not (
                fused and name.split(".", 1)[0] in _AXIS_PREFIXES
            ):
                self.cols[name] = apply_remap(self.cols[name], remap)

    def child_range(self, owner_col: str, lo: int, hi: int) -> tuple[int, int]:
        owner = self.cols[owner_col]
        return (int(np.searchsorted(owner, lo, "left")),
                int(np.searchsorted(owner, hi, "left")))


def _merge_order(ids: list[np.ndarray]):
    """Global id-sorted order over all source traces (one (n,16) id
    array per source). Returns (src_idx, sid, same_as_prev) arrays;
    same_as_prev marks duplicate-id entries (collisions)."""
    ids = [np.ascontiguousarray(x).reshape(-1, 16) for x in ids]
    n = sum(len(x) for x in ids)
    if n == 0:
        z = np.empty(0, dtype=np.int32)
        return z, z, np.empty(0, dtype=bool)
    all_ids = np.concatenate(ids)
    u = all_ids.view(">u8").astype(np.uint64).reshape(-1, 2)
    src = np.concatenate([np.full(len(x), i, np.int32) for i, x in enumerate(ids)])
    sid = np.concatenate([np.arange(len(x), dtype=np.int32) for x in ids])
    order = np.lexsort((src, u[:, 1], u[:, 0]))
    ou = u[order]
    same = np.zeros(n, dtype=bool)
    same[1:] = (ou[1:] == ou[:-1]).all(axis=1)
    return src[order], sid[order], same


def _combine_collision(blocks: list[BackendBlock], base_names: set[str],
                       members: list[tuple[int, int]], tenant: str) -> _Source:
    """Materialize + combine one duplicated trace id, re-flatten through a
    one-trace builder into a columnar source of its own."""
    b0, sid0 = members[0]
    tid = np.ascontiguousarray(
        blocks[b0].pack.read("trace.id")).reshape(-1, 16)[sid0].tobytes()
    traces = [blocks[b].materialize_traces([sid])[0] for b, sid in members]
    combined = combine_traces(traces)
    b = BlockBuilder(tenant)
    b.add_trace(tid, combined)
    fin = b.finalize()
    # today's builder may emit columns (e.g. tres.*) that pre-upgrade
    # input blocks lack; the merge machinery requires every source to
    # share one column set, so shape the collision source to the blocks'
    cols = {k: v for k, v in fin.cols.items() if k in base_names}
    if base_names - set(cols):
        raise UnsupportedColumnar(
            f"collision rebuild lacks columns {sorted(base_names - set(cols))}"
        )
    return _Source(cols, fin.dictionary)


def _const_source_row(s: _Source, n: str) -> np.ndarray | None:
    """The column's constant row if the source is constant on n: a
    stride-0 broadcast view (const-chunk read_all) or a small
    materialized array (collision rebuilds) that checks out constant.
    Code columns whose dictionary remap was deferred into the copy
    kernel (fused_remap) get the remap applied to the row here, so the
    returned row is always in the MERGED dictionary's code space."""
    a = s.cols[n]
    if a.ndim == 0 or a.size == 0:
        return None
    if is_broadcast(a):
        row = np.ascontiguousarray(a[0])
    elif a.nbytes <= 65536:
        row = np.ascontiguousarray(a[0])
        if not (a == row).all():
            return None
    else:
        return None
    if n in _DICT_COLS and s.fused_remap and s.remap is not None:
        if row.size != 1:
            return None
        v = int(row.reshape(-1)[0])
        row = np.asarray(
            s.remap[v] if 0 <= v < s.remap.shape[0] else v, dtype=a.dtype)
    return row


def _unique_vals(a: np.ndarray) -> np.ndarray:
    """np.unique that costs O(1) on stride-0 broadcast views."""
    if is_broadcast(a):
        return np.unique(np.ascontiguousarray(a[:1]))
    return np.unique(a)


def _ranges_to_idx(los: np.ndarray, his: np.ndarray) -> np.ndarray:
    """Vectorized multi-range arange: concatenate(arange(lo, hi) for each
    range) without a Python loop."""
    lens = his - los
    total = int(lens.sum())
    if total == 0:
        return np.empty(0, dtype=np.int64)
    starts = np.cumsum(lens) - lens
    return np.repeat(los - starts, lens) + np.arange(total, dtype=np.int64)


def _run_copy(src: np.ndarray, dst: np.ndarray, src_offs: np.ndarray,
              dst_offs: np.ndarray, lens: np.ndarray) -> None:
    """Move row runs src->dst: native per-run memcpy (no index arrays
    exist at all -- the index traffic, 8 bytes/row/column, used to cost
    more than the data), numpy fancy-index fallback (also taken on
    dtype mismatch, where memcpy would land rows at wrong offsets)."""
    from ..native import gather_runs

    if (src.size and src.dtype == dst.dtype
            and gather_runs(np.ascontiguousarray(src), dst, src_offs, dst_offs, lens)):
        return
    si = _ranges_to_idx(src_offs, src_offs + lens)
    di = _ranges_to_idx(dst_offs, dst_offs + lens)
    dst[di] = src[si]


def _packed_offs(lens: np.ndarray) -> np.ndarray:
    cs = np.cumsum(lens)
    return cs - lens


def _assemble(tenant: str, sources: list[_Source],
              chunks: tuple[np.ndarray, np.ndarray, np.ndarray],
              merged: Dictionary, level: int, row_group_spans: int,
              bloom: ShardedBloom | None,
              consume: bool = False) -> FinalizedBlock:
    """Assemble one output block from (src, sid_lo, sid_hi) run arrays.

    Everything is per-SOURCE vectorized: each axis of each source
    contributes via exactly one gather + one scatter per column, so cost
    does not degrade when the merge interleaves finely (many tiny runs,
    the 1000-small-blocks compaction shape)."""
    csrc, clo, chi = chunks
    csrc = csrc.astype(np.int32)
    n_chunks = csrc.shape[0]
    names = list(sources[int(csrc[0])].cols)
    src_order = [int(s) for s in np.unique(csrc)]
    by_src = {si: np.nonzero(csrc == si)[0] for si in src_order}

    # per-chunk row ranges along every axis (one vectorized searchsorted
    # per source per child axis)
    span_lo = np.zeros(n_chunks, np.int64)
    span_hi = np.zeros(n_chunks, np.int64)
    child_axes = {  # axis -> (owner col, parent range arrays)
        "sattr": "sattr.span", "ev": "ev.span", "ln": "ln.span",
        "evattr": "evattr.ev", "lnattr": "lnattr.ln",
    }
    ax_lo = {a: np.zeros(n_chunks, np.int64) for a in child_axes}
    ax_hi = {a: np.zeros(n_chunks, np.int64) for a in child_axes}
    for si in src_order:
        s = sources[si]
        ii = by_src[si]
        span_lo[ii] = s.span_off[clo[ii]]
        span_hi[ii] = s.span_off[chi[ii]]
        for a in ("sattr", "ev", "ln"):
            owner = s.cols[child_axes[a]]
            ax_lo[a][ii] = np.searchsorted(owner, span_lo[ii], "left")
            ax_hi[a][ii] = np.searchsorted(owner, span_hi[ii], "left")
        for a, parent in (("evattr", "ev"), ("lnattr", "ln")):
            owner = s.cols[child_axes[a]]
            ax_lo[a][ii] = np.searchsorted(owner, ax_lo[parent][ii], "left")
            ax_hi[a][ii] = np.searchsorted(owner, ax_hi[parent][ii], "left")

    # tres (trace-resource membership, builder.build_tres) is a
    # trace-child axis whose per-chunk ranges come straight from the
    # source's offsets column -- no searchsorted needed
    has_tres = "tres.res" in names
    tres_lo = np.zeros(n_chunks, np.int64)
    tres_hi = np.zeros(n_chunks, np.int64)
    if has_tres:
        for si in src_order:
            toff = sources[si].cols["trace.tres_off"].astype(np.int64)
            ii = by_src[si]
            tres_lo[ii] = toff[clo[ii]]
            tres_hi[ii] = toff[chi[ii]]

    # per-chunk output bases per axis
    def bases(lens: np.ndarray) -> tuple[np.ndarray, int]:
        cs = np.cumsum(lens)
        return cs - lens, int(cs[-1]) if len(lens) else 0

    tr_b, n_traces = bases(chi - clo)
    sp_b, n_spans = bases(span_hi - span_lo)
    ax_b = {}
    ax_n = {}
    for a in child_axes:
        ax_b[a], ax_n[a] = bases(ax_hi[a] - ax_lo[a])
    tres_b, n_tres = bases(tres_hi - tres_lo)

    # per (source, axis) RUN tables: (src row starts, dst row starts,
    # lens). Data moves by per-run memcpy (_run_copy); element-level
    # index arrays never exist except inside special-column temps.
    runs_of: dict[tuple[int, str], tuple[np.ndarray, np.ndarray, np.ndarray]] = {}
    axis_ranges = {"trace": (clo, chi, tr_b), "span": (span_lo, span_hi, sp_b)}
    for a in child_axes:
        axis_ranges[a] = (ax_lo[a], ax_hi[a], ax_b[a])
    if has_tres:
        axis_ranges["tres"] = (tres_lo, tres_hi, tres_b)
    for si in src_order:
        ii = by_src[si]
        for a, (alo, ahi, ab) in axis_ranges.items():
            runs_of[(si, a)] = (alo[ii], ab[ii], ahi[ii] - alo[ii])

    def dst_ordered_copy(axis: str, col: str, out: np.ndarray,
                         remap: bool = False) -> bool:
        """ONE copy pass per column in global dst order: dst writes
        stream sequentially and each source's reads stream too (the
        merge's memory-optimal order); per-run absolute src addresses
        carry the source interleave. remap=True fuses the dictionary
        re-encode into the same pass (per-run remap-table addresses)."""
        from ..native import gather_runs_addr, gather_runs_remap

        if any(sources[si].cols[col].dtype != out.dtype
               or sources[si].cols[col].shape[1:] != out.shape[1:]
               for si in src_order):
            return False  # raw row-byte copy needs uniform layout; caller
            # falls back to the dtype-converting numpy path (_run_copy guard)

        alo, ahi, ab = axis_ranges[axis]
        arrs = [np.ascontiguousarray(sources[si].cols[col]) for si in src_order]
        row_bytes = out.dtype.itemsize * int(np.prod(out.shape[1:], dtype=np.int64))
        base = np.zeros(len(sources), dtype=np.int64)
        for si, arr in zip(src_order, arrs):
            base[si] = arr.ctypes.data
        addrs = base[csrc] + alo * row_bytes
        if remap:
            rbase = np.zeros(len(sources), dtype=np.int64)
            rlen = np.zeros(len(sources), dtype=np.int64)
            for si in src_order:
                rbase[si] = sources[si].remap.ctypes.data
                rlen[si] = sources[si].remap.shape[0]
            return gather_runs_remap(addrs, out, ab, ahi - alo,
                                     rbase[csrc], rlen[csrc])
        return gather_runs_addr(addrs, out, ab, ahi - alo)

    def packed_gather(si: int, axis: str, src: np.ndarray) -> np.ndarray:
        """Gather source rows of one axis into PACKED dst order (the
        concatenation of this source's dst runs): the staging buffer for
        columns needing element-level math before placement. Broadcast
        (const) sources stay broadcast: any gather of a constant is the
        same constant."""
        s_offs, _, lens = runs_of[(si, axis)]
        n_packed = int(lens.sum())
        if is_broadcast(src):
            return np.broadcast_to(src[0], (n_packed,) + src.shape[1:])
        out = np.empty((n_packed,) + src.shape[1:], dtype=src.dtype)
        _run_copy(src, out, s_offs, _packed_offs(lens), lens)
        return out

    def packed_scatter(si: int, axis: str, packed: np.ndarray, out: np.ndarray) -> None:
        _, d_offs, lens = runs_of[(si, axis)]
        _run_copy(packed, out, _packed_offs(lens), d_offs, lens)

    # owner-column rebase offsets per PACKED row: dst parent base - src
    # parent lo, repeated per run
    parent_of = {"sattr": (sp_b, span_lo), "ev": (sp_b, span_lo), "ln": (sp_b, span_lo),
                 "evattr": (ax_b["ev"], ax_lo["ev"]), "lnattr": (ax_b["ln"], ax_lo["ln"])}

    def owner_off_packed(si: int, a: str) -> np.ndarray:
        ii = by_src[si]
        pb, plo = parent_of[a]
        return np.repeat(pb[ii] - plo[ii], (ax_hi[a] - ax_lo[a])[ii])

    # res/scope subsetting: only rows this block's spans reference
    span_resvals: dict[int, np.ndarray] = {}
    span_scopevals: dict[int, np.ndarray] = {}
    used_res: dict[int, np.ndarray] = {}
    used_scope: dict[int, np.ndarray] = {}
    res_base: dict[int, int] = {}
    scope_base: dict[int, int] = {}
    rb = sb = 0
    for si in src_order:
        rv = packed_gather(si, "span", sources[si].cols["span.res_idx"])
        sv = packed_gather(si, "span", sources[si].cols["span.scope_idx"])
        span_resvals[si], span_scopevals[si] = rv, sv
        ur = _unique_vals(rv)
        us = _unique_vals(sv)
        used_res[si] = ur[ur >= 0]
        used_scope[si] = us[us >= 0]
        res_base[si], scope_base[si] = rb, sb
        rb += used_res[si].shape[0]
        sb += used_scope[si].shape[0]

    def _translate(si: int, old: np.ndarray, used: dict[int, np.ndarray],
                   base: dict[int, int]) -> np.ndarray:
        u = used[si]
        if u.size and int(u[-1]) < (1 << 22):
            # dense lookup table: O(n) gather instead of the O(n log m)
            # searchsorted -- res/scope index spaces are small ints
            lut = np.zeros(int(u[-1]) + 1, np.int32)
            lut[u] = np.arange(u.size, dtype=np.int32)
            new = lut[np.clip(old, 0, int(u[-1]))] + base[si]
        else:
            new = np.searchsorted(u, old).astype(np.int32) + base[si]
        return np.where(old >= 0, new, old).astype(np.int32)

    axis_rows = {"trace": n_traces, "span": n_spans, **ax_n}
    if has_tres:
        axis_rows["tres"] = n_tres
    _OWNER_COLS = frozenset(
        {"sattr.span", "ev.span", "ln.span", "evattr.ev", "lnattr.ln"}
    )

    cols: dict[str, np.ndarray] = {}

    def _consume(n: str) -> None:
        # single-output jobs: each source column is read by exactly ONE
        # output column's pass, so free it the moment that pass is done.
        # Halves peak memory (sources + output no longer coexist whole)
        # and keeps the working set cache-resident. Exceptions that later
        # passes re-read: trace.tres_off and trace.span_off (the
        # recompute section) and rattr.res (every rattr VALUE column
        # filters by the owner).
        if consume and n not in ("trace.tres_off", "trace.span_off", "rattr.res"):
            for si in src_order:
                sources[si].cols.pop(n, None)

    for n in names:
        pref = n.split(".", 1)[0]
        like = sources[src_order[0]].cols[n]
        if n in ("span.trace_sid", "span.start_ms", "trace.span_off",
                 "trace.start_ms", "trace.end_ms", "trace.tres_off"):
            _consume(n)
            continue  # recomputed below
        if pref in axis_rows:
            # const fast path: when every source is constant on this
            # column with the SAME row (in merged-dictionary code space),
            # the output is that constant -- a stride-0 broadcast view
            # that costs nothing here and writes as const chunks. Index
            # columns whose values are rebased/translated per source
            # can't take it.
            if n not in ("span.res_idx", "tres.res", "span.parent_idx",
                         "span.scope_idx") and n not in _OWNER_COLS:
                rows = [_const_source_row(sources[si], n) for si in src_order]
                if all(r is not None for r in rows) and all(
                    r.dtype == rows[0].dtype and r.tobytes() == rows[0].tobytes()
                    for r in rows[1:]
                ):
                    cols[n] = np.broadcast_to(
                        rows[0].astype(like.dtype, copy=False),
                        (axis_rows[pref],) + like.shape[1:])
                    _consume(n)
                    continue
            out = np.empty((axis_rows[pref],) + like.shape[1:], dtype=like.dtype)
            for si in src_order:
                if n == "span.res_idx":
                    packed_scatter(si, pref, _translate(
                        si, span_resvals[si], used_res, res_base), out)
                elif n == "tres.res":
                    packed_scatter(si, pref, _translate(
                        si, packed_gather(si, pref, sources[si].cols[n]),
                        used_res, res_base), out)
                elif n == "span.parent_idx":
                    # parent rows live in the SAME trace, so the chunk's
                    # span-base shift rebases them; negative sentinels
                    # (-1 root, -2 orphan) pass through unchanged
                    packed = packed_gather(si, pref, sources[si].cols[n])
                    ii = by_src[si]
                    off = np.repeat((sp_b[ii] - span_lo[ii]).astype(np.int64),
                                    (span_hi - span_lo)[ii])
                    packed = np.where(
                        packed >= 0, packed + off, packed).astype(like.dtype)
                    packed_scatter(si, pref, packed, out)
                elif n == "span.scope_idx":
                    packed_scatter(si, pref, _translate(
                        si, span_scopevals[si], used_scope, scope_base), out)
                elif n in _OWNER_COLS:
                    packed = packed_gather(si, pref, sources[si].cols[n])
                    packed = (packed + owner_off_packed(si, pref)).astype(like.dtype)
                    packed_scatter(si, pref, packed, out)
                else:
                    fuse = n in _DICT_COLS and sources[si].fused_remap
                    if si == src_order[0] and dst_ordered_copy(pref, n, out, remap=fuse):
                        break  # one dst-ordered pass covered every source
                    src_col = sources[si].cols[n]
                    if fuse:
                        # kernel declined (odd dtype / stale lib): remap
                        # into a LOCAL copy -- mutating the source would
                        # double-remap it in later output blocks
                        src_col = apply_remap(src_col, sources[si].remap)
                    s_offs, d_offs, lens = runs_of[(si, pref)]
                    _run_copy(src_col, out, s_offs, d_offs, lens)
            cols[n] = out
        elif pref in ("res", "scope"):
            used = used_res if pref == "res" else used_scope
            parts = [sources[si].cols[n][used[si]] for si in src_order]
            cols[n] = np.concatenate(parts) if parts else like[:0]
        elif pref == "rattr":
            parts = []
            for si in src_order:
                owner = sources[si].cols["rattr.res"]
                keep = np.isin(owner, used_res[si])
                a = sources[si].cols[n][keep]
                if n == "rattr.res":
                    a = _translate(si, a, used_res, res_base)
                parts.append(a)
            cols[n] = np.concatenate(parts) if parts else like[:0]
        else:
            raise UnsupportedColumnar(f"unknown column family: {n}")
        _consume(n)

    # recomputed columns
    span_counts = np.empty(n_traces, dtype=np.int64)
    for si in src_order:
        so_diff = np.diff(sources[si].span_off.astype(np.int64))
        s_offs, d_offs, lens = runs_of[(si, "trace")]
        _run_copy(so_diff, span_counts, s_offs, d_offs, lens)
    cols["trace.span_off"] = np.concatenate(
        [[0], np.cumsum(span_counts)]
    ).astype(np.int32)
    cols["span.trace_sid"] = np.repeat(
        np.arange(n_traces, dtype=np.int32), span_counts
    )
    if has_tres:
        tres_counts = np.empty(n_traces, dtype=np.int64)
        for si in src_order:
            td = np.diff(sources[si].cols["trace.tres_off"].astype(np.int64))
            s_offs, d_offs, lens = runs_of[(si, "trace")]
            _run_copy(td, tres_counts, s_offs, d_offs, lens)
        cols["trace.tres_off"] = np.concatenate(
            [[0], np.cumsum(tres_counts)]
        ).astype(np.int32)

    start_ns = cols["span.start_ns"].astype(np.int64)
    base_ns = int(start_ns.min()) if start_ns.size else 0
    cols["span.start_ms"] = ((start_ns - base_ns) // 1_000_000).astype(np.int32)
    tr_start = cols["trace.start_ns"].astype(np.int64)
    tr_end = cols["trace.end_ns"].astype(np.int64)
    cols["trace.start_ms"] = ((tr_start - base_ns) // 1_000_000).astype(np.int32)
    cols["trace.end_ms"] = ((tr_end - base_ns) // 1_000_000).astype(np.int32)

    axes, col_axis, row_groups = compute_row_groups(
        cols, cols["span.start_ms"], cols["span.dur_us"], row_group_spans
    )

    m = BlockMeta.new(tenant)
    m.compaction_level = level
    m.total_traces = n_traces
    m.total_spans = n_spans
    ids = cols["trace.id"]
    m.min_id = ids[0].tobytes().hex() if n_traces else ""
    m.max_id = ids[-1].tobytes().hex() if n_traces else ""
    m.start_time_unix_nano = base_ns
    m.end_time_unix_nano = int(cols["span.end_ns"].max()) if cols["span.end_ns"].size else 0
    m.dict_size = len(merged)
    m.row_groups = row_groups

    if bloom is None:
        bloom = ShardedBloom.for_estimated_items(max(n_traces, 1))
        bloom.add_array(ids[:n_traces])
    m.bloom_shards = bloom.n_shards
    m.bloom_shard_bits = bloom.shard_bits
    return FinalizedBlock(m, cols, axes, col_axis, merged, bloom)


@dataclass
class ColumnarPlan:
    """Output of the fetch+merge stages (plan_columnar): everything the
    assemble/write stages need. The pipeline executor runs plan and
    assemble on different schedules; the sequential driver
    (compact_columnar) runs them back to back."""

    tenant: str
    job: CompactionJob
    blocks: list[BackendBlock]
    # indexed like the run tables; None holes are passthrough-only
    # blocks whose columns were never decoded
    sources: list[_Source | None]
    merged: Dictionary | None
    out_level: int
    # (src, sid_lo, sid_hi) run arrays per output block; empty when the
    # inputs hold zero traces (mark-only job)
    chunk_lists: list[tuple[np.ndarray, np.ndarray, np.ndarray]]
    single_est: bool
    # per chunk list: the source block index whose compressed chunks
    # copy through verbatim, or None for an ordinary rewrite output
    passthrough: list[int | None]


def plan_columnar(backend: RawBackend, job: CompactionJob, cfg: CompactorConfig,
                  blocks: list[BackendBlock] | None = None) -> ColumnarPlan:
    """Fetch + merge planning: decode sources, compute the global merge
    order (collisions combined), merge dictionaries, cut the run table
    into per-output chunk lists. Raises UnsupportedColumnar when the
    inputs can't merge columnar-ly. `blocks`: already-opened readers
    (the pipeline's prefetch stage passes preloaded ones)."""
    tenant = job.tenant
    from ..block.versioned import open_block_versioned

    # version dispatch: an unknown-format input must fail the job
    # loudly, never be misparsed as vtpu1 bytes
    if blocks is None:
        blocks = [open_block_versioned(backend, m) for m in job.blocks]
    # one output block => consume-as-you-go pays; multi-output jobs never
    # consume, so skip the per-column copies (estimate from input bytes:
    # single iff everything fits one target block, the common L0->L1 case)
    target_est = cfg.target_block_bytes or cfg.max_block_bytes
    single_est = sum(m.size_bytes for m in job.blocks) <= target_est * 9 // 10
    names = set(blocks[0].pack.names())
    if any(set(b.pack.names()) != names for b in blocks[1:]):
        raise UnsupportedColumnar("input blocks have differing column sets")
    out_level = max(m.compaction_level for m in job.blocks) + 1

    # merge order needs ONLY trace.id per block; full-column decode is
    # deferred until the output cuts reveal which sources any rewrite
    # output actually touches (a block that passes through whole never
    # decompresses at all)
    sources: list[_Source | None] = [None] * len(blocks)
    src_arr, sid_arr, same = _merge_order(
        [b.pack.read("trace.id") for b in blocks])
    n = len(src_arr)
    dup = same.copy()
    if n:
        dup[:-1] |= same[1:]

    # vectorized run detection (the old per-trace Python loop cost more
    # than the dictionary merge on realistic jobs): a run continues while
    # the source stays, sids stay consecutive, and neither row belongs to
    # a collision group
    if n:
        cont = np.zeros(n, dtype=bool)
        cont[1:] = ((src_arr[1:] == src_arr[:-1])
                    & (sid_arr[1:] == sid_arr[:-1] + 1)
                    & ~dup[1:] & ~dup[:-1])
        starts = np.nonzero(~cont)[0]
        seg_len = np.append(starts[1:], n) - starts
        run_src = src_arr[starts].astype(np.int64)
        run_lo = sid_arr[starts].astype(np.int64)
        run_hi = run_lo + seg_len
        if dup.any():
            # collision groups become one-trace sources appended after
            # the blocks (rare; random 16-byte ids almost never collide)
            seg_dup = dup[starts]
            cs = starts[seg_dup]  # every collision member is its own segment
            new_group = ~same[cs]
            gid = np.cumsum(new_group) - 1
            groups: list[list[tuple[int, int]]] = [[] for _ in range(int(gid[-1]) + 1)] if cs.size else []
            for t, g in zip(cs, gid):
                groups[int(g)].append((int(src_arr[t]), int(sid_arr[t])))
            coll_src = []
            for members in groups:
                sources.append(_combine_collision(blocks, names, members, tenant))
                coll_src.append(len(sources) - 1)
            # splice the one-trace collision runs back at their merged
            # position (each group sits where its first member sorted)
            all_pos = np.concatenate([starts[~seg_dup], cs[new_group]])
            all_src = np.concatenate([run_src[~seg_dup], np.asarray(coll_src, np.int64)])
            all_lo = np.concatenate([run_lo[~seg_dup], np.zeros(len(coll_src), np.int64)])
            all_hi = np.concatenate([run_hi[~seg_dup], np.ones(len(coll_src), np.int64)])
            o = np.argsort(all_pos, kind="stable")
            run_src, run_lo, run_hi = all_src[o], all_lo[o], all_hi[o]
    else:
        run_src = run_lo = run_hi = np.empty(0, np.int64)
    if run_src.size == 0:
        # zero input traces: nothing to assemble, mark-only job
        return ColumnarPlan(tenant, job, blocks, sources, None,
                            out_level, [], single_est, [])

    # merged dictionary via native K-way byte-level merge (no string
    # decode anywhere; dictionaries are their own objects, so this
    # never decompresses column data) + one remap gather per decoded
    # source below (axis columns defer their remap into _assemble's
    # fused copy kernel)
    from ..native import available as native_available
    from ..native import dict_union

    blob, offs, remaps = dict_union(
        [b.dictionary.raw() for b in blocks]
        + [s.dictionary.raw() for s in sources[len(blocks):]])
    merged = Dictionary.from_raw(blob, offs)
    fused = native_available()

    # size-target output cuts, estimated from input bytes/trace. NOTE:
    # every output block carries the FULL merged dictionary (subsetting
    # it per output would force a second remap pass over every code
    # column), so the per-block trace budget is what remains of the
    # target AFTER the dictionary blob.
    total_in = sum(m.size_bytes for m in job.blocks)
    total_traces_in = max(1, sum(m.total_traces for m in job.blocks))
    bpt = max(1.0, total_in / total_traces_in)
    target = cfg.target_block_bytes or cfg.max_block_bytes
    cap_traces = max(1, int(max(target - len(blob), target // 4) / bpt))

    # split the run table into per-output-block slices at cap_traces
    # boundaries (vectorized; a run straddling a cut is split in two)
    lens = run_hi - run_lo
    cum = np.cumsum(lens)
    total_tr = int(cum[-1])
    n_out = max(1, -(-total_tr // cap_traces))
    chunk_lists: list[tuple[np.ndarray, np.ndarray, np.ndarray]] = []
    if n_out == 1:
        chunk_lists.append((run_src, run_lo, run_hi))
    else:
        prev_run, prev_off = 0, 0  # resume point: run index + traces consumed
        for b_idx in range(n_out):
            if b_idx < n_out - 1:
                boundary = (b_idx + 1) * cap_traces
                r = int(np.searchsorted(cum, boundary, "left"))
                off_in_r = boundary - (int(cum[r]) - int(lens[r]))
            else:
                r, off_in_r = len(lens) - 1, int(lens[-1])
            s_src = run_src[prev_run : r + 1].copy()
            s_lo = run_lo[prev_run : r + 1].copy()
            s_hi = run_hi[prev_run : r + 1].copy()
            s_lo[0] = run_lo[prev_run] + prev_off
            s_hi[-1] = run_lo[r] + off_in_r
            keep = s_hi > s_lo
            if keep.any():
                chunk_lists.append((s_src[keep], s_lo[keep], s_hi[keep]))
            prev_run, prev_off = r, off_in_r

    # compressed-chunk passthrough: an output that is exactly one whole
    # input block whose chunks are already the write codec inherits the
    # block's compressed bytes verbatim (write_output copies objects);
    # only sources a rewrite output touches ever decode their columns
    if os.environ.get("TEMPO_COMPACT_PASSTHROUGH", "1") != "0":
        passthrough = [_passthrough_source(blocks, cl) for cl in chunk_lists]
    else:
        passthrough = [None] * len(chunk_lists)
    need = {int(s) for cl, pt in zip(chunk_lists, passthrough) if pt is None
            for s in np.unique(cl[0])}
    for si in sorted(need):
        if si < len(blocks) and sources[si] is None:
            sources[si] = _Source.from_block(blocks[si], independent=single_est)
    for si, s in enumerate(sources):
        if s is not None:
            s.remap_codes(remaps[si], fused=fused)

    return ColumnarPlan(tenant, job, blocks, sources, merged,
                        out_level, chunk_lists, single_est, passthrough)


def _passthrough_source(blocks: list[BackendBlock],
                        cl: tuple[np.ndarray, np.ndarray, np.ndarray]) -> int | None:
    """The input block whose ENTIRE trace set this output chunk list
    covers verbatim, or None. Such an output's decoded contents equal
    the input block's exactly (one run, whole block -- collisions always
    split runs, so none involve it), so its compressed chunks copy
    through without decompress->recompress. Gated on the chunks already
    being the codec a rewrite would produce: a block written under a
    different codec still rewrites, keeping the backend converging on
    the configured one."""
    csrc, clo, chi = cl
    if len(csrc) != 1:
        return None
    si = int(csrc[0])
    if si >= len(blocks):  # collision rebuilds always rewrite
        return None
    m = blocks[si].meta
    if int(clo[0]) != 0 or int(chi[0]) != m.total_traces or not m.total_traces:
        return None
    from ..block.colio import CODEC_CONST, CODEC_RAW, CODEC_ZSTD

    if not blocks[si].pack.chunk_codecs() <= {CODEC_ZSTD, CODEC_CONST, CODEC_RAW}:
        return None
    return si


@dataclass
class PassthroughOutput:
    """One output block that inherits a single input block's compressed
    objects verbatim (yielded by iter_outputs in place of a
    FinalizedBlock; write_output copies instead of recompressing)."""

    blk: BackendBlock
    out_level: int

    @property
    def meta(self):  # the accounting surface FinalizedBlock exposes
        return self.blk.meta


def copy_block_through(backend: RawBackend, blk: BackendBlock, out_level: int,
                       defer_meta: bool = False) -> BlockMeta:
    """Produce a compaction output by verbatim object copy: data, dict
    and bloom shards move backend-side (local: hardlink; stores:
    server-side copy), compressed chunks never decode. Same meta-last /
    defer_meta visibility contract as write_block."""
    from ..util.kerneltel import TEL

    src = blk.meta
    m = BlockMeta.from_json(src.to_json())
    m.block_id = str(uuid.uuid4())
    m.compaction_level = out_level
    names = [DATA_NAME, DICT_NAME] + [
        f"{BLOOM_PREFIX}{s}" for s in range(src.bloom_shards)]
    for name in names:
        try:
            backend.copy_object(src.tenant_id, src.block_id, name, m.block_id)
        except DoesNotExist:
            if name == DATA_NAME:
                raise  # a block without data is corrupt; fail the job
    TEL.record_passthrough(int(src.size_bytes))
    if not defer_meta:
        backend.write(m.tenant_id, m.block_id, "meta.json", m.to_json())
    return m


def write_output(backend: RawBackend, out, cfg: CompactorConfig,
                 out_level: int, defer_meta: bool = False) -> BlockMeta:
    """Write one iter_outputs product: FinalizedBlock -> full
    recompress through write_block, PassthroughOutput -> verbatim
    object copies. Both drivers (sequential + pipeline) route here so
    the passthrough behaves identically under either."""
    if isinstance(out, PassthroughOutput):
        return copy_block_through(backend, out.blk, out_level,
                                  defer_meta=defer_meta)
    return write_block(backend, out, level=cfg.level_for(out_level),
                       defer_meta=defer_meta)


def iter_outputs(plan: ColumnarPlan, cfg: CompactorConfig):
    """Assemble the plan's output blocks one at a time. Yield order and
    contents are deterministic: a pipelined consumer that writes each
    output produces bit-identical blocks to the sequential driver.
    Passthrough outputs yield as PassthroughOutput markers (no assemble
    work; write_output performs the copy)."""
    single_out = len(plan.chunk_lists) == 1
    for cl, pt in zip(plan.chunk_lists, plan.passthrough):
        if pt is not None:
            yield PassthroughOutput(plan.blocks[pt], plan.out_level)
            continue
        bloom = _union_input_blooms(plan.blocks) if single_out else None
        yield _assemble(plan.tenant, plan.sources, cl, plan.merged,
                        plan.out_level, cfg.row_group_spans, bloom,
                        consume=single_out and plan.single_est)


def compact_columnar(backend: RawBackend, job: CompactionJob, cfg: CompactorConfig) -> CompactionResult:
    """Sequential driver: plan, then assemble+write each output block
    back to back. The pipelined driver (db/compact_pipeline.py) runs the
    same plan/iter_outputs stages with assemble/write overlapped."""
    plan = plan_columnar(backend, job, cfg)
    result = CompactionResult()
    for fin in iter_outputs(plan, cfg):
        meta = write_output(backend, fin, cfg, plan.out_level)
        result.new_blocks.append(meta)
        result.traces_out += meta.total_traces
        result.spans_out += meta.total_spans

    result.compacted_ids = [m.block_id for m in job.blocks]
    for m in job.blocks:
        backend.mark_compacted(job.tenant, m.block_id)
    return result
