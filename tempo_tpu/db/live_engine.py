"""Live-head device engine: the ingester's live/cut/flushing traces
searched through the same fused filter->top-k shape as complete blocks.

Execution contract (mirrors db/search.py): the staged device (or numpy
twin) mask is CONSERVATIVE -- tag/name membership and the push-metadata
time prefilter are exact, min-duration filters on a >= bound, and
max-duration / TraceQL are not filtered at all -- then the top-k
selection (ops/select, newest first by the seconds-granularity start
key) feeds an escalating collect whose candidates are re-verified
bit-exactly through the SAME per-trace index the host oracle
(Instance.search_live_index) uses. The escalation widens k until either
every masked slot has been seen or the limit-th verified result's key
is STRICTLY newer than the selection boundary -- at that point no
unseen slot can displace a winner even under second-granularity ties,
so the result set is bit-identical to the oracle by construction.

Engine routing is a measured row-count crossover: the host twin costs
~rows/host_rate with zero device round trips, the device path costs a
~fixed dispatch+sync; both rates are EMA-learned from this process's
own queries, so the threshold tracks the actual link instead of an
assumption. Tiny heads (the common single-tenant dev case) therefore
keep running on host arithmetic, and the device engine takes over
exactly when it starts winning.

Env knobs: TEMPO_LIVE_STAGE=0 kills staging entirely (the legacy index
walk serves everything); TEMPO_LIVE_ENGINE=device|host|index forces a
path (tests / differential harnesses); TEMPO_LIVE_CROSSOVER_ROWS seeds
the crossover before measurements exist; TEMPO_LIVE_FIND_DEVICE=1
routes find-by-id through the staged id-code kernel (the hash-map
lookup measures faster, so it stays the default)."""

from __future__ import annotations

import os
import threading
import time

import numpy as np

from ..ops.livestage import (
    LiveStager,
    eval_live_device,
    eval_live_host,
    find_slot_device,
    find_slot_host,
    kv_pair_key,
)
from ..ops.select import k_bucket, select_topk_device, select_topk_host
from .search import DEFAULT_LIMIT, SearchRequest, SearchResponse, SearchResult

_I32_MIN = -(2**31)


def _env_flag(name: str, default: str = "") -> str:
    return os.environ.get(name, default)


class LiveEngine:
    """One ingester Instance's staged live-head engine. Query execution
    never holds the instance lock past the snapshot; staging mutation
    serializes on the stager's own lock."""

    def __init__(self, instance):
        self.inst = instance
        # share the instance's columnar-ingest plane when present: one
        # LiveDict for staging + WAL feature checkpoints, and staging
        # reads decoded features from the shared cache (decode once)
        col = getattr(instance, "columnar", None)
        if col is not None:
            self.stager = LiveStager(dictionary=col.dict,
                                     features_fn=col.features_for)
        else:
            self.stager = LiveStager()
        self._pending_lock = threading.Lock()
        self._pending_push: dict[bytes, float] = {}  # tid -> first unstaged push
        self.enabled = _env_flag("TEMPO_LIVE_STAGE", "1") != "0"
        try:
            self._crossover_seed = float(
                _env_flag("TEMPO_LIVE_CROSSOVER_ROWS", "4096"))
        except ValueError:
            self._crossover_seed = 4096.0
        # measured engine rates (EMAs over this process's own queries):
        # host twin scans at s/row, the device path pays ~fixed seconds
        self._host_s_per_row: float | None = None
        self._dev_fixed_s: float | None = None
        self._measured = False  # did THIS process observe an engine run?
        # seed the EMAs from the persisted CostLedger (a PREVIOUS
        # process's measurements) so routing starts measured instead of
        # re-learning from scratch every restart. The env seed still
        # wins when set -- the operator aimed the crossover on purpose.
        if not _env_flag("TEMPO_LIVE_CROSSOVER_ROWS"):
            try:
                from ..util.costledger import KEY_LIVE_SEARCH, ledger

                entry = ledger().get(KEY_LIVE_SEARCH)
                if entry:
                    h = float(entry.get("host_s_per_row", 0.0) or 0.0)
                    d = float(entry.get("device_fixed_s", 0.0) or 0.0)
                    if h > 0:
                        self._host_s_per_row = h
                    if d > 0:
                        self._dev_fixed_s = d
            except Exception:
                pass  # routing falls back to the seed constant

    # ------------------------------------------------------------- push
    def note_push(self, tids, now: float) -> None:
        """Stamp the staging-lag clock for freshly pushed trace ids --
        O(1) per id, called OFF the instance push lock."""
        if not self.enabled:
            return
        with self._pending_lock:
            for tid in tids:
                self._pending_push.setdefault(tid, now)

    def _note_staged(self, staged_tids) -> None:
        from ..util.kerneltel import TEL

        now = time.time()
        with self._pending_lock:
            lags = [now - self._pending_push.pop(tid)
                    for tid in staged_tids if tid in self._pending_push]
        for lag in lags:
            TEL.record_staging_lag(max(0.0, lag))

    # ---------------------------------------------------------- routing
    def crossover_rows(self) -> float:
        """Rows above which the device path is expected to win, from the
        measured EMAs (seeded by TEMPO_LIVE_CROSSOVER_ROWS until both
        engines have run at least once)."""
        if self._host_s_per_row and self._dev_fixed_s:
            est = self._dev_fixed_s / self._host_s_per_row
            return float(min(max(est, 256.0), float(1 << 22)))
        return self._crossover_seed

    def _observe_engine(self, engine: str, rows: int, seconds: float) -> None:
        if seconds <= 0:
            return
        self._measured = True
        if engine == "host":
            per_row = seconds / max(rows, 1)
            cur = self._host_s_per_row
            self._host_s_per_row = (per_row if cur is None
                                    else 0.7 * cur + 0.3 * per_row)
        else:
            cur = self._dev_fixed_s
            self._dev_fixed_s = (seconds if cur is None
                                 else 0.7 * cur + 0.3 * seconds)

    def _route(self, rows: int) -> tuple[str, str]:
        forced = _env_flag("TEMPO_LIVE_ENGINE")
        if forced in ("device", "host", "index"):
            return forced, "forced"
        if rows >= self.crossover_rows():
            return "device", ("measured_crossover"
                              if self._host_s_per_row and self._dev_fixed_s
                              else "seeded_crossover")
        return "host", "tiny_head"

    # --------------------------------------------------------- lifecycle
    def maybe_refresh(self) -> None:
        """Sweeper hook: bound the staging lag without waiting for a
        query. Only refreshes when pushes are pending or traces retired
        since the last generation."""
        if not self.enabled:
            return
        rows = sum(self.stager.note_rows())
        engine, _ = self._route(rows)
        # snapshot + reconcile are atomic under the stager lock (lock
        # order: stager outer, instance inner -- everywhere): a stale
        # groups snapshot must never reach refresh after a newer one,
        # or it would retire-and-restage traces the newer one staged
        with self.stager.lock:
            groups = self.inst._live_groups()
            if not groups and not self.stager.tails:
                return
            items = {tid: (g[0], g[1], g[2], g[3]) for tid, g in groups.items()}
            self.stager.refresh(items, stage_device=engine == "device")
        self._note_staged(list(items))

    # ------------------------------------------------------------ search
    def search(self, req: SearchRequest) -> SearchResponse:
        from ..util.kerneltel import TEL

        inst = self.inst
        if not self.enabled:
            TEL.record_routing("search_live", "index", "kill_switch")
            return inst.search_live_index(req)
        rows = sum(self.stager.note_rows())
        engine, reason = self._route(rows)
        if engine == "index":
            TEL.record_routing("search_live", "index", reason)
            return inst.search_live_index(req)

        from ..traceql.parser import parse

        q = parse(req.query) if req.query else None
        # snapshot + reconcile atomically (see maybe_refresh): stale
        # snapshots reaching refresh out of order would thrash slots
        with self.stager.lock:
            groups = inst._live_groups()
            if not groups:
                if self.stager.tails:  # fully drained head: retire slots
                    self.stager.refresh({}, stage_device=False)
                return SearchResponse()
            items = {tid: (g[0], g[1], g[2], g[3]) for tid, g in groups.items()}
            snap = self.stager.refresh(items, stage_device=engine == "device")
        self._note_staged(list(items))

        # resolve tag strings through the append-only dictionary: a miss
        # proves no staged row carries the pair -> exact empty result
        tag_codes: list[int] = []
        name_codes: list[int] = []
        for k, v in (req.tags or {}).items():
            if k == "name":
                c = self.stager.dict.lookup(v)
                if c < 0:
                    TEL.record_routing("search_live", engine, "dict_prune")
                    return SearchResponse()
                name_codes.append(c)
            else:
                c = self.stager.dict.lookup(kv_pair_key(k, str(v).lower()))
                if c < 0:
                    TEL.record_routing("search_live", engine, "dict_prune")
                    return SearchResponse()
                tag_codes.append(c)

        TEL.record_routing("search_live", engine, reason)
        t0 = time.perf_counter()
        t0_wall = time.time()
        if engine == "device":
            mask = eval_live_device(snap, tag_codes, name_codes,
                                    req.start, req.end, req.min_duration_ms)

            def selector(k):
                sids, _, n_match = select_topk_device(
                    mask, snap.dev["key_s"], mask, k)
                return sids, n_match
        else:
            hmask = eval_live_host(snap, tag_codes, name_codes,
                                   req.start, req.end, req.min_duration_ms)

            def selector(k):
                sids, _, n_match = select_topk_host(
                    hmask, snap.key_s, np.zeros_like(snap.key_s), k)
                return sids, n_match

        resp = self._collect(snap, groups, req, q, selector)
        self._observe_engine(engine, rows, time.perf_counter() - t0)
        # timeline: the ingester live-head leg with its routing verdict
        TEL.child_span("live:search", t0_wall, time.time(),
                       {"engine": engine, "reason": reason, "rows": rows})
        return resp

    def _collect(self, snap, groups, req: SearchRequest, q, selector) -> SearchResponse:
        """Escalating top-k collect with exact host verification: the
        device/host-twin mask proposes newest-first candidates, the
        per-trace index (the oracle's own entry) settles them."""
        inst = self.inst
        resp = SearchResponse()
        n = snap.n_slots
        if n == 0:
            return resp
        limit = req.limit or DEFAULT_LIMIT
        slot_tid = snap.slot_tid
        k = min(k_bucket(max(2 * limit, 32)), n)
        out: list[tuple[int, str, object]] = []
        seen: set[int] = set()
        while True:
            sids, n_match = selector(k)
            boundary_key = (int(snap.key_s[int(sids[-1])])
                            if len(sids) == k else None)
            for s in sids:
                s = int(s)
                if s in seen:
                    continue
                seen.add(s)
                tid = slot_tid.get(s)
                g = groups.get(tid) if tid is not None else None
                if g is None:
                    continue  # retired between snapshot and collect
                idx, decoded = inst._live_entry(tid, g[4], g[0])
                if req.tags and not idx.matches_tags(req.tags):
                    continue
                if req.min_duration_ms and idx.dur_ms < req.min_duration_ms:
                    continue
                if req.max_duration_ms and idx.dur_ms > req.max_duration_ms:
                    continue
                if q is not None:
                    from ..traceql.hosteval import trace_matches

                    if not trace_matches(q, decoded):
                        continue
                out.append((idx.start_ns, tid.hex(), idx))
            out.sort(key=lambda c: (-c[0], c[1]))
            done = len(seen) >= n_match or k >= n
            if not done and len(out) >= limit and boundary_key is not None:
                # exact-stop: the limit-th verified result is strictly
                # newer (at key granularity) than anything unseen
                from ..ops.livestage import _clip_i32
                from ..ops.stage import GKEY_ORIGIN_S

                cutoff = _clip_i32(
                    out[limit - 1][0] // 1_000_000_000 - GKEY_ORIGIN_S)
                done = cutoff > boundary_key
            if done:
                break
            k = min(k_bucket(k * 4), n)
        for start_ns, tid_hex, idx in out[:limit]:
            resp.traces.append(SearchResult(
                trace_id=tid_hex,
                root_service_name=idx.root_service,
                root_trace_name=idx.root_name,
                start_time_unix_nano=idx.start_ns,
                duration_ms=idx.dur_ms,
            ))
        resp.inspected_spans = snap.n_kv + snap.n_name
        return resp

    # -------------------------------------------------------------- find
    def find(self, trace_id: bytes):
        """Find-by-id through the live head. The hash-map lookup is the
        measured winner (O(1) host, no staging requirement), so it is
        the default; TEMPO_LIVE_FIND_DEVICE=1 (or the forced-engine env)
        routes through the staged id-code kernel instead -- both
        materialize through the same segment-combine, so results are
        bit-identical by construction."""
        from ..util.kerneltel import TEL

        inst = self.inst
        forced = _env_flag("TEMPO_LIVE_ENGINE")
        device_find = (_env_flag("TEMPO_LIVE_FIND_DEVICE") == "1"
                       or forced in ("device", "host"))
        if not self.enabled or not device_find:
            TEL.record_routing("find_live", "map",
                               "kill_switch" if not self.enabled
                               else "host_map_cheaper")
            return inst._find_live_map(trace_id)
        engine = "host" if forced == "host" else "device"
        with self.stager.lock:
            groups = inst._live_groups()
            items = {tid: (g[0], g[1], g[2], g[3]) for tid, g in groups.items()}
            snap = self.stager.refresh(items, stage_device=engine == "device")
        self._note_staged(list(items))
        TEL.record_routing("find_live", engine, "forced" if forced else "env")
        if engine == "device":
            slot = find_slot_device(snap, trace_id)
        else:
            slot = find_slot_host(snap, trace_id)
        if slot < 0:
            return None
        return inst._find_live_map(trace_id)

    def persist_crossover(self) -> None:
        """Commit this process's measured engine rates to the
        CostLedger so the NEXT process starts from them (ingester stop
        hook). Writes ONLY when this process actually observed an
        engine run: ledger-seeded values that never updated are not
        re-written (a restart loop would otherwise keep refreshing
        measured_at_unix on stale rates forever). Multi-tenant
        ingesters persist per instance; instances that measured nothing
        skip, so the last real measurement wins."""
        if not self._measured:
            return
        if self._host_s_per_row is None and self._dev_fixed_s is None:
            return
        try:
            from ..util.costledger import KEY_LIVE_SEARCH, ledger

            fields = {"crossover_rows": round(self.crossover_rows(), 1)}
            if self._host_s_per_row is not None:
                fields["host_s_per_row"] = self._host_s_per_row
            if self._dev_fixed_s is not None:
                fields["device_fixed_s"] = self._dev_fixed_s
            ledger().update(KEY_LIVE_SEARCH, **fields)
            ledger().publish()
        except Exception:
            pass  # persistence is advisory; next process re-learns

    # --------------------------------------------------------------- ops
    def stats(self) -> dict:
        """Per-instance staging state (debug/status surfaces)."""
        slots, kv, name = self.stager.note_rows()
        return {
            "enabled": self.enabled,
            "generation": self.stager.generation,
            "slots": slots, "kv_rows": kv, "name_rows": name,
            "dead_slots": self.stager.dead_slots,
            "crossover_rows": round(self.crossover_rows(), 1),
        }
