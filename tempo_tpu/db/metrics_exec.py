"""TraceQL metrics execution: `{...} | rate() by(...)` over the blocklist.

The query-side metrics engine (the reference's traceql-metrics feature,
modules/frontend + traceql metrics evaluators), built on this repo's
split-engine pattern:

  * per block, the spanset filter plans to the SAME device condition
    tree the search path uses (traceql/plan.plan_metrics_filter, span
    level -- no trace lift), and a fused filter->bucketize->segmented-
    fold kernel (ops/timeseries) produces [num_groups, num_buckets]
    accumulators in one pass: device for hot blocks (cached staged
    columns), vectorized numpy for cold ones -- identical results;
  * group keys (`by(...)`) resolve host-side through each block's own
    dictionary into dense per-span group ids; label STRINGS are the
    cross-block join key, so per-block code spaces never leak out;
  * per-block partial series merge with plain accumulator addition
    (min/max fold elementwise) -- the single-chip form of the mesh
    variant's psum (parallel/timeseries.py), which stacks blocks over
    'dp' and combines partials with one collective;
  * plans that are conservative (lossy encodings, unsupported
    constructs, pipelines with intermediate stages) fall back to the
    EXACT engine: the device/host mask only narrows the candidate
    traces, which are materialized and re-evaluated span by span with
    the exact host evaluator (traceql/hosteval) -- the same
    conservative-filter/exact-verify split as search.

Time axis: step-aligned buckets over [start_ms, end_ms); a span lands in
bucket (span_abs_ms - start_ms) // step_ms by its START time, where
span_abs_ms = block_base_ms + span.start_ms (the block-relative floored
millisecond encoding -- both engines and the exact path share this
definition so results are bit-identical across engines).
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from ..block.reader import BackendBlock
from ..ops.filter import Operands, required_columns
from ..traceql.ast import (
    Field as QField,
    MetricsQuery,
    ParseError,
    Pipeline,
    Scope,
)
from ..traceql.plan import plan_metrics_filter

# one source of truth for enum label names: the exact evaluator's maps
# (themselves the inverse of ast.STATUS_NAMES/KIND_NAMES) -- a drifted
# copy here would label columnar and exact series differently
from ..traceql.hosteval import _KIND_NAMES, _STATUS_NAMES

# unified group-key encoding (per block): every resolvable by() value
# maps into one int64 space so span- and resource-side lookups of an
# EITHER-scope attribute can be combined with a plain where()
_TAG_STR, _TAG_INT, _TAG_BOOL, _TAG_STATUS, _TAG_KIND = 0, 1, 2, 3, 4
_INT_HALF = 1 << 43


def _enc_str(codes: np.ndarray) -> np.ndarray:
    out = codes.astype(np.int64)
    return np.where(out >= 0, (_TAG_STR << 44) | out, np.int64(-1))


def _enc_int(vals: np.ndarray) -> np.ndarray:
    v = np.clip(vals.astype(np.int64), -_INT_HALF, _INT_HALF - 1)
    return (np.int64(_TAG_INT) << 44) | (v + _INT_HALF)


def _enc_tagged(tag: int, vals: np.ndarray) -> np.ndarray:
    return (np.int64(tag) << 44) | vals.astype(np.int64)


# the schema's dedicated-column maps are authoritative (the builder
# diverts these keys OUT of the generic attr tables, incl. the
# cluster/namespace/pod/container -> res.*_id2 aliases); dict-code
# columns end in _id, everything else is a raw int column
from ..block.schema import WELL_KNOWN_RES_ATTRS as _WELL_KNOWN_RES
from ..block.schema import WELL_KNOWN_SPAN_ATTRS as _WK_SPAN

_WELL_KNOWN_SPAN_STR = {k: v for k, v in _WK_SPAN.items() if v.endswith("_id")}
_WELL_KNOWN_SPAN_INT = {k: v for k, v in _WK_SPAN.items() if not v.endswith("_id")}

# ------------------------------------------------------------- request

MAX_BUCKETS = 4096  # request-axis cap: 400 at the API, not an OOM later
# accumulator cap (padded groups x padded buckets, shared with the mesh
# path): bounds memory on every engine and keeps the combined
# (group, bucket) segment index far from int32 overflow. A query whose
# by() cardinality blows past it fails with ValueError -> 400.
MAX_ACC_CELLS = 1 << 22


@dataclass
class MetricsRequest:
    """Step-aligned range-query axis (ms since epoch); end exclusive.
    (end_ms - start_ms) must be a positive multiple of step_ms --
    align_params builds a valid one from raw API seconds."""

    query: str
    start_ms: int
    end_ms: int
    step_ms: int

    @property
    def n_buckets(self) -> int:
        return (self.end_ms - self.start_ms) // self.step_ms


def align_params(query: str, start_s: float, end_s: float, step_s: float) -> MetricsRequest:
    """Raw API params -> aligned MetricsRequest: start floors and end
    ceils onto the step grid (Prometheus range-query alignment), so the
    bucket axis only depends on (step, grid), never on the exact request
    instant -- the property that makes time-sharded jobs mergeable."""
    step_ms = max(1, int(round(step_s * 1000)))
    start_ms = (int(start_s * 1000) // step_ms) * step_ms
    end_ms = -(-int(end_s * 1000) // step_ms) * step_ms
    if end_ms <= start_ms:
        end_ms = start_ms + step_ms
    if (end_ms - start_ms) // step_ms > MAX_BUCKETS:
        raise ValueError(
            f"query_range spans {(end_ms - start_ms) // step_ms} steps "
            f"(max {MAX_BUCKETS}); raise step or narrow the range")
    return MetricsRequest(query=query, start_ms=start_ms, end_ms=end_ms,
                          step_ms=step_ms)


def request_to_dict(req: MetricsRequest) -> dict:
    return {"query": req.query, "start_ms": req.start_ms,
            "end_ms": req.end_ms, "step_ms": req.step_ms}


def request_from_dict(d: dict) -> MetricsRequest:
    return MetricsRequest(query=d["query"], start_ms=int(d["start_ms"]),
                          end_ms=int(d["end_ms"]), step_ms=int(d["step_ms"]))


# ------------------------------------------------------------- response

# mergeable per-series accumulator state, by metrics fn
_STATE_FIELDS = {
    "rate": ("count",),
    "count_over_time": ("count",),
    "sum_over_time": ("vcnt", "vsum"),
    "avg_over_time": ("vcnt", "vsum"),
    "min_over_time": ("vcnt", "vmin"),
    "max_over_time": ("vcnt", "vmax"),
}
_FIELD_INIT = {"count": 0, "vcnt": 0, "vsum": 0.0,
               "vmin": np.inf, "vmax": -np.inf}


def _new_state(fn: str, nb: int) -> dict[str, np.ndarray]:
    return {f: np.full(nb, _FIELD_INIT[f],
                       dtype=np.int64 if f in ("count", "vcnt") else np.float64)
            for f in _STATE_FIELDS[fn]}


def _merge_field(name: str, dst: np.ndarray, src: np.ndarray) -> None:
    if name == "vmin":
        np.minimum(dst, src, out=dst)
    elif name == "vmax":
        np.maximum(dst, src, out=dst)
    else:
        dst += src


@dataclass
class MetricsResponse:
    """Partial or final result: per-series accumulator STATE on the
    request's bucket axis (merge-friendly); finalize with
    series_values / to_prometheus."""

    fn: str
    start_ms: int
    step_ms: int
    n_buckets: int
    label_names: tuple = ()
    series: dict = field(default_factory=dict)  # labels tuple -> state dict
    inspected_spans: int = 0
    inspected_bytes: int = 0

    def add_partial(self, labels: tuple, state: dict, offset: int = 0) -> None:
        """Merge one partial series whose arrays start at bucket
        `offset` of this response's axis (time-sharded jobs)."""
        dst = self.series.get(labels)
        if dst is None:
            dst = self.series[labels] = _new_state(self.fn, self.n_buckets)
        for f, arr in state.items():
            _merge_field(f, dst[f][offset:offset + len(arr)], arr)

    def merge(self, other: "MetricsResponse") -> None:
        off = (other.start_ms - self.start_ms) // self.step_ms
        for labels, state in other.series.items():
            self.add_partial(labels, state, offset=off)
        self.inspected_spans += other.inspected_spans
        self.inspected_bytes += other.inspected_bytes


def response_to_dict(resp: MetricsResponse) -> dict:
    return {
        "fn": resp.fn, "start_ms": resp.start_ms, "step_ms": resp.step_ms,
        "n_buckets": resp.n_buckets, "label_names": list(resp.label_names),
        "series": [
            {"labels": list(labels),
             "state": {f: a.tolist() for f, a in state.items()}}
            for labels, state in resp.series.items()
        ],
        "inspectedSpans": resp.inspected_spans,
        "inspectedBytes": resp.inspected_bytes,
    }


def response_from_dict(d: dict) -> MetricsResponse:
    resp = MetricsResponse(
        fn=d["fn"], start_ms=int(d["start_ms"]), step_ms=int(d["step_ms"]),
        n_buckets=int(d["n_buckets"]), label_names=tuple(d.get("label_names", [])),
        inspected_spans=int(d.get("inspectedSpans", 0)),
        inspected_bytes=int(d.get("inspectedBytes", 0)),
    )
    for s in d.get("series", []):
        resp.series[tuple(s["labels"])] = {
            f: np.asarray(a, dtype=np.int64 if f in ("count", "vcnt") else np.float64)
            for f, a in s["state"].items()
        }
    return resp


def series_values(resp: MetricsResponse, state: dict) -> np.ndarray:
    """Finalize one series' state into per-bucket float values; NaN
    marks buckets with no samples (value folds only -- count folds are
    dense, a bucket with nothing is a legitimate 0)."""
    fn = resp.fn
    if fn == "rate":
        return state["count"].astype(np.float64) / (resp.step_ms / 1000.0)
    if fn == "count_over_time":
        return state["count"].astype(np.float64)
    empty = state["vcnt"] == 0
    if fn == "sum_over_time":
        out = state["vsum"].copy()
    elif fn == "avg_over_time":
        with np.errstate(invalid="ignore", divide="ignore"):
            out = state["vsum"] / state["vcnt"]
    elif fn == "min_over_time":
        out = state["vmin"].copy()
    else:
        out = state["vmax"].copy()
    out[empty] = np.nan
    return out


def _fmt_value(v: float) -> str:
    """Full round-trip sample formatting (Prometheus emits shortest
    exact form): integral values as integers, others via repr -- a
    %g-style 6-digit truncation would corrupt large exact counts."""
    if v == int(v) and abs(v) < 2**53:
        return str(int(v))
    return repr(float(v))


def to_prometheus(resp: MetricsResponse) -> dict:
    """Prometheus query_range JSON (matrix result): series labels from
    the by() clause, sample timestamps at each bucket's start."""
    result = []
    for labels in sorted(resp.series):
        vals = series_values(resp, resp.series[labels])
        samples = []
        for i in range(resp.n_buckets):
            v = vals[i]
            if np.isnan(v):
                continue
            ts = (resp.start_ms + i * resp.step_ms) / 1000.0
            samples.append([ts, _fmt_value(float(v))])
        if not samples:
            continue
        result.append({"metric": dict(zip(resp.label_names, labels)),
                       "values": samples})
    return {"status": "success",
            "data": {"resultType": "matrix", "result": result}}


# --------------------------------------------------------- by() / values


def expr_label(e, i: int = 0) -> str:
    """Series label key for one by() expression (the query-surface
    attribute path for fields; positional for general expressions)."""
    if isinstance(e, QField):
        if e.scope == Scope.INTRINSIC:
            return e.name
        if e.scope == Scope.SPAN:
            return f"span.{e.name}"
        if e.scope == Scope.RESOURCE:
            return f"resource.{e.name}"
        return f".{e.name}"
    return f"by{i}"


def _label_of(enc: int, d) -> str:
    """Decode one unified group-key code back to its label string."""
    tag, v = enc >> 44, enc & ((1 << 44) - 1)
    if tag == _TAG_STR:
        return d.string(int(v))
    if tag == _TAG_INT:
        return str(int(v) - _INT_HALF)
    if tag == _TAG_BOOL:
        return "true" if v else "false"
    if tag == _TAG_STATUS:
        return _STATUS_NAMES.get(int(v), str(int(v)))
    return _KIND_NAMES.get(int(v), str(int(v)))


def _attr_enc(blk: BackendBlock, pre: str, n_owner: int, key: str) -> np.ndarray | None:
    """Generic attr table -> per-owner unified group code (-1 absent).
    str/int/bool values encode; complex rows stay absent on EVERY
    engine (the exact evaluator drops non-scalar labels too); any
    float-valued row makes the whole field unsupported (None) so the
    exact engine labels it -- a silent columnar drop would disagree
    with the exact path's float labels."""
    d = blk.dictionary
    kcode = d.lookup(key)
    out = np.full(max(n_owner, 1), -1, np.int64)
    if kcode < 0:
        return out[:n_owner]
    keys = blk.pack.read(f"{pre}.key_id")
    sel = keys == kcode
    if not sel.any():
        return out[:n_owner]
    owner_col = "sattr.span" if pre == "sattr" else "rattr.res"
    owner = blk.pack.read(owner_col)[sel]
    vt = blk.pack.read(f"{pre}.vtype")[sel]
    if (vt == 2).any():
        return None
    enc = np.full(owner.shape[0], -1, np.int64)
    if (vt == 0).any():
        enc[vt == 0] = _enc_str(blk.pack.read(f"{pre}.str_id")[sel][vt == 0])
    if (vt == 1).any():
        iv = blk.pack.read(f"{pre}.int64")[sel][vt == 1]
        if (np.abs(iv) >= _INT_HALF).any():
            # the 44-bit tagged encoding would clip (and so mislabel /
            # merge) huge int values: exact engine labels them instead
            return None
        enc[vt == 1] = _enc_int(iv)
    if (vt == 3).any():
        enc[vt == 3] = _enc_tagged(
            _TAG_BOOL, (blk.pack.read(f"{pre}.int64")[sel][vt == 3] != 0))
    ok = (enc >= 0) & (owner >= 0) & (owner < n_owner)
    out[owner[ok]] = enc[ok]
    return out[:n_owner]


def _gather_res(enc_res: np.ndarray, res_idx: np.ndarray) -> np.ndarray:
    safe = np.clip(res_idx, 0, max(enc_res.shape[0] - 1, 0))
    out = enc_res[safe] if enc_res.size else np.full(res_idx.shape[0], -1, np.int64)
    return np.where(res_idx >= 0, out, np.int64(-1))


def _by_codes(blk: BackendBlock, f) -> np.ndarray | None:
    """Per-span unified group code for one by() field; None = this
    field can't resolve columnar (exact engine takes over)."""
    if not isinstance(f, QField) or f.parent:
        return None
    pack = blk.pack
    n_spans = pack.axes["span"].n_rows if "span" in pack.axes else 0
    if f.scope == Scope.INTRINSIC:
        if f.name == "name":
            return _enc_str(pack.read("span.name_id"))
        if f.name == "status":
            return _enc_tagged(_TAG_STATUS, pack.read("span.status"))
        if f.name == "kind":
            return _enc_tagged(_TAG_KIND, pack.read("span.kind"))
        if f.name in ("rootName", "rootServiceName"):
            col = ("trace.root_name_id" if f.name == "rootName"
                   else "trace.root_service_id")
            tsid = pack.read("span.trace_sid")
            tcol = pack.read(col)
            return _enc_str(tcol[np.clip(tsid, 0, max(tcol.shape[0] - 1, 0))])
        return None  # duration/childCount/...: continuous or structural
    span_enc = res_enc = None
    if f.scope in (Scope.SPAN, Scope.EITHER):
        ded = _WELL_KNOWN_SPAN_STR.get(f.name)
        ded_int = _WELL_KNOWN_SPAN_INT.get(f.name)
        if ded is not None:
            span_enc = _enc_str(pack.read(ded))
        elif ded_int is not None:
            col = pack.read(ded_int)
            span_enc = np.where(col >= 0, _enc_int(col), np.int64(-1))
        else:
            span_enc = _attr_enc(blk, "sattr", n_spans, f.name)
            if span_enc is None:  # float-valued rows: exact engine only
                return None
    if f.scope in (Scope.RESOURCE, Scope.EITHER):
        res_idx = pack.read("span.res_idx")
        ded = _WELL_KNOWN_RES.get(f.name)
        if ded is not None and pack.has(ded):
            res_enc = _gather_res(_enc_str(pack.read(ded)), res_idx)
        else:
            n_res = int(res_idx.max()) + 1 if res_idx.size else 0
            enc_r = _attr_enc(blk, "rattr", n_res, f.name)
            if enc_r is None:
                return None
            res_enc = _gather_res(enc_r, res_idx)
    if span_enc is not None and res_enc is not None:
        return np.where(span_enc >= 0, span_enc, res_enc)
    return span_enc if span_enc is not None else res_enc


def _value_column(blk: BackendBlock, expr) -> tuple[np.ndarray, np.ndarray] | None:
    """Per-span (float64 value, present mask) for a *_over_time(field)
    argument, from the EXACT host columns (int64/f64/start_ns), so both
    engines fold the true values; None = exact engine only."""
    if not isinstance(expr, QField) or expr.parent:
        return None
    pack = blk.pack
    n_spans = pack.axes["span"].n_rows if "span" in pack.axes else 0
    if expr.scope == Scope.INTRINSIC:
        if expr.name == "duration":
            s = pack.read("span.start_ns").astype(np.int64)
            e = pack.read("span.end_ns").astype(np.int64)
            return (np.maximum(e - s, 0) / 1e9,
                    np.ones(n_spans, dtype=bool))
        return None

    def attr_vals(pre: str, n_owner: int):
        d = blk.dictionary
        kcode = d.lookup(expr.name)
        val = np.zeros(max(n_owner, 1))
        pres = np.zeros(max(n_owner, 1), dtype=bool)
        if kcode < 0:
            return val[:n_owner], pres[:n_owner]
        keys = pack.read(f"{pre}.key_id")
        sel = keys == kcode
        if not sel.any():
            return val[:n_owner], pres[:n_owner]
        owner_col = "sattr.span" if pre == "sattr" else "rattr.res"
        owner = pack.read(owner_col)[sel]
        vt = pack.read(f"{pre}.vtype")[sel]
        v = np.where(vt == 1, pack.read(f"{pre}.int64")[sel].astype(np.float64),
                     pack.read(f"{pre}.f64")[sel])
        num = (vt == 1) | (vt == 2)
        ok = num & (owner >= 0) & (owner < n_owner)
        val[owner[ok]] = v[ok]
        pres[owner[ok]] = True
        return val[:n_owner], pres[:n_owner]

    span_vp = res_vp = None
    if expr.scope in (Scope.SPAN, Scope.EITHER):
        ded_int = _WELL_KNOWN_SPAN_INT.get(expr.name)
        if ded_int is not None:
            col = pack.read(ded_int)
            span_vp = (col.astype(np.float64), col >= 0)
        else:
            span_vp = attr_vals("sattr", n_spans)
    if expr.scope in (Scope.RESOURCE, Scope.EITHER):
        res_idx = pack.read("span.res_idx")
        n_res = int(res_idx.max()) + 1 if res_idx.size else 0
        rv, rp = attr_vals("rattr", n_res)
        safe = np.clip(res_idx, 0, max(n_res - 1, 0))
        if n_res:
            res_vp = (rv[safe], rp[safe] & (res_idx >= 0))
        else:
            res_vp = (np.zeros(n_spans), np.zeros(n_spans, dtype=bool))
    if span_vp is not None and res_vp is not None:
        val = np.where(span_vp[1], span_vp[0], res_vp[0])
        return val, span_vp[1] | res_vp[1]
    return span_vp if span_vp is not None else res_vp


# -------------------------------------------------------- block engines


def _check_cardinality(n_groups: int, nb: int) -> None:
    from ..ops.device import bucket

    if bucket(max(n_groups, 1)) * bucket(max(nb, 1)) > MAX_ACC_CELLS:
        raise ValueError(
            f"metrics series cardinality too high: {n_groups} groups x "
            f"{nb} buckets exceeds the accumulator budget; narrow the "
            "by() clause, the time range, or raise step")


def _block_axis(blk: BackendBlock, req: MetricsRequest):
    """Clip the request's bucket axis to the block's time range:
    (bucket_offset, n_local_buckets, t0_rel_ms). The kernel only ever
    folds the overlapping slice, and t0 stays within int32 (block-
    relative ms)."""
    base_ms = blk.meta.start_time_unix_nano // 1_000_000
    end_ms = -(-blk.meta.end_time_unix_nano // 1_000_000)
    b_lo = max(0, (base_ms - req.start_ms) // req.step_ms)
    b_hi = min(req.n_buckets, -(-(end_ms - req.start_ms) // req.step_ms))
    if b_hi <= b_lo:
        return 0, 0, 0
    t0_rel = req.start_ms + b_lo * req.step_ms - base_ms
    return int(b_lo), int(b_hi - b_lo), int(t0_rel)


def _outs_to_series(outs, fn: str, gid_labels: list, b_off: int,
                    resp: MetricsResponse) -> None:
    """Kernel accumulators -> merged response series at bucket offset."""
    if fn in ("rate", "count_over_time"):
        counts = outs[0]
        for g, labels in enumerate(gid_labels):
            row = counts[g]
            if row.any():
                resp.add_partial(labels, {"count": row.astype(np.int64)}, b_off)
        return
    _, vcnt, vsum, vmin, vmax = outs
    per_fn = {"sum_over_time": ("vsum", vsum), "avg_over_time": ("vsum", vsum),
              "min_over_time": ("vmin", vmin), "max_over_time": ("vmax", vmax)}
    fname, arr = per_fn[fn]
    for g, labels in enumerate(gid_labels):
        if vcnt[g].any():
            resp.add_partial(
                labels,
                {"vcnt": vcnt[g].astype(np.int64),
                 fname: arr[g].astype(np.float64)},
                b_off,
            )


def resolve_groups(blk: BackendBlock, by: tuple):
    """by() fields -> (per-span dense gid int32 (-1 drops the span),
    group label tuples). None when some field can't resolve columnar."""
    pack = blk.pack
    n_spans = pack.axes["span"].n_rows if "span" in pack.axes else 0
    if not by:
        return np.zeros(n_spans, np.int32), [()]
    encs = []
    for f in by:
        e = _by_codes(blk, f)
        if e is None:
            return None
        encs.append(e)
    stacked = np.stack(encs, axis=1)  # (n_spans, k)
    present = (stacked >= 0).all(axis=1)
    gid = np.full(n_spans, -1, np.int32)
    if not present.any():
        return gid, []
    uniq, inv = np.unique(stacked[present], axis=0, return_inverse=True)
    gid[present] = inv.reshape(-1).astype(np.int32)
    d = blk.dictionary
    labels = [tuple(_label_of(int(code), d) for code in row) for row in uniq]
    return gid, labels


def metrics_block(blk: BackendBlock, q: MetricsQuery, req: MetricsRequest,
                  resp: MetricsResponse, mode: str = "auto",
                  planned=None) -> None:
    """Evaluate one block's contribution and merge it into resp.
    planned: the block's plan_metrics_filter result when the driver
    already computed it (the serial cold-prefetch loop); None plans
    here."""
    if not blk.meta.overlaps_time(req.start_ms // 1000, -(-req.end_ms // 1000)):
        return
    b_off, nb, t0_rel = _block_axis(blk, req)
    if nb == 0:
        return
    import time as _time

    from ..util.kerneltel import TEL

    t0_wall = _time.time()
    io0 = blk.pack.bytes_read
    if planned is None:
        planned = plan_metrics_filter(q, blk.dictionary)
    if planned.prune:
        return
    groups = None if mode == "exact" else resolve_groups(blk, q.agg.by)
    vals = None
    has_val = q.agg.field is not None
    if groups is not None and has_val:
        vals = _value_column(blk, q.agg.field)
    if mode == "exact":
        exact, exact_reason = True, "forced"
    elif planned.needs_verify:
        exact, exact_reason = True, "lossy_plan"
    elif groups is None:
        exact, exact_reason = True, "unplannable_by"
    elif has_val and vals is None:
        exact, exact_reason = True, "unplannable_value"
    else:
        exact, exact_reason = False, ""
    if exact:
        TEL.record_routing("metrics", "exact", exact_reason)
        _metrics_block_exact(blk, q, req, resp, planned, b_off, nb)
        resp.inspected_bytes += blk.pack.bytes_read - io0
        TEL.child_span(f"block:{blk.meta.block_id[:8]}", t0_wall, _time.time(),
                       {"engine": "exact", "reason": exact_reason,
                        "compile": False})
        return
    gid, labels = groups
    if not labels:
        return
    _check_cardinality(len(labels), nb)
    val, pres = vals if vals is not None else (None, None)
    query = (planned.tree, planned.conds)
    operands = Operands.build(planned.rows, planned.tables or None)
    # trace.span_off only serves the search path's tracify; the span-
    # level metrics kernels never touch it -- don't read or stage it
    needed = [n for n in required_columns(planned.conds)
              if n != "trace.span_off"] + ["span.start_ms"]
    # the device kernel buckets in int32 (block-relative ms): a step or
    # origin past int32 ms (~24.8 days) runs on the int64 host engine
    # instead -- identical results, no overflow
    i32_ok = req.step_ms < 2**31 and -(2**31) < t0_rel < 2**31
    use_device = i32_ok and (mode == "device" or (
        mode == "auto"
        and (getattr(blk, "device_pinned", False)
             or getattr(blk, "_staged_cache", None) is not None)
    ))
    n_spans = blk.pack.axes["span"].n_rows if "span" in blk.pack.axes else 0
    if use_device:
        from ..ops.stage import stage_block
        from ..ops.timeseries import eval_timeseries_device

        TEL.record_routing("metrics", "device",
                           "forced" if mode == "device" else "hot_block")
        staged = stage_block(blk, needed)
        outs = eval_timeseries_device(
            query, staged, operands, gid, val, pres,
            t0_rel, req.step_ms, nb, len(labels))
        info = TEL.last_launch()
        span_attrs = {"engine": "device", "bucket": staged.n_spans_b,
                      "compile": bool(info and info[0] == "timeseries"
                                      and info[2])}
    else:
        from ..ops.timeseries import eval_timeseries_host

        TEL.record_routing(
            "metrics", "host",
            "forced" if mode == "host"
            else ("cold_block" if i32_ok else "i32_range"))
        col_names = [n for n in needed
                     if not n.startswith("span@") and blk.pack.has(n)]
        if not all(blk.pack.has_cached_array(n) for n in col_names):
            # cold block: one coalesced ranged read + one threaded
            # decode for the whole eval set (ops/stream stage timings)
            # instead of per-column fetches -- a no-op if the driver's
            # HostPrefetch already ran these stages ahead
            from ..ops.stream import staged_warm

            staged_warm(blk, col_names)
        cols = {n: blk.pack.read(n) for n in col_names}
        outs = eval_timeseries_host(
            query, cols, operands, n_spans, blk.meta.total_traces,
            gid, val, pres, t0_rel, req.step_ms, nb, len(labels))
        span_attrs = {"engine": "host", "bucket": int(n_spans),
                      "compile": False}
    _outs_to_series(outs, q.agg.fn, labels, b_off, resp)
    resp.inspected_spans += n_spans
    resp.inspected_bytes += blk.pack.bytes_read - io0
    TEL.child_span(f"block:{blk.meta.block_id[:8]}", t0_wall, _time.time(),
                   span_attrs)


# ------------------------------------------------------------ exact path


def _label_value(v) -> str | None:
    from ..traceql.hosteval import _is_num

    if isinstance(v, bool):
        return "true" if v else "false"
    if isinstance(v, str):
        return v
    if isinstance(v, tuple) and len(v) == 2 and isinstance(v[0], str):
        if v[0] == "status":
            return _STATUS_NAMES.get(int(v[1]), str(v[1]))
        if v[0] == "kind":
            return _KIND_NAMES.get(int(v[1]), str(v[1]))
    if _is_num(v):
        return str(int(v)) if isinstance(v, int) else repr(float(v))
    return None


def _fold_params(q: MetricsQuery) -> tuple:
    """(filt, count_fn, fname, vscale) shared by every exact fold --
    the block engine and the live-head engine must scale duration-typed
    fold values identically or their series disagree."""
    filt = Pipeline(q.filter, q.stages) if q.stages else q.filter
    agg = q.agg
    count_fn = agg.fn in ("rate", "count_over_time")
    fname = {"sum_over_time": "vsum", "avg_over_time": "vsum",
             "min_over_time": "vmin", "max_over_time": "vmax"}.get(agg.fn)
    # duration-typed fold values are SECONDS on the wire (the columnar
    # engines fold span.start/end_ns deltas / 1e9); the exact evaluator
    # yields nanoseconds, so scale by the argument's static type
    vscale = 1.0
    if agg.field is not None:
        from ..traceql.validate import _expr_type

        try:
            if _expr_type(agg.field) == "duration":
                vscale = 1e-9
        except Exception:
            pass
    return filt, count_fn, fname, vscale


def _fold_span(local: dict, agg, sp, res, ctx, b: int, nb: int,
               count_fn: bool, fname, vscale: float) -> None:
    """Fold ONE matched span into the per-label state dict -- the inner
    accumulator every exact engine shares."""
    from ..traceql.hosteval import _is_num, _value

    labels = []
    for f in agg.by:
        lv = _label_value(_value(f, sp, res, ctx))
        if lv is None:
            return
        labels.append(lv)
    key = tuple(labels)
    state = local.get(key)
    if state is None:
        _check_cardinality(len(local) + 1, nb)
    if count_fn:
        if state is None:
            state = local[key] = {"count": np.zeros(nb, np.int64)}
        state["count"][b] += 1
        return
    v = _value(agg.field, sp, res, ctx)
    if not _is_num(v):
        return
    if state is None:
        varr = (np.zeros(nb, np.float64) if fname == "vsum"
                else np.full(nb, _FIELD_INIT[fname], np.float64))
        state = local[key] = {"vcnt": np.zeros(nb, np.int64),
                              fname: varr}
    state["vcnt"][b] += 1
    v = float(v) * vscale
    if fname == "vsum":
        state[fname][b] += v
    elif fname == "vmin":
        state[fname][b] = min(state[fname][b], v)
    else:
        state[fname][b] = max(state[fname][b], v)


def metrics_live_traces(traces, q: MetricsQuery, req: MetricsRequest,
                        resp: MetricsResponse) -> None:
    """Fold DECODED live traces (the ingester's merged live head) into
    resp with the exact host evaluator -- the host-twin leg that makes
    unflushed spans visible to TraceQL metrics (ROADMAP #4 follow-up).
    Buckets use absolute span-start ms on the request's step grid.
    The block engines floor through the block base (base_ms + rel_ms,
    the columnar ms encoding), so a span within 1 ms of a step edge
    inside a block whose base_ns has a sub-ms remainder can land one
    bucket differently after flush -- bounded at 1 ms, irreducible
    without re-encoding blocks, and invisible at realistic steps."""
    from ..traceql.hosteval import _matched_spans, _TraceCtx

    filt, count_fn, fname, vscale = _fold_params(q)
    agg = q.agg
    nb = req.n_buckets
    local: dict[tuple, dict[str, np.ndarray]] = {}
    n_spans = 0
    for tr in traces:
        ctx = _TraceCtx(tr)
        for sp, res in _matched_spans(filt, ctx):
            n_spans += 1
            b = (sp.start_unix_nano // 1_000_000 - req.start_ms) // req.step_ms
            if not 0 <= b < nb:
                continue
            _fold_span(local, agg, sp, res, ctx, int(b), nb,
                       count_fn, fname, vscale)
    for key, state in local.items():
        resp.add_partial(key, state, 0)
    resp.inspected_spans += n_spans


def _metrics_block_exact(blk: BackendBlock, q: MetricsQuery, req: MetricsRequest,
                         resp: MetricsResponse, planned, b_off: int, nb: int) -> None:
    """Exact engine: the conservative columnar mask narrows the
    candidate traces; each is materialized and re-evaluated span by
    span with the exact host evaluator (incl. pipelines, parent scope,
    lossy leaves). Folds use exact span start times under the SAME
    floored-ms bucket definition as the columnar engines."""
    from ..ops.hostfilter import eval_span_mask_host
    from ..traceql.hosteval import _matched_spans, _TraceCtx

    n_traces = blk.meta.total_traces
    n_spans = blk.pack.axes["span"].n_rows if "span" in blk.pack.axes else 0
    if planned.tree is None:
        sids = list(range(n_traces))
    else:
        operands = Operands.build(planned.rows, planned.tables or None)
        col_names = [n for n in required_columns(planned.conds)
                     if not n.startswith("span@") and n != "trace.span_off"
                     and blk.pack.has(n)]
        if not all(blk.pack.has_cached_array(n) for n in col_names):
            from ..ops.stream import staged_warm

            staged_warm(blk, col_names)
        cols = {n: blk.pack.read(n) for n in col_names}
        mask = eval_span_mask_host((planned.tree, planned.conds), cols,
                                   operands, n_spans, n_traces)
        tsid = cols.get("span.trace_sid")
        if tsid is None:
            tsid = blk.pack.read("span.trace_sid")
        sids = np.unique(tsid[mask]).tolist()
    resp.inspected_spans += n_spans
    if not sids:
        return
    filt, count_fn, fname, vscale = _fold_params(q)
    base_ns = blk.meta.start_time_unix_nano
    base_ms = base_ns // 1_000_000
    t0_abs = req.start_ms + b_off * req.step_ms
    agg = q.agg
    local: dict[tuple, dict[str, np.ndarray]] = {}
    for lo in range(0, len(sids), 512):  # bounded materialization
        for tr in blk.materialize_traces(sids[lo:lo + 512]):
            ctx = _TraceCtx(tr)
            for sp, res in _matched_spans(filt, ctx):
                rel_ms = (sp.start_unix_nano - base_ns) // 1_000_000
                b = (base_ms + rel_ms - t0_abs) // req.step_ms
                if not 0 <= b < nb:
                    continue
                _fold_span(local, agg, sp, res, ctx, int(b), nb,
                           count_fn, fname, vscale)
    for key, state in local.items():
        resp.add_partial(key, state, b_off)


# ---------------------------------------------------------- orchestrator


def parse_metrics_query(query: str) -> MetricsQuery:
    from ..traceql.parser import parse

    q = parse(query)
    if not isinstance(q, MetricsQuery):
        raise ParseError(
            "not a metrics query: expected a terminal rate() / "
            "*_over_time() stage (e.g. `{ ... } | rate() by(...)`)")
    return q


def _cold_metric_wants(blk: BackendBlock, planned) -> list[str] | None:
    """The disk-resident column set one metrics evaluation of blk will
    read (filter columns + the bucket axis), or None when the block is
    warm or pruned -- the cold streaming prefetch's want list. Group-by
    and value columns aren't predicted here; they ride the same ranged
    reads when adjacent and the engine's own cold read covers the rest."""
    if planned.prune:
        return None
    names = [n for n in required_columns(planned.conds)
             if n != "trace.span_off" and not n.startswith("span@")
             and blk.pack.has(n)]
    names.append("span.start_ms")
    names = [n for n in dict.fromkeys(names) if blk.pack.has(n)]
    if not names or all(blk.pack.has_cached_array(n) for n in names):
        return None
    return names


def metrics_query_range_blocks(
    blocks: list[BackendBlock],
    req: MetricsRequest,
    pool=None,
    mesh=None,
    mode: str = "auto",
) -> MetricsResponse:
    """Run one metrics range query over a block set: per-block fused
    folds (device or host by temperature), partial series merged by
    label strings. With a multi-chip mesh, clean same-structure plans
    run as ONE stacked shard_map program with a psum combine
    (parallel/timeseries); everything else stays per-block."""
    q = parse_metrics_query(req.query)
    resp = MetricsResponse(
        fn=q.agg.fn, start_ms=req.start_ms, step_ms=req.step_ms,
        n_buckets=req.n_buckets,
        label_names=tuple(expr_label(e, i) for i, e in enumerate(q.agg.by)),
    )
    in_range = [b for b in blocks
                if b.meta.overlaps_time(req.start_ms // 1000,
                                        -(-req.end_ms // 1000))]
    if not in_range:
        return resp
    if mesh is not None and getattr(mesh.devices, "size", 1) > 1 and len(in_range) > 1:
        from .metrics_mesh import try_metrics_mesh

        done = try_metrics_mesh(mesh, in_range, q, req, resp)
        if done:
            return resp
    lock = None
    if pool is not None:
        import threading

        from ..util.kerneltel import TEL

        lock = threading.Lock()
        self_trace = TEL.active_trace()  # pool threads lose the contextvar

        def run(blk):
            token = TEL.set_active_trace(self_trace)
            part = MetricsResponse(fn=resp.fn, start_ms=resp.start_ms,
                                   step_ms=resp.step_ms, n_buckets=resp.n_buckets,
                                   label_names=resp.label_names)
            try:
                metrics_block(blk, q, req, part, mode=mode)
            finally:
                TEL.reset_active_trace(token)
            with lock:
                resp.merge(part)

        list(pool.map(run, in_range))
    else:
        # serial driver: run cold blocks' fetch+decompress stages ahead
        # on the stream pipeline so block N+1's ranged reads and
        # threaded decode are in flight while block N's engine
        # evaluates -- same depth/byte budget as the search path. Plans
        # are computed once here and handed through to metrics_block.
        plans = {id(blk): plan_metrics_filter(q, blk.dictionary)
                 for blk in in_range}
        cold_wants = [
            (blk, names) for blk in in_range
            if (names := _cold_metric_wants(blk, plans[id(blk)])) is not None]
        prefetch = None
        if len(cold_wants) > 1:  # a lone cold block has nothing to overlap
            from ..ops.stream import HostPrefetch

            prefetch = HostPrefetch(cold_wants)
        try:
            for blk in in_range:
                if prefetch is not None:
                    prefetch.wait(blk)  # False (engine reads itself) on miss
                metrics_block(blk, q, req, resp, mode=mode,
                              planned=plans[id(blk)])
        finally:
            if prefetch is not None:
                prefetch.close()
    return resp
