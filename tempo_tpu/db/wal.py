"""Write-ahead log: append blocks + replay, row-based v1 and columnar v2.

The WAL is the framework's checkpoint (SURVEY.md 5.4): every accepted
push is appended and flushed to the OS before it is acknowledged
(survives a process crash); fsync to stable media runs on a BOUNDED
interval (fsync_interval_s, default 0.25 s, 0 = every flush), so a
HOST crash can lose pushes acked inside that window -- RF-way
replication covers that gap, and RF=1 deployments can set the interval
to 0 through IngesterConfig.wal_fsync_interval_s. On restart,
RescanBlocks replays the files back into in-progress head blocks.
Like the reference
-- whose WAL stays row-based v2 even when complete blocks are parquet
(tempodb/wal/wal.go:91-92) -- the WAL is row-oriented for append speed
while complete blocks are columnar.

File name: <block uuid>+<tenant>+<version>   (parse-able, reference-
style blockID:tenant:version naming, tempodb/wal/wal.go:163-165)

v1 ("w1", legacy, still readable for migration):
  Record: uvarint total_len | trace_id(16) | uint32le start_s |
          uint32le end_s | segment bytes
v2 ("w2", columnar, the default write format -- ingest/walcodec.py):
  Record: uvarint total_len | uint32le crc32 | windowed segments or
          feature checkpoints; one push window = ONE record, and
          replay re-enters the live-search stage buckets without proto
          re-decode when feature records cover the segments.

A torn final record (crash mid-append) is detected by length and
truncated away during replay; a v2 CRC mismatch truncates from the
corrupt record on.
"""

from __future__ import annotations

import os
import struct
import uuid
from dataclasses import dataclass, field

from ..chaos import plane as _chaos
from ..ingest import walcodec
from ..wire import pbwire as w

WAL_VERSION = "w1"
WAL2_VERSION = walcodec.WAL2_VERSION
DEFAULT_WAL_VERSION = WAL2_VERSION
_REC_HDR = struct.Struct("<II")


@dataclass
class WALRecord:
    trace_id: bytes
    start_s: int
    end_s: int
    segment: bytes


class _AppendFile:
    """Shared append-file mechanics for both WAL block versions. Not
    thread-safe; callers serialize per instance.

    Durability contract: flush() hands bytes to the OS (survives a
    process crash); fsync runs at most every fsync_interval_s, plus
    always on close/cut (flush(sync=True)). The reference's v2 append
    block never fsyncs at all (wal durability there comes from RF-way
    replication, wal/append_block.go) -- a bounded interval is strictly
    stronger, without paying a disk round trip per push."""

    VERSION = WAL_VERSION

    def __init__(self, dirpath: str, tenant: str, block_id: str | None = None,
                 fsync_interval_s: float = 0.25):
        self.block_id = block_id or str(uuid.uuid4())
        self.tenant = tenant
        self.path = os.path.join(dirpath, f"{self.block_id}+{tenant}+{self.VERSION}")
        self._f = open(self.path, "ab")
        self._unflushed = 0
        self._unsynced = False  # bytes handed to the OS but not fsynced
        self._fsync_interval_s = fsync_interval_s
        self._last_fsync = 0.0

    def _write_frame(self, rec: bytes) -> bool:
        """One framed record to the file. chaos seam (gated: this is the
        hottest write path): truncate = a torn append (crash mid-write;
        replay must drop the tail), drop = a lost record, error = disk
        fault. Returns False when the record was dropped."""
        if _chaos.is_active():
            rec = _chaos.mangle("wal.append", rec, tenant=self.tenant,
                                key=self.block_id)
            if not rec:
                return False  # dropped: nothing hit the file
        self._f.write(rec)
        self._unflushed += 1
        return True

    def flush(self, sync: bool = False) -> None:
        if self._unflushed:
            self._f.flush()
            self._unsynced = True
            self._unflushed = 0
        if self._unsynced:
            import time as _time

            now = _time.monotonic()
            if sync or now - self._last_fsync >= self._fsync_interval_s:
                # chaos seam: an injected fsync error is a failed
                # stable write -- the push must NOT be acked as durable
                if _chaos.is_active():
                    _chaos.tap("wal.fsync", tenant=self.tenant,
                               key=self.block_id)
                os.fsync(self._f.fileno())
                self._last_fsync = now
                self._unsynced = False

    def size_bytes(self) -> int:
        self._f.flush()
        return os.path.getsize(self.path)

    def close(self) -> None:
        self.flush(sync=True)
        self._f.close()

    def clear(self) -> None:
        self.close()
        try:
            os.unlink(self.path)
        except FileNotFoundError:
            pass


class WALBlock(_AppendFile):
    """Row-based v1 append file: one record per segment (legacy write
    format, kept for migration -- IngesterConfig.wal_version selects)."""

    VERSION = WAL_VERSION

    def append(self, trace_id: bytes, start_s: int, end_s: int, segment: bytes) -> None:
        tid = trace_id.rjust(16, b"\x00")
        body = tid + _REC_HDR.pack(start_s & 0xFFFFFFFF, end_s & 0xFFFFFFFF) + segment
        hdr = bytearray()
        w.write_varint(hdr, len(body))
        self._write_frame(bytes(hdr) + body)

    # ---- replay
    @staticmethod
    def read_records(path: str) -> tuple[list[WALRecord], bool]:
        """-> (records, clean). clean=False if a torn tail was dropped."""
        with open(path, "rb") as f:
            data = f.read()

        # native frame scan (native/vtpu_native.cc) when available
        from ..native import varint_frames

        frames = varint_frames(data)
        if frames is not None:
            offs, lens, clean, torn_at = frames
            out = []
            for i, (off, ln) in enumerate(zip(offs, lens)):
                off, ln = int(off), int(ln)
                if ln < 16 + _REC_HDR.size:
                    # framed but impossibly small: torn at this frame's
                    # header, i.e. right after the previous frame's body
                    # (no assumption about the varint's own encoding)
                    clean = False
                    torn_at = int(offs[i - 1] + lens[i - 1]) if i > 0 else 0
                    break
                tid = data[off : off + 16]
                s, e = _REC_HDR.unpack_from(data, off + 16)
                out.append(WALRecord(tid, s, e, data[off + 16 + _REC_HDR.size : off + ln]))
            if not clean:
                with open(path, "ab") as f:
                    f.truncate(torn_at)
            return out, clean

        out: list[WALRecord] = []
        pos = 0
        clean = True
        n = len(data)
        while pos < n:
            start_pos = pos
            try:
                ln, pos = w.read_varint(data, pos)
            except ValueError:
                clean = False
                break
            if pos + ln > n or ln < 16 + _REC_HDR.size:
                clean = False
                pos = start_pos
                break
            tid = data[pos : pos + 16]
            s, e = _REC_HDR.unpack_from(data, pos + 16)
            seg = data[pos + 16 + _REC_HDR.size : pos + ln]
            out.append(WALRecord(tid, s, e, seg))
            pos += ln
        if not clean:
            # truncate the torn tail so future appends produce a valid file
            with open(path, "ab") as f:
                f.truncate(start_pos)
        return out, clean


def _scan_frames(data: bytes) -> tuple[list[tuple[int, int]], bool, int]:
    """Generic varint frame scan: -> ([(body_off, body_len)], clean,
    torn_at). torn_at is the truncation offset when not clean."""
    from ..native import varint_frames

    frames = varint_frames(data)
    if frames is not None:
        offs, lens, clean, torn_at = frames
        return ([(int(o), int(ln)) for o, ln in zip(offs, lens)],
                bool(clean), int(torn_at))
    out: list[tuple[int, int]] = []
    pos = 0
    clean = True
    torn_at = len(data)
    n = len(data)
    while pos < n:
        start_pos = pos
        try:
            ln, pos = w.read_varint(data, pos)
        except ValueError:
            clean, torn_at = False, start_pos
            break
        if pos + ln > n:
            clean, torn_at = False, start_pos
            break
        out.append((pos, ln))
        pos += ln
    return out, clean, torn_at


class WAL2Block(_AppendFile):
    """Columnar v2 append file: one record per push WINDOW (all traces
    of one distributor push, single CRC-guarded frame + single file
    write on the ack path) plus lazy FEATURE records checkpointing
    already-decoded segment features so replay re-enters the stage
    buckets without proto re-decode (ingest/walcodec.py)."""

    VERSION = WAL2_VERSION

    def __init__(self, dirpath: str, tenant: str, block_id: str | None = None,
                 fsync_interval_s: float = 0.25):
        super().__init__(dirpath, tenant, block_id, fsync_interval_s)
        self._windows = 0
        # segments appended but not yet feature-checkpointed:
        # (window_idx, trace_idx, segment ref)
        self._pending_feat: list[tuple[int, int, bytes]] = []
        # live dict code -> file-local code (file codes are assigned in
        # first-reference order; their strings ship as dict deltas)
        self._file_code: dict[int, int] = {}

    def append_window(self, batch: list[tuple[bytes, int, int, bytes]]) -> None:
        """batch: [(trace_id, start_s, end_s, segment)] -- one record."""
        rec = walcodec.encode_window(batch)
        if not self._write_frame(rec):
            return  # chaos drop: the window never hit the file
        for i, (_, _, _, seg) in enumerate(batch):
            self._pending_feat.append((self._windows, i, seg))
        self._windows += 1

    def append(self, trace_id: bytes, start_s: int, end_s: int, segment: bytes) -> None:
        """Single-segment window: keeps version-agnostic callers working."""
        self.append_window([(trace_id, start_s, end_s, segment)])

    def flush_features(self, features_of, ldict) -> int:
        """Checkpoint features for every pending segment whose features
        are ALREADY decoded (features_of returns None to skip -- the
        checkpoint must never add decode work to the write path).
        ldict maps live codes back to strings for the file-local dict
        delta. Returns the number of entries written."""
        entries = []
        delta: list[str] = []
        still: list[tuple[int, int, bytes]] = []
        for w_idx, t_idx, seg in self._pending_feat:
            feat = features_of(seg)
            if feat is None:
                still.append((w_idx, t_idx, seg))
                continue
            kv = [self._file_code_of(c, ldict, delta) for c in feat.kv_codes]
            nm = [self._file_code_of(c, ldict, delta) for c in feat.name_codes]
            entries.append((w_idx, t_idx, kv, nm, feat.lo_ns, feat.hi_ns))
        self._pending_feat = still
        if not entries:
            return 0
        if not self._write_frame(walcodec.encode_features(delta, entries)):
            return 0
        try:
            from ..util.kerneltel import TEL

            TEL.record_ingest_features(len(entries))
        except Exception:
            pass
        return len(entries)

    def _file_code_of(self, live_code: int, ldict, delta: list[str]) -> int:
        fc = self._file_code.get(live_code)
        if fc is None:
            fc = self._file_code[live_code] = len(self._file_code)
            delta.append(ldict.string(live_code))
        return fc

    # ---- replay
    @staticmethod
    def read_records(path: str) -> tuple[list[WALRecord], bool,
                                         dict[int, tuple], list[str]]:
        """-> (records, clean, features, dict_delta). features maps a
        record's INDEX in `records` to (kv_strings, name_strings, lo_ns,
        hi_ns); dict_delta is the file's dictionary strings in file-code
        order (replay seeds them first so live codes reproduce). A CRC
        mismatch or malformed record truncates the file there, exactly
        like a torn tail."""
        with open(path, "rb") as f:
            data = f.read()
        spans, clean, torn_at = _scan_frames(data)
        records: list[WALRecord] = []
        features: dict[int, tuple] = {}
        strings: list[str] = []
        windows: list[list[int]] = []
        prev_end = 0
        for off, ln in spans:
            parsed = walcodec.decode_record(data, off, ln)
            if parsed is None:
                # CRC reject / malformed / truncated-to-tiny frame: the
                # stream past this point is untrusted
                clean, torn_at = False, prev_end
                break
            rtype, body = parsed
            if rtype == walcodec.REC_WINDOW:
                idxs = []
                for tid, s, e, seg in body:
                    idxs.append(len(records))
                    records.append(WALRecord(tid, s, e, seg))
                windows.append(idxs)
            else:  # REC_FEATURES
                delta, entries = body
                strings.extend(delta)
                bad = False
                for w_idx, t_idx, kv, nm, lo, hi in entries:
                    if (w_idx >= len(windows) or t_idx >= len(windows[w_idx])
                            or any(c >= len(strings) for c in kv)
                            or any(c >= len(strings) for c in nm)):
                        bad = True
                        break
                    features[windows[w_idx][t_idx]] = (
                        tuple(strings[c] for c in kv),
                        tuple(strings[c] for c in nm), lo, hi)
                if bad:
                    clean, torn_at = False, prev_end
                    break
            prev_end = off + ln
        if not clean:
            with open(path, "ab") as f:
                f.truncate(torn_at)
        return records, clean, features, strings


@dataclass
class ReplayedBlock:
    block_id: str
    tenant: str
    path: str
    records: list[WALRecord] = field(default_factory=list)
    clean: bool = True
    version: str = WAL_VERSION
    # v2 only: record index -> (kv_strings, name_strings, lo_ns, hi_ns)
    features: dict = field(default_factory=dict)
    dict_delta: list = field(default_factory=list)


class WAL:
    """Directory manager + block factory + replay scan
    (reference: tempodb/wal/wal.go:39-142)."""

    def __init__(self, dirpath: str, fsync_interval_s: float = 0.25):
        self.dir = dirpath
        self.fsync_interval_s = fsync_interval_s
        os.makedirs(dirpath, exist_ok=True)

    def new_block(self, tenant: str, version: str | None = None):
        cls = WALBlock if (version or DEFAULT_WAL_VERSION) == WAL_VERSION else WAL2Block
        return cls(self.dir, tenant, fsync_interval_s=self.fsync_interval_s)

    def rescan_blocks(self) -> list[ReplayedBlock]:
        out: list[ReplayedBlock] = []
        for name in sorted(os.listdir(self.dir)):
            parts = name.split("+")
            if len(parts) != 3 or parts[2] not in (WAL_VERSION, WAL2_VERSION):
                continue  # unknown files are left alone
            path = os.path.join(self.dir, name)
            if parts[2] == WAL2_VERSION:
                records, clean, features, delta = WAL2Block.read_records(path)
                out.append(ReplayedBlock(parts[0], parts[1], path, records,
                                         clean, version=WAL2_VERSION,
                                         features=features, dict_delta=delta))
            else:
                records, clean = WALBlock.read_records(path)
                out.append(ReplayedBlock(parts[0], parts[1], path, records, clean))
        return out

    def delete_block_file(self, block_id: str, tenant: str) -> None:
        for version in (WAL_VERSION, WAL2_VERSION):
            try:
                os.unlink(os.path.join(self.dir, f"{block_id}+{tenant}+{version}"))
            except FileNotFoundError:
                pass
