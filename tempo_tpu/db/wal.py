"""Write-ahead log: row-based append blocks + replay.

The WAL is the framework's checkpoint (SURVEY.md 5.4): every accepted
push is appended and flushed to the OS before it is acknowledged
(survives a process crash); fsync to stable media runs on a BOUNDED
interval (fsync_interval_s, default 0.25 s, 0 = every flush), so a
HOST crash can lose pushes acked inside that window -- RF-way
replication covers that gap, and RF=1 deployments can set the interval
to 0 through IngesterConfig.wal_fsync_interval_s. On restart,
RescanBlocks replays the files back into in-progress head blocks.
Like the reference
-- whose WAL stays row-based v2 even when complete blocks are parquet
(tempodb/wal/wal.go:91-92) -- the WAL is row-oriented for append speed
while complete blocks are columnar.

File name: <block uuid>+<tenant>+w1   (parse-able, reference-style
blockID:tenant:version naming, tempodb/wal/wal.go:163-165)
Record:    uvarint total_len | trace_id(16) | uint32le start_s |
           uint32le end_s | segment bytes
A torn final record (crash mid-append) is detected by length and
truncated away during replay.
"""

from __future__ import annotations

import os
import struct
import uuid
from dataclasses import dataclass, field

from ..chaos import plane as _chaos
from ..wire import pbwire as w

WAL_VERSION = "w1"
_REC_HDR = struct.Struct("<II")


@dataclass
class WALRecord:
    trace_id: bytes
    start_s: int
    end_s: int
    segment: bytes


class WALBlock:
    """One append file. Not thread-safe; callers serialize per instance.

    Durability contract: flush() hands bytes to the OS (survives a
    process crash); fsync runs at most every fsync_interval_s, plus
    always on close/cut (flush(sync=True)). The reference's v2 append
    block never fsyncs at all (wal durability there comes from RF-way
    replication, wal/append_block.go) -- a bounded interval is strictly
    stronger, without paying a disk round trip per push."""

    def __init__(self, dirpath: str, tenant: str, block_id: str | None = None,
                 fsync_interval_s: float = 0.25):
        self.block_id = block_id or str(uuid.uuid4())
        self.tenant = tenant
        self.path = os.path.join(dirpath, f"{self.block_id}+{tenant}+{WAL_VERSION}")
        self._f = open(self.path, "ab")
        self._unflushed = 0
        self._unsynced = False  # bytes handed to the OS but not fsynced
        self._fsync_interval_s = fsync_interval_s
        self._last_fsync = 0.0

    def append(self, trace_id: bytes, start_s: int, end_s: int, segment: bytes) -> None:
        tid = trace_id.rjust(16, b"\x00")
        body = tid + _REC_HDR.pack(start_s & 0xFFFFFFFF, end_s & 0xFFFFFFFF) + segment
        hdr = bytearray()
        w.write_varint(hdr, len(body))
        rec = bytes(hdr) + body
        # chaos seam (gated: this is the hottest write path): truncate
        # = a torn append (crash mid-write; replay must drop the
        # tail), drop = a lost record, error = disk fault
        if _chaos.is_active():
            rec = _chaos.mangle("wal.append", rec, tenant=self.tenant,
                                key=self.block_id)
            if not rec:
                return  # dropped: nothing hit the file
        self._f.write(rec)
        self._unflushed += 1

    def flush(self, sync: bool = False) -> None:
        if self._unflushed:
            self._f.flush()
            self._unsynced = True
            self._unflushed = 0
        if self._unsynced:
            import time as _time

            now = _time.monotonic()
            if sync or now - self._last_fsync >= self._fsync_interval_s:
                # chaos seam: an injected fsync error is a failed
                # stable write -- the push must NOT be acked as durable
                if _chaos.is_active():
                    _chaos.tap("wal.fsync", tenant=self.tenant,
                               key=self.block_id)
                os.fsync(self._f.fileno())
                self._last_fsync = now
                self._unsynced = False

    def size_bytes(self) -> int:
        self._f.flush()
        return os.path.getsize(self.path)

    def close(self) -> None:
        self.flush(sync=True)
        self._f.close()

    def clear(self) -> None:
        self.close()
        try:
            os.unlink(self.path)
        except FileNotFoundError:
            pass

    # ---- replay
    @staticmethod
    def read_records(path: str) -> tuple[list[WALRecord], bool]:
        """-> (records, clean). clean=False if a torn tail was dropped."""
        with open(path, "rb") as f:
            data = f.read()

        # native frame scan (native/vtpu_native.cc) when available
        from ..native import varint_frames

        frames = varint_frames(data)
        if frames is not None:
            offs, lens, clean, torn_at = frames
            out = []
            for i, (off, ln) in enumerate(zip(offs, lens)):
                off, ln = int(off), int(ln)
                if ln < 16 + _REC_HDR.size:
                    # framed but impossibly small: torn at this frame's
                    # header, i.e. right after the previous frame's body
                    # (no assumption about the varint's own encoding)
                    clean = False
                    torn_at = int(offs[i - 1] + lens[i - 1]) if i > 0 else 0
                    break
                tid = data[off : off + 16]
                s, e = _REC_HDR.unpack_from(data, off + 16)
                out.append(WALRecord(tid, s, e, data[off + 16 + _REC_HDR.size : off + ln]))
            if not clean:
                with open(path, "ab") as f:
                    f.truncate(torn_at)
            return out, clean

        out: list[WALRecord] = []
        pos = 0
        clean = True
        n = len(data)
        while pos < n:
            start_pos = pos
            try:
                ln, pos = w.read_varint(data, pos)
            except ValueError:
                clean = False
                break
            if pos + ln > n or ln < 16 + _REC_HDR.size:
                clean = False
                pos = start_pos
                break
            tid = data[pos : pos + 16]
            s, e = _REC_HDR.unpack_from(data, pos + 16)
            seg = data[pos + 16 + _REC_HDR.size : pos + ln]
            out.append(WALRecord(tid, s, e, seg))
            pos += ln
        if not clean:
            # truncate the torn tail so future appends produce a valid file
            with open(path, "ab") as f:
                f.truncate(start_pos)
        return out, clean


@dataclass
class ReplayedBlock:
    block_id: str
    tenant: str
    path: str
    records: list[WALRecord] = field(default_factory=list)
    clean: bool = True


class WAL:
    """Directory manager + block factory + replay scan
    (reference: tempodb/wal/wal.go:39-142)."""

    def __init__(self, dirpath: str, fsync_interval_s: float = 0.25):
        self.dir = dirpath
        self.fsync_interval_s = fsync_interval_s
        os.makedirs(dirpath, exist_ok=True)

    def new_block(self, tenant: str) -> WALBlock:
        return WALBlock(self.dir, tenant, fsync_interval_s=self.fsync_interval_s)

    def rescan_blocks(self) -> list[ReplayedBlock]:
        out: list[ReplayedBlock] = []
        for name in sorted(os.listdir(self.dir)):
            parts = name.split("+")
            if len(parts) != 3 or parts[2] != WAL_VERSION:
                continue  # unknown files are left alone
            path = os.path.join(self.dir, name)
            records, clean = WALBlock.read_records(path)
            out.append(ReplayedBlock(parts[0], parts[1], path, records, clean))
        return out

    def delete_block_file(self, block_id: str, tenant: str) -> None:
        try:
            os.unlink(os.path.join(self.dir, f"{block_id}+{tenant}+{WAL_VERSION}"))
        except FileNotFoundError:
            pass
