"""No-decode compaction: concatenate small input blocks into one
COMPOUND block by verbatim object copy.

The many-tiny-blocks compaction shape (level 0 after an ingest burst)
is dominated by per-block fixed costs and pays a full decode -> K-way
merge -> re-encode even though the data is hours old at most. The
reference's answer is a row-level no-decode parquet copy
(vparquet/compactor.go:23-80); this design takes the same idea to its
limit for the first level: a compound block is K verbatim part copies
under one block id --

    tenant/<cid>/meta.json              version "vtpu1c", parts list
    tenant/<cid>/p0/{data.vtpu,dict.vtpu,bloom-*}
    tenant/<cid>/p1/...

so "compacting" 100 small blocks is 100 object copies at backend IO
speed (no decompress, no merge, no re-encode) and the block COUNT drops
100x for the poller/bloom/job machinery. The poller EXPANDS a compound
into its per-part BlockMetas (block_id "cid/p3"), so every downstream
path -- search, find, sharding, further compaction -- sees ordinary
vtpu1 blocks and needs zero changes. Parts come out one level up, where
the ordinary columnar rewrite merges them into genuinely sorted big
blocks; a part consumed by that rewrite gets its own compacted marker
(backend.mark_compacted handles meta-less parts), and a compound whose
parts are all consumed ages out as a whole.
"""

from __future__ import annotations

import json
import threading
import time
import uuid

from ..backend.base import COMPACTED_META_NAME, DoesNotExist, RawBackend
from ..block.builder import BLOOM_PREFIX, DATA_NAME, DICT_NAME
from ..block.meta import BlockMeta
from ..util.kerneltel import TEL

COMPOUND_VERSION = "vtpu1c"


def part_metas(compound_doc: dict) -> list[BlockMeta]:
    return [BlockMeta.from_json(json.dumps(p).encode())
            for p in compound_doc.get("parts", [])]


def compact_concat(backend: RawBackend, job, cfg) -> "CompactionResult":
    """Concatenate the job's input blocks into one compound block."""
    from .compactor import CompactionResult

    tenant = job.tenant
    cid = str(uuid.uuid4())
    out_level = max(m.compaction_level for m in job.blocks) + 1
    parts: list[dict] = []
    result = CompactionResult()
    for i, m in enumerate(job.blocks):
        part_id = f"{cid}/p{i}"
        names = [DATA_NAME, DICT_NAME] + [
            f"{BLOOM_PREFIX}{s}" for s in range(m.bloom_shards)
        ]
        for name in names:
            try:
                # backend-side copy (local: hardlink; stores: server-side
                # copy API) -- part bytes never move through Python
                backend.copy_object(tenant, m.block_id, name, part_id)
            except DoesNotExist:
                if name == DATA_NAME:
                    raise  # a block without data is corrupt; fail the job
        pm = json.loads(m.to_json())
        pm["block_id"] = part_id
        pm["compaction_level"] = out_level
        parts.append(pm)
        TEL.record_passthrough(int(m.size_bytes))
        result.traces_out += m.total_traces
        result.spans_out += m.total_spans
    doc = {
        "version": COMPOUND_VERSION,
        "block_id": cid,
        "tenant_id": tenant,
        "compaction_level": out_level,
        "total_traces": result.traces_out,
        "total_spans": result.spans_out,
        "size_bytes": sum(m.size_bytes for m in job.blocks),
        "created_at": time.time(),
        "parts": parts,
    }
    # meta last: pollers never see a partial compound
    backend.write(tenant, cid, "meta.json",
                  json.dumps(doc, separators=(",", ":")).encode())
    for m in job.blocks:
        backend.mark_compacted(tenant, m.block_id)
    result.new_blocks = part_metas(doc)
    result.compacted_ids = [m.block_id for m in job.blocks]
    return result


# markers are monotonic (a part never un-compacts), so positive results
# cache process-wide: a K-part compound costs K marker probes per poll
# only while its parts are still being consumed. Bounded: entries for
# aged-out compounds are never probed again, so a long-lived process
# with compaction churn would otherwise grow this forever
_MARKER_CACHE_MAX = 4096
_marker_cache: dict[tuple[str, str], float] = {}
_marker_lock = threading.Lock()


def expand_compound(backend: RawBackend, tenant: str, doc: dict):
    """Compound meta doc -> [(part BlockMeta, is_compacted)]. A part is
    compacted when the ordinary rewrite that consumed it left a marker
    in its directory; transient marker-read errors conservatively keep
    the part LIVE (searchable) for this cycle."""
    out = []
    for pm in doc.get("parts", []):
        meta = BlockMeta.from_json(json.dumps(pm).encode())
        key = (tenant, meta.block_id)
        stamp = _marker_cache.get(key)
        if stamp is None:
            try:
                marker = backend.read(tenant, meta.block_id, COMPACTED_META_NAME)
            except DoesNotExist:
                out.append((meta, False))
                continue
            except Exception:
                out.append((meta, False))  # transient read error: stay live
                continue
            try:
                stamp = float(json.loads(marker).get("compacted_at_unix", 0.0))
            except (ValueError, TypeError):
                stamp = time.time()  # corrupt marker: hold, don't age out
            stamp = stamp or time.time()
            with _marker_lock:
                while len(_marker_cache) >= _MARKER_CACHE_MAX:
                    # insertion order ~ discovery order: oldest parts
                    # age out of their compound docs first anyway
                    _marker_cache.pop(next(iter(_marker_cache)))
                _marker_cache[key] = stamp
        meta.compacted_at_unix = stamp
        out.append((meta, True))
    return out
