"""Cross-query batching executor: coalesce concurrent jobs into fused
multi-query kernel launches.

Under concurrency every in-flight query used to dispatch its own kernel
sequence over the SAME staged block -- Q small launches paying Q
dispatch round trips. This module is the scheduling half of the fix
(ops/multiquery.py is the kernel half), the trace-search analog of
continuous batching in inference serving (Orca, OSDI '22: merge
concurrent requests into one device step):

  * a short admission window (TEMPO_BATCH_WINDOW_MS, default 3 ms)
    opens when the first eligible job arrives; jobs submitted inside it
    group by *coalesce key* -- (block, row-group range, staged column
    set, program-shape bucket) -- so every member lowers onto the SAME
    compiled program;
  * each group executes as ONE fused launch pair (multi-query filter +
    batched top-k) and the per-query results demux back to their
    submitters, exact-verify fallback preserved per query;
  * a lone query never waits past the window, and skips it entirely
    when nobody else is inside the executor (the in-flight fast path);
  * ineligible plans (regex tables, generic attr conds, struct
    relations, cold blocks) never enter the window: callers fall back
    to the single-query path unchanged.

Two executors share the machinery: `search` fuses TraceQL/tag search
jobs through the predicate-program kernel; `find` fuses trace-by-ID
lookups through the batched bisection kernel (ops/find already takes a
(Q, 4) query block -- the batcher just forms the Q axis).

Occupancy, coalesce ratio, window waits and demux counts flow through
util/kerneltel into /metrics and /status/kernels.
"""

from __future__ import annotations

import os
import threading
import time
from dataclasses import dataclass, field

import numpy as np

from ..util.profiler import timed_lock

DEFAULT_WINDOW_MS = 3.0
DEFAULT_MAX_BATCH = 16
_FOLLOWER_TIMEOUT_S = 600.0


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


def _mq_budget_bytes() -> int:
    """Fused-launch intermediate budget ((Q, P, S) cond masks + cumsums
    in HBM): a group estimated past it runs its members sequentially
    instead. TEMPO_BATCH_MQ_BUDGET overrides (bytes)."""
    return int(_env_float("TEMPO_BATCH_MQ_BUDGET", float(1 << 30)))


def resolve_batch_config(enabled=None, window_ms=None, max_batch=None):
    """(enabled, window_s, max_batch) from explicit config, falling back
    to env knobs: TEMPO_BATCH=0 disables, TEMPO_BATCH_WINDOW_MS,
    TEMPO_BATCH_MAX."""
    if enabled is None:
        enabled = os.environ.get("TEMPO_BATCH", "1") not in ("0", "false")
    if window_ms is None:
        window_ms = _env_float("TEMPO_BATCH_WINDOW_MS", DEFAULT_WINDOW_MS)
    if max_batch is None:
        max_batch = int(_env_float("TEMPO_BATCH_MAX", DEFAULT_MAX_BATCH))
    return bool(enabled), max(0.0, window_ms) / 1e3, max(1, max_batch)


class _Group:
    __slots__ = ("items", "done", "full", "closed", "results")

    def __init__(self):
        self.items: list = []
        self.done = threading.Event()
        self.full = threading.Event()
        self.closed = False
        self.results: list | None = None


class BatchExecutor:
    """Leader/follower admission-window batcher. The first submitter
    for a key becomes the group leader: it holds the window open (or
    until the group fills), then runs `runner(key, items)` and fans the
    per-item results (or per-item exceptions) back out. Followers that
    land inside the window just wait for demux."""

    def __init__(self, name: str, runner, window_s: float = DEFAULT_WINDOW_MS / 1e3,
                 max_batch: int = DEFAULT_MAX_BATCH, enabled: bool = True):
        self.name = name
        self.runner = runner  # (key, items) -> list of results/Exceptions
        self.window_s = window_s
        self.max_batch = max_batch
        self.enabled = enabled
        # cataloged hot lock: every submitter serializes through the
        # admission window here (TEMPO_LOCK_PROFILE arms wait timing)
        self._lock = timed_lock(f"batchexec_{name}")
        self._groups: dict = {}
        self._inflight = 0  # submitters currently inside submit_many

    def submit(self, key, item):
        out = self.submit_many(key, [item])[0]
        if isinstance(out, Exception):
            raise out
        return out

    def submit_many(self, key, items: list) -> list:
        """Submit items under one coalesce key; blocks until the fused
        group (this thread's and any window-mates') executes. Returns
        per-item results; a failed item comes back as its Exception so
        one poisoned query never discards its siblings' results (multi
        callers route per-item failures through per-job error paths)."""
        if len(items) > self.max_batch:  # a single oversized submission
            out: list = []  # still respects the configured group cap
            for i in range(0, len(items), self.max_batch):
                out.extend(self.submit_many(key, items[i:i + self.max_batch]))
            return out
        with self._lock:
            self._inflight += 1
            g = self._groups.get(key)
            if (g is None or g.closed
                    or len(g.items) + len(items) > self.max_batch):
                g = _Group()
                self._groups[key] = g
                leader = True
            else:
                leader = False
            lo = len(g.items)
            g.items.extend(items)
            if not leader and len(g.items) >= self.max_batch:
                g.full.set()
        try:
            if leader:
                self._lead(key, g)
            elif not g.done.wait(_FOLLOWER_TIMEOUT_S):
                raise TimeoutError(
                    f"batch group leader stalled ({self.name})")
        finally:
            with self._lock:
                self._inflight -= 1
        return g.results[lo:lo + len(items)]

    def _lead(self, key, g: _Group) -> None:
        from ..util.kerneltel import TEL

        t0 = time.monotonic()
        t0_wall = time.time()
        # lone-query fast path: only hold the window open when another
        # SUBMITTER is inside the executor (each counts once in
        # _inflight no matter how many items it carries; the leader
        # itself is one). Purely sequential traffic therefore never
        # pays the window; a concurrent burst's stragglers group with
        # each other while the first arrival's launch is in flight.
        if self.window_s > 0:
            with self._lock:
                others = self._inflight > 1
            if others:
                g.full.wait(self.window_s)
        with self._lock:
            g.closed = True
            if self._groups.get(key) is g:
                del self._groups[key]
            items = list(g.items)
        wait_s = time.monotonic() - t0
        # timeline: the admission window this leader held open (zero-
        # length on the lone-query fast path), with its final occupancy
        TEL.child_span("batch-window", t0_wall, t0_wall + wait_s,
                       {"executor": self.name, "occupancy": len(items)})
        try:
            results = self.runner(key, items)
            if not isinstance(results, list) or len(results) != len(items):
                raise RuntimeError(
                    f"batch runner returned {len(results) if isinstance(results, list) else type(results)} "
                    f"results for {len(items)} items")
            g.results = results
        except Exception as e:  # group-level failure: every member sees it
            g.results = [e] * len(items)
        finally:
            g.done.set()
        TEL.record_batch(self.name, len(items), wait_s)


# ------------------------------------------------------------- search path


@dataclass
class _SearchItem:
    blk: object
    req: object
    planned: object
    lowered: object
    needed: list
    groups_range: object
    limit: int


def _collect_seeded(blk, req, planned, seed, tm_row, counts_row, key_dev,
                    limit: int):
    """db/search._collect_topk with the FIRST selection pre-computed by
    the fused batched top-k (the seed was sliced to exactly the k the
    collect loop asks for first); escalation (verification rejected
    enough candidates) falls back to per-query device selects on this
    query's mask row. Returns candidate records (materialize=False)."""
    from ..ops.select import select_topk_device
    from .search import _collect_topk

    state = [seed]

    def selector(k):
        if state:
            return state.pop()
        return select_topk_device(tm_row, key_dev, counts_row, k)

    return _collect_topk(blk, req, planned.needs_verify, selector, limit,
                         materialize=False)


def _sequential_search(it: _SearchItem):
    from dataclasses import replace

    from .search import search_block

    # honor the route's default limit (search_blocks passes the config
    # default; search_block alone would fall back to the module default)
    req = it.req if it.req.limit else replace(it.req, limit=it.limit)
    return search_block(it.blk, req, groups_range=it.groups_range)


def _run_search_group(key, items: list, mesh_fn=None) -> list:
    """Execute one coalesced search group: stage once, ONE fused
    multi-query filter launch, ONE batched top-k launch, per-query
    verify + materialize. Any fused-path failure degrades to per-item
    single-query execution (never to an error the sequential path would
    not have raised)."""
    from ..util.kerneltel import TEL

    if len(items) == 1:
        return [_seq_or_exc(items[0])]
    try:
        return _run_search_group_fused(items, mesh_fn)
    except Exception:
        TEL.record_routing("search_batch", "fallback", "fused_error",
                           n=len(items))
        return [_seq_or_exc(it) for it in items]


def _seq_or_exc(it: _SearchItem):
    try:
        return _sequential_search(it)
    except Exception as e:
        return e


def _mesh_batch_enabled() -> bool:
    """TEMPO_MESH_BATCH=0 pins window leaders to the single-chip fused
    launch even on a multi-device mesh (the legacy-path escape hatch the
    differential suite also uses)."""
    return os.environ.get("TEMPO_MESH_BATCH", "1") not in ("0", "false")


def _run_search_group_fused(items: list, mesh_fn=None) -> list:
    import time as _time

    from ..ops.multiquery import (
        _p2,
        eval_multiquery,
        mq_bytes_estimate,
        pack_queries,
        select_multiquery,
    )
    from ..ops.select import k_bucket
    from ..ops.stage import stage_block
    from ..util.kerneltel import TEL
    from .search import SearchResponse, _materialize

    blk = items[0].blk
    shape = items[0].lowered.shape
    q_b = _p2(len(items), lo=1)
    io0 = blk.pack.bytes_read
    t0w = _time.time()
    staged = stage_block(blk, items[0].needed + ["trace.start_ms"],
                         groups=items[0].groups_range)
    if mq_bytes_estimate(shape, q_b, staged.n_spans_b) > _mq_budget_bytes():
        TEL.record_routing("search_batch", "fallback", "mq_budget",
                           n=len(items))
        return [_seq_or_exc(it) for it in items]
    progs = pack_queries([it.lowered for it in items], q_b)
    lowered = [it.lowered for it in items]
    # >1 chip attached: the window leader lowers the whole group to ONE
    # Q-programs x sharded-rows mesh launch (parallel/multiquery), so
    # the admission window amortizes across every chip instead of
    # competing with sp-sharding for the executor. Shape-ineligible
    # buckets and TEMPO_MESH_BATCH=0 keep the single-chip fused launch.
    mesh = mesh_fn() if mesh_fn is not None else None
    engine = "device"
    if mesh is not None and _mesh_batch_enabled():
        from ..parallel.multiquery import mesh_batch_eligible, mesh_eval_multiquery

        if mesh_batch_eligible(mesh, staged):
            tm, counts = mesh_eval_multiquery(mesh, lowered, staged, progs)
            engine = "mesh"
        else:
            tm, counts = eval_multiquery(lowered, staged, progs)
    else:
        tm, counts = eval_multiquery(lowered, staged, progs)
    key_dev = staged.cols["trace.start_ms"]
    nt = blk.meta.total_traces
    TEL.record_routing("search_batch", engine,
                       "mesh_batched" if engine == "mesh" else "coalesced",
                       n=len(items))
    TEL.child_span(
        f"batch:{blk.meta.block_id[:8]}", t0w, _time.time(),
        {"engine": engine, "bucket": staged.n_spans_b,
         "occupancy": len(items)})

    responses: list = []
    if nt == 0:
        for it in items:
            r = SearchResponse()
            r.inspected_spans = staged.n_spans
            responses.append(r)
        responses[0].inspected_bytes = blk.pack.bytes_read - io0
        return responses
    ks = [min(k_bucket(max(2 * it.limit, 32)), nt) for it in items]
    rows = select_multiquery(tm, key_dev, counts, max(ks))
    for qi, it in enumerate(items):
        try:
            sids_k, cnts_k, valid_k, n_match = rows[qi]
            kq = ks[qi]
            seed = (sids_k[:kq][valid_k[:kq]], cnts_k[:kq][valid_k[:kq]],
                    n_match)
            out = _collect_seeded(blk, it.req, it.planned, seed,
                                  tm[qi], counts[qi], key_dev, it.limit)
            results = [_materialize(c) for c in out]
            results.sort(key=lambda r: -r.start_time_unix_nano)
            resp = SearchResponse()
            resp.traces = results[:it.limit]
            resp.inspected_spans = staged.n_spans
            responses.append(resp)
        except Exception as e:  # verify/materialize is per-query: isolate
            responses.append(e)
    # IO attribution mirrors the sequential hot path: only the query
    # that triggered reads pays them (here, the group's one staging
    # pass), so the first response carries the delta and its mates
    # report 0 -- same as cache-hit queries on the sequential engine
    for r in responses:
        if not isinstance(r, Exception):
            r.inspected_bytes = blk.pack.bytes_read - io0
            break
    TEL.record_demux("search", len(items))
    return responses


def batched_search_block(batcher: BatchExecutor, blk, req,
                         groups_range=None, promote_touches: int = 2,
                         default_limit: int | None = None):
    """Route one block search through the batching executor when
    eligible; None means "take today's path unchanged":

      * the plan must lower to a predicate program (ops/multiquery);
      * the block must be warm -- staged columns resident, or touched
        promote_touches times (search_blocks_fused's promotion rule), or
        device-pinned for row-group shard jobs (search_block's rule);
      * tres-eligible plans keep the cheaper host membership scan, and
        stream-sized scans keep the chunked path.

    The sequential engine's per-query host_scan_cheaper estimate is
    deliberately NOT mirrored: it weighs one host scan against one
    device round trip, but under the batcher the round trip amortizes
    over the window (RTT/occupancy), which is the point of the
    subsystem -- a lone query on a warm block pays at most one RTT over
    the host estimate, bounded by the admission window policy."""
    probe = _probe_search_entry(batcher, blk, req, groups_range,
                                promote_touches, default_limit)
    if probe is None or not isinstance(probe, tuple):
        return probe  # ineligible (None) or a static empty response
    key, item = probe
    return batcher.submit(key, item)


# --------------------------------------------------------------- find path


@dataclass
class _FindItem:
    metas: list
    trace_id: bytes
    db: object = field(repr=False, default=None)


def _find_seq_or_exc(it: _FindItem):
    """Sequential twin of one find item (the pre-batching path)."""
    from ..wire.combine import combine_traces

    try:
        found = it.db._device_find(it.metas, it.trace_id)
        return combine_traces(found) if found else None
    except Exception as e:
        return e


def _run_find_group(key, items: list) -> list:
    """One coalesced trace-by-ID group: bloom-gate per (block, id) on
    host, then ONE batched bisection over every surviving block for ALL
    Q ids, per-id hit rows materialized and combined. Engine choice
    mirrors TempoDB._device_find: the sharded mesh program when >1 chip
    is attached, the fused single-chip batch (auto host/device) else.
    Any fused-path failure degrades to per-item sequential lookups so
    one bad block never fails the whole window's queries."""
    from ..util.kerneltel import TEL

    if len(items) == 1:
        return [_find_seq_or_exc(items[0])]
    try:
        return _run_find_group_fused(items)
    except Exception:
        TEL.record_routing("find_batch", "fallback", "fused_error",
                           n=len(items))
        return [_find_seq_or_exc(it) for it in items]


def _run_find_group_fused(items: list) -> list:
    from ..block import schema as S
    from ..ops.find import lookup_ids_blocks_cached
    from ..wire.combine import combine_traces

    db = items[0].db
    metas, pool = items[0].metas, db.pool
    blocks = [db.open_block(m) for m in metas]
    ids = [it.trace_id.rjust(16, b"\x00") for it in items]
    # a block survives the gate if ANY id in the window may be present;
    # the bisection compare is exact, so ids the bloom would have pruned
    # for a given block simply miss (-1) there
    if pool is not None:
        gates = list(pool.map(
            lambda b: any(b.bloom_test(it.trace_id) for it in items), blocks))
    else:
        gates = [any(b.bloom_test(it.trace_id) for it in items)
                 for b in blocks]
    keep = [b for b, ok in zip(blocks, gates) if ok]
    if not keep:
        return [None] * len(items)
    query = np.asarray([S.trace_id_to_codes(i) for i in ids], dtype=np.int32)
    if db.mesh.devices.size > 1:
        from ..parallel.find import sharded_find_rows

        codes = (list(pool.map(lambda b: b.trace_index["trace.id_codes"], keep))
                 if pool is not None
                 else [b.trace_index["trace.id_codes"] for b in keep])
        sids = sharded_find_rows(db.mesh, codes, query)  # (B, Q)
    else:
        if pool is not None:  # overlap the id-index reads
            list(pool.map(lambda b: b.trace_index, keep))
        sids = lookup_ids_blocks_cached(keep, query)  # (B, Q)
    per_block: dict[int, list[tuple[int, int]]] = {}
    for bi in range(sids.shape[0]):
        for qi in range(sids.shape[1]):
            if sids[bi, qi] >= 0:
                per_block.setdefault(bi, []).append((qi, int(sids[bi, qi])))
    found: list[list] = [[] for _ in items]
    for bi, pairs in per_block.items():
        traces = keep[bi].materialize_traces([row for _, row in pairs])
        for (qi, _), tr in zip(pairs, traces):
            if tr is not None:
                found[qi].append(tr)
    from ..util.kerneltel import TEL

    TEL.record_demux("find", len(items))
    return [combine_traces(f) if f else None for f in found]


def batched_find(batcher: BatchExecutor, db, metas: list, trace_id: bytes):
    """Trace-by-ID lookup through the find batcher: concurrent lookups
    against the same candidate partition share one bisection batch."""
    key = ("find", metas[0].tenant_id, tuple(m.block_id for m in metas))
    item = _FindItem(metas=metas, trace_id=trace_id, db=db)
    return batcher.submit(key, item)


# ------------------------------------------------------------- aggregates


class QueryBatchers:
    """The per-TempoDB pair of batching executors (search + find) under
    one resolved config. `mesh_fn` (lazy: the mesh is built on first
    use) lets window leaders lower a whole group onto the device mesh
    when more than one chip is attached."""

    def __init__(self, enabled=None, window_ms=None, max_batch=None,
                 mesh_fn=None):
        on, window_s, max_b = resolve_batch_config(enabled, window_ms, max_batch)
        self.enabled = on

        def search_runner(key, items):
            return _run_search_group(key, items, mesh_fn)

        self.search = BatchExecutor("search", search_runner,
                                    window_s=window_s, max_batch=max_b,
                                    enabled=on)
        self.find = BatchExecutor("find", _run_find_group,
                                  window_s=window_s, max_batch=max_b,
                                  enabled=on)


def batched_search_block_many(batcher: BatchExecutor, entries: list,
                              promote_touches: int = 2,
                              default_limit: int | None = None) -> list:
    """Many (blk, req, groups_range) searches from ONE caller thread,
    grouped by coalesce key and submitted together so a single worker
    draining a burst still forms full batches (the frontend's
    batch-aware dequeue lands here). Returns per-entry SearchResponse,
    None where the entry was ineligible (caller falls back), or the
    entry's own Exception (caller routes it through its per-job error
    path)."""
    out: list = [None] * len(entries)
    # batched_search_block with a one-item window would lose the mates;
    # instead lower each entry, bucket by key, and submit_many per key
    staged: dict = {}
    for i, (blk, req, groups_range) in enumerate(entries):
        probe = _probe_search_entry(batcher, blk, req, groups_range,
                                    promote_touches, default_limit)
        if probe is None:
            continue
        if isinstance(probe, tuple):
            key, item = probe
            staged.setdefault(key, []).append((i, item))
        else:  # an immediate empty response (prune / out of range)
            out[i] = probe
    for key, pairs in staged.items():
        results = batcher.submit_many(key, [it for _, it in pairs])
        for (i, _), r in zip(pairs, results):
            out[i] = r
    return out


def _probe_search_entry(batcher, blk, req, groups_range, promote_touches,
                        default_limit: int | None = None):
    """Eligibility probe shared with batched_search_block: returns
    (key, item) when batchable, a SearchResponse for static empties,
    or None to fall back. default_limit overrides db/search's module
    default for limit-less requests (TempoDBConfig.search_default_limit
    parity on the search_blocks route)."""
    from ..ops.filter import required_columns
    from ..ops.multiquery import lower_plan
    from ..util.kerneltel import TEL
    from .search import (
        _STREAM_MIN_STAGE_BYTES,
        DEFAULT_LIMIT,
        SearchResponse,
        _plan_for_block,
        _tres_eligible,
    )

    if batcher is None or not batcher.enabled:
        return None
    if not blk.meta.overlaps_time(req.start, req.end):
        return SearchResponse()
    planned = _plan_for_block(blk, req)
    if not planned.prune and groups_range is not None and planned.has_struct:
        planned = _plan_for_block(blk, req, allow_struct=False)
    if planned.prune:
        return SearchResponse()
    lowered = lower_plan(planned)
    if lowered is None:
        TEL.record_routing("search_batch", "fallback", "ineligible_plan")
        return None
    if _tres_eligible(blk, planned):
        TEL.record_routing("search_batch", "fallback", "tres_host")
        return None
    needed = required_columns(planned.conds) + list(planned.extra_cols)
    from ..block import schema as S

    span_ax = blk.pack.axes.get(S.AX_SPAN)
    n_rows = span_ax.n_rows if span_ax else 0
    n_span_cols = max(1, sum(
        1 for n in needed if n.startswith(("span.", "sattr."))))
    if n_rows * 4 * n_span_cols > _STREAM_MIN_STAGE_BYTES:
        TEL.record_routing("search_batch", "fallback", "stream_scan")
        return None
    stage_key = (tuple(needed + ["trace.start_ms"]),
                 tuple(groups_range) if groups_range is not None else None)
    store = getattr(blk, "_staged_cache", None)
    staged_hit = store is not None and stage_key in store
    touches = getattr(blk, "search_touches", 0)
    hot = (staged_hit
           or (groups_range is not None and getattr(blk, "device_pinned", False))
           or touches + 1 >= promote_touches)
    if not hot:
        TEL.record_routing("search_batch", "fallback", "cold_block")
        return None
    blk.search_touches = touches + 1
    item = _SearchItem(
        blk=blk, req=req, planned=planned, lowered=lowered, needed=needed,
        groups_range=list(groups_range) if groups_range is not None else None,
        limit=req.limit or default_limit or DEFAULT_LIMIT,
    )
    key = ("search", blk.meta.tenant_id, blk.meta.block_id,
           stage_key[1], stage_key[0], lowered.shape)
    return key, item
