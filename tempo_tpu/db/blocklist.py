"""In-memory per-tenant blocklist + backend poller + tenant index.

Mirrors the reference's blocklist/poller design (tempodb/blocklist/
list.go:29-123, poller.go:122-180): queriers and compactors never list
the backend on the query path -- they consult this in-memory list,
refreshed by a poll loop. Elected builders write a per-tenant
`index.json.gz` so the other readers do one object read instead of
O(blocks) meta reads. Updates that arrive while a poll is in flight are
patched into the fresh results (ApplyPollResults semantics).
"""

from __future__ import annotations

import gzip
import json
import threading
import time
from concurrent.futures import ThreadPoolExecutor

from ..backend.base import DoesNotExist, RawBackend, TENANT_INDEX_NAME
from ..block.meta import BlockMeta


# blocks compacted this recently stay searchable: rides out the
# lister-vs-swap race window (two poll cycles' worth by default)
COMPACTED_GRACE_S = 60.0


class Blocklist:
    def __init__(self):
        self._lock = threading.Lock()
        self._metas: dict[str, list[BlockMeta]] = {}
        self._compacted: dict[str, list[BlockMeta]] = {}
        # blocks added/removed since the current poll started
        self._added: dict[str, list[BlockMeta]] = {}
        self._removed: dict[str, set[str]] = {}
        # per-tenant mutation generation: bumps whenever the tenant's
        # searchable block set actually changes (flush, compaction,
        # poll drift). The frontend result cache keys on it, so any
        # blocklist change invalidates cached query results naturally.
        self._gen: dict[str, int] = {}

    def generation(self, tenant: str) -> int:
        with self._lock:
            return self._gen.get(tenant, 0)

    def tenants(self) -> list[str]:
        with self._lock:
            return [t for t, m in self._metas.items() if m]

    def metas(self, tenant: str) -> list[BlockMeta]:
        with self._lock:
            return list(self._metas.get(tenant, []))

    def compacted_metas(self, tenant: str) -> list[BlockMeta]:
        with self._lock:
            return list(self._compacted.get(tenant, []))

    def metas_by_id(self, tenant: str, block_ids: list[str]) -> list[BlockMeta]:
        """Resolve block ids -> metas (job payloads ship ids, not metas;
        a worker resolves them against its own polled blocklist). Missing
        ids are skipped -- poll lag is the caller's retry condition."""
        with self._lock:
            by_id = {m.block_id: m for m in self._metas.get(tenant, [])}
        return [by_id[b] for b in block_ids if b in by_id]

    def update(
        self,
        tenant: str,
        add: list[BlockMeta] | None = None,
        remove: list[str] | None = None,
        add_compacted: list[BlockMeta] | None = None,
    ) -> None:
        """Immediate local mutation (flush/compaction) -- also remembered
        so an in-flight poll can't resurrect/delete it."""
        with self._lock:
            metas = self._metas.setdefault(tenant, [])
            removed = self._removed.setdefault(tenant, set())
            changed = False
            if add:
                known = {m.block_id for m in metas}
                for m in add:
                    if m.block_id not in known:
                        metas.append(m)
                        changed = True
                self._added.setdefault(tenant, []).extend(add)
            if remove:
                rm = set(remove)
                kept = [m for m in metas if m.block_id not in rm]
                changed = changed or len(kept) != len(metas)
                self._metas[tenant] = kept
                removed |= rm
            if add_compacted:
                self._compacted.setdefault(tenant, []).extend(add_compacted)
            if changed:
                self._gen[tenant] = self._gen.get(tenant, 0) + 1

    def apply_poll_results(
        self, metas: dict[str, list[BlockMeta]], compacted: dict[str, list[BlockMeta]]
    ) -> None:
        with self._lock:
            for tenant in set(metas) | set(self._metas):
                fresh = metas.get(tenant, [])
                ids = {m.block_id for m in fresh}
                # patch in updates that raced the poll
                for m in self._added.get(tenant, []):
                    if m.block_id not in ids:
                        fresh.append(m)
                        ids.add(m.block_id)
                rm = self._removed.get(tenant, set())
                before = {m.block_id for m in self._metas.get(tenant, [])}
                self._metas[tenant] = [m for m in fresh if m.block_id not in rm]
                # a steady-state poll returning the same set must NOT
                # bump: generation-keyed result-cache entries would
                # churn on every poll cycle with nothing changed
                if {m.block_id for m in self._metas[tenant]} != before:
                    self._gen[tenant] = self._gen.get(tenant, 0) + 1
            self._compacted = {t: list(v) for t, v in compacted.items()}
            self._added.clear()
            self._removed.clear()


class Poller:
    """Scans the backend (or reads tenant indexes) into poll results; when
    `build_index` is set this poller also writes the per-tenant index
    (the reference elects N builders per tenant via the ring;
    services/compactor wires that ownership in)."""

    def __init__(
        self,
        backend: RawBackend,
        build_index: bool = True,
        stale_index_max_age_s: float = 0.0,
        concurrency: int = 16,
    ):
        self.backend = backend
        self.build_index = build_index
        self.stale_max = stale_index_max_age_s
        self.concurrency = concurrency
        # ring-sharded polling hook (fleet.PollerShard.install): when
        # this poller's instance does NOT own a tenant, it reads the
        # owner's index instead of listing the backend -- each member
        # pays 1/M of the poll. Default: own everything (solo poller).
        self.owns_tenant = lambda tenant: True
        self.last_shard: dict[str, list[str]] = {"owned": [], "deferred": []}

    def poll(self) -> tuple[dict[str, list[BlockMeta]], dict[str, list[BlockMeta]]]:
        metas: dict[str, list[BlockMeta]] = {}
        compacted: dict[str, list[BlockMeta]] = {}
        shard: dict[str, list[str]] = {"owned": [], "deferred": []}
        for tenant in self.backend.tenants():
            owned = self.owns_tenant(tenant)
            shard["owned" if owned else "deferred"].append(tenant)
            m, c = self.poll_tenant(tenant, owned=owned)
            metas[tenant] = m
            compacted[tenant] = c
        self.last_shard = shard
        return metas, compacted

    def poll_tenant(self, tenant: str,
                    owned: bool = True) -> tuple[list[BlockMeta], list[BlockMeta]]:
        if not owned:
            # non-owner: the shard owner's index IS the blocklist; fall
            # through to a full list only when no owner has written one
            # yet (cold start), so correctness never depends on sharding
            got = self._read_index(tenant)
            if got is not None:
                return got
        if not self.build_index:
            got = self._read_index(tenant)
            if got is not None:
                return got
        metas, compacted = self._list_tenant(tenant)
        if self.build_index and owned:
            self._write_index(tenant, metas, compacted)
        return metas, compacted

    # ---- raw listing
    def _list_tenant(self, tenant: str) -> tuple[list[BlockMeta], list[BlockMeta]]:
        metas: list[BlockMeta] = []
        compacted: list[BlockMeta] = []

        def read_one(block_id: str) -> list[tuple[BlockMeta, bool]]:
            try:
                raw = self.backend.read(tenant, block_id, "meta.json")
            except DoesNotExist:
                try:
                    return [(BlockMeta.from_json(
                        self.backend.read(tenant, block_id, "meta.compacted.json")), True)]
                except DoesNotExist:
                    return []
            doc = json.loads(raw)
            if doc.get("version") == "vtpu1c":
                # compound block (db/concat_compact.py): expand into its
                # per-part metas so every downstream path sees ordinary
                # blocks; fully-consumed compounds age out as a whole
                from .concat_compact import expand_compound

                pairs = expand_compound(self.backend, tenant, doc)
                newest = max((m.compacted_at_unix for m, c in pairs if c),
                             default=0.0)
                if (pairs and all(c for _, c in pairs)
                        and time.time() - newest > COMPACTED_GRACE_S):
                    # collapse to the whole only after every part's
                    # searchable-grace window has lapsed: a part consumed
                    # seconds ago may still be covering for a rewrite
                    # output the lister's snapshot predates
                    whole = BlockMeta.from_json(json.dumps(
                        {k: v for k, v in doc.items() if k != "parts"}).encode())
                    whole.compacted_at_unix = newest
                    return [(whole, True)]
                return pairs
            return [(BlockMeta.from_json(raw), False)]

        block_ids = self.backend.blocks(tenant)
        with ThreadPoolExecutor(max_workers=self.concurrency) as pool:
            for pairs in pool.map(read_one, block_ids):
                for meta, is_compacted in pairs:
                    (compacted if is_compacted else metas).append(meta)
        # swap-window grace: a scan can race a compaction/rewrite swap --
        # the directory listing snapshot predates the REPLACEMENT block
        # while the old one is already marked compacted, so the torn view
        # would drop both. Recently-compacted blocks therefore stay
        # SEARCHABLE for a grace window; trace-level dedupe makes the
        # double visibility harmless (the reference keeps serving
        # compacted blocks until queriers complete two poll cycles).
        now = time.time()
        ids = {m.block_id for m in metas}
        metas += [m for m in compacted
                  if m.compacted_at_unix
                  and now - m.compacted_at_unix < COMPACTED_GRACE_S
                  and m.block_id not in ids
                  # compound WHOLES (vtpu1c) are containers, not openable
                  # blocks; their parts got their own grace individually
                  and m.version != "vtpu1c"]
        metas.sort(key=lambda m: m.block_id)
        compacted.sort(key=lambda m: m.block_id)
        return metas, compacted

    # ---- tenant index
    def _write_index(self, tenant, metas, compacted) -> None:
        doc = {
            "created_at": time.time(),
            "meta": [json.loads(m.to_json()) for m in metas],
            "compacted": [json.loads(m.to_json()) for m in compacted],
        }
        data = gzip.compress(json.dumps(doc).encode("utf-8"))
        self.backend.write_tenant_object(tenant, TENANT_INDEX_NAME, data)

    def _read_index(self, tenant) -> tuple[list[BlockMeta], list[BlockMeta]] | None:
        try:
            raw = self.backend.read_tenant_object(tenant, TENANT_INDEX_NAME)
        except DoesNotExist:
            return None
        doc = json.loads(gzip.decompress(raw))
        if self.stale_max and time.time() - doc.get("created_at", 0) > self.stale_max:
            return None
        to_meta = lambda d: BlockMeta.from_json(json.dumps(d).encode())  # noqa: E731
        return [to_meta(d) for d in doc["meta"]], [to_meta(d) for d in doc["compacted"]]
