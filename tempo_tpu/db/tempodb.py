"""TempoDB: the storage-engine facade (reader / writer / compactor).

The role of tempodb.New + Reader/Writer/Compactor interfaces in the
reference (tempodb/tempodb.go:68-197): backend selection, WAL, blocklist
+ polling, parallel multi-block Find, per-block Search fan-out, and the
compaction/retention drivers. Services (L5) sit on top of this facade;
everything below it is columnar blocks + device kernels.
"""

from __future__ import annotations

import os
import threading
import time
from dataclasses import dataclass, field

import numpy as np

from ..backend import open_backend
from ..backend.base import RawBackend
from ..block.builder import build_block_from_traces
from ..block.meta import BlockMeta
from ..block.reader import BackendBlock
from ..util.distinct import DistinctStringCollector
from ..wire.combine import combine_traces
from ..wire.model import Trace
from . import compactor as comp
from .blocklist import Blocklist, Poller
from .search import SearchRequest, SearchResponse, search_block, search_tag_values, search_tags
from .wal import WAL


@dataclass
class TempoDBConfig:
    backend: dict = field(default_factory=lambda: {"backend": "local", "path": "./tempo-data"})
    wal_path: str = "./tempo-wal"
    row_group_spans: int = 1 << 16
    # chunk codec for ingest-written blocks (colio codec matrix:
    # zstd | gzip | lzma | raw); compaction output uses compaction.zstd_level
    block_codec: str = "zstd"
    pool_workers: int = 8
    blocklist_poll_s: float = 15.0
    block_cache_blocks: int = 64
    search_default_limit: int = 20
    device_find: bool = True  # batched/sharded device Find (ops/find, parallel/find)
    device_search: bool = True  # stacked multi-block device search (parallel/search)
    # searches of a block before its columns are staged on device (first
    # touches run the zero-RTT host engine; see search_blocks_fused)
    device_promote_touches: int = 2
    # cross-query batching executor (db/batchexec): None fields resolve
    # from the TEMPO_BATCH / TEMPO_BATCH_WINDOW_MS / TEMPO_BATCH_MAX env
    batch_enabled: bool | None = None
    batch_window_ms: float | None = None
    batch_max: int | None = None
    compaction: comp.CompactorConfig = field(default_factory=comp.CompactorConfig)


class TempoDB:
    def __init__(self, cfg: TempoDBConfig, backend: RawBackend | None = None):
        self.cfg = cfg
        # chaos seam: in an armed process (TEMPO_CHAOS / --chaos.rules)
        # every backend op runs through the fault-injection wrapper;
        # unarmed processes get the raw backend with zero indirection
        from ..chaos.backendwrap import maybe_wrap

        self.backend = maybe_wrap(backend or open_backend(cfg.backend))
        os.makedirs(cfg.wal_path, exist_ok=True)
        self.wal = WAL(os.path.join(cfg.wal_path, "wal"))
        self.blocklist = Blocklist()
        self.poller = Poller(self.backend)
        # context-propagating: pooled engine legs keep the caller's
        # ambient self-trace + affinity placement (util/ctxpool)
        from ..util.ctxpool import ContextThreadPool

        self.pool = ContextThreadPool(max_workers=cfg.pool_workers)
        # fan-out pool for the query engines: on a 1-core box with a
        # LOCAL backend the handoffs only add GIL ping-pong (~20% of a
        # cold scan), so every engine gets None and runs serial; remote
        # backends keep the pool (IO waits release the GIL and overlap)
        self.io_pool = (
            self.pool
            if (os.cpu_count() or 2) > 1 or getattr(self.backend, "is_remote", True)
            else None
        )
        self._block_cache: dict[tuple[str, str], BackendBlock] = {}
        self._cache_lock = threading.Lock()
        self._poll_thread: threading.Thread | None = None
        self._stop = threading.Event()
        self._mesh = None
        # cross-query batching: concurrent search / find jobs that share
        # a coalesce key merge into one fused kernel launch (batchexec)
        from .batchexec import QueryBatchers

        self.batchers = QueryBatchers(
            enabled=cfg.batch_enabled, window_ms=cfg.batch_window_ms,
            max_batch=cfg.batch_max, mesh_fn=self._batch_mesh)
        # compaction ownership + dedupe hooks, overridden by the service layer
        self.owns_job = lambda job_hash: True
        from ..util.metrics import Counter, Histogram

        self.poll_duration = Histogram("tempo_blocklist_poll_duration_seconds")
        self.poll_errors = Counter("tempo_blocklist_poll_errors_total")
        self.polls = Counter("tempo_blocklist_polls_total")
        # measured-crossover routing: seed the cold-scan host-rate EMA
        # from the persisted CostLedger (util/costledger) once
        from .search import seed_host_rate_from_ledger

        seed_host_rate_from_ledger()

    def _batch_mesh(self):
        """Mesh handed to the batching executors' window leaders
        (db/batchexec -> parallel/multiquery): all visible chips, or
        None on a single chip / with device search off -- the
        single-chip fused launch is already optimal there."""
        if not self.cfg.device_search:
            return None
        mesh = self.mesh
        return mesh if mesh.devices.size > 1 else None

    @property
    def mesh(self):
        """Device mesh for the sharded Find/search paths (all visible
        chips; a single chip yields a 1x1 mesh so the same mesh program
        the multi-chip dryrun validates also serves single-chip)."""
        if self._mesh is None:
            from ..parallel import make_mesh

            self._mesh = make_mesh()
        return self._mesh

    # ------------------------------------------------------------ blocks
    def open_block(self, meta: BlockMeta) -> BackendBlock:
        key = (meta.tenant_id, meta.block_id)
        with self._cache_lock:
            blk = self._block_cache.get(key)
            if blk is None:
                from ..block.versioned import open_block_versioned

                blk = open_block_versioned(self.backend, meta)
                # cached readers are long-lived over immutable blocks:
                # mark them device-worthy so search_block's auto mode
                # stages (and keeps) their columns on the accelerator
                blk.device_pinned = self.cfg.device_search
                if len(self._block_cache) >= self.cfg.block_cache_blocks:
                    self._block_cache.pop(next(iter(self._block_cache)))
                self._block_cache[key] = blk
            return blk

    def write_block(self, tenant: str, traces: list[tuple[bytes, Trace]]) -> BlockMeta:
        """Build + flush a complete block from sorted traces (ingester's
        CompleteBlock + WriteBlock path, tempodb.go:199-251)."""
        meta = build_block_from_traces(
            self.backend, tenant, traces, row_group_spans=self.cfg.row_group_spans,
            codec=self.cfg.block_codec,
        )
        self.blocklist.update(tenant, add=[meta])
        return meta

    # ------------------------------------------------------------- find
    def find_candidates(
        self, tenant: str, trace_id: bytes, time_start: int = 0, time_end: int = 0
    ) -> list[BlockMeta]:
        """Blocks whose id range + time window may hold the trace (the
        unit the frontend's ID-space sharder partitions)."""
        hex_id = trace_id.rjust(16, b"\x00").hex()
        return [
            m
            for m in self.blocklist.metas(tenant)
            if m.may_contain_id(hex_id) and m.overlaps_time(time_start, time_end)
        ]

    def find_trace_by_id(
        self, tenant: str, trace_id: bytes, time_start: int = 0, time_end: int = 0
    ) -> Trace | None:
        """Parallel candidate-block lookup + combine
        (reference: tempodb.Find, tempodb/tempodb.go:271-352)."""
        candidates = self.find_candidates(tenant, trace_id, time_start, time_end)
        return self.find_in_blocks(tenant, trace_id, candidates)

    def find_in_blocks(
        self, tenant: str, trace_id: bytes, candidates: list[BlockMeta]
    ) -> Trace | None:
        """Lookup restricted to an explicit block set -- one frontend
        ID-shard job (tracebyidsharding.go:30-48 analog: the frontend
        partitions the candidate blocks, we execute one partition)."""
        if not candidates:
            return None
        if self.cfg.device_find and self.batchers.enabled:
            # concurrent lookups against the same candidate partition
            # share one batched bisection (the Q axis of ops/find)
            from .batchexec import batched_find

            return batched_find(self.batchers.find, self, candidates, trace_id)
        if self.cfg.device_find:
            found = self._device_find(candidates, trace_id)
        else:
            results = list(
                self.pool.map(lambda m: self.open_block(m).find_trace_by_id(trace_id), candidates)
            )
            found = [t for t in results if t is not None]
        if not found:
            return None
        return combine_traces(found)

    def find_in_blocks_multi(self, items: list) -> list:
        """Many (tenant, trace_id, candidates) lookups at once: jobs
        sharing a candidate partition submit to the find batcher as one
        group from this thread (and merge with any window-mates)."""
        from .batchexec import _FindItem

        out: list = [None] * len(items)
        groups: dict[tuple, list[tuple[int, object]]] = {}
        for i, (tenant, trace_id, candidates) in enumerate(items):
            if not candidates:
                continue
            if not (self.cfg.device_find and self.batchers.enabled):
                out[i] = self.find_in_blocks(tenant, trace_id, candidates)
                continue
            key = ("find", candidates[0].tenant_id,
                   tuple(m.block_id for m in candidates))
            groups.setdefault(key, []).append((i, _FindItem(
                metas=candidates, trace_id=trace_id, db=self)))
        for key, pairs in groups.items():
            results = self.batchers.find.submit_many(key, [it for _, it in pairs])
            for (i, _), r in zip(pairs, results):
                out[i] = r
        return out

    def _device_find(self, candidates: list[BlockMeta], trace_id: bytes) -> list[Trace]:
        """Device Find: host bloom gate (one ranged read per block), then
        ONE batched bisection kernel over every surviving block's sorted
        id index — sharded over the mesh when >1 chip is attached. Each
        block reports its own hit row so partial traces combine, the
        device analog of the reference's per-block fan-out + combiner
        (tempodb/tempodb.go:271-352)."""
        from ..block import schema as S
        from ..ops.find import lookup_ids_blocks_cached
        from ..parallel.find import sharded_find_rows

        blocks = [self.open_block(m) for m in candidates]
        gates = list(self.pool.map(lambda b: b.bloom_test(trace_id), blocks))
        blocks = [b for b, ok in zip(blocks, gates) if ok]
        if not blocks:
            return []
        query = np.asarray(
            [S.trace_id_to_codes(trace_id.rjust(16, b"\x00"))], dtype=np.int32
        )
        if self.mesh.devices.size > 1:
            codes = list(self.pool.map(lambda b: b.trace_index["trace.id_codes"], blocks))
            sids = sharded_find_rows(self.mesh, codes, query)
        else:
            # single chip: lookup_ids_blocks_cached auto-routes to the
            # host searchsorted engine (zero device round trips)
            list(self.pool.map(lambda b: b.trace_index, blocks))  # parallel IO
            sids = lookup_ids_blocks_cached(blocks, query)
        hits = [(blk, int(sid)) for blk, sid in zip(blocks, sids[:, 0]) if sid >= 0]
        return list(self.pool.map(lambda h: h[0].materialize_traces([h[1]])[0], hits))

    # ------------------------------------------------------------ search
    def search(self, tenant: str, req: SearchRequest) -> SearchResponse:
        metas = [m for m in self.blocklist.metas(tenant) if m.overlaps_time(req.start, req.end)]
        return self.search_blocks(tenant, metas, req)

    def search_blocks(self, tenant: str, metas: list[BlockMeta], req: SearchRequest,
                      _skip_batcher: bool = False) -> SearchResponse:
        """Search a set of blocks as one unit -- the execution engine
        behind both TempoDB.search and the frontend's block-batch jobs.
        Single chip: fused per-block kernels + ONE cross-block device
        top-k sync (db/search.search_blocks_fused). Mesh: the stacked
        sharded program (parallel/search.py). Falls back to per-block
        search when the device budget or plan shape demands it.
        _skip_batcher: the caller already probed batch eligibility for
        this query and got a fallback -- don't plan and count it twice."""
        resp = SearchResponse()
        if not metas:
            return resp
        if (self.cfg.device_search and len(metas) == 1
                and self.batchers.enabled and not _skip_batcher):
            # single-block unit: concurrent queries against the same hot
            # block coalesce into one fused multi-query launch
            from .batchexec import batched_search_block

            got = batched_search_block(
                self.batchers.search, self.open_block(metas[0]), req,
                promote_touches=self.cfg.device_promote_touches,
                default_limit=self.cfg.search_default_limit)
            if got is not None:
                return got
        if self.cfg.device_search:
            if self.mesh.devices.size > 1 and len(metas) > 1:
                from .search import search_blocks_device

                got = search_blocks_device(
                    [self.open_block(m) for m in metas], req, self.mesh,
                    default_limit=self.cfg.search_default_limit, pool=self.io_pool,
                )
            else:
                from .search import search_blocks_fused

                got = search_blocks_fused(
                    [self.open_block(m) for m in metas], req,
                    pool=self.io_pool, default_limit=self.cfg.search_default_limit,
                    promote_touches=self.cfg.device_promote_touches,
                )
            if got is not None:  # None -> oversize / plan-shape fallback
                return got
        fallback = (self.io_pool.map(lambda m: search_block(self.open_block(m), req), metas)
                    if self.io_pool is not None
                    else (search_block(self.open_block(m), req) for m in metas))
        for r in fallback:
            resp.merge(r, req.limit or self.cfg.search_default_limit)
            if len(resp.traces) >= (req.limit or self.cfg.search_default_limit):
                break
        resp.traces.sort(key=lambda t: -t.start_time_unix_nano)
        return resp

    def search_block_shard(self, tenant: str, meta: BlockMeta, req: SearchRequest, groups_range) -> SearchResponse:
        """One sharded search job (frontend's StartPage/TotalPages analog).
        Concurrent shard jobs over the same row-group range coalesce
        through the batching executor; ineligible plans run unchanged."""
        blk = self.open_block(meta)
        if self.cfg.device_search and self.batchers.enabled:
            from .batchexec import batched_search_block

            got = batched_search_block(
                self.batchers.search, blk, req, groups_range=groups_range,
                promote_touches=self.cfg.device_promote_touches)
            if got is not None:
                return got
        return search_block(blk, req, groups_range=groups_range)

    def search_block_shard_multi(self, items: list) -> list:
        """Many (tenant, meta, req, groups_range) shard jobs at once;
        same-shard jobs submit to the batcher together."""
        from .batchexec import batched_search_block_many

        out: list = [None] * len(items)
        if self.cfg.device_search and self.batchers.enabled:
            entries = [(self.open_block(m), req, groups)
                       for (tenant, m, req, groups) in items]
            out = batched_search_block_many(
                self.batchers.search, entries,
                promote_touches=self.cfg.device_promote_touches)
        for i, (tenant, m, req, groups) in enumerate(items):
            if out[i] is None:
                out[i] = search_block(self.open_block(m), req,
                                      groups_range=groups)
        return out

    def search_blocks_multi(self, items: list) -> list:
        """Execute many (tenant, metas, req) search jobs at once -- the
        frontend's batch-aware dequeue hands a whole burst here so even
        a single worker thread forms full fused batches. Single-block
        jobs group by coalesce key and join the batcher window together;
        everything else runs the normal per-job path."""
        from .batchexec import batched_search_block_many

        out: list = [None] * len(items)
        singles: list[tuple[int, tuple]] = []
        for i, (tenant, metas, req) in enumerate(items):
            if (self.cfg.device_search and self.batchers.enabled
                    and len(metas) == 1):
                singles.append((i, (self.open_block(metas[0]), req, None)))
        if singles:
            got = batched_search_block_many(
                self.batchers.search, [e for _, e in singles],
                promote_touches=self.cfg.device_promote_touches,
                default_limit=self.cfg.search_default_limit)
            for (i, _), r in zip(singles, got):
                out[i] = r
        for i, (tenant, metas, req) in enumerate(items):
            if out[i] is None:
                # single-block entries were already probed (and refused)
                # by the batcher above: go straight to the engine
                out[i] = self.search_blocks(tenant, metas, req,
                                            _skip_batcher=len(metas) == 1)
        return out

    # ------------------------------------------------------------ metrics
    def metrics_query_range(self, tenant: str, req) -> "object":
        """TraceQL metrics range query over the backend blocklist
        (db/metrics_exec): per-block fused filter->bucketize->fold on
        device or host by temperature, partial series merged by label;
        the stacked mesh fold takes over on multi-chip."""
        from .metrics_exec import MetricsRequest, metrics_query_range_blocks

        assert isinstance(req, MetricsRequest)
        start_s, end_s = req.start_ms // 1000, -(-req.end_ms // 1000)
        metas = [m for m in self.blocklist.metas(tenant)
                 if m.overlaps_time(start_s, end_s)]
        blocks = [self.open_block(m) for m in metas]
        mesh = (self.mesh if self.cfg.device_search
                and self.mesh.devices.size > 1 else None)
        return metrics_query_range_blocks(
            blocks, req, pool=self.io_pool, mesh=mesh)

    def search_tags(self, tenant: str, max_bytes: int = 0) -> list[str]:
        c = DistinctStringCollector(max_bytes)
        for m in self.blocklist.metas(tenant):
            search_tags(self.open_block(m), c)
        return c.strings()

    def search_tag_values(self, tenant: str, tag: str, max_bytes: int = 0) -> list[str]:
        c = DistinctStringCollector(max_bytes)
        for m in self.blocklist.metas(tenant):
            search_tag_values(self.open_block(m), tag, c)
        return c.strings()

    # ----------------------------------------------------------- polling
    def poll_now(self) -> None:
        from ..util.metrics import timed

        self.polls.inc()
        with timed(self.poll_duration):
            metas, compacted = self.poller.poll()
        self.blocklist.apply_poll_results(metas, compacted)
        with self._cache_lock:  # drop cached readers for vanished blocks
            live = {(t, m.block_id) for t in metas for m in metas[t]}
            for key in [k for k in self._block_cache if k not in live]:
                self._block_cache.pop(key, None)

    def enable_polling(self) -> None:
        if self._poll_thread:
            return

        def loop():
            while not self._stop.wait(self.cfg.blocklist_poll_s):
                try:
                    self.poll_now()
                except Exception:  # noqa: BLE001 - poll errors keep last list
                    self.poll_errors.inc()

        self.poll_now()
        self._poll_thread = threading.Thread(target=loop, daemon=True, name="blocklist-poller")
        self._poll_thread.start()

    # --------------------------------------------------------- compaction
    def _apply_compaction_result(self, tenant: str, res: comp.CompactionResult,
                                 metas_by_id: dict[str, BlockMeta]) -> None:
        """Apply one job's result to the blocklist -- shared by the
        sequential (compact_once) and pipelined (compact_tenants) sweeps
        so their post-job state can't drift."""
        removed = set(res.compacted_ids)
        self.blocklist.update(
            tenant,
            add=res.new_blocks,
            remove=list(removed),
            add_compacted=[m for bid, m in metas_by_id.items() if bid in removed],
        )

    def compact_once(self, tenant: str) -> list[comp.CompactionResult]:
        """One compaction sweep for a tenant: select jobs, run owned ones."""
        metas = self.blocklist.metas(tenant)
        metas_by_id = {m.block_id: m for m in metas}
        jobs = comp.select_jobs(tenant, metas, self.cfg.compaction)
        results = []
        for job in jobs:
            if not self.owns_job(job.hash):
                continue
            res = comp.compact(self.backend, job, self.cfg.compaction)
            self._apply_compaction_result(tenant, res, metas_by_id)
            results.append(res)
        return results

    def compact_tenants(self, tenants: list[str] | None = None) -> list:
        """Concurrent compaction sweep across tenants through the
        pipeline executor (db/compact_pipeline): select owned jobs per
        tenant, run them with TEMPO_COMPACT_CONCURRENCY workers under the
        host-RAM admission budget (per-tenant round-robin admission),
        and apply each job's blocklist update the moment it commits --
        exactly the update compact_once makes, from the worker thread
        (Blocklist.update is lock-guarded). Returns the pipeline's
        JobOutcome list; per-job errors ride in the outcomes rather than
        aborting the sweep."""
        from .compact_pipeline import CompactionPipeline

        if tenants is None:
            tenants = self.tenants()
        jobs_by_tenant: dict[str, list[comp.CompactionJob]] = {}
        metas_by_tenant: dict[str, dict[str, BlockMeta]] = {}
        for tenant in tenants:
            metas = self.blocklist.metas(tenant)
            jobs = [j for j in comp.select_jobs(tenant, metas, self.cfg.compaction)
                    if self.owns_job(j.hash)]
            if jobs:
                jobs_by_tenant[tenant] = jobs
                metas_by_tenant[tenant] = {m.block_id: m for m in metas}

        def on_result(tenant: str, job: comp.CompactionJob,
                      res: comp.CompactionResult) -> None:
            self._apply_compaction_result(tenant, res, metas_by_tenant[tenant])

        pipeline = CompactionPipeline(self.backend, self.cfg.compaction)
        return pipeline.run(jobs_by_tenant, on_result=on_result)

    def retention_once(self, tenant: str) -> comp.RetentionResult:
        res = comp.apply_retention(
            self.backend,
            tenant,
            self.blocklist.metas(tenant),
            self.blocklist.compacted_metas(tenant),
            self.cfg.compaction,
            owns=self.owns_job,
        )
        if res.marked:
            self.blocklist.update(tenant, remove=res.marked)
        return res

    def tenants(self) -> list[str]:
        return self.blocklist.tenants()

    def close(self) -> None:
        self._stop.set()
        if self._poll_thread:
            self._poll_thread.join(timeout=2)
        self.pool.shutdown(wait=False)
