"""Column blob IO: named numpy arrays in one backend object, chunked by
row group.

Layout: [chunk buffers, each independently zstd-compressed] [footer JSON]
[uint32le footer len] [magic 'VTPU'].

Every column belongs to an *axis* (span rows, trace rows, attr rows, ...)
and is stored as one compressed chunk per row group along that axis. The
footer maps column name -> dtype/shape/axis/chunk table, so a reader can
fetch the footer with two small range reads and then range-read only the
(column, row-group) chunks a query touches -- the role parquet column
chunks + pages play for the reference (vparquet block_search.go,
parquetquery), but deserializing straight into flat device-uploadable
arrays with zero transposition.
"""

from __future__ import annotations

import json
import struct
import threading
from collections import OrderedDict

import numpy as np
import zstandard

MAGIC = b"VTPU"
_TAIL = struct.Struct("<I4s")

CODEC_RAW = "raw"
CODEC_ZSTD = "zstd"
_MIN_COMPRESS = 128


class AxisChunks:
    """Row boundaries of the row groups along one axis: offsets[g] ..
    offsets[g+1] are the rows of group g."""

    def __init__(self, offsets: list[int]):
        assert len(offsets) >= 2 and offsets[0] == 0
        self.offsets = list(offsets)

    @property
    def n_groups(self) -> int:
        return len(self.offsets) - 1

    @property
    def n_rows(self) -> int:
        return self.offsets[-1]


def pack_columns_stream(
    cols: dict[str, np.ndarray],
    axes: dict[str, AxisChunks] | None = None,
    col_axis: dict[str, str] | None = None,
    level: int = 3,
):
    """Yield the serialized pack as byte parts, ONE COLUMN AT A TIME
    (chunks of a column compress as one threaded native batch, then the
    footer+tail last). Peak memory is a single column's chunks, so the
    streamed-flush write path (backend appender) never buffers the whole
    block -- the role of the reference's incremental backend.Append
    tracker (v2/streaming_block.go:13-90)."""
    axes = axes or {}
    col_axis = col_axis or {}
    footer: dict = {"cols": {}, "axes": {k: v.offsets for k, v in axes.items()}}
    offset = 0

    for name, arr in cols.items():
        arr = np.ascontiguousarray(arr)
        axis = col_axis.get(name)
        raws: list[bytes] = []
        if axis is not None:
            ax = axes[axis]
            if ax.n_rows != arr.shape[0]:
                raise ValueError(
                    f"column {name}: {arr.shape[0]} rows != axis {axis} ({ax.n_rows})"
                )
            for g in range(ax.n_groups):
                lo, hi = ax.offsets[g], ax.offsets[g + 1]
                raws.append(arr[lo:hi].tobytes())
        else:
            raws.append(arr.tobytes())

        # compress this column's compressible chunks in one threaded
        # native batch (native/vtpu_native.cc); python zstd as fallback
        to_compress = [i for i, r in enumerate(raws) if len(r) >= _MIN_COMPRESS]
        compressed: dict[int, bytes] = {}
        if to_compress:
            from ..native import zstd_compress_chunks

            outs = zstd_compress_chunks([raws[i] for i in to_compress], level)
            if outs is None:
                comp = zstandard.ZstdCompressor(level=level)
                outs = [comp.compress(raws[i]) for i in to_compress]
            compressed = dict(zip(to_compress, outs))

        recs: list[list] = []
        for i, raw in enumerate(raws):
            z = compressed.get(i)
            if z is not None and len(z) < len(raw):
                data, codec = z, CODEC_ZSTD
            else:
                data, codec = raw, CODEC_RAW
            recs.append([offset, len(data), len(raw), codec])
            offset += len(data)
            yield data
        footer["cols"][name] = {
            "dtype": str(arr.dtype),
            "shape": list(arr.shape),
            "axis": axis,
            "chunks": recs,
        }

    fbytes = json.dumps(footer, separators=(",", ":")).encode("utf-8")
    yield fbytes
    yield _TAIL.pack(len(fbytes), MAGIC)


def pack_columns(
    cols: dict[str, np.ndarray],
    axes: dict[str, AxisChunks] | None = None,
    col_axis: dict[str, str] | None = None,
    level: int = 3,
) -> bytes:
    """Serialize columns. Columns named in col_axis are chunked along the
    given axis' row groups; others are stored as a single chunk."""
    return b"".join(pack_columns_stream(cols, axes, col_axis, level))


class ColumnPack:
    """Lazy chunked-column reader over a backend object via range reads."""

    # decompressed-chunk LRU budget, shared per pack: the host-RAM analog
    # of the OS page cache the reference's parquet reader leans on --
    # random trace materialization re-touches the same row-group chunks
    CHUNK_CACHE_BYTES = 256 << 20

    def __init__(self, read_range, total_size: int):
        """read_range(offset, length) -> bytes."""
        self._read_range = read_range
        self._size = total_size
        tail = self._read_range(total_size - _TAIL.size, _TAIL.size)
        flen, magic = _TAIL.unpack(tail)
        if magic != MAGIC:
            raise ValueError("not a vtpu column pack (bad magic)")
        fbytes = self._read_range(total_size - _TAIL.size - flen, flen)
        footer = json.loads(fbytes)
        self._cols: dict[str, dict] = footer["cols"]
        self.axes: dict[str, AxisChunks] = {
            k: AxisChunks(v) for k, v in footer.get("axes", {}).items()
        }
        self.bytes_read = _TAIL.size + flen  # inspected-bytes accounting
        self._dctx = zstandard.ZstdDecompressor()
        self._cache: OrderedDict[int, bytes] = OrderedDict()  # chunk offset -> raw
        self._cache_bytes = 0
        self._cache_lock = threading.Lock()

    @classmethod
    def from_bytes(cls, data: bytes) -> "ColumnPack":
        return cls(lambda off, ln: data[off : off + ln], len(data))

    def names(self) -> list[str]:
        return list(self._cols)

    def has(self, name: str) -> bool:
        return name in self._cols

    def _cache_get(self, off: int) -> bytes | None:
        with self._cache_lock:
            hit = self._cache.get(off)
            if hit is not None:
                self._cache.move_to_end(off)
            return hit

    def _cache_put(self, off: int, raw: bytes) -> None:
        if len(raw) > self.CHUNK_CACHE_BYTES // 4:
            return  # one huge chunk must not wipe the whole cache
        with self._cache_lock:
            if off in self._cache:
                return
            self._cache[off] = raw
            self._cache_bytes += len(raw)
            while self._cache_bytes > self.CHUNK_CACHE_BYTES and self._cache:
                _, old = self._cache.popitem(last=False)
                self._cache_bytes -= len(old)

    def _chunk(self, rec: list) -> bytes:
        off, stored_len, raw_len, codec = rec
        if raw_len == 0 and stored_len == 0:
            # zero-length chunks share the byte offset of the NEXT chunk
            # (writer advances offset by stored size) -- never cache them
            # under that offset or they poison the real chunk's entry
            return b""
        hit = self._cache_get(off)
        if hit is not None:
            return hit
        data = self._read_range(off, stored_len)
        self.bytes_read += stored_len
        if codec == CODEC_ZSTD:
            data = self._dctx.decompress(data, max_output_size=raw_len)
        self._cache_put(off, data)
        return data

    def _chunks(self, recs: list[list]) -> bytes:
        """Fetch + decode many chunks; zstd chunks decompress as one
        threaded native batch when >1 (native/vtpu_native.cc)."""
        parts: list[bytes | None] = [
            b"" if (rec[1] == 0 and rec[2] == 0) else self._cache_get(rec[0])
            for rec in recs
        ]
        miss = [i for i, p in enumerate(parts) if p is None]
        zst = [i for i in miss if recs[i][3] == CODEC_ZSTD]
        if len(zst) > 1:
            from ..native import available, zstd_decompress_chunks

            if available():
                outs = zstd_decompress_chunks(
                    [self._read_range(recs[i][0], recs[i][1]) for i in zst],
                    [recs[i][2] for i in zst],
                )
                if outs is not None:
                    self.bytes_read += sum(recs[i][1] for i in zst)
                    for i, raw in zip(zst, outs):
                        parts[i] = raw
                        self._cache_put(recs[i][0], raw)
        for i in miss:
            if parts[i] is None:
                parts[i] = self._chunk(recs[i])
        return b"".join(parts)

    def read(self, name: str) -> np.ndarray:
        meta = self._cols[name]
        raw = self._chunks(meta["chunks"])
        return np.frombuffer(raw, dtype=np.dtype(meta["dtype"])).reshape(meta["shape"])

    def read_groups(self, name: str, groups: list[int]) -> np.ndarray:
        """Concatenated rows of the given row groups (in the given order).
        Column must be axis-chunked."""
        meta = self._cols[name]
        if meta["axis"] is None:
            raise ValueError(f"column {name} is not axis-chunked")
        raw = self._chunks([meta["chunks"][g] for g in groups])
        shape = [-1] + meta["shape"][1:]
        return np.frombuffer(raw, dtype=np.dtype(meta["dtype"])).reshape(shape)

    def read_many(self, names: list[str]) -> dict[str, np.ndarray]:
        self.warm([(n, None) for n in names if n in self._cols])
        return {n: self.read(n) for n in names if n in self._cols}

    def read_groups_many(
        self, wants: list[tuple[str, list[int] | None]]
    ) -> dict[str, np.ndarray]:
        """Batched multi-column read: (name, groups|None for all). ALL
        columns' missing chunks decompress as ONE native threaded batch,
        so a trace materialization that touches 20 columns pays one
        parallel decode instead of 20 serial ones."""
        wants = [(n, g) for n, g in wants if n in self._cols]
        self.warm(wants)
        out: dict[str, np.ndarray] = {}
        for name, groups in wants:
            out[name] = self.read(name) if groups is None else self.read_groups(name, groups)
        return out

    def warm(self, wants: list[tuple[str, list[int] | None]]) -> None:
        """Prefetch + batch-decompress every missing chunk of the wanted
        (column, groups) set into the chunk cache."""
        recs = []
        for name, groups in wants:
            meta = self._cols.get(name)
            if meta is None:
                continue
            chunks = meta["chunks"]
            recs.extend(chunks if groups is None else [chunks[g] for g in groups])
        miss = [r for r in recs if r[3] == CODEC_ZSTD and self._cache_get(r[0]) is None]
        if len(miss) <= 1:
            return
        from ..native import available, zstd_decompress_chunks

        if not available():
            return
        outs = zstd_decompress_chunks(
            [self._read_range(r[0], r[1]) for r in miss], [r[2] for r in miss]
        )
        if outs is not None:
            self.bytes_read += sum(r[1] for r in miss)
            for r, raw in zip(miss, outs):
                self._cache_put(r[0], raw)

    def read_all(self) -> dict[str, np.ndarray]:
        # one threaded decompress batch for every chunk of every column
        self.warm([(n, None) for n in self._cols])
        return {n: self.read(n) for n in self._cols}
